"""Connector failure/recovery matrix (round 4): each injectable-client
connector exercised through its failure modes — flaky clients,
mid-stream disconnects, replay-after-failure — mirroring the
reference's per-backend integration suites (SURVEY §4.3)."""

from __future__ import annotations

import json

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.dataflow import EngineError


def _collect(table):
    rows = []
    pw.io.subscribe(
        table, on_change=lambda key, row, time, is_addition: rows.append(row)
    )
    pw.run(monitoring_level="none")
    pw.clear_graph()
    return rows


# ----------------------------------------------------------- object stores


class FlakyS3:
    """boto3-shaped; lists fine, the object fetch always fails."""

    def __init__(self, objects):
        self.objects = dict(objects)

    def list_objects_v2(self, Bucket, Prefix, **kw):
        return {
            "Contents": [{"Key": k, "ETag": "1"} for k in sorted(self.objects)],
            "IsTruncated": False,
        }

    def get_object(self, Bucket, Key):
        raise ConnectionError(f"transient fetch failure: {Key}")


def test_s3_static_read_transient_get_fails_loudly():
    """Static reads have no retry loop: a failing fetch must surface,
    not produce a partial table."""
    with pytest.raises(ConnectionError):
        pw.io.s3.read(
            "s3://b/", format="plaintext", mode="static", _client=FlakyS3({"k": b"v\n"})
        )
    pw.clear_graph()


class HalfDeadS3:
    """boto3-shaped; first listing works, then the listing dies."""

    def __init__(self):
        self.calls = 0

    def list_objects_v2(self, Bucket, Prefix, **kw):
        self.calls += 1
        if self.calls > 1:
            raise ConnectionError("listing failed")
        return {
            "Contents": [{"Key": "a.txt", "ETag": "1"}],
            "IsTruncated": False,
        }

    def get_object(self, Bucket, Key):
        import io

        return {"Body": io.BytesIO(b"alpha\n")}

def test_s3_streaming_listing_failure_fails_run():
    t = pw.io.s3.read(
        "s3://b/", format="plaintext", mode="streaming", _client=HalfDeadS3()
    )
    pw.io.subscribe(t, on_change=lambda key, row, time, is_addition: None)
    with pytest.raises(EngineError, match="failed"):
        pw.run(monitoring_level="none")
    pw.clear_graph()


# --------------------------------------------------------------- writers


class DeadSink:
    def __init__(self):
        self.writes = 0

    def write(self, *a, **kw):
        self.writes += 1
        raise IOError("sink gone")


def test_elasticsearch_write_failure_surfaces():
    """A failing sink client must not be swallowed."""

    class ES:
        def __init__(self):
            self.ops = []

        def bulk(self, operations=None, **kw):
            raise ConnectionError("cluster red")

        def index(self, **kw):
            raise ConnectionError("cluster red")

    t = pw.debug.table_from_rows(schema=pw.schema_from_types(a=int), rows=[(1,)])
    pw.io.elasticsearch.write(t, "http://localhost", None, "idx", _client=ES())
    with pytest.raises(Exception):
        pw.run(monitoring_level="none")
    pw.clear_graph()


# ------------------------------------------------------- python subjects


def test_subject_offsets_resume_skips_consumed(tmp_path):
    """An offset-aware subject resumes from its bookmark after restart
    and never re-emits consumed input (exactly-once source contract)."""

    produced = ["a", "b", "c", "d"]

    class Cursor(pw.io.python.ConnectorSubject):
        supports_offsets = True

        def run(self):
            start = int(self.offsets.get("pos", 0))
            for i in range(start, len(produced)):
                self.next_with_offset("pos", i + 1, w=produced[i])
            self.commit()

    class S(pw.Schema):
        w: str

    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))

    def run_once():
        events = []
        t = pw.io.python.read(Cursor(), schema=S, persistent_id="cur")
        pw.io.subscribe(
            t, on_change=lambda key, row, time, is_addition: events.append(row["w"])
        )
        pw.run(
            monitoring_level="none",
            persistence_config=pw.persistence.Config.simple_config(backend),
        )
        pw.clear_graph()
        return events

    assert sorted(run_once()) == ["a", "b", "c", "d"]
    assert run_once() == []  # nothing re-delivered
    produced.extend(["e"])
    assert run_once() == ["e"]  # only the delta


def test_subject_without_offsets_resets_cleanly(tmp_path):
    """An offset-UNAWARE subject re-produces everything; recovery resets
    the log so sinks see one copy, not two."""

    class Naive(pw.io.python.ConnectorSubject):
        def run(self):
            for w in ["p", "q"]:
                self.next(w=w)
            self.commit()

    class S(pw.Schema):
        w: str

    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))

    def run_once():
        events = []
        t = pw.io.python.read(Naive(), schema=S, persistent_id="naive")
        pw.io.subscribe(
            t, on_change=lambda key, row, time, is_addition: events.append(row["w"])
        )
        pw.run(
            monitoring_level="none",
            persistence_config=pw.persistence.Config.simple_config(backend),
        )
        pw.clear_graph()
        return sorted(events)

    assert run_once() == ["p", "q"]
    assert run_once() == ["p", "q"]  # re-produced once, never doubled


# --------------------------------------------------------------- sqlite


def test_sqlite_read_static(tmp_path):
    import sqlite3

    db = tmp_path / "d.db"
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE users (id INTEGER, name TEXT)")
    conn.executemany("INSERT INTO users VALUES (?, ?)", [(1, "ada"), (2, "bob")])
    conn.commit()
    conn.close()

    class S(pw.Schema):
        id: int
        name: str

    t = pw.io.sqlite.read(str(db), "users", schema=S, mode="static")
    rows = sorted((r["id"], r["name"]) for r in _collect(t))
    assert rows == [(1, "ada"), (2, "bob")]


def test_sqlite_missing_table_fails(tmp_path):
    import sqlite3

    db = tmp_path / "d.db"
    sqlite3.connect(db).close()

    class S(pw.Schema):
        id: int

    with pytest.raises(Exception):
        t = pw.io.sqlite.read(str(db), "ghost", schema=S, mode="static")
        _collect(t)
    pw.clear_graph()


def test_gdrive_object_size_limit_skips_payload():
    class FakeDrive:
        sizes = {"big": 1000}

        def list_objects(self):
            return [("small", 1), ("big", 1)]

        def get_object(self, key):
            return b"x" * (1000 if key == "big" else 4)

    t = pw.io.gdrive.read(
        "folder",
        mode="static",
        format="binary",
        object_size_limit=100,
        _client=FakeDrive(),
    )
    rows = sorted(_collect(t), key=lambda r: len(r["data"]))
    assert [len(r["data"]) for r in rows] == [0, 4]  # big skipped, small kept


def test_size_limit_cache_and_offset_interactions(tmp_path, monkeypatch):
    """The review-flagged failure modes: a cached full payload must not
    bypass a later limit; a skipped object must re-download when the
    limit is raised (the skip is recorded per-limit in offsets)."""
    monkeypatch.setenv("PATHWAY_TPU_FS_ONESHOT", "1")

    class Drive:
        sizes = {}

        def __init__(self):
            self.gets = 0

        def list_objects(self):
            return [("doc", "v1")]

        def get_object(self, key):
            self.gets += 1
            return b"x" * 200

    cache_dir = str(tmp_path / "cache")
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))

    def run_once(limit, client):
        t = pw.io.gdrive.read(
            "folder",
            mode="streaming",
            format="binary",
            object_size_limit=limit,
            object_cache=cache_dir,
            persistent_id="gd",
            _client=client,
        )
        rows = []
        pw.io.subscribe(
            t,
            on_change=lambda key, row, time, is_addition: rows.append(
                (len(row["data"]), is_addition)
            ),
        )
        pw.run(
            monitoring_level="none",
            persistence_config=pw.persistence.Config.simple_config(backend),
        )
        pw.clear_graph()
        return rows

    # 1. no limit: full payload served and cached
    c1 = Drive()
    assert run_once(None, c1) == [(200, True)]
    assert c1.gets == 1

    # 2. limit added: the cached 200-byte payload must NOT be served;
    #    the row revises to empty
    c2 = Drive()
    rows2 = run_once(100, c2)
    assert (200, False) in rows2 and (0, True) in rows2
    assert c2.gets == 0, "cache hit should have avoided the download"

    # 3. same limit again: nothing re-delivers
    assert run_once(100, Drive()) == []

    # 4. limit raised past the size: full content comes back (from cache)
    rows4 = run_once(1000, Drive())
    assert (0, False) in rows4 and (200, True) in rows4


def test_size_limit_metadata_skip_avoids_download(monkeypatch):
    monkeypatch.setenv("PATHWAY_TPU_FS_ONESHOT", "1")

    class Drive:
        sizes = {"big": 500}

        def __init__(self):
            self.gets = 0

        def list_objects(self):
            return [("big", "v1")]

        def get_object(self, key):
            self.gets += 1
            return b"x" * 500

    c = Drive()
    t = pw.io.gdrive.read(
        "folder", mode="static", format="binary", object_size_limit=100, _client=c
    )
    rows = _collect(t)
    assert [len(r["data"]) for r in rows] == [0]
    assert c.gets == 0, "listing size metadata should skip the download"


# ----------------------------------------------------------- http client


class FakeHttp:
    """requests-shaped double: request() serves scripted payloads for
    reads and records bodies for writes."""

    def __init__(self, payloads=None, fail=False, status=200):
        self.payloads = list(payloads or [])
        self.fail = fail
        self.status = status
        self.sent = []

    def request(
        self,
        method,
        url,
        data=None,
        headers=None,
        stream=False,
        timeout=None,
        allow_redirects=True,
        **kw,
    ):
        if self.fail:
            raise ConnectionError("endpoint down")
        if data is not None or kw.get("json") is not None:
            self.sent.append((method, json.loads(data) if data else kw.get("json")))
        payload = self.payloads.pop(0) if self.payloads else []
        status = self.status
        body_text = json.dumps(payload)

        class R:
            status_code = status
            text = body_text

            @staticmethod
            def json():
                return payload

        return R()

    def get(self, url, **kw):
        return self.request("GET", url, **kw)


def test_http_read_static(tmp_path):
    class S(pw.Schema):
        id: int
        word: str

    t = pw.io.http.read(
        "http://x/feed",
        schema=S,
        mode="static",
        _session=FakeHttp([[{"id": 1, "word": "a"}, {"id": 2, "word": "b"}]]),
    )
    rows = sorted((r["id"], r["word"]) for r in _collect(t))
    assert rows == [(1, "a"), (2, "b")]


def test_http_read_static_dead_endpoint_fails():
    class S(pw.Schema):
        id: int

    t = pw.io.http.read(
        "http://x/feed", schema=S, mode="static", _session=FakeHttp(fail=True)
    )
    pw.io.subscribe(t, on_change=lambda key, row, time, is_addition: None)
    with pytest.raises(EngineError, match="failed"):
        pw.run(monitoring_level="none")
    pw.clear_graph()


def test_http_write_posts_changes_and_fails_on_error_status():
    session = FakeHttp()
    t = pw.debug.table_from_rows(schema=pw.schema_from_types(a=int), rows=[(7,)])
    pw.io.http.write(t, "http://x/sink", _session=session)
    pw.run(monitoring_level="none")
    pw.clear_graph()
    assert session.sent and session.sent[0][1]["a"] == 7

    bad = FakeHttp(status=500)
    t2 = pw.debug.table_from_rows(schema=pw.schema_from_types(a=int), rows=[(7,)])
    pw.io.http.write(t2, "http://x/sink", _session=bad)
    with pytest.raises(Exception):
        pw.run(monitoring_level="none")
    pw.clear_graph()
