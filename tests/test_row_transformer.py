"""Row transformers (legacy complex columns, R31).

Mirrors the reference's class-transformer docs/tests: the linked-list
length example (recursive cross-row pointer chasing), two-table
transformers, and incremental updates."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner
from .utils import T, run_table


def _linked_list(values):
    """Build a table id->next forming a chain."""
    rows = []
    n = len(values)
    keys = [pw.ref_scalar("node", i) for i in range(n)]

    class S(pw.Schema):
        next: pw.Pointer | None

    for i in range(n):
        nxt = pw.Pointer(keys[i + 1]) if i + 1 < n else None
        rows.append((nxt,))
    t = pw.debug.table_from_rows(S, rows)
    # re-key so pointers line up
    return t.with_id_from_keys(keys) if hasattr(t, "with_id_from_keys") else _rekey(t, keys)


def _rekey(t, keys):
    # rebuild via static rows with explicit keys
    from pathway_tpu.internals.table import Column, LogicalOp, Table
    from pathway_tpu.internals.universe import Universe
    from pathway_tpu.internals import dtype as dt

    state = run_table(t)
    recs = [(int(k), row, 0, 1) for k, row in zip(keys, state.values())]
    cols = {"next": Column(dt.ANY)}
    op = LogicalOp("static", [], {"rows": recs})
    pw.clear_graph()
    return Table(cols, Universe(), op, name="linked_list")


def test_linked_list_length():
    @pw.transformer
    class compute_lengths:
        class linked_list(pw.ClassArg):
            next = pw.input_attribute()

            @pw.output_attribute
            def len(self) -> int:
                if self.next is None:
                    return 0
                return 1 + self.transformer.linked_list[self.next].len

    chain = _linked_list([10, 20, 30, 40])
    result = compute_lengths(linked_list=chain).linked_list
    state = run_table(result)
    assert sorted(r[0] for r in state.values()) == [0, 1, 2, 3]
    pw.clear_graph()


def test_two_table_transformer():
    class PtrSchema(pw.Schema):
        val: int

    base = pw.debug.table_from_rows(PtrSchema, [(10,), (20,)])
    bstate = run_table(base)
    keys = sorted(bstate.keys())

    class RefSchema(pw.Schema):
        target: pw.Pointer

    refs = pw.debug.table_from_rows(
        RefSchema, [(pw.Pointer(keys[0]),), (pw.Pointer(keys[1]),), (pw.Pointer(keys[0]),)]
    )

    @pw.transformer
    class deref:
        class targets(pw.ClassArg):
            val = pw.input_attribute()

        class refs(pw.ClassArg):
            target = pw.input_attribute()

            @pw.output_attribute
            def resolved(self) -> int:
                return self.transformer.targets[self.target].val * 2

    result = deref(targets=base, refs=refs).refs
    state = run_table(result)
    assert sorted(r[0] for r in state.values()) == [20, 20, 40]
    pw.clear_graph()


def test_transformer_with_computed_attribute_and_id():
    @pw.transformer
    class t:
        class rows(pw.ClassArg):
            x = pw.input_attribute()

            @pw.attribute
            def doubled(self):
                return self.x * 2

            @pw.output_attribute
            def out(self) -> int:
                return self.doubled + 1

            @pw.output_attribute
            def self_id(self):
                return self.id

    class S(pw.Schema):
        x: int

    tab = pw.debug.table_from_rows(S, [(1,), (5,)])
    res = t(rows=tab).rows
    state = run_table(res)
    vals = sorted((r[0], int(r[1])) for r in state.values())
    assert [v for v, _ in vals] == [3, 11]
    assert all(int(k) == i for (_, i), k in zip(vals, sorted(state.keys())))
    pw.clear_graph()


def test_transformer_incremental_update():
    @pw.transformer
    class double:
        class rows(pw.ClassArg):
            x = pw.input_attribute()

            @pw.output_attribute
            def y(self) -> int:
                return self.x * 10

    tab = pw.debug.table_from_markdown(
        """
          | x | __time__ | __diff__
        1 | 1 | 0        | 1
        2 | 2 | 0        | 1
        1 | 1 | 2        | -1
        """
    )
    res = double(rows=tab).rows
    runner = GraphRunner()
    cap, _ = runner.capture(res)
    runner.run()
    assert sorted(r[0] for r in cap.state.values()) == [20]
    hist = sorted((r[0], d) for _k, r, _t, d in cap.stream)
    assert (10, 1) in hist and (10, -1) in hist  # retraction flowed through
    pw.clear_graph()


def test_cycle_detection():
    @pw.transformer
    class cyc:
        class rows(pw.ClassArg):
            x = pw.input_attribute()

            @pw.output_attribute
            def a(self):
                return self.b

            @pw.output_attribute
            def b(self):
                return self.a

    class S(pw.Schema):
        x: int

    tab = pw.debug.table_from_rows(S, [(1,)])
    res = cyc(rows=tab).rows
    from pathway_tpu.engine.dataflow import EngineError

    with pytest.raises(EngineError):  # CycleError routed via error system
        run_table(res)
    pw.clear_graph()


def test_method_returns_attribute():
    m = pw.method(lambda self: 1)
    from pathway_tpu.internals.row_transformer import _MethodAttribute

    assert isinstance(m, _MethodAttribute)


def test_method_column_called_in_select():
    """pw.method columns (reference row_transformer.py:254 Method +
    tests/test_transformers.py:288): the column holds per-row bound
    callables; calling it in a select evaluates per row."""

    @pw.transformer
    class foo_transformer:
        class table(pw.ClassArg):
            a = pw.input_attribute()

            @pw.output_attribute
            def b(self) -> int:
                return self.a * 10

            @pw.method
            def c(self, arg) -> int:
                return (self.a + self.b) * arg

    t = T(
        """
      | a
    1 | 1
    2 | 2
    3 | 3
    """
    )
    mt = foo_transformer(table=t).table
    r = mt.select(ret=mt.c(10))
    assert sorted(run_table(r).values()) == [(110,), (220,), (330,)]


def test_method_called_from_output_attribute():
    """self.c(x) inside another attribute (reference
    test_transformers.py:253 test_call_self_method)."""

    @pw.transformer
    class foo_transformer:
        class table(pw.ClassArg):
            a = pw.input_attribute()

            @pw.output_attribute
            def b(self) -> int:
                return self.a + self.c(self.a)

            @pw.method
            def c(self, arg) -> int:
                return self.a * arg

    t = T(
        """
      | a
    1 | 1
    """
    )
    mt = foo_transformer(table=t).table
    assert list(run_table(mt.select(ret=mt.b)).values()) == [(2,)]


def test_method_column_streams_with_state():
    """Method cells evaluate against CURRENT transformer state: a later
    epoch's input update changes what an earlier-bound method returns."""

    @pw.transformer
    class foo_transformer:
        class table(pw.ClassArg):
            a = pw.input_attribute()

            @pw.method
            def scaled(self, k) -> int:
                return self.a * k

    t = T(
        """
      | a | __time__ | __diff__
    1 | 1 | 2        | 1
    2 | 5 | 4        | 1
    """
    )
    mt = foo_transformer(table=t).table
    r = mt.select(ret=mt.scaled(3))
    assert sorted(run_table(r).values()) == [(3,), (15,)]


def test_method_column_invalidates_on_state_change():
    """Regression (r3 review): a state update that only method cells
    observe must re-emit the method rows so downstream selects
    recompute — method cells read ANY row, so every input change
    invalidates them."""

    @pw.transformer
    class foo_transformer:
        class table(pw.ClassArg):
            a = pw.input_attribute()

            @pw.method
            def plus_peer_sum(self, k) -> int:
                # reads every row: state changes invisible to outputs
                total = 0
                for key in list(self.transformer.table._ctx.states["table"]):
                    total += self.transformer.table[pw.Pointer(key)].a
                return total * k

    t = T(
        """
      | a | __time__ | __diff__
    1 | 1 | 2        | 1
    2 | 4 | 4        | 1
    """
    )
    mt = foo_transformer(table=t).table
    r = mt.select(ret=mt.plus_peer_sum(10))
    rows = run_table(r)
    # final state: both rows see the FULL final sum (1+4)*10
    assert sorted(rows.values()) == [(50,), (50,)]


def test_bound_method_pickle_rebinds_to_live_node():
    """A BoundMethod pickled out of another operator's snapshotted state
    (or sent cross-process) must re-bind to the live transformer node on
    restore, not come back permanently broken."""
    import pickle

    from pathway_tpu.internals.graph_runner import GraphRunner

    @pw.transformer
    class rebind_transformer:
        class table(pw.ClassArg):
            a = pw.input_attribute()

            @pw.method
            def c(self, arg) -> int:
                return self.a * arg

    t = T(
        """
      | a
    1 | 7
    """
    )
    mt = rebind_transformer(table=t).table
    runner = GraphRunner()
    cap, names = runner.capture(mt)
    runner.run()
    (row,) = cap.state.values()
    method_cell = row[names.index("c")]
    assert method_cell(10) == 70

    # round-trip through pickle, as downstream operator snapshots do
    restored = pickle.loads(pickle.dumps(method_cell))
    assert restored._node is None
    assert restored(10) == 70, "detached method did not re-bind"


def test_transformer_node_snapshot_restores_method_cells():
    """The owning node's own snapshot/restore round-trips method cells
    back into callable BoundMethods (the enc/dec marker formats must
    agree)."""
    from pathway_tpu.internals.graph_runner import GraphRunner
    from pathway_tpu.internals.row_transformer import BoundMethod, _RowTransformerNode

    @pw.transformer
    class snap_transformer:
        class table(pw.ClassArg):
            a = pw.input_attribute()

            @pw.method
            def c(self, arg) -> int:
                return self.a + arg

    t = T(
        """
      | a
    1 | 5
    """
    )
    mt = snap_transformer(table=t).table
    runner = GraphRunner()
    cap, names = runner.capture(mt)
    runner.run()
    node = next(
        n for n in runner.engine.nodes if isinstance(n, _RowTransformerNode)
    )
    state = node.snapshot_state()
    assert not any(
        isinstance(v, BoundMethod) for row in state["emitted"].values() for v in row
    ), "snapshot leaked live BoundMethods"
    node.emitted = {}
    node.restore_state(state)
    cells = [
        v
        for row in node.emitted.values()
        for v in row
        if isinstance(v, BoundMethod)
    ]
    assert cells, "restore did not rebuild BoundMethod cells"
    assert cells[0](1) == 6
