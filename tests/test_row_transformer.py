"""Row transformers (legacy complex columns, R31).

Mirrors the reference's class-transformer docs/tests: the linked-list
length example (recursive cross-row pointer chasing), two-table
transformers, and incremental updates."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner
from .utils import run_table


def _linked_list(values):
    """Build a table id->next forming a chain."""
    rows = []
    n = len(values)
    keys = [pw.ref_scalar("node", i) for i in range(n)]

    class S(pw.Schema):
        next: pw.Pointer | None

    for i in range(n):
        nxt = pw.Pointer(keys[i + 1]) if i + 1 < n else None
        rows.append((nxt,))
    t = pw.debug.table_from_rows(S, rows)
    # re-key so pointers line up
    return t.with_id_from_keys(keys) if hasattr(t, "with_id_from_keys") else _rekey(t, keys)


def _rekey(t, keys):
    # rebuild via static rows with explicit keys
    from pathway_tpu.internals.table import Column, LogicalOp, Table
    from pathway_tpu.internals.universe import Universe
    from pathway_tpu.internals import dtype as dt

    state = run_table(t)
    recs = [(int(k), row, 0, 1) for k, row in zip(keys, state.values())]
    cols = {"next": Column(dt.ANY)}
    op = LogicalOp("static", [], {"rows": recs})
    pw.clear_graph()
    return Table(cols, Universe(), op, name="linked_list")


def test_linked_list_length():
    @pw.transformer
    class compute_lengths:
        class linked_list(pw.ClassArg):
            next = pw.input_attribute()

            @pw.output_attribute
            def len(self) -> int:
                if self.next is None:
                    return 0
                return 1 + self.transformer.linked_list[self.next].len

    chain = _linked_list([10, 20, 30, 40])
    result = compute_lengths(linked_list=chain).linked_list
    state = run_table(result)
    assert sorted(r[0] for r in state.values()) == [0, 1, 2, 3]
    pw.clear_graph()


def test_two_table_transformer():
    class PtrSchema(pw.Schema):
        val: int

    base = pw.debug.table_from_rows(PtrSchema, [(10,), (20,)])
    bstate = run_table(base)
    keys = sorted(bstate.keys())

    class RefSchema(pw.Schema):
        target: pw.Pointer

    refs = pw.debug.table_from_rows(
        RefSchema, [(pw.Pointer(keys[0]),), (pw.Pointer(keys[1]),), (pw.Pointer(keys[0]),)]
    )

    @pw.transformer
    class deref:
        class targets(pw.ClassArg):
            val = pw.input_attribute()

        class refs(pw.ClassArg):
            target = pw.input_attribute()

            @pw.output_attribute
            def resolved(self) -> int:
                return self.transformer.targets[self.target].val * 2

    result = deref(targets=base, refs=refs).refs
    state = run_table(result)
    assert sorted(r[0] for r in state.values()) == [20, 20, 40]
    pw.clear_graph()


def test_transformer_with_computed_attribute_and_id():
    @pw.transformer
    class t:
        class rows(pw.ClassArg):
            x = pw.input_attribute()

            @pw.attribute
            def doubled(self):
                return self.x * 2

            @pw.output_attribute
            def out(self) -> int:
                return self.doubled + 1

            @pw.output_attribute
            def self_id(self):
                return self.id

    class S(pw.Schema):
        x: int

    tab = pw.debug.table_from_rows(S, [(1,), (5,)])
    res = t(rows=tab).rows
    state = run_table(res)
    vals = sorted((r[0], int(r[1])) for r in state.values())
    assert [v for v, _ in vals] == [3, 11]
    assert all(int(k) == i for (_, i), k in zip(vals, sorted(state.keys())))
    pw.clear_graph()


def test_transformer_incremental_update():
    @pw.transformer
    class double:
        class rows(pw.ClassArg):
            x = pw.input_attribute()

            @pw.output_attribute
            def y(self) -> int:
                return self.x * 10

    tab = pw.debug.table_from_markdown(
        """
          | x | __time__ | __diff__
        1 | 1 | 0        | 1
        2 | 2 | 0        | 1
        1 | 1 | 2        | -1
        """
    )
    res = double(rows=tab).rows
    runner = GraphRunner()
    cap, _ = runner.capture(res)
    runner.run()
    assert sorted(r[0] for r in cap.state.values()) == [20]
    hist = sorted((r[0], d) for _k, r, _t, d in cap.stream)
    assert (10, 1) in hist and (10, -1) in hist  # retraction flowed through
    pw.clear_graph()


def test_cycle_detection():
    @pw.transformer
    class cyc:
        class rows(pw.ClassArg):
            x = pw.input_attribute()

            @pw.output_attribute
            def a(self):
                return self.b

            @pw.output_attribute
            def b(self):
                return self.a

    class S(pw.Schema):
        x: int

    tab = pw.debug.table_from_rows(S, [(1,)])
    res = cyc(rows=tab).rows
    from pathway_tpu.engine.dataflow import EngineError

    with pytest.raises(EngineError):  # CycleError routed via error system
        run_table(res)
    pw.clear_graph()


def test_method_unsupported():
    with pytest.raises(NotImplementedError):
        pw.method(lambda self: 1)
