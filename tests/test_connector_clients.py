"""Service-backed connectors driven end-to-end with fake clients.

Reference model: the Rust integration suites exercise each
reader/parser and writer/formatter pair in-process
(/root/reference/tests/integration/test_dsv.rs, test_debezium.rs,
test_bson.rs; integration_tests/kafka/). Here every connector's full
loop — reader thread → parse → commit → engine, or engine → format →
client — runs against an injected fake client, no services needed.
"""

from __future__ import annotations

import json

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner
from pathway_tpu.io._formats import (
    BsonFormatter,
    DebeziumMessageParser,
    DsvFormatter,
    DsvParser,
    JsonLinesFormatter,
    JsonLinesParser,
    PsqlSnapshotFormatter,
    PsqlUpdatesFormatter,
)


class WordSchema(pw.Schema):
    word: str


class KV(pw.Schema):
    k: str
    v: int


def _run(table):
    runner = GraphRunner()
    cap, names = runner.capture(table)
    runner.run()
    pw.clear_graph()
    return cap, names


def _rows(cap, names, *cols):
    idx = [names.index(c) for c in cols]
    return sorted(tuple(row[i] for i in idx) for row in cap.state.values())


def _run_with_outputs(tables=()):
    """Run the registered graph outputs (sinks) to completion."""
    from pathway_tpu.internals.parse_graph import G

    runner = GraphRunner()
    for table, sink in list(G.outputs):
        sink["build"](runner, table)
    caps = [runner.capture(t) for t in tables]
    runner.run()
    pw.clear_graph()
    return caps


# ---------------------------------------------------------------------------
# kafka (fake consumer/producer)
# ---------------------------------------------------------------------------


def test_kafka_read_with_fake_consumer():
    msgs = [(None, json.dumps({"k": w, "v": i}).encode()) for i, w in enumerate("abc")]
    t = pw.io.kafka.read({}, "topic", schema=KV, _consumer=iter(msgs))
    cap, names = _run(t)
    assert _rows(cap, names, "k", "v") == [("a", 0), ("b", 1), ("c", 2)]


def test_kafka_write_with_fake_producer():
    class FakeProducer:
        def __init__(self):
            self.sent = []

        def produce(self, topic, payload):
            self.sent.append((topic, payload))

        def poll(self, timeout):
            pass

    prod = FakeProducer()
    t = pw.debug.table_from_rows(schema=KV, rows=[("x", 1), ("y", 2)])
    pw.io.kafka.write(t, {}, "out-topic", _producer=prod)
    _run_with_outputs()
    recs = sorted(json.loads(p)["k"] for _t, p in prod.sent)
    assert recs == ["x", "y"]
    assert all(t == "out-topic" for t, _p in prod.sent)


# ---------------------------------------------------------------------------
# postgres (fake connection)
# ---------------------------------------------------------------------------


class FakePg:
    def __init__(self):
        self.executed: list[tuple[str, tuple]] = []
        self.commits = 0
        self.closed = False

    def cursor(self):
        pg = self

        class Cur:
            def execute(self, sql, params):
                pg.executed.append((sql, params))

            def close(self):
                pass

        return Cur()

    def commit(self):
        self.commits += 1

    def close(self):
        self.closed = True


def test_postgres_write_updates():
    pg = FakePg()
    t = pw.debug.table_from_rows(schema=KV, rows=[("x", 1), ("y", 2)])
    pw.io.postgres.write(t, {"host": "h"}, "tbl", _connection_factory=lambda s: pg)
    _run_with_outputs()
    assert len(pg.executed) == 2
    sql, params = pg.executed[0]
    assert sql.startswith("INSERT INTO tbl (k,v,time,diff) VALUES")
    assert params in (("x", 1), ("y", 2))
    assert pg.commits >= 1 and pg.closed


def test_postgres_write_snapshot_upsert_and_delete():
    pg = FakePg()
    t = pw.debug.table_from_markdown(
        """
          | k | v | __time__ | __diff__
        1 | x | 1 | 0        | 1
        1 | x | 1 | 2        | -1
        1 | x | 5 | 2        | 1
        """
    )
    pw.io.postgres.write_snapshot(
        t, {"host": "h"}, "snap", ["k"], _connection_factory=lambda s: pg
    )
    _run_with_outputs()
    inserts = [e for e in pg.executed if e[0].startswith("INSERT")]
    deletes = [e for e in pg.executed if e[0].startswith("DELETE")]
    assert any("ON CONFLICT (k) DO UPDATE SET" in sql for sql, _ in inserts)
    assert deletes and deletes[0][1] == ("x",)


# ---------------------------------------------------------------------------
# s3 / minio / s3_csv / pyfilesystem / gdrive (fake object stores)
# ---------------------------------------------------------------------------


class FakeS3:
    """boto3-shaped client over an in-memory dict."""

    def __init__(self, objects: dict[str, bytes]):
        self.objects = objects

    def list_objects_v2(self, Bucket, Prefix, **kw):
        contents = [
            {"Key": k, "ETag": str(hash(v))}
            for k, v in sorted(self.objects.items())
            if k.startswith(Prefix)
        ]
        return {"Contents": contents, "IsTruncated": False}

    def get_object(self, Bucket, Key):
        import io

        return {"Body": io.BytesIO(self.objects[Key])}


def test_s3_read_static_jsonlines():
    objs = {
        "data/a.jsonl": b'{"word": "cat"}\n{"word": "dog"}\n',
        "data/b.jsonl": b'{"word": "emu"}\n',
        "other/skip.jsonl": b'{"word": "no"}\n',
    }
    t = pw.io.s3.read(
        "s3://bucket/data/",
        format="json",
        schema=WordSchema,
        mode="static",
        _client=FakeS3(objs),
    )
    cap, names = _run(t)
    assert _rows(cap, names, "word") == [("cat",), ("dog",), ("emu",)]


def test_s3_read_streaming_upserts(monkeypatch):
    monkeypatch.setenv("PATHWAY_TPU_FS_ONESHOT", "1")
    objs = {"d/a.txt": b"hello\nworld\n"}
    t = pw.io.s3.read(
        "s3://b/d/", format="plaintext", mode="streaming", _client=FakeS3(objs)
    )
    cap, names = _run(t)
    assert _rows(cap, names, "data") == [("hello",), ("world",)]


def test_s3_csv_and_minio():
    objs = {"p/x.csv": b"k,v\nx,1\ny,2\n"}
    t = pw.io.s3_csv.read(
        "s3://b/p/", schema=KV, mode="static", _client=FakeS3(objs)
    )
    cap, names = _run(t)
    assert _rows(cap, names, "k") == [("x",), ("y",)]
    settings = pw.io.minio.MinIOSettings(
        "play.min.io", "bucket", "ak", "sk"
    )
    t2 = pw.io.minio.read(
        "p/", settings, format="csv", schema=KV, mode="static", _client=FakeS3(objs)
    )
    cap2, names2 = _run(t2)
    # csv strings coerce to the schema's int dtype
    assert _rows(cap2, names2, "v") == [(1,), (2,)]


class FakeFS:
    """Minimal PyFilesystem-shaped object."""

    def __init__(self, files: dict[str, bytes]):
        self.files = files

        class Walk:
            def __init__(self, outer):
                self.outer = outer

            def files(self, path):
                return [p for p in sorted(self.outer.files) if p.startswith(path)]

        self.walk = Walk(self)

    def getinfo(self, p, namespaces=None):
        class Info:
            size = len(self.files[p])
            modified = None

        return Info()

    def readbytes(self, p):
        return self.files[p]


def test_pyfilesystem_read():
    src = FakeFS({"/docs/a.txt": b"alpha\nbeta\n"})
    t = pw.io.pyfilesystem.read(src, "/docs", format="plaintext", mode="static")
    cap, names = _run(t)
    assert _rows(cap, names, "data") == [("alpha",), ("beta",)]


class FakeDrive:
    def __init__(self, files: dict[str, bytes]):
        self.files = files

    def list_objects(self):
        return [(k, str(hash(v))) for k, v in sorted(self.files.items())]

    def get_object(self, key):
        return self.files[key]


def test_gdrive_read():
    t = pw.io.gdrive.read(
        "folder-id",
        mode="static",
        format="plaintext",
        _client=FakeDrive({"f1": b"doc one\n", "f2": b"doc two\n"}),
    )
    cap, names = _run(t)
    assert _rows(cap, names, "data") == [("doc one",), ("doc two",)]


# ---------------------------------------------------------------------------
# debezium (fake consumer over change envelopes)
# ---------------------------------------------------------------------------


def _dbz(op, before=None, after=None, key=None):
    value = json.dumps({"payload": {"op": op, "before": before, "after": after}})
    kp = json.dumps({"payload": key}) if key is not None else None
    return (kp, value)


def test_debezium_postgres_inserts_updates_deletes():
    msgs = [
        _dbz("r", after={"k": "x", "v": 1}, key={"k": "x"}),
        _dbz("c", after={"k": "y", "v": 2}, key={"k": "y"}),
        _dbz("u", before={"k": "x", "v": 1}, after={"k": "x", "v": 7}, key={"k": "x"}),
        _dbz("d", before={"k": "y", "v": 2}, key={"k": "y"}),
    ]
    t = pw.io.debezium.read({}, "cdc", schema=KV, _consumer=iter(msgs))
    cap, names = _run(t)
    assert _rows(cap, names, "k", "v") == [("x", 7)]


def test_debezium_mongodb_upserts():
    msgs = [
        _dbz("r", after=json.dumps({"k": "x", "v": 1}), key={"id": "1"}),
        _dbz("u", after=json.dumps({"k": "x", "v": 9}), key={"id": "1"}),
        _dbz("r", after=json.dumps({"k": "z", "v": 3}), key={"id": "2"}),
        _dbz("d", key={"id": "2"}),
    ]
    t = pw.io.debezium.read(
        {}, "cdc", schema=KV, db_type="mongodb", _consumer=iter(msgs)
    )
    cap, names = _run(t)
    assert _rows(cap, names, "k", "v") == [("x", 9)]


def test_debezium_tombstone_ignored():
    p = DebeziumMessageParser()
    assert p.parse(None, None) == []
    assert p.parse(None, "null") == []


# ---------------------------------------------------------------------------
# nats (fake subscription / publisher)
# ---------------------------------------------------------------------------


def test_nats_read_and_write():
    payloads = [json.dumps({"k": w, "v": i}).encode() for i, w in enumerate("pq")]
    t = pw.io.nats.read("nats://x", "subj", schema=KV, _subscription=iter(payloads))
    cap, names = _run(t)
    assert _rows(cap, names, "k") == [("p",), ("q",)]

    class FakePub:
        def __init__(self):
            self.published = []

        def publish(self, subject, payload):
            self.published.append((subject, payload))

    pub = FakePub()
    t2 = pw.debug.table_from_rows(schema=KV, rows=[("a", 1)])
    pw.io.nats.write(t2, "nats://x", "out", _publisher=pub)
    _run_with_outputs()
    assert len(pub.published) == 1
    subj, payload = pub.published[0]
    assert subj == "out" and json.loads(payload)["k"] == "a"


# ---------------------------------------------------------------------------
# elasticsearch / mongodb / bigquery / pubsub / logstash / slack (fake sinks)
# ---------------------------------------------------------------------------


def test_elasticsearch_write():
    class FakeES:
        def __init__(self):
            self.docs = []

        def index(self, index, document):
            self.docs.append((index, document))

    es = FakeES()
    t = pw.debug.table_from_rows(schema=KV, rows=[("x", 1), ("y", 2)])
    pw.io.elasticsearch.write(t, "http://localhost", None, "idx", _client=es)
    _run_with_outputs()
    assert sorted(d["k"] for _i, d in es.docs) == ["x", "y"]
    assert all(i == "idx" and d["diff"] == 1 for i, d in es.docs)


def test_mongodb_write():
    class FakeColl:
        def __init__(self):
            self.docs = []

        def insert_many(self, docs):
            self.docs.extend(docs)

    coll = FakeColl()
    t = pw.debug.table_from_rows(schema=KV, rows=[("x", 1)])
    pw.io.mongodb.write(t, _collection=coll)
    _run_with_outputs()
    assert coll.docs[0]["k"] == "x" and coll.docs[0]["diff"] == 1


def test_bigquery_write():
    class FakeBQ:
        def __init__(self):
            self.rows = []

        def insert_rows_json(self, target, rows):
            self.rows.append((target, list(rows)))
            return []

    bq = FakeBQ()
    t = pw.debug.table_from_rows(schema=KV, rows=[("x", 1), ("y", 2)])
    pw.io.bigquery.write(t, "ds", "tbl", _client=bq)
    _run_with_outputs()
    assert bq.rows and bq.rows[0][0] == "ds.tbl"
    assert sorted(r["k"] for _t, rs in bq.rows for r in rs) == ["x", "y"]


def test_pubsub_write():
    class FakePublisher:
        def __init__(self):
            self.msgs = []

        def topic_path(self, project, topic):
            return f"projects/{project}/topics/{topic}"

        def publish(self, topic, data, **attrs):
            self.msgs.append((topic, data, attrs))

    pub = FakePublisher()
    t = pw.debug.table_from_rows(schema=KV, rows=[("x", 1)])
    pw.io.pubsub.write(t, project_id="p", topic_id="t", _publisher=pub)
    _run_with_outputs()
    topic, data, attrs = pub.msgs[0]
    assert topic == "projects/p/topics/t"
    assert json.loads(data)["k"] == "x" and attrs["pathway_diff"] == "1"


def test_logstash_write_with_retries():
    calls = []

    def post(endpoint, payload):
        calls.append((endpoint, payload))
        if len(calls) == 1:
            raise ConnectionError("transient")

    t = pw.debug.table_from_rows(schema=KV, rows=[("x", 1)])
    pw.io.logstash.write(t, "http://ls:8080", n_retries=2, _post=post)
    _run_with_outputs()
    assert len(calls) == 2  # first failed, retry succeeded
    assert json.loads(calls[-1][1])["k"] == "x"


def test_slack_send_alerts():
    posts = []
    t = pw.debug.table_from_rows(schema=KV, rows=[("alert!", 1)])
    pw.io.slack.send_alerts(
        t.k, "C123", "xoxb-token", _post=lambda url, payload, tok: posts.append(payload)
    )
    _run_with_outputs()
    assert posts == [{"channel": "C123", "text": "alert!"}]


# ---------------------------------------------------------------------------
# deltalake (fake table handle / writer)
# ---------------------------------------------------------------------------


def test_deltalake_read_versions(monkeypatch):
    monkeypatch.setenv("PATHWAY_TPU_FS_ONESHOT", "1")

    class FakeDelta:
        def __init__(self):
            self.rows = [{"k": "x", "v": 1}, {"k": "y", "v": 2}]

        def version(self):
            return 3

        def to_pylist(self):
            return list(self.rows)

    t = pw.io.deltalake.read("s3://lake/tbl", schema=KV, _table=FakeDelta())
    cap, names = _run(t)
    assert _rows(cap, names, "k", "v") == [("x", 1), ("y", 2)]


def test_deltalake_write_batches():
    written = []
    t = pw.debug.table_from_rows(schema=KV, rows=[("x", 1), ("y", 2)])
    pw.io.deltalake.write(t, "/lake/tbl", _writer=written.append)
    _run_with_outputs()
    rows = [r for batch in written for r in batch]
    assert sorted(r["k"] for r in rows) == ["x", "y"]
    assert all(r["diff"] == 1 for r in rows)


# ---------------------------------------------------------------------------
# formatter/parser units (reference tests/integration/test_dsv.rs etc.)
# ---------------------------------------------------------------------------


def test_dsv_parser_and_formatter():
    p = DsvParser(separator=";")
    assert p.parse("a;b") == []  # header
    assert p.parse("1;2") == [("insert", {"a": "1", "b": "2"})]
    f = DsvFormatter(["a", "b"])
    assert f.header() == "a,b,time,diff"
    assert f.format({"a": 1, "b": "x"}, 4, -1) == "1,x,4,-1"


def test_jsonlines_parser_field_selection():
    p = JsonLinesParser(field_names=["a"])
    assert p.parse('{"a": 1, "b": 2}') == [("insert", {"a": 1})]
    with pytest.raises(ValueError):
        p.parse("[1, 2]")


def test_psql_formatters():
    f = PsqlUpdatesFormatter("t", ["a", "b"])
    sql, params = f.format({"a": 1, "b": 2}, 10, 1)
    assert sql == "INSERT INTO t (a,b,time,diff) VALUES (%s,%s,10,1)"
    assert params == (1, 2)
    s = PsqlSnapshotFormatter("t", ["a"], ["a", "b"])
    sql, params = s.format({"a": 1, "b": 2}, 10, 1)
    assert "ON CONFLICT (a) DO UPDATE SET" in sql and "t.time<=10" in sql
    sql, params = s.format({"a": 1, "b": 2}, 11, -1)
    assert sql.startswith("DELETE FROM t WHERE a=%s") and params == (1,)
    with pytest.raises(ValueError):
        PsqlSnapshotFormatter("t", ["missing"], ["a"])


def test_bson_formatter():
    f = BsonFormatter(["a"])
    assert f.format({"a": (1, 2)}, 3, 1) == {"a": [1, 2], "time": 3, "diff": 1}


def test_kafka_formats_and_json_pointers():
    """Reference kafka surface (kafka/__init__.py:27): plaintext format,
    json_field_paths as RFC 6901 pointers, and message-key upserts."""
    import pathway_tpu as pw
    from tests.utils import run_table

    msgs = [
        (None, json.dumps({"pet": {"name": "rex", "ratings": [9, 7]}}).encode()),
        (None, json.dumps({"pet": {"name": "ada", "ratings": [10]}}).encode()),
    ]

    class S(pw.Schema):
        name: str
        rating: int

    t = pw.io.kafka.read(
        {}, "pets", schema=S, format="json",
        json_field_paths={"name": "/pet/name", "rating": "/pet/ratings/0"},
        _consumer=msgs,
    )
    rows = sorted(run_table(t).values())
    assert rows == [("ada", 10), ("rex", 9)]
    pw.clear_graph()

    # plaintext + message keys: same key upserts (replaces), not appends
    msgs2 = [
        (b"k1", b"first"),
        (b"k2", b"other"),
        (b"k1", b"second"),
    ]
    t2 = pw.io.kafka.read({}, "t", format="plaintext", _consumer=msgs2)
    rows2 = sorted(v[0] for v in run_table(t2).values())
    assert rows2 == ["other", "second"]
    pw.clear_graph()

    # autogenerate_key: all three rows retained
    t3 = pw.io.kafka.read(
        {}, "t", format="plaintext", autogenerate_key=True, _consumer=msgs2
    )
    assert len(run_table(t3)) == 3
    pw.clear_graph()


def test_kafka_metadata_topics_and_timestamp_filter():
    import pathway_tpu as pw
    from tests.utils import run_table

    msgs = [
        {"key": b"a", "value": b"x", "topic": "keep", "partition": 2,
         "offset": 5, "timestamp_ms": 1000},
        {"key": b"b", "value": b"y", "topic": "drop", "timestamp_ms": 2000},
        {"key": b"c", "value": b"z", "topic": "keep", "timestamp_ms": 500},
    ]
    t = pw.io.kafka.read(
        {}, ["keep"], format="plaintext", with_metadata=True,
        start_from_timestamp_ms=900, _consumer=msgs,
    )
    rows = list(run_table(t).values())
    # topic filter drops "drop"; timestamp filter drops the 500ms one
    assert len(rows) == 1
    data, meta = rows[0]
    assert data == "x"
    assert meta.value["topic"] == "keep" and meta.value["partition"] == 2
    assert meta.value["offset"] == 5 and meta.value["timestamp_millis"] == 1000
    pw.clear_graph()


def test_kafka_read_from_upstash_builds_sasl_settings():
    import pathway_tpu as pw
    from tests.utils import run_table

    t = pw.io.kafka.read_from_upstash(
        "ep:9092", "user", "pass", "topic",
        format="plaintext", autogenerate_key=True,
        _consumer=[(None, b"hello")],
    )
    assert [v[0] for v in run_table(t).values()] == ["hello"]
