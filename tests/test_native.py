"""C++ native runtime tests: blob store, consolidation kernel parity,
snapshot log durability (incl. torn-tail crash tolerance), shard routing.

Mirrors the role of the reference's Rust integration tests
(/root/reference/tests/integration/test_file_kv.rs, test_stream_snapshot.rs)."""

import os
import struct

import numpy as np
import pytest

from pathway_tpu import native
from pathway_tpu.engine.value import hash_int_array, ref_scalar, shard_of

pytestmark = pytest.mark.skipif(not native.is_available(), reason="native lib not built")


def test_store_basic():
    s = native.NativeStore()
    assert len(s) == 0
    s[1] = ("a", 1.5, None)
    s[2**63 + 5] = {"nested": [1, 2]}
    assert len(s) == 2
    assert s[1] == ("a", 1.5, None)
    assert s.get(999) is None
    assert 1 in s and 999 not in s
    s[1] = ("b",)  # overwrite
    assert s[1] == ("b",)
    assert len(s) == 2
    assert s.pop(1) == ("b",)
    assert s.pop(1, "dflt") == "dflt"
    assert len(s) == 1
    items = dict(s.items())
    assert items == {2**63 + 5: {"nested": [1, 2]}}
    s.clear()
    assert len(s) == 0


def test_consolidate_parity():
    from pathway_tpu.engine.dataflow import consolidate

    updates = []
    rng = np.random.default_rng(0)
    for i in range(500):
        key = int(rng.integers(0, 50))
        row = (int(rng.integers(0, 5)), "v")
        updates.append((key, row, int(rng.choice([-1, 1]))))
    native_out = native.consolidate_native(updates)
    # python reference path (below the native threshold we call it directly)
    by = {}
    for k, r, d in updates:
        by[(k, r)] = by.get((k, r), 0) + d
    expect = {kr: d for kr, d in by.items() if d != 0}
    got = {}
    for k, r, d in native_out:
        got[(k, r)] = got.get((k, r), 0) + d
    assert got == expect
    # and the engine's consolidate() (which routes through native for >=64)
    engine_out = consolidate(updates)
    got2 = {}
    for k, r, d in engine_out:
        got2[(k, r)] = got2.get((k, r), 0) + d
    assert got2 == expect


def test_consolidate_numeric_tower():
    # 1.0 and 1 are equal values → must cancel (canonical serialization)
    out = native.consolidate_native([(7, (1.0,), 1), (7, (1,), -1)])
    assert out == []


def test_consolidate_path_parity_bool_nan():
    """Python and native paths must group identically (bool != int,
    NaN == NaN, NaN payloads canonicalized)."""
    from pathway_tpu.engine.dataflow import consolidate

    nan1 = float("nan")
    nan2 = np.float64("nan") * -1.0  # different payload sign bit
    cases = [
        [(1, (True,), 1), (1, (1,), -1)],  # bool vs int: distinct, no cancel
        [(2, (nan1,), 1), (2, (float(nan2),), -1)],  # NaNs cancel
    ]
    for updates in cases:
        small = consolidate(list(updates))
        big = consolidate(list(updates) + [(100 + i, ("pad",), 1) for i in range(70)])
        big_wo_pad = [u for u in big if u[0] < 100]
        assert small == big_wo_pad, f"batch-size-dependent result for {updates}"
    assert consolidate([(1, (True,), 1), (1, (1,), -1)]) == [(1, (1,), -1), (1, (True,), 1)]
    assert consolidate([(2, (nan1,), 1), (2, (float(nan2),), -1)]) == []


def test_consolidate_fallback_on_opaque_objects():
    """Rows with arbitrary objects (inexact serialization) must take the
    python path honoring __eq__."""

    class Obj:
        def __eq__(self, other):
            return isinstance(other, Obj)

        def __hash__(self):
            return 42

    assert native.consolidate_native([(1, (Obj(),), 1), (1, (Obj(),), -1)]) is None
    from pathway_tpu.engine.dataflow import consolidate

    ups = [(1, (Obj(),), 1), (1, (Obj(),), -1)] + [(100 + i, (Obj(),), 1) for i in range(70)]
    out = consolidate(ups)
    assert all(k >= 100 for k, _, _ in out) and len(out) == 70


def test_consolidate_retract_before_insert():
    out = native.consolidate_native([(5, ("new",), 1), (5, ("old",), -1)])
    assert out == [(5, ("old",), -1), (5, ("new",), 1)]


def test_log_roundtrip(tmp_path):
    p = str(tmp_path / "snap.log")
    w = native.SnapshotLogWriter(p, append=False)
    w.append_obj(1, 10, 111, {"offset": 5})
    w.append_obj(2, 11, 222, ("row", 3.5))
    w.flush()
    w.close()
    # append mode continues an existing log
    w = native.SnapshotLogWriter(p, append=True)
    w.append_obj(1, 12, 333, "third")
    w.close()
    r = native.SnapshotLogReader(p)
    recs = list(r.iter_objects())
    assert recs == [(1, 10, 111, {"offset": 5}), (2, 11, 222, ("row", 3.5)), (1, 12, 333, "third")]


def test_log_torn_tail_tolerated(tmp_path):
    p = str(tmp_path / "torn.log")
    w = native.SnapshotLogWriter(p, append=False)
    w.append_obj(1, 1, 1, "good")
    w.append_obj(1, 2, 2, "also good")
    w.close()
    # simulate crash mid-append: truncate the file inside the last record
    size = os.path.getsize(p)
    with open(p, "r+b") as f:
        f.truncate(size - 3)
    r = native.SnapshotLogReader(p)
    assert [obj for _, _, _, obj in r.iter_objects()] == ["good"]
    r.close()
    # append after a torn tail must truncate it so post-crash records are
    # reachable (crash-recovery path)
    w = native.SnapshotLogWriter(p, append=True)
    w.append_obj(1, 3, 3, "post-crash")
    w.close()
    r = native.SnapshotLogReader(p)
    assert [obj for _, _, _, obj in r.iter_objects()] == ["good", "post-crash"]


def test_store_snapshot_load(tmp_path):
    p = str(tmp_path / "state.log")
    s = native.NativeStore()
    for i in range(100):
        s[i] = (i, f"row{i}")
    w = native.SnapshotLogWriter(p, append=False)
    n = s.snapshot_to(w, kind=7, time=42)
    assert n == 100
    w.close()
    s2 = native.NativeStore()
    r = native.SnapshotLogReader(p)
    assert s2.load_from(r, kind=7) == 100
    assert dict(s2.items()) == dict(s.items())


def test_hash_batch_matches_python():
    lib = native.NATIVE
    import ctypes

    vals = np.arange(1000, dtype=np.uint64)
    out = np.zeros(1000, dtype=np.uint64)
    lib.pn_hash64_batch(
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        1000,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
    )
    np.testing.assert_array_equal(out, hash_int_array(vals))


def test_shard_batch_matches_python():
    lib = native.NATIVE
    import ctypes
    from pathway_tpu.engine.value import SHARD_MASK

    keys = np.array([int(ref_scalar(i)) for i in range(200)], dtype=np.uint64)
    out = np.zeros(200, dtype=np.uint32)
    lib.pn_shard_batch(
        keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        200,
        SHARD_MASK,
        8,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
    )
    expect = np.array([shard_of(int(k), 8) for k in keys], dtype=np.uint32)
    np.testing.assert_array_equal(out, expect)
