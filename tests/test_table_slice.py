"""Table.slice / TableSlice and the table-API stragglers.

Mirrors the reference semantics of
/root/reference/python/pathway/internals/table_slice.py:16-153 and
table.py with_prefix:1850 / with_suffix:1872 / update_id_type:2003 /
remove_errors:2491 / live:2565.
"""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.value import Error
from pathway_tpu.internals.graph_runner import GraphRunner
from pathway_tpu.internals.table_slice import TableSlice

from .utils import T, assert_table_equality_wo_index


def _pets():
    return T(
        """
          | age | owner | pet
        1 | 10  | Alice | dog
        2 | 9   | Bob   | dog
        3 | 8   | Alice | cat
        """
    )


def test_slice_keys_and_iter():
    t = _pets()
    s = t.slice
    assert isinstance(s, TableSlice)
    assert list(s.keys()) == ["age", "owner", "pet"]
    refs = list(s)
    assert [r._name for r in refs] == ["age", "owner", "pet"]
    assert all(r._table is t for r in refs)


def test_slice_getitem_str_and_ref_and_list():
    t = _pets()
    s = t.slice
    assert s["age"]._name == "age"
    assert s[t.age]._name == "age"
    assert s[pw.this.age]._name == "age"
    sub = s[["age", "pet"]]
    assert isinstance(sub, TableSlice)
    assert list(sub.keys()) == ["age", "pet"]


def test_slice_getattr_rejects_method_names():
    s = _pets().slice
    assert s.age._name == "age"
    with pytest.raises(ValueError, match="method name"):
        s.select
    with pytest.raises(AttributeError, match="not found"):
        s.nonexistent


def test_slice_without_and_rename():
    s = _pets().slice
    assert list(s.without("age").keys()) == ["owner", "pet"]
    assert list(s.without(pw.this.age, "pet").keys()) == ["owner"]
    with pytest.raises(KeyError):
        s.without("missing")
    renamed = s.rename({"age": "years"})
    assert list(renamed.keys()) == ["owner", "pet", "years"]
    assert renamed["years"]._name == "age"  # still refers to source column


def test_slice_prefix_suffix():
    s = _pets().slice
    assert list(s.with_prefix("u_").keys()) == ["u_age", "u_owner", "u_pet"]
    assert list(s.with_suffix("_c").keys()) == ["age_c", "owner_c", "pet_c"]
    # chained, as in the reference docstring
    assert list(s.without("age").with_suffix("_col").keys()) == [
        "owner_col",
        "pet_col",
    ]


def test_slice_rejects_foreign_table_refs():
    t1, t2 = _pets(), _pets()
    with pytest.raises(ValueError, match="of which the slice was created"):
        t1.slice.without(t2.age)
    with pytest.raises(ValueError, match="column reference"):
        t1.slice.without(pw.left.age)


def test_slice_splat_into_select():
    t = _pets()
    r = t.select(*t.slice.without("age"))
    assert_table_equality_wo_index(
        r,
        T(
            """
              | owner | pet
            1 | Alice | dog
            2 | Bob   | dog
            3 | Alice | cat
            """
        ),
    )


def test_slice_of_slice_property():
    s = _pets().slice
    assert s.slice is s


def test_table_with_prefix_suffix():
    t = _pets()
    assert_table_equality_wo_index(
        t.with_prefix("u_"),
        T(
            """
              | u_age | u_owner | u_pet
            1 | 10    | Alice   | dog
            2 | 9     | Bob     | dog
            3 | 8     | Alice   | cat
            """
        ),
    )
    assert t.with_suffix("_x").column_names() == ["age_x", "owner_x", "pet_x"]


def test_update_id_type():
    t = _pets()
    out = t.update_id_type(pw.Pointer)
    assert out.column_names() == t.column_names()
    with pytest.raises(TypeError):
        t.update_id_type(int)


def test_remove_errors():
    t = T(
        """
          | a  | b
        1 | 10 | 2
        2 | 7  | 0
        3 | 9  | 3
        """
    )
    res = t.select(
        a=pw.this.a, q=pw.apply(lambda a, b: a // b, pw.this.a, pw.this.b)
    )
    cleaned = res.remove_errors()
    runner = GraphRunner()
    runner.engine.terminate_on_error = False
    cap, names = runner.capture(cleaned)
    runner.run()
    rows = sorted(cap.state.values())
    assert rows == [(9, 3), (10, 5)]
    assert not any(any(v is Error for v in row) for row in rows)
