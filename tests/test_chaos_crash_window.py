"""Deterministic fault-injection over the FEED→ADVANCE crash window.

The exactly-once contract of the runtime lives in one ordering
(parallel/multiprocess.py): workers append the epoch's batch plus a
KIND_FEED offsets record durably BEFORE replying to the coordinator;
process 0 flushes its sinks, writes a durable ``__delivered__`` marker,
and only then broadcasts ADVANCE. These tests use the chaos harness
(pathway_tpu.resilience.chaos) to kill the cluster at every scripted
position inside that window and assert that recovery neither loses nor
double-counts an epoch.

Delivery granularity at a non-transactional file sink: crashes at any
site up to the sink flush, and after the delivered marker, recover to
byte-identical output. The one remaining window — after the sink wrote
the epoch but before the delivered marker — re-delivers that single
epoch on restart (at-least-once there, idempotent in net state); a
transactional sink protocol would be needed to close it.

All tests here are ``slow`` + ``chaos`` (see pytest.ini); run them with
``pytest -m chaos``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.resilience import Recovery, RetryPolicy, chaos

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORDS = ["cat", "dog", "bird", "cat", "dog", "cat", "emu", "dog"]
FINAL = {"cat": 3, "dog": 3, "bird": 1, "emu": 1}


# ---------------------------------------------------------------------------
# in-process supervised recovery: byte-identical output
# ---------------------------------------------------------------------------


def _build_wordcount(out: str, store: str, pause: float = 0.06):
    """One epoch per input row (per-row commit + slow stream + fast
    autocommit): clean runs are deterministic, so crash/recovery runs
    can be compared byte-for-byte against an uninterrupted one."""
    from pathway_tpu.io._connector import input_table_from_reader

    class S(pw.Schema):
        word: str

    def reader(ctx):
        start = int(ctx.offsets.get("pos", 0))
        for i, w in enumerate(WORDS):
            if i < start:
                continue
            ctx.insert({"word": w}, offsets={"pos": i + 1})
            ctx.commit()
            time.sleep(pause)

    t = input_table_from_reader(
        S,
        reader,
        name="wsrc",
        persistent_id="w",
        supports_offsets=True,
        autocommit_duration_ms=10,
    )
    c = t.groupby(pw.this.word).reduce(pw.this.word, n=pw.reducers.count())
    pw.io.jsonlines.write(c, out)
    return pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(store)
    )


def _clean_reference(tmp_path) -> str:
    cfg = _build_wordcount(str(tmp_path / "ref.jsonl"), str(tmp_path / "ref_store"))
    pw.run(monitoring_level="none", persistence_config=cfg)
    pw.clear_graph()
    with open(tmp_path / "ref.jsonl") as f:
        return f.read()


@pytest.mark.parametrize(
    "rule",
    [
        # mid-epoch, while the batch's KIND_DATA records are being
        # appended (no KIND_FEED yet → recovery trims and re-reads)
        {"site": "persistence.append_data", "hit": 5, "action": "raise"},
        # epoch fed + delivered + marked, crash before the offset
        # cursor advances (recovery promotes via the delivered marker)
        {"site": "persistence.before_advance", "time": 3, "action": "raise"},
    ],
    ids=lambda r: r["site"],
)
def test_supervised_recovery_byte_identical(tmp_path, rule):
    """pw.run(recovery=...) restarts through a scripted mid-epoch crash
    and the sink output is byte-identical to an uninterrupted run."""
    ref = _clean_reference(tmp_path)
    assert ref, "clean reference run produced no output"

    out = str(tmp_path / "chaos.jsonl")
    cfg = _build_wordcount(out, str(tmp_path / "chaos_store"))
    chaos.activate([dict(rule)])
    try:
        pw.run(
            monitoring_level="none",
            persistence_config=cfg,
            recovery=Recovery(
                max_restarts=3,
                backoff=RetryPolicy(
                    first_delay_ms=1, jitter_ms=0, sleep=lambda s: None
                ),
            ),
        )
    finally:
        chaos.deactivate()
        pw.clear_graph()
    with open(out) as f:
        assert f.read() == ref


def test_post_flush_pre_marker_window_is_idempotent(tmp_path):
    """The one at-least-once window: crash after the sink flushed the
    epoch but before the delivered marker. The restart re-delivers that
    single epoch (documented), and the re-delivery is idempotent — net
    state equals the clean run's, nothing lost."""
    ref = _clean_reference(tmp_path)

    out = str(tmp_path / "chaos.jsonl")
    cfg = _build_wordcount(out, str(tmp_path / "chaos_store"))
    chaos.activate([{"site": "engine.after_sink_flush", "time": 4, "action": "raise"}])
    try:
        pw.run(
            monitoring_level="none",
            persistence_config=cfg,
            recovery=Recovery(
                max_restarts=3,
                backoff=RetryPolicy(
                    first_delay_ms=1, jitter_ms=0, sleep=lambda s: None
                ),
            ),
        )
    finally:
        chaos.deactivate()
        pw.clear_graph()

    def net(text: str) -> dict[str, int]:
        state: dict[str, int] = {}
        for line in text.splitlines():
            rec = json.loads(line)
            if rec["diff"] > 0:
                state[rec["word"]] = rec["n"]
            else:
                state.pop(rec["word"], None)
        return state

    with open(out) as f:
        got = f.read()
    assert net(got) == net(ref) == FINAL
    # and nothing was lost: every reference line is present
    assert set(ref.splitlines()) <= set(got.splitlines())


# ---------------------------------------------------------------------------
# subprocess SIGKILL mid-epoch (acceptance scenario)
# ---------------------------------------------------------------------------

KILL_PROGRAM = textwrap.dedent(
    """
    import os, time
    import pathway_tpu as pw
    from pathway_tpu.io._connector import input_table_from_reader

    WORDS = ["cat", "dog", "bird", "cat", "dog", "cat", "emu", "dog"]

    class S(pw.Schema):
        word: str

    def reader(ctx):
        start = int(ctx.offsets.get("pos", 0))
        for i, w in enumerate(WORDS):
            if i < start:
                continue
            ctx.insert({"word": w}, offsets={"pos": i + 1})
            ctx.commit()
            time.sleep(0.06)

    t = input_table_from_reader(
        S, reader, name="wsrc", persistent_id="w",
        supports_offsets=True, autocommit_duration_ms=10,
    )
    c = t.groupby(pw.this.word).reduce(pw.this.word, n=pw.reducers.count())
    pw.io.jsonlines.write(c, os.environ["KP_OUT"])
    pw.run(
        monitoring_level="none",
        persistence_config=pw.persistence.Config.simple_config(
            pw.persistence.Backend.filesystem(os.environ["KP_STORE"])
        ),
        recovery=True,
    )
    """
)


def _spawn(tmp_path, out: str, chaos_spec: str | None):
    env = dict(os.environ)
    env.pop("PATHWAY_CHAOS", None)
    env.update(
        KP_OUT=out,
        KP_STORE=str(tmp_path / "store"),
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    if chaos_spec is not None:
        env["PATHWAY_CHAOS"] = chaos_spec
    prog = tmp_path / "kp.py"
    prog.write_text(KILL_PROGRAM)
    return subprocess.Popen(
        [sys.executable, str(prog)],
        env=env,
        cwd=str(tmp_path),
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        text=True,
    )


def test_sigkill_mid_epoch_byte_identical(tmp_path):
    """Scripted chaos SIGKILLs the run mid-epoch (while KIND_DATA
    records of an open epoch are being appended, before the sink saw
    it); a respawn with the same persistence store resumes from the
    snapshot and the combined sink output is byte-identical to an
    uninterrupted run."""
    ref = _clean_reference(tmp_path)

    out1 = str(tmp_path / "k1.jsonl")
    p1 = _spawn(
        tmp_path,
        out1,
        json.dumps({"site": "persistence.append_data", "hit": 5, "action": "kill"}),
    )
    try:
        p1.wait(timeout=60)
    finally:
        if p1.poll() is None:
            p1.kill()
    assert p1.returncode == -signal.SIGKILL, p1.returncode

    out2 = str(tmp_path / "k2.jsonl")
    p2 = _spawn(tmp_path, out2, None)
    try:
        _, err = p2.communicate(timeout=120)
        assert p2.returncode == 0, err[-3000:]
    finally:
        if p2.poll() is None:
            p2.kill()

    with open(out1) as f:
        part1 = f.read()
    with open(out2) as f:
        part2 = f.read()
    # run 1 ends exactly at the last delivered epoch boundary; run 2
    # suppresses re-delivery of recovered epochs and emits the rest
    assert part1 + part2 == ref
    assert part1, "crash landed before any epoch was delivered"


# ---------------------------------------------------------------------------
# multiprocess cluster: kill at every position in the FEED→ADVANCE window
# ---------------------------------------------------------------------------

MP_PROGRAM = textwrap.dedent(
    """
    import os, time
    import pathway_tpu as pw
    from pathway_tpu.io._connector import input_table_from_reader

    N = int(os.environ["MC_N"])
    PID = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    NPROC = int(os.environ.get("PATHWAY_PROCESSES", "1"))
    WORDS = ["cat", "dog", "bird"]

    class S(pw.Schema):
        word: str

    def reader(ctx):
        start = int(ctx.offsets.get("pos", 0))
        for i in range(N):
            if i % NPROC != ctx.process_id:
                continue
            if i < start:
                continue
            ctx.insert({"word": WORDS[i % 3]}, offsets={"pos": i + 1})
            ctx.commit()
            time.sleep(0.01)

    t = input_table_from_reader(
        S, reader, name="slow_src", parallel_readers=True,
        persistent_id="mc", supports_offsets=True,
        autocommit_duration_ms=50,
    )
    c = t.groupby(pw.this.word).reduce(pw.this.word, n=pw.reducers.count())
    pw.io.jsonlines.write(c, os.environ["MC_OUT"] + "." + str(PID))
    pw.run(
        monitoring_level="none",
        persistence_config=pw.persistence.Config.simple_config(
            pw.persistence.Backend.filesystem(os.environ["MC_STORE"]),
            snapshot_interval_ms=200,
        ),
    )
    """
)


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_cluster(
    tmp_path,
    out: str,
    chaos_spec: str | None,
    n: int,
    extra_env: dict[str, str] | None = None,
):
    prog = tmp_path / "mc.py"
    prog.write_text(MP_PROGRAM)
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PATHWAY_CHAOS", None)
        env.update(
            MC_N=str(n),
            MC_OUT=out,
            MC_STORE=str(tmp_path / "store"),
            JAX_PLATFORMS="cpu",
            PATHWAY_THREADS="1",
            PATHWAY_PROCESSES="2",
            PATHWAY_PROCESS_ID=str(pid),
            PATHWAY_FIRST_PORT=str(port),
            PATHWAY_CLUSTER_TOKEN="chaos-test",
            PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
        )
        env.update(extra_env or {})
        if chaos_spec is not None:
            env["PATHWAY_CHAOS"] = chaos_spec
        procs.append(
            subprocess.Popen(
                [sys.executable, str(prog)],
                env=env,
                cwd=str(tmp_path),
                stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    return procs


def _net(path, state=None, lenient_first_touch=False):
    """Exactly-once oracle: strict retract/insert pairing, except that
    across a crash boundary each word's first event may catch the
    stream up to the restarted engine's state."""
    state = dict(state or {})
    synced: set = set()
    if not os.path.exists(path):
        return state
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            w, cnt, diff = rec["word"], rec["n"], rec["diff"]
            if diff > 0:
                state[w] = cnt
            else:
                if not lenient_first_touch or w in synced:
                    assert state.get(w) == cnt, f"retract mismatch {rec}"
                state.pop(w, None)
            synced.add(w)
    return state


# every scripted position in the FEED→ADVANCE window, with the kill
# scoped to the process that executes the site (workers feed + advance,
# process 0 flushes sinks and writes the delivered marker)
WINDOW_SITES = [
    ("worker.after_feed_log", 1),
    ("coordinator.after_sink_flush", 0),
    ("coordinator.after_mark_delivered", 0),
    ("worker.before_advance", 1),
    ("worker.after_advance", 1),
]


@pytest.mark.parametrize("site,process", WINDOW_SITES, ids=[s for s, _ in WINDOW_SITES])
def test_cluster_killed_at_every_window_position(tmp_path, site, process):
    """SIGKILL the cluster at a scripted position between a worker's
    KIND_FEED append and its ADVANCE; the respawned cluster must
    converge to the exact final counts — no epoch lost, none
    double-counted."""
    n = 120
    spec = json.dumps(
        {"site": site, "process": process, "hit": 3, "action": "kill"}
    )
    out1 = str(tmp_path / "out1.jsonl")
    procs = _spawn_cluster(tmp_path, out1, spec, n)
    try:
        # the chaos rule SIGKILLs its process on the 3rd visit to the
        # site; the peer then loses the cluster — reap everything
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if any(p.poll() is not None for p in procs):
                break
            time.sleep(0.1)
        assert any(
            p.poll() is not None for p in procs
        ), f"chaos rule for {site} never fired"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait(timeout=10)
    killed = [p.returncode for p in procs if p.returncode == -signal.SIGKILL]
    assert killed, [p.returncode for p in procs]

    out2 = str(tmp_path / "out2.jsonl")
    procs = _spawn_cluster(tmp_path, out2, None, n)
    try:
        for p in procs:
            _, err = p.communicate(timeout=120)
            assert p.returncode == 0, err[-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    state = _net(out1 + ".0")
    final = _net(out2 + ".0", state, lenient_first_touch=True)
    assert final == {"cat": 40, "dog": 40, "bird": 40}, (site, final)

# ---------------------------------------------------------------------------
# cluster fault domain: partial restart — only the dead worker respawns
# ---------------------------------------------------------------------------

# the worker-side positions of the FEED→ADVANCE window; the coordinator
# sites stay in WINDOW_SITES (killing process 0 kills the fault domain
# itself — that is the supervisor's job, not a partial restart)
PARTIAL_RESTART_SITES = [
    "worker.after_feed_log",
    "worker.before_advance",
    "worker.after_advance",
]


@pytest.mark.parametrize("site", PARTIAL_RESTART_SITES)
def test_partial_restart_respawns_only_dead_worker(tmp_path, site):
    """SIGKILL worker 1 at a scripted window position with the cluster
    fault domain armed (lease + respawn): the coordinator must detect
    the death, respawn ONLY worker 1 (fenced by the bumped generation —
    the `generation: 0` guard keeps the chaos rule from re-killing the
    replacement), and finish the run in its ORIGINAL process with exact
    final counts — no row lost, none double-counted in net state. (The
    survivor's sink file crosses the regroup boundary mid-file: the
    epoch in flight when the regroup unwinds the engine may have its
    sink flush dropped, so the rebuilt engine's first retract per word
    can reference a count the file never recorded — the same
    at-least-once window the cross-file matrix above documents.)"""
    n = 120
    spec = json.dumps(
        {
            "site": site,
            "process": 1,
            "generation": 0,
            "hit": 3,
            "action": "kill",
        }
    )
    out = str(tmp_path / "out.jsonl")
    flight_dir = str(tmp_path / "blackbox")
    procs = _spawn_cluster(
        tmp_path,
        out,
        spec,
        n,
        extra_env={
            "PATHWAY_CLUSTER_LEASE_MS": "1500",
            "PATHWAY_CLUSTER_RESPAWN": "1",
            "PATHWAY_FLIGHT_RECORDER_DIR": flight_dir,
        },
    )
    p0, p1 = procs
    try:
        _, err0 = p0.communicate(timeout=180)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        p1.wait(timeout=10)

    # the original worker 1 was SIGKILLed by the chaos rule...
    assert p1.returncode == -signal.SIGKILL, (p1.returncode, err0[-3000:])
    # ...and the coordinator finished the run in its one original
    # process: a partial restart, not a supervisor (full) restart
    assert p0.returncode == 0, err0[-3000:]
    assert "cluster partial restart" in err0

    # exact final counts by net accounting (retract pops, insert sets):
    # the regroup may drop the in-flight epoch's flush, so strict
    # retract/insert pairing cannot hold across the boundary, but the
    # net state must land exactly on the clean-run counts
    state: dict = {}
    with open(out + ".0") as f:
        for line in f:
            rec = json.loads(line)
            if rec["diff"] > 0:
                state[rec["word"]] = rec["n"]
            else:
                state.pop(rec["word"], None)
    assert state == {"cat": 40, "dog": 40, "bird": 40}

    # the black box kept the evidence: a cluster.partial_restart dump
    # whose ring names the dead worker, and no supervisor restart
    from pathway_tpu.internals import flight_recorder as fr

    dumps = [fr.load_dump(p) for p in fr.list_dumps(flight_dir)]
    restarts = [d for d in dumps if d.get("reason") == "cluster.partial_restart"]
    assert restarts, [d.get("reason") for d in dumps]
    kinds = {e["kind"] for d in restarts for e in d["events"]}
    assert "cluster.partial_restart" in kinds
    assert "supervisor.restart" not in kinds
