"""pw.debug / pw.demo helper breadth (reference debug/__init__.py 716
LoC + demo/__init__.py): markdown parsing corners, update-stream
printing, pandas round trips, demo stream generators, csv replay."""

from __future__ import annotations

import io
import json
from contextlib import redirect_stdout

import pathway_tpu as pw

from .utils import T, run_table


def test_markdown_types_and_ids():
    t = T(
        """
      | i | f   | s   | b
    1 | 1 | 1.5 | xy  | True
    2 | -2| 0.5 | z   | False
    """
    )
    rows = sorted(run_table(t).values())
    assert rows == [(-2, 0.5, "z", False), (1, 1.5, "xy", True)]


def test_markdown_scripted_stream_compute_and_print_update_stream():
    t = T(
        """
      | v | __time__ | __diff__
    1 | 1 | 2        | 1
    1 | 1 | 4        | -1
    1 | 5 | 4        | 1
    """
    )
    buf = io.StringIO()
    with redirect_stdout(buf):
        pw.debug.compute_and_print_update_stream(t)
    out = buf.getvalue()
    # the three changes appear with their times and signs
    assert "5" in out and "-1" in out
    import re

    # retraction of value 1 at time 4 and insertion of 5 at time 4
    assert re.search(r"1\s*\|\s*4\s*\|\s*-1", out), out
    assert re.search(r"5\s*\|\s*4\s*\|\s*1", out), out


def test_table_from_pandas_roundtrip():
    import pandas as pd

    df = pd.DataFrame({"a": [1, 2], "s": ["x", "y"]})
    t = pw.debug.table_from_pandas(df)
    back = pw.debug.table_to_pandas(t, include_id=False)
    assert sorted(back["a"].tolist()) == [1, 2]
    assert sorted(back["s"].tolist()) == ["x", "y"]


def test_demo_range_stream_completes():
    t = pw.demo.range_stream(nb_rows=5, input_rate=1000.0)
    rows = run_table(t)
    assert len(rows) == 5
    assert sorted(v[0] for v in rows.values()) == [0.0, 1.0, 2.0, 3.0, 4.0]


def test_demo_generate_custom_stream():
    class S(pw.Schema):
        n: int
        sq: int

    t = pw.demo.generate_custom_stream(
        {"n": lambda i: i, "sq": lambda i: i * i},
        schema=S,
        nb_rows=4,
        input_rate=1000.0,
    )
    rows = sorted(run_table(t).values())
    assert rows == [(0, 0), (1, 1), (2, 4), (3, 9)]


def test_demo_noisy_linear_stream_shape():
    t = pw.demo.noisy_linear_stream(nb_rows=6, input_rate=1000.0)
    rows = run_table(t)
    assert len(rows) == 6
    xs = sorted(v[0] for v in rows.values())
    assert xs == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]


def test_replay_csv(tmp_path):
    p = tmp_path / "in.csv"
    p.write_text("a,b\n1,x\n2,y\n3,z\n")

    class S(pw.Schema):
        a: int
        b: str

    t = pw.demo.replay_csv(str(p), schema=S, input_rate=10000.0)
    rows = sorted(run_table(t).values())
    assert rows == [(1, "x"), (2, "y"), (3, "z")]


def test_compute_and_print_sorted_by_id(capsys):
    t = T(
        """
      | v
    2 | 20
    1 | 10
    """
    )
    pw.debug.compute_and_print(t)
    out = capsys.readouterr().out
    assert "| v" in out.replace("  ", " ")
    # rows print sorted by row id (the displayed pointer strings)
    body = [l for l in out.splitlines() if l.startswith("^")]
    ids = [l.split("|")[0].strip() for l in body]
    assert len(ids) == 2 and ids == sorted(ids), out
