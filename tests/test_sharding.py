"""Device-mesh sharding on the virtual 8-device CPU mesh
(conftest.py sets xla_force_host_platform_device_count=8).

Mirrors SURVEY.md §4's implication: multi-chip behavior must be testable
without TPU hardware. Covers make_mesh geometry, data/param shardings,
the sharded contrastive training step (tp × dp), and the driver's
dryrun_multichip contract."""

from __future__ import annotations

import numpy as np
import pytest

import jax

from pathway_tpu.parallel.sharding import (
    DATA_AXIS,
    MODEL_AXIS,
    data_sharding,
    make_mesh,
    replicated,
)


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


def test_make_mesh_geometry():
    mesh = make_mesh(model_parallel=4)
    assert mesh.shape == {DATA_AXIS: 2, MODEL_AXIS: 4}
    mesh2 = make_mesh(model_parallel=1)
    assert mesh2.shape == {DATA_AXIS: 8, MODEL_AXIS: 1}


def test_make_mesh_auto_tp_respects_heads():
    mesh = make_mesh(heads=6)  # 4 does not divide 6 -> falls to 2
    assert mesh.shape[MODEL_AXIS] == 2


def test_data_sharding_places_batch_across_devices():
    mesh = make_mesh(model_parallel=1)
    x = np.arange(16 * 4, dtype=np.float32).reshape(16, 4)
    arr = jax.device_put(x, data_sharding(mesh))
    assert len(arr.sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(arr), x)


def test_replicated_sharding():
    mesh = make_mesh(model_parallel=2)
    x = np.ones((3, 3), np.float32)
    arr = jax.device_put(x, replicated(mesh))
    assert len(arr.sharding.device_set) == 8


def test_contrastive_trainer_tp_dp_step():
    """Full training step with real tensor-parallel weight shardings and
    data-parallel batch over the 8-device mesh (dp=4 × tp=2)."""
    from pathway_tpu.models.encoder import EncoderConfig
    from pathway_tpu.models.training import ContrastiveTrainer

    cfg = EncoderConfig(
        vocab_size=128,
        hidden_size=32,
        num_layers=1,
        num_heads=4,
        intermediate_size=64,
        max_position=32,
        pooling="mean",
    )
    mesh = make_mesh(model_parallel=2)
    trainer = ContrastiveTrainer(config=cfg, mesh=mesh)
    rng = np.random.default_rng(0)
    B, S = 8, 16
    ids = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
    mask = np.ones((B, S), bool)
    loss1 = trainer.step(ids, mask, ids, mask)
    loss2 = trainer.step(ids, mask, ids, mask)
    assert np.isfinite(loss1) and np.isfinite(loss2)
    assert loss2 < loss1  # learning on repeated batch


def test_sentence_encoder_data_parallel_consistency():
    """Mesh-sharded encode must equal single-device encode up to bf16
    forward noise: sharding the batch changes XLA's per-device shapes
    and hence the reduction/fusion order inside the same bf16 network,
    so bitwise equality is not achievable — bound the drift instead."""
    from pathway_tpu.models.sentence_encoder import SentenceEncoder

    rng = np.random.default_rng(1)
    toks = [[101] + rng.integers(999, 2000, 5).tolist() + [102] for _ in range(16)]
    enc_mesh = SentenceEncoder(max_seq_len=32, max_batch=64, mesh=make_mesh(model_parallel=1))
    enc_solo = SentenceEncoder(max_seq_len=32, max_batch=64, mesh=None)
    a = np.asarray(enc_mesh.encode_tokens(toks))
    b = np.asarray(enc_solo.encode_tokens(toks))
    np.testing.assert_allclose(a, b, rtol=2e-3, atol=1e-3)
    # normalized embeddings: directions must be essentially identical
    assert (a * b).sum(axis=1).min() > 0.9999


def test_driver_dryrun_multichip_contract():
    import importlib.util, os

    spec = importlib.util.spec_from_file_location(
        "graft_entry",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "__graft_entry__.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)
    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == 8


def test_shard_batch_key_routing():
    """The C++ shard router agrees with the Python key→shard rule."""
    from pathway_tpu import native

    if not native.is_available():
        pytest.skip("native runtime unavailable")
    import ctypes

    keys = np.array([1, 2, 0xFFFF, 12345, 2**63], dtype=np.uint64)
    out = np.zeros(len(keys), dtype=np.uint32)
    native.NATIVE.pn_shard_batch(
        keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        len(keys),
        0xFFFF,
        8,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
    )
    expected = (keys & np.uint64(0xFFFF)) % np.uint64(8)
    np.testing.assert_array_equal(out, expected.astype(np.uint32))
