"""Reducer breadth under streams: custom accumulators (udf_reducer),
stateful_many/single, ndarray reducers, earliest/latest ordering, and
per-group retraction behavior (reference custom_reducers.py +
reduce.rs coverage)."""

from __future__ import annotations

import numpy as np
import pytest

import pathway_tpu as pw

from .utils import T, run_table


def test_udf_reducer_custom_accumulator_with_retraction():
    # NOTE: the engine recomputes custom accumulators from scratch per
    # group change (graph_runner._make_stateful_reducer), so retract()
    # is exercised only at the STREAM level (the retraction at t=6
    # changes the recomputed result), not via the retract() method.
    class StdDevAcc(pw.BaseCustomAccumulator):
        def __init__(self, cnt, s, s2):
            self.cnt, self.s, self.s2 = cnt, s, s2

        @classmethod
        def from_row(cls, row):
            (v,) = row
            return cls(1, v, v * v)

        def update(self, other):
            self.cnt += other.cnt
            self.s += other.s
            self.s2 += other.s2

        def retract(self, other):
            self.cnt -= other.cnt
            self.s -= other.s
            self.s2 -= other.s2

        def compute_result(self) -> float:
            mean = self.s / self.cnt
            return self.s2 / self.cnt - mean * mean

    stddev = pw.reducers.udf_reducer(StdDevAcc)
    t = T(
        """
      | g | v | __time__ | __diff__
    1 | a | 2 | 2        | 1
    2 | a | 4 | 2        | 1
    3 | a | 9 | 4        | 1
    3 | a | 9 | 6        | -1
    """
    )
    r = t.groupby(pw.this.g).reduce(pw.this.g, var=stddev(pw.this.v))
    ((g, var),) = run_table(r).values()
    assert g == "a" and var == pytest.approx(1.0)  # {2,4}: mean 3, var 1


def test_stateful_single_running_max():
    def mx(state, value):
        return value if state is None or value > state else state

    t = T(
        """
      | g | v | __time__
    1 | a | 3 | 2
    2 | a | 7 | 4
    3 | a | 5 | 6
    """
    )
    r = t.groupby(pw.this.g).reduce(
        pw.this.g, m=pw.reducers.stateful_single(mx)(pw.this.v)
    )
    ((_, m),) = run_table(r).values()
    assert m == 7


def test_stateful_many_batch_folding():
    def fold(state, rows):
        # rows arrive as (count, row_tuple) pairs (reference
        # custom_reducers.stateful_many contract)
        total = state or 0
        for cnt, row in rows:
            total += row[0] * cnt
        return total

    t = T(
        """
      | g | v | __time__ | __diff__
    1 | a | 5 | 2        | 1
    2 | a | 3 | 4        | 1
    """
    )
    r = t.groupby(pw.this.g).reduce(
        pw.this.g, s=pw.reducers.stateful_many(fold)(pw.this.v)
    )
    ((_, s),) = run_table(r).values()
    assert s == 8


def test_ndarray_reducer():
    t = T(
        """
      | g | v
    1 | a | 1
    2 | a | 2
    3 | b | 5
    """
    )
    r = t.groupby(pw.this.g).reduce(
        pw.this.g, arr=pw.reducers.ndarray(pw.this.v)
    )
    rows = {v[0]: np.sort(np.asarray(v[1])) for v in run_table(r).values()}
    assert rows["a"].tolist() == [1, 2] and rows["b"].tolist() == [5]


def test_earliest_latest_follow_epoch_order():
    t = T(
        """
      | g | v | __time__
    1 | a | 10 | 2
    2 | a | 20 | 4
    3 | a | 30 | 6
    """
    )
    r = t.groupby(pw.this.g).reduce(
        pw.this.g,
        first=pw.reducers.earliest(pw.this.v),
        last=pw.reducers.latest(pw.this.v),
    )
    ((_, first, last),) = run_table(r).values()
    assert (first, last) == (10, 30)


def test_unique_reducer_errors_on_conflict():
    t = T(
        """
      | g | v
    1 | a | 1
    2 | a | 2
    """
    )
    r = t.groupby(pw.this.g).reduce(
        pw.this.g, u=pw.fill_error(pw.reducers.unique(pw.this.v), -1)
    )
    ((_, u),) = run_table(r).values()
    assert u == -1  # conflicting values -> ERROR -> filled


def test_sorted_tuple_skip_nones():
    # empty markdown cells parse as None directly
    t = T(
        """
      | g | v
    1 | a | 3
    2 | a |
    3 | a | 1
    """
    )
    r = t.groupby(pw.this.g).reduce(
        pw.this.g, tup=pw.reducers.sorted_tuple(pw.this.v, skip_nones=True)
    )
    ((_, tup),) = run_table(r).values()
    assert tup == (1, 3)
