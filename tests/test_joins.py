"""Join coverage mirroring /root/reference/python/pathway/tests/test_joins.py:
all hows, multi-condition, id-based, chained, streamed retractions."""

from __future__ import annotations

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner
from .utils import T, run_table


def _run(table):
    runner = GraphRunner()
    cap, names = runner.capture(table)
    runner.run()
    pw.clear_graph()
    return cap, names


def _rows(table, *cols):
    cap, names = _run(table)
    idx = [names.index(c) for c in cols]
    return sorted(
        (tuple(r[i] for i in idx) for r in cap.state.values()),
        key=lambda t: tuple((v is None, v) if v is not None else (True, 0) for v in t),
    )


LEFT = """
  | k | a
1 | x | 1
2 | y | 2
3 | z | 3
"""
RIGHT = """
  | k | b
1 | x | 10
2 | y | 20
3 | w | 40
"""


def test_join_outer():
    res = T(LEFT).join_outer(T(RIGHT), pw.left.k == pw.right.k).select(
        a=pw.left.a, b=pw.right.b
    )
    assert _rows(res, "a", "b") == [(1, 10), (2, 20), (3, None), (None, 40)]


def test_join_right():
    res = T(LEFT).join_right(T(RIGHT), pw.left.k == pw.right.k).select(
        a=pw.left.a, b=pw.right.b
    )
    assert _rows(res, "a", "b") == [(1, 10), (2, 20), (None, 40)]


def test_join_multi_condition():
    left = T(
        """
          | k | g | a
        1 | x | 1 | 1
        2 | x | 2 | 2
        """
    )
    right = T(
        """
          | k | g | b
        1 | x | 1 | 10
        2 | x | 3 | 30
        """
    )
    res = left.join(
        right, left.k == right.k, left.g == right.g
    ).select(a=left.a, b=right.b)
    assert _rows(res, "a", "b") == [(1, 10)]


def test_join_on_id():
    left = T(LEFT)
    keyed = left.select(a2=pw.this.a * 100)  # same universe, same keys
    res = left.join(keyed, left.id == keyed.id).select(a=left.a, a2=keyed.a2)
    assert _rows(res, "a", "a2") == [(1, 100), (2, 200), (3, 300)]


def test_chained_joins():
    t1 = T(LEFT)
    t2 = T(RIGHT)
    t3 = T(
        """
          | k | c
        1 | x | 7
        """
    )
    j1 = t1.join(t2, t1.k == t2.k).select(k=t1.k, a=t1.a, b=t2.b)
    res = j1.join(t3, j1.k == t3.k).select(a=j1.a, b=j1.b, c=t3.c)
    assert _rows(res, "a", "b", "c") == [(1, 10, 7)]


def test_join_streamed_retractions():
    """Deleting a right row retracts exactly its join pairs."""
    left = T(LEFT)
    right = pw.debug.table_from_markdown(
        """
          | k | b  | __time__ | __diff__
        1 | x | 10 | 0        | 1
        2 | y | 20 | 0        | 1
        1 | x | 10 | 2        | -1
        """
    )
    res = left.join(right, left.k == right.k).select(a=left.a, b=right.b)
    cap, names = _run(res)
    final = sorted(
        (r[names.index("a")], r[names.index("b")]) for r in cap.state.values()
    )
    assert final == [(2, 20)]
    # history: (1,10) inserted then retracted
    hist = [
        (r[names.index("a")], r[names.index("b")], d)
        for _k, r, _t, d in cap.stream
    ]
    assert (1, 10, 1) in hist and (1, 10, -1) in hist


def test_join_duplicate_keys_produce_cross_product():
    left = T(
        """
          | k | a
        1 | x | 1
        2 | x | 2
        """
    )
    right = T(
        """
          | k | b
        1 | x | 10
        2 | x | 20
        """
    )
    res = left.join(right, left.k == right.k).select(a=left.a, b=right.b)
    assert _rows(res, "a", "b") == [(1, 10), (1, 20), (2, 10), (2, 20)]


def test_join_filter_after():
    left, right = T(LEFT), T(RIGHT)
    res = (
        left.join(right, left.k == right.k)
        .filter(pw.right.b > 10)
        .select(a=left.a, b=right.b)
    )
    assert _rows(res, "a", "b") == [(2, 20)]


def test_join_this_desugaring():
    left, right = T(LEFT), T(RIGHT)
    res = left.join(right, left.k == right.k).select(
        pw.left.a, pw.right.b, s=pw.left.a + pw.right.b
    )
    assert _rows(res, "a", "b", "s") == [(1, 10, 11), (2, 20, 22)]
