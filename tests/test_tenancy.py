"""Multi-tenant serving plane: spec parsing, tenant-packed device
slabs (segments, growth, mask bit-identity, ledger reconciliation,
cold demotion), per-tenant admission gates, weighted deficit
round-robin batching, and the activity-gated per-tenant metric
surfaces (including the PATHWAY_METRIC_TENANTS cardinality fold)."""

from __future__ import annotations

import json
import threading

import numpy as np
import pytest

from pathway_tpu.internals.ledger import LEDGER, hot_row_bytes, parse_bytes
from pathway_tpu.ops.index_metrics import INDEX_METRICS
from pathway_tpu.ops.knn import DeviceKnnIndex
from pathway_tpu.tenancy import TenancyConfig, TenantQuotas
from pathway_tpu.tenancy.config import (
    TENANT_HEADER,
    active_tenancy,
    parse_quota_spec,
    parse_tenancy_spec,
    set_active_tenancy,
    use_tenancy,
)
from pathway_tpu.tenancy.metrics import OTHER, TENANCY_METRICS, metric_tenants
from pathway_tpu.tenancy.packed import (
    _MIN_EXTENT,
    TenantOverBudget,
    TenantPackedIndex,
    reset_slabs,
    shared_slab,
)


@pytest.fixture(autouse=True)
def _clean_registries(monkeypatch):
    monkeypatch.delenv("PATHWAY_TENANCY", raising=False)
    monkeypatch.delenv("PATHWAY_METRIC_TENANTS", raising=False)
    set_active_tenancy(None)
    TENANCY_METRICS.reset()
    LEDGER.reset()
    INDEX_METRICS.reset()
    reset_slabs()
    yield
    set_active_tenancy(None)
    TENANCY_METRICS.reset()
    LEDGER.reset()
    INDEX_METRICS.reset()
    reset_slabs()


def _rng(seed=0):
    return np.random.default_rng(seed)


# ---------------------------------------------------------------------------
# config / spec parsing


def test_parse_tenancy_spec_forms():
    assert parse_tenancy_spec(None) is None
    assert parse_tenancy_spec(False) is None
    assert parse_tenancy_spec("off") is None
    assert parse_tenancy_spec("") is None
    on = parse_tenancy_spec(True)
    assert isinstance(on, TenancyConfig) and on.quotas == {} and on.default is None
    assert isinstance(parse_tenancy_spec("on"), TenancyConfig)
    cfg = parse_tenancy_spec(
        "qps=50,burst=4,inflight=2,hbm=64M,weight=2,floor_k=3,"
        "demote_every=16,decay=0.25,demote_below=0.1"
    )
    assert cfg.default == TenantQuotas(
        qps=50.0,
        burst=4,
        max_inflight=2,
        hbm_bytes=parse_bytes("64M"),
        weight=2.0,
        min_top_k=3,
    )
    assert cfg.demote_every == 16
    assert cfg.decay == 0.25 and cfg.demote_below == 0.1
    # dict form: named quotas + default + cfg knobs
    cfg = parse_tenancy_spec(
        {
            "quotas": {"acme": {"qps": 5, "rate": 5}, "big": "weight=3"},
            "default": {"inflight": 4},
            "demote_every": 8,
        }
    )
    assert cfg.quotas["acme"].qps == 5.0
    assert cfg.quotas["big"].weight == 3.0
    assert cfg.default.max_inflight == 4
    assert cfg.demote_every == 8
    # flat dict knobs become the default quota
    cfg = parse_tenancy_spec({"qps": 9})
    assert cfg.default.qps == 9.0
    # passthrough
    assert parse_tenancy_spec(cfg) is cfg
    q = TenantQuotas(weight=2.0)
    assert parse_quota_spec(q) is q
    assert parse_quota_spec(None) is None


def test_parse_tenancy_spec_rejects_malformed():
    with pytest.raises(ValueError):
        parse_tenancy_spec("zps=1")
    with pytest.raises(ValueError):
        parse_tenancy_spec("qps")  # no '='
    with pytest.raises(ValueError):
        parse_tenancy_spec({"default": {"qps": 1}, "qps": 2})  # both forms
    with pytest.raises(ValueError):
        parse_tenancy_spec(3.5)
    with pytest.raises(ValueError):
        parse_quota_spec({"nope": 1})
    with pytest.raises(ValueError):
        parse_quota_spec("inflight=many")


def test_quota_validation():
    for bad in (
        dict(qps=0.0),
        dict(qps=-1.0),
        dict(burst=0),
        dict(max_inflight=0),
        dict(hbm_bytes=0),
        dict(weight=0.0),
        dict(min_top_k=0),
    ):
        with pytest.raises(ValueError):
            TenantQuotas(**bad)
    with pytest.raises(ValueError):
        TenancyConfig(demote_every=-1)
    with pytest.raises(ValueError):
        TenancyConfig(decay=0.0)
    with pytest.raises(ValueError):
        TenancyConfig(decay=1.5)


def test_quota_for_falls_back_to_default():
    named = TenantQuotas(qps=1.0)
    dflt = TenantQuotas(weight=2.0)
    cfg = TenancyConfig(quotas={"acme": named}, default=dflt)
    assert cfg.quota_for("acme") is named
    assert cfg.quota_for("anyone") is dflt
    assert TenancyConfig().quota_for("anyone") is None
    assert TENANT_HEADER == "X-Pathway-Tenant"


def test_active_tenancy_precedence(monkeypatch):
    assert active_tenancy() is None
    monkeypatch.setenv("PATHWAY_TENANCY", "qps=7,weight=2")
    env_cfg = active_tenancy()
    assert env_cfg is not None and env_cfg.default.qps == 7.0
    # the run-scoped config wins over the env var
    run_cfg = TenancyConfig(default=TenantQuotas(qps=3.0))
    set_active_tenancy(run_cfg)
    assert active_tenancy() is run_cfg
    set_active_tenancy(None)
    assert active_tenancy().default.qps == 7.0
    # malformed env spec reads as "no tenancy", not a crash
    monkeypatch.setenv("PATHWAY_TENANCY", "zps=1")
    assert active_tenancy() is None


def test_use_tenancy_context_manager():
    with use_tenancy("inflight=3"):
        assert active_tenancy().default.max_inflight == 3
        with use_tenancy(None):
            assert active_tenancy() is None
        assert active_tenancy().default.max_inflight == 3
    assert active_tenancy() is None


# ---------------------------------------------------------------------------
# tenant-packed device slab


def test_packed_segments_grant_min_extent_and_count_live_docs():
    idx = TenantPackedIndex(8, reserved_space=64)
    rng = _rng(1)
    idx.add_tenant_batch("a", [0, 1, 2], rng.standard_normal((3, 8)))
    # the grant is the 8-row floor extent, but only live rows count
    assert idx._tenant_rows["a"] == _MIN_EXTENT
    assert idx.tenant_docs("a") == 3
    assert idx._live_docs_shard() == [3]
    (start, size), = idx._segments["a"]
    assert size == _MIN_EXTENT
    # only occupied slots carry the tenant id; granted-but-free rows
    # stay -1 (masked like empty rows)
    extent = [int(t) for t in idx._tenant_host[start : start + size]]
    assert extent.count(idx._tid["a"]) == 3
    assert extent.count(-1) == _MIN_EXTENT - 3


def test_packed_remove_returns_slot_to_tenant_segment():
    idx = TenantPackedIndex(8, reserved_space=64)
    rng = _rng(2)
    idx.add_tenant_batch("a", [0, 1, 2], rng.standard_normal((3, 8)))
    idx.remove_tenant("a", 1)
    assert idx.tenant_docs("a") == 2
    assert idx._live_docs_shard() == [2]
    rows_before = idx._tenant_rows["a"]
    idx.add_tenant("a", 9, rng.standard_normal(8))
    # the freed slot is reused: no new extent granted
    assert idx._tenant_rows["a"] == rows_before
    assert idx.tenant_docs("a") == 3


def test_packed_growth_remaps_segments_and_keeps_results():
    idx = TenantPackedIndex(8, reserved_space=16)
    rng = _rng(3)
    vecs = {t: rng.standard_normal((20, 8)).astype(np.float32) for t in ("a", "b")}
    for i in range(20):
        for t in ("a", "b"):
            idx.add_tenant(t, i, vecs[t][i])
    assert idx.capacity >= 40
    for t in ("a", "b"):
        assert idx.tenant_docs(t) == 20
        # segments stay in-bounds and disjoint after the remap
        rows = []
        for start, size in idx._segments[t]:
            assert 0 <= start and start + size <= idx.capacity
            rows.extend(range(start, start + size))
        assert len(rows) == len(set(rows))
        hits = idx.search_tenant_batch(t, vecs[t][:4], 1)
        assert [row[0][0] for row in hits] == [0, 1, 2, 3]


def test_masked_search_bit_identical_to_private_index():
    dim, res, k = 16, 128, 5
    rng = _rng(4)
    slab = TenantPackedIndex(dim, reserved_space=res)
    solo = DeviceKnnIndex(dim, reserved_space=res)
    corpora = {t: rng.standard_normal((20, dim)).astype(np.float32) for t in ("a", "b", "c")}
    for i in range(20):  # interleaved adds: tenants' rows mix in the slab
        for t in ("a", "b", "c"):
            idx_key = f"{t}{i}"
            slab.add_tenant(t, idx_key, corpora[t][i])
    solo.add_batch_arrays([f"b{i}" for i in range(20)], corpora["b"])
    q = rng.standard_normal((6, dim)).astype(np.float32)
    got = slab.search_tenant_batch("b", q, k)
    want = solo.search_batch(q, k)
    assert got == want  # keys AND scores, bit-for-bit


def test_search_never_crosses_tenants():
    idx = TenantPackedIndex(8, reserved_space=64)
    rng = _rng(5)
    for t in ("a", "b"):
        idx.add_tenant_batch(
            t, [f"{t}{i}" for i in range(10)], rng.standard_normal((10, 8))
        )
    rows = idx.search_tenant_batch("a", rng.standard_normal((4, 8)), 10)
    keys = {key for row in rows for key, _ in row}
    assert keys and all(k.startswith("a") for k in keys)
    # an empty tenant gets empty rows, not other tenants' docs
    assert idx.search_tenant_batch("ghost", rng.standard_normal((2, 8)), 3) == [[], []]


def test_hbm_quota_enforced_at_grant_time():
    budget = 10 * hot_row_bytes(8)  # 10 rows
    cfg = TenancyConfig(quotas={"small": TenantQuotas(hbm_bytes=budget)})
    idx = TenantPackedIndex(8, reserved_space=64, config=cfg)
    rng = _rng(6)
    idx.add_tenant_batch("small", list(range(8)), rng.standard_normal((8, 8)))
    with pytest.raises(TenantOverBudget) as exc:
        idx.add_tenant_batch("small", [100, 101, 102], rng.standard_normal((3, 8)))
    assert exc.value.tenant == "small"
    assert exc.value.budget_bytes == budget
    assert exc.value.need_bytes > budget
    # unquota'd tenants are unaffected
    idx.add_tenant_batch("big", list(range(20)), rng.standard_normal((20, 8)))
    assert idx.tenant_docs("big") == 20


def test_cold_demotion_and_promotion_cycle():
    # idle's single warm-up hit decays 1.0 -> 0.5 on the first sweep,
    # so a 0.6 threshold demotes it there
    cfg = TenancyConfig(demote_every=2, demote_below=0.6)
    idx = TenantPackedIndex(8, reserved_space=64, config=cfg)
    rng = _rng(7)
    vecs = {t: rng.standard_normal((6, 8)).astype(np.float32) for t in ("hot", "idle")}
    for t in ("hot", "idle"):
        idx.add_tenant_batch(t, list(range(6)), vecs[t])
    q = rng.standard_normal((1, 8)).astype(np.float32)
    want_idle = idx.search_tenant_batch("idle", q, 3)
    # two "hot" searches trigger the sweep; "idle" never hit -> demoted
    idx.search_tenant_batch("hot", q, 3)
    idx.search_tenant_batch("hot", q, 3)
    assert idx.tenant_is_cold("idle")
    assert idx.tenant_docs("idle") == 6
    assert idx._tenant_rows["idle"] == 0  # extents freed for reuse
    assert idx._free_extents
    # cold host scan returns the same keys in the same order
    cold = idx.search_tenant_batch("idle", q, 3)
    assert [k for k, _ in cold[0]] == [k for k, _ in want_idle[0]]
    # a second hit while cold promotes the tenant back into the slab
    idx.search_tenant_batch("idle", q, 3)
    assert not idx.tenant_is_cold("idle")
    back = idx.search_tenant_batch("idle", q, 3)
    assert [k for k, _ in back[0]] == [k for k, _ in want_idle[0]]


def test_packed_keys_must_be_tenant_namespaced():
    idx = TenantPackedIndex(8, reserved_space=64)
    with pytest.raises(TypeError):
        idx.add_batch_arrays(["bare-key"], np.zeros((1, 8), np.float32))


def test_ledger_reconciles_tenant_account_with_hot_under_churn():
    idx = TenantPackedIndex(16, reserved_space=64)
    rng = _rng(8)
    for t in ("a", "b", "c"):
        idx.add_tenant_batch(
            t, [f"{t}{i}" for i in range(12)], rng.standard_normal((12, 16))
        )
    q = rng.standard_normal((1, 16)).astype(np.float32)
    idx.search_tenant_batch("a", q, 3)  # materialize the device slab
    # churn: removals, a wholesale demotion, growth from new adds
    for i in range(6):
        idx.remove_tenant("a", f"a{i}")
    idx._demote("b")
    idx.add_tenant_batch(
        "c", [f"c{i}" for i in range(12, 40)], rng.standard_normal((28, 16))
    )
    idx.search_tenant_batch("c", q, 3)  # re-sync after growth
    idx._publish_metrics()
    acc = LEDGER.accounts()
    row_b = hot_row_bytes(idx.dim)
    # the per-tenant account (named owners + __unassigned__) sums
    # exactly to the slab's hot allocation
    assert acc["index.tenant"]["bytes"] == acc["index.hot"]["bytes"]
    assert acc["index.hot"]["bytes"] == idx.capacity * row_b
    named = sum(idx._tenant_rows.values()) * row_b
    rows = LEDGER._rows
    spare = rows[("index.tenant", f"{idx.name}/__unassigned__")][0]
    assert named + spare == acc["index.tenant"]["bytes"]
    # demoted tenant holds no slab bytes; its row dropped
    assert ("index.tenant", f"{idx.name}/b") not in rows
    # per-tenant registry mirrors the slab occupancy
    snap = TENANCY_METRICS.snapshot()["tenants"]
    assert snap["b"]["cold"] and snap["b"]["hbm_bytes"] == 0
    assert snap["a"]["docs"] == idx.tenant_docs("a") == 6
    assert snap["c"]["hbm_bytes"] == idx._tenant_rows["c"] * row_b


def test_shared_slab_registry_is_per_geometry():
    a = shared_slab(16, metric="cos")
    b = shared_slab(16, metric="cos", reserved_space=4096)
    c = shared_slab(16, metric="dot")
    assert a is b
    assert a is not c
    reset_slabs()
    assert shared_slab(16, metric="cos") is not a


def test_tenant_view_strips_namespacing():
    idx = TenantPackedIndex(8, reserved_space=64)
    rng = _rng(9)
    view = idx.view("acme")
    view.add("k0", rng.standard_normal(8))
    view.add_batch([(f"k{i}", rng.standard_normal(8), {"i": i}) for i in (1, 2)])
    assert len(view) == 3
    assert view.dim == 8 and view.metric == idx.metric
    row = view.search_one(rng.standard_normal(8), 3)
    assert {k for k, _ in row} == {"k0", "k1", "k2"}
    view.remove("k1")
    assert len(view) == 2 and idx.tenant_docs("acme") == 2


def test_stdlib_tenant_kwarg_routes_to_shared_slab():
    from pathway_tpu.stdlib.indexing.nearest_neighbors import (
        BruteForceKnn,
        _TenantPayloadView,
    )

    ia = BruteForceKnn(None, dimensions=8, reserved_space=64, tenant="a")._index_factory()()
    ib = BruteForceKnn(None, dimensions=8, reserved_space=64, tenant="b")._index_factory()()
    assert isinstance(ia, _TenantPayloadView)
    assert ia._view.packed is ib._view.packed  # one slab, one compile
    rng = _rng(10)
    ia.add("x", rng.standard_normal(8))
    ib.add("y", rng.standard_normal(8))
    assert len(ia) == 1 and len(ib) == 1
    hits = ia.search_batch(rng.standard_normal((1, 8)), 5)
    assert [k for k, _ in hits[0]] == ["x"]
    spec = BruteForceKnn(None, dimensions=8, tenant="a")._index_spec()
    assert spec["tenant"] == "a"


# ---------------------------------------------------------------------------
# fair-share admission


def _controller(**cfg_kw):
    from pathway_tpu.serving.admission import AdmissionController, ServingConfig
    from pathway_tpu.serving.metrics import ServingMetrics

    cfg_kw.setdefault("max_queue", 100)
    return AdmissionController(ServingConfig(**cfg_kw), metrics=ServingMetrics())


def test_tenant_qps_bucket_sheds_typed_429():
    from pathway_tpu.serving.admission import RateLimited, TenantRateLimited
    from pathway_tpu.serving.deadline import Deadline

    with use_tenancy({"quotas": {"noisy": {"qps": 1000, "burst": 2}}}):
        ctl = _controller()
        for _ in range(2):
            ctl.admit(Deadline(60_000), tenant="noisy")
        with pytest.raises(TenantRateLimited) as exc:
            ctl.admit(Deadline(60_000), tenant="noisy")
        assert isinstance(exc.value, RateLimited)
        assert exc.value.status == 429
        assert exc.value.reason == "tenant_rate_limited"
        assert exc.value.tenant == "noisy"
        assert exc.value.retry_after_s >= 0.0
        # other tenants ride the default (unquota'd) path untouched
        ctl.admit(Deadline(60_000), tenant="quiet")
        snap = TENANCY_METRICS.snapshot()["tenants"]
        assert snap["noisy"]["shed"] == {"tenant_rate_limited": 1}
        assert snap["noisy"]["admitted"] == 2
        assert snap["quiet"]["admitted"] == 1


def test_tenant_inflight_cap_and_release():
    from pathway_tpu.serving.admission import TenantRateLimited
    from pathway_tpu.serving.deadline import Deadline

    with use_tenancy({"default": {"inflight": 2}}):
        ctl = _controller()
        t1 = ctl.admit(Deadline(60_000), tenant="acme")
        ctl.admit(Deadline(60_000), tenant="acme")
        with pytest.raises(TenantRateLimited):
            ctl.admit(Deadline(60_000), tenant="acme")
        assert TENANCY_METRICS.snapshot()["tenants"]["acme"]["inflight"] == 2
        ctl.release(t1)
        ctl.admit(Deadline(60_000), tenant="acme")  # slot freed
        assert TENANCY_METRICS.snapshot()["tenants"]["acme"]["inflight"] == 2


def test_untenanted_admission_ignores_tenancy_state():
    from pathway_tpu.serving.deadline import Deadline

    with use_tenancy({"default": {"qps": 0.001, "burst": 1, "inflight": 1}}):
        ctl = _controller()
        for _ in range(5):
            ctl.release(ctl.admit(Deadline(60_000)))
    assert not TENANCY_METRICS.active()


# ---------------------------------------------------------------------------
# weighted deficit round-robin batching


def _batcher(batch_max=8):
    from pathway_tpu.serving.admission import ServingConfig
    from pathway_tpu.serving.batching import AdaptiveBatcher
    from pathway_tpu.serving.metrics import ServingMetrics

    b = AdaptiveBatcher(
        lambda items: None,
        config=ServingConfig(batch_max=batch_max, batch_window_ms=0.0),
        metrics=ServingMetrics(),
    )
    # pin a sentinel worker so submit() never spawns the drain thread:
    # these tests drive _take_batch() directly for determinism
    b._thread = threading.current_thread()
    return b


def test_wdrr_drains_tenants_by_quota_weight():
    from pathway_tpu.serving.deadline import Deadline

    b = _batcher(batch_max=8)
    with use_tenancy({"quotas": {"heavy": {"weight": 3}, "light": {"weight": 1}}}):
        for i in range(12):
            b.submit(("heavy", i), Deadline(60_000), tenant="heavy")
        for i in range(12):
            b.submit(("light", i), Deadline(60_000), tenant="light")
        assert b.pending() == 24
        items, _, _, tenants = b._take_batch()
    assert len(items) == 8
    assert tenants.count("heavy") == 6  # 3:1 deficit credit
    assert tenants.count("light") == 2
    # each tenant's own items stay in deadline (submit) order
    assert [i for t, i in items if t == "heavy"] == list(range(6))
    assert [i for t, i in items if t == "light"] == [0, 1]
    assert b.pending() == 16


def test_wdrr_interleaves_legacy_heap_as_anonymous_tenant():
    from pathway_tpu.serving.deadline import Deadline

    b = _batcher(batch_max=8)
    for i in range(4):
        b.submit(("none", i), Deadline(60_000))
    for i in range(4):
        b.submit(("t", i), Deadline(60_000), tenant="t")
    items, _, _, tenants = b._take_batch()
    assert len(items) == 8
    assert tenants.count(None) == 4 and tenants.count("t") == 4


def test_untenanted_batcher_keeps_legacy_single_heap():
    from pathway_tpu.serving.deadline import Deadline

    b = _batcher(batch_max=4)
    for i in range(4):
        b.submit(i, Deadline(60_000))
    assert not b._tenant_heaps and not b._rr
    items, _, _, tenants = b._take_batch()
    assert items == [0, 1, 2, 3]
    assert tenants == [None, None, None, None]


def test_wdrr_drops_expired_without_charging_deficit():
    from pathway_tpu.serving.deadline import Deadline

    b = _batcher(batch_max=8)
    with use_tenancy(True):
        for i in range(3):
            b.submit(("dead", i), Deadline(-1.0), tenant="dead")
        b.submit(("live", 0), Deadline(60_000), tenant="live")
        items, _, _, tenants = b._take_batch()
    assert items == [("live", 0)] and tenants == ["live"]
    assert b.dropped_expired_total == 3


# ---------------------------------------------------------------------------
# metric surfaces: cardinality fold + activity gating (satellite 1)


def test_metric_tenants_knob(monkeypatch):
    assert metric_tenants() == 50
    monkeypatch.setenv("PATHWAY_METRIC_TENANTS", "3")
    assert metric_tenants() == 3
    monkeypatch.setenv("PATHWAY_METRIC_TENANTS", "garbage")
    assert metric_tenants() == 50
    monkeypatch.setenv("PATHWAY_METRIC_TENANTS", "0")
    assert metric_tenants() == 50


def test_snapshot_folds_overflow_tenants_into_other(monkeypatch):
    monkeypatch.setenv("PATHWAY_METRIC_TENANTS", "2")
    for i in range(4):
        TENANCY_METRICS.record_admit(f"t{i}")
    TENANCY_METRICS.record_shed("t2", "tenant_rate_limited")
    TENANCY_METRICS.record_shed("t3", "tenant_rate_limited")
    TENANCY_METRICS.add_chip_seconds("t3", 0.5)
    TENANCY_METRICS.set_index("t2", docs=7, hbm_bytes=100)
    snap = TENANCY_METRICS.snapshot()
    assert set(snap["tenants"]) == {"t0", "t1", OTHER}
    assert snap["tenant_count"] == 4 and snap["folded"] == 2
    other = snap["tenants"][OTHER]
    assert other["admitted"] == 2
    assert other["shed"] == {"tenant_rate_limited": 2}
    assert other["chip_seconds"] == 0.5
    assert other["docs"] == 7 and other["hbm_bytes"] == 100
    # first-seen tenants keep their named series (stable label sets)
    assert snap["tenants"]["t0"]["admitted"] == 1


def test_prometheus_renders_folded_other_series(monkeypatch):
    from pathway_tpu.internals.http_monitoring import MonitoringHttpServer

    monkeypatch.setenv("PATHWAY_METRIC_TENANTS", "2")
    for i in range(5):
        TENANCY_METRICS.record_admit(f"t{i}")
    TENANCY_METRICS.record_shed("t4", "tenant_rate_limited")
    text = "\n".join(MonitoringHttpServer._tenancy_lines())
    assert 'pathway_serving_tenant_admitted_total{tenant="t0"} 1' in text
    assert 'pathway_serving_tenant_admitted_total{tenant="other"} 3' in text
    assert 'tenant="t4"' not in text  # folded, never named
    assert 'pathway_serving_tenant_shed_total{tenant="other",reason="tenant_rate_limited"} 1' in text
    assert "pathway_tenant_count 5" in text
    assert "pathway_tenant_folded 3" in text


def test_tenancy_off_scrape_and_status_byte_identical():
    from pathway_tpu.internals.http_monitoring import MonitoringHttpServer
    from pathway_tpu.internals.monitoring import StatsMonitor

    mon = StatsMonitor()
    # the input/output latency gauges are wall-clock relative; pin them
    # so scrape-to-scrape equality tests the tenancy plane, not time
    mon.input_latency_ms = lambda now=None: 0
    mon.output_latency_ms = lambda now=None: 0
    srv = MonitoringHttpServer(mon, port=0)
    quiet_prom = srv._prometheus()
    quiet_status = srv._status()
    assert "pathway_tenant" not in quiet_prom
    assert "tenants" not in json.loads(quiet_status)
    TENANCY_METRICS.record_admit("acme")
    loud = srv._prometheus()
    assert "pathway_tenant_count" in loud
    assert json.loads(srv._status())["tenants"]["tenants"]["acme"]["admitted"] == 1
    # back to never-named: the scrape is byte-identical again
    TENANCY_METRICS.reset()
    assert srv._prometheus() == quiet_prom
    assert srv._status() == quiet_status


def test_doctor_verdict_carries_tenant_rows():
    from pathway_tpu.internals.ledger import HealthWatchdog, render_verdict

    wd = HealthWatchdog()
    assert wd.verdict()["tenants"] is None  # inactive: nothing rendered
    assert "tenants:" not in render_verdict(wd.verdict())
    TENANCY_METRICS.record_admit("acme")
    TENANCY_METRICS.set_index("acme", docs=3, hbm_bytes=2048)
    v = wd.verdict()
    assert v["tenants"]["tenants"]["acme"]["docs"] == 3
    text = render_verdict(v)
    assert "tenants: 1 active" in text
    assert "acme" in text


# ---------------------------------------------------------------------------
# live-row imbalance (satellite 2)


def test_imbalance_counts_live_rows_not_granted_extents():
    idx = TenantPackedIndex(8, reserved_space=64)
    rng = _rng(11)
    idx.add_tenant_batch("a", [0, 1, 2], rng.standard_normal((3, 8)))
    assert idx._tenant_rows["a"] == _MIN_EXTENT  # 8 rows reserved
    idx._publish_metrics()
    entry = INDEX_METRICS.indexes[idx.name]
    assert entry["docs_shard"] == [3]  # live rows, not the 8-row grant
    idx.remove_tenant("a", 0)
    idx._publish_metrics()
    assert INDEX_METRICS.indexes[idx.name]["docs_shard"] == [2]


def test_live_docs_shard_matches_valid_mask_on_plain_index():
    idx = DeviceKnnIndex(8, reserved_space=32)
    rng = _rng(12)
    idx.add_batch_arrays(list(range(5)), rng.standard_normal((5, 8)))
    assert idx._live_docs_shard() == [5]
    idx.remove(3)
    assert idx._live_docs_shard() == [4]
    assert idx._live_docs_shard() == [int(n) for n in idx._docs_shard]
