"""Expression namespaces (.str/.num/.dt), datetime/duration values,
parse helpers, json access.

Mirrors /root/reference/python/pathway/tests test coverage of the
expressions/ method namespaces and engine/time.rs datetime ops."""

from __future__ import annotations

from datetime import datetime, timedelta

import pathway_tpu as pw
from .utils import T, run_table


def _col(table, name="r"):
    state = run_table(table)
    out = sorted(
        (row[0] for row in state.values()),
        key=lambda v: (v is None, repr(v)),
    )
    pw.clear_graph()
    return out


def test_str_namespace():
    t = T(
        """
          | s
        1 | Hello_World
        """
    )
    res = t.select(
        lo=pw.this.s.str.lower(),
        up=pw.this.s.str.upper(),
        ln=pw.this.s.str.len(),
        sw=pw.this.s.str.startswith("Hel"),
        rep=pw.this.s.str.replace("_", " "),
        sl=pw.this.s.str.slice(0, 5),
        rev=pw.this.s.str.reversed(),
    )
    (row,) = run_table(res).values()
    assert row == (
        "hello_world",
        "HELLO_WORLD",
        11,
        True,
        "Hello World",
        "Hello",
        "dlroW_olleH",
    )


def test_str_parse_helpers():
    t = T(
        """
          | s
        1 | 42
        """
    )
    res = t.select(
        i=pw.this.s.str.parse_int(),
        f=pw.this.s.str.parse_float(),
    )
    (row,) = run_table(res).values()
    assert row == (42, 42.0)


def test_num_namespace():
    t = T(
        """
          | x
        1 | -2.25
        """
    )
    res = t.select(
        a=pw.this.x.num.abs(),
        r=pw.this.x.num.round(1),
        fl=pw.this.x.num.floor(),
        ce=pw.this.x.num.ceil(),
    )
    (row,) = run_table(res).values()
    assert row == (2.25, -2.2, -3.0, -2.0)


def test_dt_namespace_from_strptime():
    t = T(
        """
          | s
        1 | 2023-03-25T12:30:45
        """
    )
    res = t.select(d=pw.this.s.dt.strptime("%Y-%m-%dT%H:%M:%S")).select(
        y=pw.this.d.dt.year(),
        mo=pw.this.d.dt.month(),
        day=pw.this.d.dt.day(),
        h=pw.this.d.dt.hour(),
        mi=pw.this.d.dt.minute(),
        sec=pw.this.d.dt.second(),
    )
    (row,) = run_table(res).values()
    assert row == (2023, 3, 25, 12, 30, 45)


def test_dt_strftime_roundtrip():
    t = T(
        """
          | s
        1 | 2024-01-02T03:04:05
        """
    )
    res = t.select(
        out=pw.this.s.dt.strptime("%Y-%m-%dT%H:%M:%S").dt.strftime("%d/%m/%Y %H:%M")
    )
    (row,) = run_table(res).values()
    assert row == ("02/01/2024 03:04",)


def test_datetime_arithmetic_durations():
    t = pw.debug.table_from_rows(_dt_schema(), [(datetime(2024, 1, 1, 12, 0, 0),)])
    res = t.select(
        plus=pw.this.d + timedelta(hours=3),
        minus=pw.this.d - timedelta(days=1),
    ).select(
        h=pw.this.plus.dt.hour(),
        day=pw.this.minus.dt.day(),
    )
    (row,) = run_table(res).values()
    assert row == (15, 31)


def _dt_schema():
    class S(pw.Schema):
        d: pw.DateTimeNaive

    return S


def test_json_field_access():
    import json

    class S(pw.Schema):
        data: pw.Json

    t = pw.debug.table_from_rows(
        S, [(pw.Json({"name": "alice", "age": 3, "tags": ["a", "b"]}),)]
    )
    res = t.select(
        name=pw.this.data["name"].as_str(),
        age=pw.this.data["age"].as_int(),
        tag0=pw.this.data["tags"][0].as_str(),
    )
    (row,) = run_table(res).values()
    assert row == ("alice", 3, "a")


def test_if_else_chains_and_boolean_logic():
    t = T(
        """
          | a  | b
        1 | 1  | 10
        2 | 5  | 2
        3 | 7  | 7
        """
    )
    res = t.select(
        m=pw.if_else(pw.this.a > pw.this.b, pw.this.a, pw.this.b),
        both=(pw.this.a > 2) & (pw.this.b > 2),
        either=(pw.this.a > 6) | (pw.this.b > 6),
        inv=~(pw.this.a == pw.this.b),
    )
    state = run_table(res)
    got = sorted(state.values())
    assert got == [
        (5, False, False, True),
        (7, True, True, False),
        (10, False, True, True),
    ]


def test_coalesce_require_unwrap():
    t = T(
        """
          | a | b
        1 | 1 | 5
        2 |   | 6
        """
    )
    res = t.select(
        c=pw.coalesce(pw.this.a, 0),
        r=pw.require(pw.this.b, pw.this.a),
    )
    state = run_table(res)
    assert sorted(state.values(), key=repr) == [(0, None), (1, 5)]


def test_cast_between_types():
    t = T(
        """
          | x
        1 | 3
        """
    )
    res = t.select(
        f=pw.cast(float, pw.this.x),
        s=pw.cast(str, pw.this.x),
        b=pw.cast(bool, pw.this.x),
    )
    (row,) = run_table(res).values()
    assert row == (3.0, "3", True)


def test_str_namespace_full_matrix():
    """Every .str method produces the python-string-equivalent result
    (reference expressions/string.py parity, one row per method)."""
    t = T(
        """
          | s
        1 | __Mixed-Case_
        """
    )
    s = "__Mixed-Case_"
    r = t.select(
        up=pw.this.s.str.upper(),
        low=pw.this.s.str.lower(),
        cap=pw.this.s.str.capitalize(),
        title=pw.this.s.str.title(),
        swap=pw.this.s.str.swapcase(),
        casef=pw.this.s.str.casefold(),
        ln=pw.this.s.str.len(),
        strip=pw.this.s.str.strip("_"),
        lstrip=pw.this.s.str.lstrip("_"),
        rstrip=pw.this.s.str.rstrip("_"),
        cnt=pw.this.s.str.count("_"),
        find=pw.this.s.str.find("Case"),
        rfind=pw.this.s.str.rfind("_"),
        starts=pw.this.s.str.startswith("__"),
        ends=pw.this.s.str.endswith("_"),
        rep=pw.this.s.str.replace("-", "+"),
        rmp=pw.this.s.str.removeprefix("__"),
        rms=pw.this.s.str.removesuffix("_"),
        rev=pw.this.s.str.reversed(),
        lj=pw.this.s.str.ljust(15, "."),
        rj=pw.this.s.str.rjust(15, "."),
        zf=pw.this.s.str.zfill(15),
        sl=pw.this.s.str.slice(2, 7),
    )
    (row,) = run_table(r).values()
    names = r.column_names()
    got = dict(zip(names, row))
    assert got["up"] == s.upper()
    assert got["low"] == s.lower()
    assert got["cap"] == s.capitalize()
    assert got["title"] == s.title()
    assert got["swap"] == s.swapcase()
    assert got["casef"] == s.casefold()
    assert got["ln"] == len(s)
    assert got["strip"] == s.strip("_")
    assert got["lstrip"] == s.lstrip("_")
    assert got["rstrip"] == s.rstrip("_")
    assert got["cnt"] == s.count("_")
    assert got["find"] == s.find("Case")
    assert got["rfind"] == s.rfind("_")
    assert got["starts"] is True and got["ends"] is True
    assert got["rep"] == s.replace("-", "+")
    assert got["rmp"] == s.removeprefix("__")
    assert got["rms"] == s.removesuffix("_")
    assert got["rev"] == s[::-1]
    assert got["lj"] == s.ljust(15, ".")
    assert got["rj"] == s.rjust(15, ".")
    assert got["zf"] == s.zfill(15)
    assert got["sl"] == s[2:7]


def test_str_parse_methods():
    t = pw.debug.table_from_rows(
        schema=pw.schema_from_types(i=str, f=str, b=str),
        rows=[("-42", "2.5", "yes")],
    )
    r = t.select(
        i=pw.this.i.str.parse_int(),
        f=pw.this.f.str.parse_float(),
        b=pw.this.b.str.parse_bool(),
    )
    (row,) = run_table(r).values()
    assert row == (-42, 2.5, True)
    bad = pw.debug.table_from_rows(
        schema=pw.schema_from_types(b=str), rows=[("maybe",)]
    )
    opt = bad.select(b=pw.this.b.str.parse_bool(optional=True))
    (row2,) = run_table(opt).values()
    assert row2 == (None,)


def test_num_namespace_full_matrix():
    import math

    t = T(
        """
          | x
        1 | -2.25
        """
    )
    x = -2.25
    r = t.select(
        ab=pw.this.x.num.abs(),
        ce=pw.this.x.num.ceil(),
        fl=pw.this.x.num.floor(),
        ro=pw.this.x.num.round(1),
        sq=(pw.this.x * pw.this.x).num.sqrt(),
        ex=pw.this.x.num.exp(),
        si=pw.this.x.num.sin(),
        co=pw.this.x.num.cos(),
        ta=pw.this.x.num.tan(),
        lg=(-pw.this.x).num.log(),
        l2=(-pw.this.x).num.log2(),
        l10=(-pw.this.x).num.log10(),
    )
    (row,) = run_table(r).values()
    names = r.column_names()
    got = dict(zip(names, row))
    assert got["ab"] == 2.25
    assert got["ce"] == -2
    assert got["fl"] == -3
    assert got["ro"] == -2.2
    assert abs(got["sq"] - 2.25) < 1e-9
    assert abs(got["ex"] - math.exp(x)) < 1e-9
    assert abs(got["si"] - math.sin(x)) < 1e-9
    assert abs(got["co"] - math.cos(x)) < 1e-9
    assert abs(got["ta"] - math.tan(x)) < 1e-9
    assert abs(got["lg"] - math.log(2.25)) < 1e-9
    assert abs(got["l2"] - math.log2(2.25)) < 1e-9
    assert abs(got["l10"] - math.log10(2.25)) < 1e-9


def test_num_fill_na():
    from typing import Optional

    t = pw.debug.table_from_rows(
        schema=pw.schema_from_types(x=Optional[float]),
        rows=[(1.5,), (None,)],
    )
    r = t.select(a=pw.this.x.num.fill_na(0.0))
    vals = sorted(v[0] for v in run_table(r).values())
    assert vals == [0.0, 1.5]


def test_str_split_and_to_bytes():
    t = T(
        """
          | s
        1 | a,b,c
        """
    )
    r = t.select(
        parts=pw.this.s.str.split(","),
        raw=pw.this.s.str.to_bytes(),
        again=pw.this.s.str.to_bytes().str.to_string(),
    )
    (row,) = run_table(r).values()
    assert tuple(row[0]) == ("a", "b", "c")
    assert row[1] == b"a,b,c"
    assert row[2] == "a,b,c"
