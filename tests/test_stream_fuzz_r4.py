"""Randomized stream fuzzing: scripted multi-epoch streams with
retractions run through groupby/join/filter pipelines and checked
against brute-force Python recomputation of the final state — the
"fails on seeded mutations" style the reference gets from its
DiffEntry checkers (tests/utils.py:119).
"""

from __future__ import annotations

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner


def _scripted_table(rows, schema):
    """rows: list of (key, values_tuple, time, diff)."""
    from pathway_tpu.internals.table import Column, LogicalOp, Table
    from pathway_tpu.internals.universe import Universe

    dtypes = schema.dtypes()
    cols = {n: Column(t) for n, t in dtypes.items()}
    op = LogicalOp("static", [], {"rows": rows})
    return Table(cols, Universe(), op, name="fuzz_src")


def _random_stream(rng, n_keys=12, n_events=120, n_epochs=9):
    """Insert/retract events that keep multiplicities in {0, 1}: a live
    row may be retracted (exactly as inserted) and re-inserted with new
    values later."""
    live: dict[int, tuple] = {}
    rows = []
    for i in range(n_events):
        # nondecreasing epochs: a retraction must never be scheduled
        # before the insert it undoes
        t = 2 * (1 + i * n_epochs // n_events)
        key = int(rng.integers(0, n_keys))
        if key in live and rng.random() < 0.4:
            g, v = live.pop(key)
            rows.append((key, (g, v), t, -1))
        else:
            if key in live:
                g, v = live.pop(key)
                rows.append((key, (g, v), t, -1))
            g = f"g{int(rng.integers(0, 4))}"
            v = int(rng.integers(-50, 50))
            live[key] = (g, v)
            rows.append((key, (g, v), t, 1))
    return rows


def _final_state(rows):
    """Brute-force: apply diffs in time order -> {key: values}."""
    live = {}
    for key, vals, _t, diff in rows:
        if diff > 0:
            live[key] = vals
        else:
            live.pop(key, None)
    return live


class FuzzSchema(pw.Schema):
    g: str
    v: int


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4, 5, 6, 7])
def test_groupby_sum_count_matches_bruteforce(seed):
    rng = np.random.default_rng(seed)
    rows = _random_stream(rng)
    t = _scripted_table(rows, FuzzSchema)
    res = t.groupby(pw.this.g).reduce(
        g=pw.this.g,
        s=pw.reducers.sum(pw.this.v),
        n=pw.reducers.count(),
        mx=pw.reducers.max(pw.this.v),
    )
    runner = GraphRunner()
    cap, _ = runner.capture(res)
    runner.run()
    pw.clear_graph()

    live = _final_state(rows)
    want: dict[str, list[int]] = {}
    for g, v in live.values():
        want.setdefault(g, []).append(v)
    got = {row[0]: (row[1], row[2], row[3]) for row in cap.state.values()}
    expect = {g: (sum(vs), len(vs), max(vs)) for g, vs in want.items()}
    assert got == expect, f"seed {seed}: {got} != {expect}"


@pytest.mark.parametrize("seed", [10, 11, 12, 13])
def test_filter_select_matches_bruteforce(seed):
    rng = np.random.default_rng(seed)
    rows = _random_stream(rng)
    t = _scripted_table(rows, FuzzSchema)
    res = t.filter(pw.this.v >= 0).select(
        g=pw.this.g, doubled=pw.this.v * 2 + 1
    )
    runner = GraphRunner()
    cap, _ = runner.capture(res)
    runner.run()
    pw.clear_graph()

    live = _final_state(rows)
    expect = sorted(
        (g, v * 2 + 1) for g, v in live.values() if v >= 0
    )
    got = sorted(cap.state.values())
    assert got == expect, f"seed {seed}"


@pytest.mark.parametrize("seed", [20, 21, 22, 23])
def test_join_matches_bruteforce(seed):
    rng = np.random.default_rng(seed)
    left_rows = _random_stream(rng, n_keys=10, n_events=80)
    right_live = {f"g{i}": int(rng.integers(1, 100)) for i in range(4)}
    right_rows = [
        (1000 + i, (g, w), 2, 1) for i, (g, w) in enumerate(right_live.items())
    ]

    class RightSchema(pw.Schema):
        g: str
        w: int

    lt = _scripted_table(left_rows, FuzzSchema)
    rt = _scripted_table(right_rows, RightSchema)
    res = lt.join(rt, pw.left.g == pw.right.g).select(
        g=pw.left.g, prod=pw.left.v * pw.right.w
    )
    runner = GraphRunner()
    cap, _ = runner.capture(res)
    runner.run()
    pw.clear_graph()

    live = _final_state(left_rows)
    expect = sorted(
        (g, v * right_live[g]) for g, v in live.values() if g in right_live
    )
    got = sorted(cap.state.values())
    assert got == expect, f"seed {seed}"


@pytest.mark.parametrize("n_workers", [1, 4])
def test_sharded_fuzz_equality(n_workers):
    """The same fuzzed stream gives identical results on 1 and 4 engine
    shards (worker-invariance under retraction churn)."""
    rng = np.random.default_rng(99)
    rows = _random_stream(rng, n_keys=20, n_events=150)
    t = _scripted_table(rows, FuzzSchema)
    res = t.groupby(pw.this.g).reduce(
        g=pw.this.g, s=pw.reducers.sum(pw.this.v), n=pw.reducers.count()
    )
    runner = GraphRunner(n_workers=n_workers)
    cap, _ = runner.capture(res)
    runner.run()
    pw.clear_graph()

    live = _final_state(rows)
    want: dict[str, list[int]] = {}
    for g, v in live.values():
        want.setdefault(g, []).append(v)
    expect = {g: (sum(vs), len(vs)) for g, vs in want.items()}
    got = {row[0]: (row[1], row[2]) for row in cap.state.values()}
    assert got == expect
