"""Cross-feature: chaos-SIGKILL during tiered-index promotion x cluster
partial restart.

The tiered index (PR 11) promotes cold rows to the hot slab inside
``maybe_rebalance`` (chaos site ``index.tier.promote``); the cluster
fault domain (PR 7) respawns only a dead worker and fences zombies by
generation. This test crosses them: worker 1 is SIGKILLed *inside* a
tier promotion, the coordinator partial-restarts it, and the respawned
worker (bumped generation, so the chaos rule no longer matches) must
both finish the streaming run with exact final counts AND complete a
tier promotion cycle cleanly — a crash inside index code must look to
the fault domain exactly like any other worker death.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROGRAM = textwrap.dedent(
    """
    import os, threading, time
    import numpy as np
    import pathway_tpu as pw
    from pathway_tpu.io._connector import input_table_from_reader
    from pathway_tpu.ops.tiered_knn import TieredKnnIndex, TierConfig

    N = int(os.environ["XT_N"])
    PID = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    NPROC = int(os.environ.get("PATHWAY_PROCESSES", "1"))
    GEN = int(os.environ.get("PATHWAY_CLUSTER_GENERATION", "0") or 0)
    WORDS = ["cat", "dog", "bird"]

    def tier_churn():
        rng = np.random.default_rng(7)
        centers = rng.normal(size=(4, 16)).astype(np.float32) * 2.0
        assign = rng.integers(0, 4, size=100)
        vecs = (centers[assign] + rng.normal(size=(100, 16))).astype(
            np.float32
        )
        qs = (
            centers[rng.integers(0, 4, size=4)]
            + rng.normal(size=(4, 16))
        ).astype(np.float32)
        idx = TieredKnnIndex(
            dim=16,
            reserved_space=128,
            tiers=TierConfig(n_clusters=4, n_probe=4, cold_dtype="f32"),
        )
        idx.add_batch_arrays(list(range(100)), vecs)
        while True:
            idx.force_demote()
            for _ in range(8):
                idx.search_batch(qs, 5)
            # generation 0: the chaos rule SIGKILLs the process HERE,
            # mid-promotion. After the partial restart (GEN > 0) the
            # rule no longer matches and the cycle must complete.
            idx.maybe_rebalance(force=True)
            if GEN > 0:
                got = idx.search_batch(np.asarray(vecs, np.float32), 1)
                found = sorted(row[0][0] for row in got if row)
                ok = (
                    found == list(range(100))
                    and idx.hot_docs() + idx.cold_docs() == 100
                )
                with open(os.environ["XT_MARKER"], "w") as f:
                    f.write("ok" if ok else f"bad coverage={len(found)}")
                return

    churn = None
    if PID == 1:
        # non-daemon: a respawned worker must not exit before the
        # verification marker lands
        churn = threading.Thread(target=tier_churn, daemon=False)
        churn.start()

    class S(pw.Schema):
        word: str

    def reader(ctx):
        start = int(ctx.offsets.get("pos", 0))
        for i in range(N):
            if i % NPROC != ctx.process_id:
                continue
            if i < start:
                continue
            ctx.insert({"word": WORDS[i % 3]}, offsets={"pos": i + 1})
            ctx.commit()
            time.sleep(0.01)

    t = input_table_from_reader(
        S, reader, name="slow_src", parallel_readers=True,
        persistent_id="xt", supports_offsets=True,
        autocommit_duration_ms=50,
    )
    c = t.groupby(pw.this.word).reduce(pw.this.word, n=pw.reducers.count())
    pw.io.jsonlines.write(c, os.environ["XT_OUT"] + "." + str(PID))
    pw.run(
        monitoring_level="none",
        persistence_config=pw.persistence.Config.simple_config(
            pw.persistence.Backend.filesystem(os.environ["XT_STORE"]),
            snapshot_interval_ms=200,
        ),
    )
    if churn is not None:
        churn.join(timeout=60)
    """
)


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.parametrize("hit", [1, 2])
def test_sigkill_in_tier_promotion_partial_restart(tmp_path, hit):
    """SIGKILL worker 1 at the ``hit``-th visit to index.tier.promote
    (the promotion moves keys in two halves, so hit=2 lands mid-move
    with the hot slab torn); the fault domain must partial-restart it
    and the respawned worker must complete both the stream and a clean
    promotion cycle."""
    n = 120
    out = str(tmp_path / "out.jsonl")
    marker = str(tmp_path / "tier.ok")
    spec = json.dumps(
        {
            "site": "index.tier.promote",
            "process": 1,
            "generation": 0,
            "hit": hit,
            "action": "kill",
        }
    )
    prog = tmp_path / "xt.py"
    prog.write_text(PROGRAM)
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PATHWAY_CHAOS", None)
        env.update(
            XT_N=str(n),
            XT_OUT=out,
            XT_STORE=str(tmp_path / "store"),
            XT_MARKER=marker,
            JAX_PLATFORMS="cpu",
            PATHWAY_THREADS="1",
            PATHWAY_PROCESSES="2",
            PATHWAY_PROCESS_ID=str(pid),
            PATHWAY_FIRST_PORT=str(port),
            PATHWAY_CLUSTER_TOKEN="xt-test",
            PATHWAY_CLUSTER_LEASE_MS="1500",
            PATHWAY_CLUSTER_RESPAWN="1",
            PATHWAY_CHAOS=spec,
            PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(prog)],
                env=env,
                cwd=str(tmp_path),
                stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    p0, p1 = procs
    try:
        _, err0 = p0.communicate(timeout=240)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        p1.wait(timeout=10)

    # the original worker died inside the promotion...
    assert p1.returncode == -signal.SIGKILL, (p1.returncode, err0[-3000:])
    # ...and the coordinator executed a PARTIAL restart, finishing the
    # run in its one original process
    assert p0.returncode == 0, err0[-3000:]
    assert "cluster partial restart" in err0

    # stream contract: exact net final counts, nothing lost or doubled
    state: dict = {}
    with open(out + ".0") as f:
        for line in f:
            rec = json.loads(line)
            if rec["diff"] > 0:
                state[rec["word"]] = rec["n"]
            else:
                state.pop(rec["word"], None)
    assert state == {"cat": 40, "dog": 40, "bird": 40}

    # index contract: the respawned worker completed a full promotion
    # cycle — every key answered exactly once, tiers account for all
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not os.path.exists(marker):
        time.sleep(0.2)
    assert os.path.exists(marker), "respawned worker never verified its index"
    with open(marker) as f:
        assert f.read() == "ok"
