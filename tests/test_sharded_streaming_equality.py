"""Worker-count invariance under SCRIPTED STREAMS: the reference's
PATHWAY_THREADS CI matrix applied to multi-epoch pipelines with
retractions — every operator family must produce identical final state
at 1 and 4 workers."""

from __future__ import annotations

import pathway_tpu as pw
from pathway_tpu.stdlib import temporal

from .test_sharded import assert_same_result

STREAM = """
  | g | v | __time__ | __diff__
1 | a | 1 | 2        | 1
2 | b | 2 | 2        | 1
3 | a | 3 | 4        | 1
4 | c | 4 | 4        | 1
2 | b | 2 | 6        | -1
5 | a | 5 | 6        | 1
3 | a | 3 | 8        | -1
"""


def _stream():
    return pw.debug.table_from_markdown(STREAM)


def test_streamed_groupby_invariant_across_workers():
    def build():
        t = _stream()
        return t.groupby(pw.this.g).reduce(
            pw.this.g,
            s=pw.reducers.sum(pw.this.v),
            n=pw.reducers.count(),
            tup=pw.reducers.sorted_tuple(pw.this.v),
        )

    assert_same_result(build)


def test_streamed_join_invariant_across_workers():
    def build():
        left = _stream()
        right = pw.debug.table_from_markdown(
            """
          | g | w | __time__ | __diff__
        7 | a | 10 | 2       | 1
        8 | b | 20 | 4       | 1
        9 | c | 30 | 6       | 1
        8 | b | 20 | 8       | -1
        """
        )
        return left.join(right, left.g == right.g).select(
            g=left.g, v=left.v, w=right.w
        )

    assert_same_result(build)


def test_streamed_window_invariant_across_workers():
    def build():
        t = pw.debug.table_from_markdown(
            """
          | t | v | __time__ | __diff__
        1 | 1 | 1 | 2        | 1
        2 | 3 | 2 | 4        | 1
        3 | 5 | 3 | 6        | 1
        2 | 3 | 2 | 8        | -1
        """
        )
        return t.windowby(
            pw.this.t, window=temporal.tumbling(duration=4)
        ).reduce(
            start=pw.this._pw_window_start,
            total=pw.reducers.sum(pw.this.v),
        )

    assert_same_result(build)


def test_streamed_distinct_and_flatten_invariant():
    def build():
        t = _stream()
        parts = t.select(
            g=pw.this.g,
            ps=pw.apply_with_type(lambda v: tuple(range(v)), pw.ANY, pw.this.v),
        )
        flat = parts.flatten(pw.this.ps)
        return flat.groupby(pw.this.g, pw.this.ps).reduce(
            pw.this.g, pw.this.ps, n=pw.reducers.count()
        )

    assert_same_result(build)


def test_streamed_sorting_index_invariant():
    from pathway_tpu.stdlib.indexing import build_sorted_index, sort_from_index

    def build():
        t = _stream()
        nodes = t.select(key=pw.this.v)
        pn = sort_from_index(build_sorted_index(nodes)["index"])
        return nodes.select(pw.this.key) + pn

    assert_same_result(build)
