"""Round-4 breadth: connector failure-mode matrix, format edge cases,
temporal streaming variants, and the multi-worker x persistence x
restart cross-product (VERDICT r3 Next #9 — tests that fail on seeded
mutations, mirroring the reference's per-backend failure suites and
``_stream`` window variants)."""

from __future__ import annotations

import json
import os
import threading
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.dataflow import EngineError
from pathway_tpu.internals.graph_runner import GraphRunner

from .utils import T, assert_stream_equality, run_table


# ------------------------------------------------- connector failure modes


def _run_to_completion(table):
    rows = []
    pw.io.subscribe(
        table, on_change=lambda key, row, time, is_addition: rows.append(row)
    )
    pw.run(monitoring_level="none")
    pw.clear_graph()
    return rows


def test_python_subject_crash_mid_stream_fails_run():
    """A subject that dies after emitting rows must fail the run, not
    truncate the table silently."""

    class Crashy(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(data="one")
            self.commit()
            raise OSError("source went away")

    class S(pw.Schema):
        data: str

    t = pw.io.python.read(Crashy(), schema=S)
    pw.io.subscribe(t, on_change=lambda key, row, time, is_addition: None)
    with pytest.raises(EngineError, match="failed"):
        pw.run(monitoring_level="none")
    pw.clear_graph()


def test_fs_read_missing_path_fails_run(tmp_path):
    with pytest.raises(FileNotFoundError, match="does not exist"):
        pw.io.plaintext.read(str(tmp_path / "nope" / "missing"), mode="static")
    pw.clear_graph()


def test_csv_malformed_row_routes_error(tmp_path):
    """A row whose field count mismatches the header must not pass
    silently: static reads surface the parse failure."""
    p = tmp_path / "bad.csv"
    p.write_text("a,b\n1,2\n3\n")

    class S(pw.Schema):
        a: int
        b: int

    with pytest.raises(Exception):
        t = pw.io.csv.read(str(p), schema=S, mode="static")
        _run_to_completion(t)
    pw.clear_graph()


def test_kafka_fake_consumer_error_fails_run():
    """A kafka client erroring mid-poll aborts the run (reference:
    reader errors propagate, connectors/mod.rs panics cross workers)."""

    class ExplodingConsumer:
        def __init__(self):
            self.n = 0

        def poll(self, timeout=None):
            self.n += 1
            if self.n > 2:
                raise ConnectionError("broker lost")
            return None

    class S(pw.Schema):
        data: str

    t = pw.io.kafka.read(
        rdkafka_settings={}, topic="t", schema=S, format="raw", _consumer=ExplodingConsumer()
    )
    pw.io.subscribe(t, on_change=lambda key, row, time, is_addition: None)
    with pytest.raises(EngineError, match="failed"):
        pw.run(monitoring_level="none")
    pw.clear_graph()


# ---------------------------------------------------- format edge cases


def test_dsv_quoted_separator_and_unicode(tmp_path):
    p = tmp_path / "q.csv"
    p.write_text('a,b\n"x,y",Zürich\n"line\nbreak",ok\n')

    class S(pw.Schema):
        a: str
        b: str

    t = pw.io.csv.read(str(p), schema=S, mode="static")
    rows = sorted(_run_to_completion(t), key=lambda r: r["b"])
    assert rows[0]["a"] == "x,y" and rows[0]["b"] == "Zürich"
    assert rows[1]["a"] == "line\nbreak"


def test_jsonlines_nested_null_and_unicode(tmp_path):
    p = tmp_path / "n.jsonl"
    p.write_text(
        json.dumps({"k": "α", "v": {"deep": [1, None, "ß"]}}) + "\n"
        + json.dumps({"k": "b", "v": None}) + "\n"
    )

    class S(pw.Schema):
        k: str
        v: pw.Json | None

    t = pw.io.jsonlines.read(str(p), schema=S, mode="static")
    rows = {r["k"]: r["v"] for r in _run_to_completion(t)}
    deep = rows["α"].value if hasattr(rows["α"], "value") else rows["α"]
    assert deep == {"deep": [1, None, "ß"]}
    b = rows["b"]
    assert b is None or (hasattr(b, "value") and b.value is None)


def test_csv_write_roundtrip_with_special_chars(tmp_path):
    src = tmp_path / "in.jsonl"
    src.write_text(json.dumps({"s": 'quote " comma, done', "n": 7}) + "\n")

    class S(pw.Schema):
        s: str
        n: int

    t = pw.io.jsonlines.read(str(src), schema=S, mode="static")
    out = tmp_path / "out.csv"
    pw.io.csv.write(t, str(out))
    pw.run(monitoring_level="none")
    pw.clear_graph()

    t2 = pw.io.csv.read(str(out), schema=S, mode="static")
    rows = _run_to_completion(t2)
    assert rows[0]["s"] == 'quote " comma, done' and rows[0]["n"] == 7


# ------------------------------------------- temporal streaming variants


def test_asof_now_join_streamed_answers_once():
    """asof_now queries answer against the right side AS OF arrival and
    do not revise when the right side changes later (reference
    _asof_now_join semantics)."""
    left = T(
        """
          | q  | __time__ | __diff__
        1 | 10 | 4        | 1
        2 | 20 | 8        | 1
        """
    )
    right = T(
        """
          | r  | __time__ | __diff__
        1 | 1  | 2        | 1
        1 | 1  | 6        | -1
        1 | 2  | 6        | 1
        """
    )
    res = left.asof_now_join(right).select(q=left.q, r=right.r)
    assert_stream_equality(
        res,
        [
            ((10, 1), 4, 1),  # q=10 saw r=1 (as of t=4)
            ((20, 2), 8, 1),  # q=20 saw r=2; the earlier answer did NOT revise
        ],
    )


def test_exactly_once_behavior_emits_single_final_result():
    """exactly_once windows emit one final value per window and freeze:
    late updates past the shift do not revise (reference
    temporal_behavior.py ExactlyOnceBehavior)."""
    t = T(
        """
          | t  | v | __time__ | __diff__
        1 | 1  | 1 | 2        | 1
        2 | 2  | 2 | 2        | 1
        3 | 12 | 5 | 4        | 1
        4 | 3  | 9 | 6        | 1
        """
    )
    win = t.windowby(
        pw.this.t,
        window=pw.temporal.tumbling(duration=10),
        behavior=pw.temporal.exactly_once_behavior(),
    ).reduce(s=pw.reducers.sum(pw.this.v))
    state = run_table(win)
    sums = sorted(v[-1] for v in state.values())
    # the late v=9 arrived after window [0,10) closed -> not included
    assert sums == [3, 5], sums


def test_sliding_window_instance_isolated_streams():
    """windowby instance= keeps per-instance windows independent under
    streamed arrival."""
    t = T(
        """
          | who | t | v | __time__ | __diff__
        1 | a   | 1 | 1 | 2        | 1
        2 | b   | 1 | 5 | 2        | 1
        3 | a   | 2 | 2 | 4        | 1
        """
    )
    win = t.windowby(
        pw.this.t,
        window=pw.temporal.tumbling(duration=10),
        instance=pw.this.who,
    ).reduce(who=pw.this._pw_instance, s=pw.reducers.sum(pw.this.v))
    state = run_table(win)
    got = sorted((v[0], v[1]) for v in state.values())
    assert got == [("a", 3), ("b", 5)]


# ------------------- multi-worker x persistence x restart cross-product


@pytest.fixture
def _oneshot_fs(monkeypatch):
    monkeypatch.setenv("PATHWAY_TPU_FS_ONESHOT", "1")


@pytest.mark.parametrize("n_workers", [1, 4])
def test_persistence_restart_matrix(tmp_path, n_workers, _oneshot_fs):
    """The recovery contract must hold identically for 1 and 4 engine
    shards: restart resumes from offsets, re-delivers nothing, and new
    input still flows."""
    in_dir = tmp_path / "in"
    in_dir.mkdir()
    (in_dir / "a.jsonl").write_text(
        "".join(json.dumps({"w": w}) + "\n" for w in ["x", "y", "x"])
    )

    class S(pw.Schema):
        w: str

    backend = pw.persistence.Backend.filesystem(str(tmp_path / f"p{n_workers}"))

    def run_once(events):
        t = pw.io.jsonlines.read(
            str(in_dir), schema=S, mode="streaming", persistent_id="src"
        )
        counts = t.groupby(pw.this.w).reduce(
            w=pw.this.w, n=pw.reducers.count()
        )
        pw.io.subscribe(
            counts,
            on_change=lambda key, row, time, is_addition: events.append(
                (row["w"], row["n"], is_addition)
            ),
        )
        os.environ["PATHWAY_THREADS"] = str(n_workers)
        try:
            pw.run(
                monitoring_level="none",
                persistence_config=pw.persistence.Config.simple_config(backend),
            )
        finally:
            os.environ.pop("PATHWAY_THREADS", None)
        pw.clear_graph()

    ev1: list = []
    run_once(ev1)
    final1 = {}
    for w, n, add in ev1:
        if add:
            final1[w] = n
    assert final1 == {"x": 2, "y": 1}

    # restart with no new input: nothing re-delivers
    ev2: list = []
    run_once(ev2)
    assert ev2 == [], ev2

    # new input after restart: only the delta flows, counts include old
    (in_dir / "b.jsonl").write_text(json.dumps({"w": "x"}) + "\n")
    ev3: list = []
    run_once(ev3)
    final3 = {w: n for w, n, add in ev3 if add}
    assert final3 == {"x": 3}, ev3


def test_interval_join_with_cutoff_behavior_drops_late():
    """interval_join with a cutoff behavior: left rows arriving past the
    cutoff are ignored (reference test_interval_join_stream.py)."""
    left = T(
        """
          | t | v | __time__ | __diff__
        1 | 1 | 1 | 2        | 1
        2 | 9 | 2 | 4        | 1
        3 | 1 | 3 | 8        | 1
        """
    )
    right = T(
        """
          | t | w  | __time__ | __diff__
        1 | 1 | 10 | 2        | 1
        2 | 9 | 90 | 2        | 1
        """
    )
    res = left.interval_join(
        right,
        pw.left.t,
        pw.right.t,
        pw.temporal.interval(0, 0),
        behavior=pw.temporal.common_behavior(cutoff=2),
    ).select(v=pw.left.v, w=pw.right.w)
    state = run_table(res)
    got = sorted((r[0], r[1]) for r in state.values())
    # the late (t=1, v=3) row arrived when the watermark (9) was past
    # t + cutoff = 3 -> dropped; the on-time rows joined
    assert got == [(1, 10), (2, 90)], got


def test_window_join_streamed_revision():
    left = T(
        """
          | t | v | __time__ | __diff__
        1 | 1 | 1 | 2        | 1
        """
    )
    right = T(
        """
          | t | w | __time__ | __diff__
        1 | 2 | 5 | 4        | 1
        1 | 2 | 5 | 6        | -1
        1 | 2 | 7 | 6        | 1
        """
    )
    res = left.window_join(
        right, pw.left.t, pw.right.t, pw.temporal.tumbling(duration=4)
    ).select(v=pw.left.v, w=pw.right.w)
    assert_stream_equality(
        res,
        [
            ((1, 5), 4, 1),
            ((1, 5), 6, -1),
            ((1, 7), 6, 1),
        ],
    )


def test_datetime_tumbling_and_sliding_windows():
    """Windows over DATE_TIME columns with timedelta durations and no
    explicit origin (reference windowby datetime support)."""
    import datetime

    rows = [
        (datetime.datetime(2024, 5, 1, 12, 0), 1),
        (datetime.datetime(2024, 5, 1, 12, 7), 2),
        (datetime.datetime(2024, 5, 1, 12, 20), 5),
    ]
    t = pw.debug.table_from_rows(
        schema=pw.schema_from_types(ts=pw.DATE_TIME_NAIVE, v=int), rows=rows
    )
    w = t.windowby(
        pw.this.ts,
        window=pw.temporal.tumbling(duration=datetime.timedelta(minutes=10)),
    ).reduce(
        start=pw.this._pw_window_start, s=pw.reducers.sum(pw.this.v)
    )
    state = run_table(w)
    got = sorted((row[0].minute, row[1]) for row in state.values())
    assert got == [(0, 3), (20, 5)]
    pw.clear_graph()

    t2 = pw.debug.table_from_rows(
        schema=pw.schema_from_types(ts=pw.DATE_TIME_NAIVE, v=int), rows=rows
    )
    w2 = t2.windowby(
        pw.this.ts,
        window=pw.temporal.sliding(
            hop=datetime.timedelta(minutes=10),
            duration=datetime.timedelta(minutes=20),
        ),
    ).reduce(start=pw.this._pw_window_start, s=pw.reducers.sum(pw.this.v))
    state2 = run_table(w2)
    by_start = {row[0].minute: row[1] for row in state2.values()}
    # window [11:50,12:10) holds v=1,2; [12:00,12:20) holds 1,2;
    # [12:10,12:30) holds 5; [12:20,12:40) holds 5
    assert by_start[50] == 3 and by_start[0] == 3
    assert by_start[10] == 5 and by_start[20] == 5


def test_datetime_session_window_and_interval_join():
    import datetime

    D = datetime.datetime
    rows = [(D(2024, 5, 1, 12, 0), 1), (D(2024, 5, 1, 12, 2), 2), (D(2024, 5, 1, 13, 0), 5)]
    t = pw.debug.table_from_rows(
        schema=pw.schema_from_types(ts=pw.DATE_TIME_NAIVE, v=int), rows=rows
    )
    w = t.windowby(
        pw.this.ts,
        window=pw.temporal.session(max_gap=datetime.timedelta(minutes=10)),
    ).reduce(s=pw.reducers.sum(pw.this.v))
    assert sorted(v[0] for v in run_table(w).values()) == [3, 5]
    pw.clear_graph()

    left = pw.debug.table_from_rows(
        schema=pw.schema_from_types(ts=pw.DATE_TIME_NAIVE, v=int), rows=rows[:2]
    )
    right = pw.debug.table_from_rows(
        schema=pw.schema_from_types(ts=pw.DATE_TIME_NAIVE, w=int),
        rows=[(D(2024, 5, 1, 12, 1), 7)],
    )
    res = left.interval_join(
        right,
        pw.left.ts,
        pw.right.ts,
        pw.temporal.interval(
            datetime.timedelta(minutes=-5), datetime.timedelta(minutes=5)
        ),
    ).select(v=pw.left.v, w=pw.right.w)
    assert sorted(run_table(res).values()) == [(1, 7), (2, 7)]


def test_asof_join_with_cutoff_behavior():
    """asof_join behavior: late left rows past the cutoff never match
    (reference _asof_join.py:437 behavior application)."""
    left = T(
        """
          | t | v | __time__ | __diff__
        1 | 1 | 1 | 2        | 1
        2 | 9 | 2 | 4        | 1
        3 | 2 | 3 | 8        | 1
        """
    )
    right = T(
        """
          | t | w  | __time__ | __diff__
        1 | 0 | 10 | 2        | 1
        """
    )
    res = left.asof_join(
        right,
        pw.left.t,
        pw.right.t,
        behavior=pw.temporal.common_behavior(cutoff=2),
    ).select(v=pw.left.v, w=pw.right.w)
    got = sorted(v for v in run_table(res).values())
    # the late (t=2, v=3) row arrived when the watermark (9) was past
    # t + cutoff -> dropped from the join
    assert got == [(1, 10), (2, 10)], got


def test_window_join_with_cutoff_behavior():
    left = T(
        """
          | t | v | __time__ | __diff__
        1 | 1 | 1 | 2        | 1
        2 | 9 | 2 | 4        | 1
        3 | 1 | 3 | 8        | 1
        """
    )
    right = T(
        """
          | t | w  | __time__ | __diff__
        1 | 2 | 10 | 2        | 1
        2 | 9 | 90 | 2        | 1
        """
    )
    res = left.window_join(
        right,
        pw.left.t,
        pw.right.t,
        pw.temporal.tumbling(duration=4),
        behavior=pw.temporal.common_behavior(cutoff=2),
    ).select(v=pw.left.v, w=pw.right.w)
    got = sorted(v for v in run_table(res).values())
    assert got == [(1, 10), (2, 90)], got


def test_asof_join_behavior_consistent_without_right_columns():
    """Review regression: a behavior-dropped left row must vanish from
    the result regardless of which columns the select touches."""
    left = T(
        """
          | t | v | __time__ | __diff__
        1 | 1 | 1 | 2        | 1
        2 | 9 | 2 | 4        | 1
        3 | 2 | 3 | 8        | 1
        """
    )
    right = T(
        """
          | t | w  | __time__ | __diff__
        1 | 0 | 10 | 2        | 1
        """
    )
    j = left.asof_join(
        right, pw.left.t, pw.right.t, behavior=pw.temporal.common_behavior(cutoff=2)
    )
    left_only = sorted(v[0] for v in run_table(j.select(v=pw.left.v)).values())
    assert left_only == [1, 2], left_only


def test_window_join_cutoff_is_per_window_not_per_row():
    """Review regression: a row still inside its window's allowed
    lateness (watermark < window_end + cutoff) must join, even when its
    own event time is far behind the watermark."""
    left = T(
        """
          | t | v | __time__ | __diff__
        1 | 5 | 1 | 2        | 1
        2 | 0 | 2 | 4        | 1
        """
    )
    right = T(
        """
          | t | w  | __time__ | __diff__
        1 | 1 | 10 | 2        | 1
        2 | 5 | 50 | 2        | 1
        """
    )
    res = left.window_join(
        right,
        pw.left.t,
        pw.right.t,
        pw.temporal.tumbling(duration=4),
        behavior=pw.temporal.common_behavior(cutoff=2),
    ).select(v=pw.left.v, w=pw.right.w)
    assert sorted(run_table(res).values()) == [(1, 50), (2, 10)]
