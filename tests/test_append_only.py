"""Append-only property tracking: schema declarations flow through the
logical plan (reference analogue: internals/column_properties.py +
column.py context append_only rules), and the engine consumes the proof
— insert-only sources skip upsert state, append-only sinks skip epoch
consolidation, and a retraction into a declared append-only source is an
error."""

import pytest

import pathway_tpu as pw
from pathway_tpu.engine import dataflow as df


def _static():
    return pw.debug.table_from_markdown(
        """
          | name  | amount
        1 | alice | 10
        2 | bob   | 20
        """
    )


def test_static_table_is_append_only():
    t = _static()
    assert t.is_append_only
    assert all(c.append_only for c in t._columns.values())


def test_update_stream_static_table_is_not_append_only():
    t = pw.debug.table_from_markdown(
        """
          | v | __time__ | __diff__
        1 | 1 | 2        | 1
        1 | 1 | 4        | -1
        """
    )
    assert not t.is_append_only


def test_select_preserves_append_only():
    t = _static()
    out = t.select(x=pw.this.amount * 2, y=pw.this.name)
    assert out.is_append_only
    assert out._columns["x"].append_only


def test_nondeterministic_udf_breaks_append_only():
    t = _static()
    out = t.select(
        x=pw.apply(lambda v: v, pw.this.amount)  # deterministic default
    )
    assert out._columns["x"].append_only
    from pathway_tpu.internals.expression import ApplyExpression

    e = ApplyExpression(lambda v: v, int, (t.amount,), {}, deterministic=False)
    out2 = t.select(x=e)
    assert not out2._columns["x"].append_only
    assert not out2.is_append_only


def test_filter_with_append_only_predicate_preserves():
    t = _static()
    out = t.filter(pw.this.amount > 5)
    assert out.is_append_only


def test_groupby_is_not_append_only():
    t = _static()
    out = t.groupby(pw.this.name).reduce(
        name=pw.this.name, s=pw.reducers.sum(pw.this.amount)
    )
    assert not out.is_append_only


def test_concat_of_append_only_is_append_only():
    a = _static()
    b = pw.debug.table_from_markdown(
        """
          | name | amount
        9 | carl | 30
        """
    )
    assert a.concat_reindex(b).is_append_only


def test_intersect_of_append_only_preserves():
    a = _static()
    b = _static()
    assert a.intersect(b).is_append_only


def test_deduplicate_is_not_append_only():
    t = _static()
    assert not t.deduplicate(value=pw.this.amount).is_append_only


def test_schema_declaration_marks_connector_source():
    class S(pw.Schema, append_only=True):
        a: int
        b: str

    from pathway_tpu.io._connector import input_table_from_reader

    t = input_table_from_reader(S, lambda ctx: None, name="src")
    assert t.is_append_only
    assert t.select(x=pw.this.a + 1).is_append_only


def test_undeclared_connector_source_not_append_only():
    class S(pw.Schema):
        a: int

    from pathway_tpu.io._connector import input_table_from_reader

    t = input_table_from_reader(S, lambda ctx: None, name="src")
    assert not t.is_append_only


def test_append_only_source_skips_upsert_state():
    """Engine consumption: a declared append-only source must not grow
    the old-value dict (unbounded memory on long streams), and results
    are identical to the consolidating path."""

    class S(pw.Schema, append_only=True):
        a: int

    class Src(pw.io.python.ConnectorSubject):
        def run(self):
            for i in range(50):
                self.next(a=i)

    received = []
    t = pw.io.python.read(Src(), schema=S)
    assert t.is_append_only
    pw.io.subscribe(
        t,
        on_change=lambda key, row, time, is_addition: received.append(
            (row["a"], 1 if is_addition else -1)
        ),
    )
    pw.run()
    assert sorted(v for v, _ in received) == list(range(50))
    assert all(d == 1 for _, d in received)


def test_append_only_source_rejects_retraction():
    # direct engine-level check: feed_batch refuses diff != 1
    g = df.EngineGraph()
    n = df.SessionSourceNode(g)
    n.append_only = True
    with pytest.raises(df.EngineError, match="append_only"):
        n.feed_batch([(1, ("x",), 1), (2, ("y",), -1)], 0)
    # and keeps no old-value state on the clean path
    n.feed_batch([(1, ("x",), 1), (2, ("y",), 1)], 0)
    assert n.state == {}


def test_append_only_with_primary_key_runs_clean():
    """A primary-keyed append-only schema must not trip the engine's
    no-upsert guard: pk rows skip the upsert protocol entirely."""

    class S(pw.Schema, append_only=True):
        k: int = pw.column_definition(primary_key=True)
        v: str

    class Src(pw.io.python.ConnectorSubject):
        def run(self):
            for i in range(10):
                self.next(k=i, v=f"row{i}")

    received = []
    t = pw.io.python.read(Src(), schema=S)
    assert t.is_append_only
    pw.io.subscribe(
        t,
        on_change=lambda key, row, time, is_addition: received.append(
            (row["k"], is_addition)
        ),
    )
    pw.run()
    assert sorted(k for k, _ in received) == list(range(10))
    assert all(add for _, add in received)


def test_ix_lookup_is_not_append_only():
    """ix() joins against another table that can retract — an expression
    containing it must never be marked append-only even when the key
    expression is."""
    src = _static()
    other = pw.debug.table_from_markdown(
        """
          | w | __time__ | __diff__
        1 | 5 | 2        | 1
        1 | 6 | 4        | 1
        1 | 5 | 4        | -1
        """
    )
    from pathway_tpu.internals.expression import IxExpression
    from pathway_tpu.internals.table import _expr_append_only

    e = IxExpression(other, src.id, "w", optional=True)
    assert not _expr_append_only(e)


def test_append_only_scanner_connector_runs_clean(tmp_path):
    """File-scanner connectors speak the upsert wire protocol (diff=2)
    even for fresh rows — an append-only schema must treat those as
    inserts, not crash (review finding r5)."""

    class S(pw.Schema, append_only=True):
        a: int
        b: str

    import json as _json

    with open(tmp_path / "rows.jsonl", "w") as f:
        for i in range(5):
            f.write(_json.dumps({"a": i, "b": f"r{i}"}) + "\n")

    t = pw.io.jsonlines.read(str(tmp_path), schema=S, mode="static")
    assert t.is_append_only
    keys, cols = pw.debug.table_to_dicts(t.select(a=pw.this.a))
    assert sorted(cols["a"][k] for k in keys) == list(range(5))


def test_append_only_scanner_streaming_upsert_markers():
    """Engine-level: fresh diff=2 markers pass the append-only fast path
    as inserts, RE-EMITTED keys are dropped (scanners re-emit a whole
    file's keys when its mtime changes), and deletions are refused."""
    g = df.EngineGraph()
    n = df.SessionSourceNode(g)
    n.append_only = True
    out = n.feed_batch([(1, ("x",), 2), (2, ("y",), 1)], 0)
    assert [(k, d) for k, _r, d in out] == [(1, 1), (2, 1)]
    assert n.state == {}
    # scanner rescan: keys 1,2 again plus a genuinely new key 3
    out2 = n.feed_batch([(1, ("x",), 2), (2, ("y",), 2), (3, ("z",), 2)], 2)
    assert [(k, d) for k, _r, d in out2] == [(3, 1)]
    with pytest.raises(df.EngineError, match="append_only"):
        n.feed_batch([(4, None, 2)], 4)


def test_append_only_file_append_no_duplicates(tmp_path):
    """Appending lines to a watched file must deliver ONLY the new rows
    once, not re-deliver old ones (review finding r5)."""
    import json as _json
    import threading
    import time as _time

    class S(pw.Schema, append_only=True):
        a: int

    d = tmp_path / "in"
    d.mkdir()
    with open(d / "rows.jsonl", "w") as f:
        for i in range(3):
            f.write(_json.dumps({"a": i}) + "\n")

    got = []
    t = pw.io.jsonlines.read(
        str(d), schema=S, mode="streaming", autocommit_duration_ms=100
    )
    assert t.is_append_only
    pw.io.subscribe(
        t,
        on_change=lambda key, row, time, is_addition: got.append(
            (row["a"], is_addition)
        ),
    )

    from pathway_tpu.internals.graph_runner import GraphRunner
    from pathway_tpu.internals.parse_graph import G

    runner = GraphRunner()
    for spec in list(G.subscriptions):
        runner.subscribe(spec["table"], on_change=spec.get("on_change"))

    def mutate():
        _time.sleep(1.0)
        with open(d / "rows.jsonl", "a") as f:
            for i in range(3, 6):
                f.write(_json.dumps({"a": i}) + "\n")
        deadline = _time.monotonic() + 20
        while _time.monotonic() < deadline and len(got) < 6:
            _time.sleep(0.1)
        _time.sleep(0.6)  # a re-scan tick — would surface duplicates
        runner.engine.stop()

    th = threading.Thread(target=mutate, daemon=True)
    th.start()
    runner.run()
    th.join(timeout=10)
    pw.clear_graph()

    assert sorted(v for v, _ in got) == list(range(6)), got
    assert all(add for _, add in got)


def test_append_only_pipeline_end_to_end():
    """Full run through select+filter with append-only sinks gives the
    same results as the consolidating path."""
    t = _static()
    out = t.filter(pw.this.amount >= 10).select(
        name=pw.this.name, double=pw.this.amount * 2
    )
    assert out.is_append_only
    keys, cols = pw.debug.table_to_dicts(out)
    got = {cols["name"][k]: cols["double"][k] for k in keys}
    assert got == {"alice": 20, "bob": 40}
