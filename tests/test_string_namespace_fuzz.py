"""String expression namespace vs Python's own str semantics: every
``.str`` method runs over a fuzzed corpus through the FULL engine
(columnar evaluators + fallback) and must agree cell-for-cell with the
plain Python call — the oracle style the reference gets from its
per-method expression tests (reference internals/expressions/string.py)."""

from __future__ import annotations

import numpy as np
import pytest

import pathway_tpu as pw

from .utils import run_table

CORPUS = [
    "",
    " ",
    "abc",
    "  padded  ",
    "MiXeD CaSe",
    "tab\tsep",
    "ünïcödé Straße",
    "a,b,,c",
    "  lead",
    "trail  ",
    "UPPER",
    "lower",
    "12345",
    "-17",
    "3.5",
    "true",
    "prefix_mid_suffix",
    "aaabbbaaa",
]


def _table():
    return pw.debug.table_from_rows(
        pw.schema_from_types(s=str), [(c,) for c in CORPUS]
    )


# (method-name, engine-expression builder, python oracle)
CASES = [
    ("lower", lambda c: c.str.lower(), lambda s: s.lower()),
    ("upper", lambda c: c.str.upper(), lambda s: s.upper()),
    ("reversed", lambda c: c.str.reversed(), lambda s: s[::-1]),
    ("len", lambda c: c.str.len(), lambda s: len(s)),
    ("strip", lambda c: c.str.strip(), lambda s: s.strip()),
    ("strip_chars", lambda c: c.str.strip("a "), lambda s: s.strip("a ")),
    ("lstrip", lambda c: c.str.lstrip(), lambda s: s.lstrip()),
    ("rstrip", lambda c: c.str.rstrip(), lambda s: s.rstrip()),
    ("startswith", lambda c: c.str.startswith("a"), lambda s: s.startswith("a")),
    ("endswith", lambda c: c.str.endswith("  "), lambda s: s.endswith("  ")),
    ("count", lambda c: c.str.count("a"), lambda s: s.count("a")),
    ("count_rng", lambda c: c.str.count("a", 1, 7), lambda s: s.count("a", 1, 7)),
    ("find", lambda c: c.str.find("b"), lambda s: s.find("b")),
    ("rfind", lambda c: c.str.rfind("a"), lambda s: s.rfind("a")),
    ("replace", lambda c: c.str.replace("a", "X"), lambda s: s.replace("a", "X")),
    (
        "replace_n",
        lambda c: c.str.replace("a", "X", 2),
        lambda s: s.replace("a", "X", 2),
    ),
    ("split", lambda c: c.str.split(","), lambda s: tuple(s.split(","))),
    ("title", lambda c: c.str.title(), lambda s: s.title()),
    ("capitalize", lambda c: c.str.capitalize(), lambda s: s.capitalize()),
    ("casefold", lambda c: c.str.casefold(), lambda s: s.casefold()),
    ("swapcase", lambda c: c.str.swapcase(), lambda s: s.swapcase()),
    ("ljust", lambda c: c.str.ljust(12, "."), lambda s: s.ljust(12, ".")),
    ("rjust", lambda c: c.str.rjust(12, "."), lambda s: s.rjust(12, ".")),
    ("zfill", lambda c: c.str.zfill(8), lambda s: s.zfill(8)),
    (
        "removeprefix",
        lambda c: c.str.removeprefix("pre"),
        lambda s: s.removeprefix("pre"),
    ),
    (
        "removesuffix",
        lambda c: c.str.removesuffix("fix"),
        lambda s: s.removesuffix("fix"),
    ),
    ("slice", lambda c: c.str.slice(1, 5), lambda s: s[1:5]),
    ("to_bytes", lambda c: c.str.to_bytes(), lambda s: s.encode()),
    ("to_string", lambda c: c.str.to_string(), lambda s: str(s)),
]


@pytest.mark.parametrize("name,build,oracle", CASES, ids=[c[0] for c in CASES])
def test_str_method_matches_python(name, build, oracle):
    t = _table()
    out = t.select(s=pw.this.s, r=build(t.s))
    state = run_table(out)
    got = {s: r for s, r in state.values()}
    want = {s: oracle(s) for s in CORPUS}
    # engine may represent lists as tuples; normalize
    norm = lambda v: tuple(v) if isinstance(v, (list, tuple)) else v
    mism = {
        s: (norm(got[s]), norm(want[s]))
        for s in CORPUS
        if norm(got[s]) != norm(want[s])
    }
    assert not mism, f"{name}: {mism}"
    pw.clear_graph()


def test_parse_int_float_bool():
    t = pw.debug.table_from_rows(
        pw.schema_from_types(s=str), [("17",), ("-3",), ("0",)]
    )
    out = t.select(v=t.s.str.parse_int())
    assert sorted(v[0] for v in run_table(out).values()) == [-3, 0, 17]
    pw.clear_graph()

    t2 = pw.debug.table_from_rows(
        pw.schema_from_types(s=str), [("3.5",), ("-0.25",)]
    )
    out2 = t2.select(v=t2.s.str.parse_float())
    assert sorted(v[0] for v in run_table(out2).values()) == [-0.25, 3.5]
    pw.clear_graph()

    t3 = pw.debug.table_from_rows(
        pw.schema_from_types(s=str), [("on",), ("no",), ("true",)]
    )
    out3 = t3.select(v=t3.s.str.parse_bool())
    assert sorted(v[0] for v in run_table(out3).values()) == [False, True, True]
    pw.clear_graph()


@pytest.mark.parametrize("seed", [0, 1])
def test_str_chained_random_pipelines(seed):
    """Random 3-deep chains of string methods agree with the same chain
    of Python calls."""
    rng = np.random.default_rng(seed)
    chain_pool = [
        (lambda e: e.str.lower(), lambda s: s.lower()),
        (lambda e: e.str.strip(), lambda s: s.strip()),
        (lambda e: e.str.replace("a", "b"), lambda s: s.replace("a", "b")),
        (lambda e: e.str.title(), lambda s: s.title()),
        (lambda e: e.str.slice(0, 6), lambda s: s[0:6]),
        (lambda e: e.str.swapcase(), lambda s: s.swapcase()),
    ]
    picks = [chain_pool[int(i)] for i in rng.integers(0, len(chain_pool), 3)]
    t = _table()
    e = t.s
    for b, _ in picks:
        e = b(e)
    out = t.select(s=pw.this.s, r=e)
    state = run_table(out)
    for s, r in state.values():
        w = s
        for _, o in picks:
            w = o(w)
        assert r == w, (s, r, w)
    pw.clear_graph()
