"""Two-tier index (HBM hot tier over a host cold tier): equivalence,
recall, snapshot, chaos, and pw.run wiring.

The invariants under test mirror the flat-index guarantees:

- tiering OFF or everything fits hot -> bit-identical to the flat
  DeviceKnnIndex (same keys, same float scores, same metrics stream);
- full-recall settings (f32 cold tier, probe >= n_clusters) -> same
  answer set as flat brute force under arbitrary add/remove/re-add
  churn and forced demotion, scores equal to float tolerance;
- int8 cold tier keeps recall@10 above the floor when the whole
  corpus is forcibly demoted;
- tier_state()/restore_tier_state() round-trips the exact hot/cold
  assignment, not a re-clustered approximation;
- a crash mid-promotion (chaos site ``index.tier.promote``) never
  loses a vector and never answers a key twice.
"""

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.ops.index_metrics import INDEX_METRICS
from pathway_tpu.ops.knn import DeviceKnnIndex
from pathway_tpu.ops.tiered_knn import (
    ColdStore,
    TierConfig,
    TieredKnnIndex,
    active_tiers,
    cold_row_bytes,
    hot_row_bytes,
    parse_bytes,
    parse_tier_spec,
    quantize_int8,
)
from pathway_tpu.resilience import chaos
from pathway_tpu.resilience.chaos import ChaosInjected


@pytest.fixture(autouse=True)
def _reset_index_plane():
    yield
    INDEX_METRICS.reset()
    from pathway_tpu.internals import flight_recorder

    flight_recorder.RECORDER.clear()


def _rows(rows):
    return [[(k, round(float(s), 4)) for k, s in row] for row in rows]


def _clustered(rng, n_docs, dim=32, n_centers=64, n_queries=16):
    """Cluster structure with rank gaps above the int8 noise floor."""
    centers = rng.normal(size=(n_centers, dim)).astype(np.float32) * 2.0
    assign = rng.integers(0, n_centers, size=n_docs)
    vecs = (centers[assign] + rng.normal(size=(n_docs, dim))).astype(np.float32)
    qs = (
        centers[rng.integers(0, n_centers, size=n_queries)]
        + rng.normal(size=(n_queries, dim))
    ).astype(np.float32)
    return vecs, qs


def _full_recall_cfg(**kw):
    """Settings where tiering can lose nothing: exact f32 cold vectors
    and every cluster probed."""
    kw.setdefault("n_clusters", 8)
    kw.setdefault("n_probe", 8)
    kw.setdefault("cold_dtype", "f32")
    return TierConfig(**kw)


# ------------------------------------------------------------- spec parsing


def test_parse_tier_spec_forms():
    assert parse_tier_spec(None) is None
    assert parse_tier_spec("off") is None
    assert parse_tier_spec(False) is None
    for on in (True, "on", "auto"):
        assert isinstance(parse_tier_spec(on), TierConfig)
    cfg = parse_tier_spec("hot=4096,clusters=32,probe=8,cold=int8,hbm=4G")
    assert cfg.hot_rows == 4096
    assert cfg.n_clusters == 32 and cfg.n_probe == 8
    assert cfg.cold_dtype == "int8"
    assert cfg.hbm_bytes == 4 * 1024**3
    assert parse_tier_spec(4096).hot_rows == 4096
    assert parse_tier_spec({"hot_rows": 16}).hot_rows == 16
    got = parse_tier_spec(cfg)
    assert got == cfg
    for bad in ("hot=", "nope=3", "hot=-1", 3.5, {"n_probe": 0}):
        with pytest.raises(ValueError):
            parse_tier_spec(bad)
    assert parse_bytes("512M") == 512 * 1024**2


def test_footprint_math():
    # f32 hot row: dim floats + key/valid bookkeeping; int8 cold row:
    # dim bytes + one f32 scale
    assert hot_row_bytes(384, "f32") == 384 * 4 + 5
    assert cold_row_bytes(384, "int8") == 384 + 4
    assert cold_row_bytes(384, "f32") == 384 * 4
    cfg = TierConfig(hbm_bytes=hot_row_bytes(384) * 1000)
    assert cfg.resolve_hot_rows(384) == 1000


def test_int8_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    v = rng.normal(size=(64, 48)).astype(np.float32)
    v /= np.linalg.norm(v, axis=1, keepdims=True)
    q, scale = quantize_int8(v)
    assert q.dtype == np.int8
    back = q.astype(np.float32) * (scale[:, None] / 127.0)
    assert float(np.abs(back - v).max()) <= float(scale.max()) / 127.0 + 1e-7


def test_cold_store_put_fetch_erase_grow():
    rng = np.random.default_rng(1)
    store = ColdStore(dim=8, dtype="f32", capacity=4)
    v = rng.normal(size=(10, 8)).astype(np.float32)
    slots = store.put(v)  # forces growth past the initial capacity
    np.testing.assert_allclose(store.fetch(slots), v, atol=1e-6)
    store.erase(slots[:5])
    again = store.put(v[:5])
    assert set(map(int, again)) == set(map(int, slots[:5]))


# ------------------------------------------------------- flat equivalence


@pytest.mark.parametrize("metric", ["cos", "l2", "ip"])
def test_fits_hot_bit_identical_to_flat(metric):
    """When the corpus fits in the hot tier the tiered index IS the
    flat index: same keys AND bit-equal scores."""
    rng = np.random.default_rng(3)
    vecs = rng.normal(size=(60, 16)).astype(np.float32)
    flat = DeviceKnnIndex(dim=16, metric=metric, reserved_space=64)
    tier = TieredKnnIndex(
        dim=16, metric=metric, reserved_space=64, tiers=_full_recall_cfg()
    )
    for i in range(60):
        flat.add(i, vecs[i], {"i": i})
        tier.add(i, vecs[i], {"i": i})
    assert tier.cold_docs() == 0
    q = rng.normal(size=(7, 16)).astype(np.float32)
    rf = flat.search_batch(q, 5)
    rt = tier.search_batch(q, 5)
    assert [[(k, float(s)) for k, s in row] for row in rf] == [
        [(k, float(s)) for k, s in row] for row in rt
    ]


@pytest.mark.parametrize("metric", ["cos", "l2"])
def test_churn_equivalence_at_full_recall(metric):
    """Adds, removes, re-adds, and a forced demotion of every cluster:
    at full-recall settings the tiered answers match flat brute force
    (scores to f32 tolerance; key order can differ only on ties)."""
    rng = np.random.default_rng(4)
    n = 160
    vecs, qs = _clustered(rng, n, dim=16, n_centers=12, n_queries=9)
    flat = DeviceKnnIndex(dim=16, metric=metric, reserved_space=64)
    tier = TieredKnnIndex(
        dim=16,
        metric=metric,
        reserved_space=64,
        tiers=_full_recall_cfg(hot_rows=64),
    )
    for i in range(n):
        flat.add(i, vecs[i])
        tier.add(i, vecs[i])
    # churn: retract every third key, re-add a rotated payload for some
    for i in range(0, n, 3):
        flat.remove(i)
        tier.remove(i)
    for i in range(0, n, 6):
        flat.add(i, np.roll(vecs[i], 1))
        tier.add(i, np.roll(vecs[i], 1))
    assert len(flat) == len(tier)
    tier.force_demote()
    assert tier.hot_docs() == 0 and tier.cold_docs() == len(flat)

    rf = flat.search_batch(qs, 5)
    rt = tier.search_batch(qs, 5)
    for row_f, row_t in zip(rf, rt):
        sf = np.asarray([s for _, s in row_f])
        st = np.asarray([s for _, s in row_t])
        np.testing.assert_allclose(st, sf, rtol=1e-5, atol=1e-5)
        if not np.isclose(sf[:-1], sf[1:]).any():
            assert [k for k, _ in row_f] == [k for k, _ in row_t]


def test_recall_floor_under_forced_demotion_int8():
    """Everything demoted to the int8 cold tier: recall@10 against
    exact flat brute force stays above the 0.95 floor."""
    rng = np.random.default_rng(5)
    vecs, qs = _clustered(rng, 4000, dim=96, n_centers=128, n_queries=32)
    keys = list(range(len(vecs)))
    flat = DeviceKnnIndex(dim=96, metric="cos", reserved_space=4096)
    flat.add_batch_arrays(keys, vecs)
    truth = [set(k for k, _ in row) for row in flat.search_batch(qs, 10)]

    tier = TieredKnnIndex(
        dim=96,
        metric="cos",
        reserved_space=4096,
        tiers=TierConfig(n_clusters=16, n_probe=12, cold_dtype="int8"),
    )
    tier.add_batch_arrays(keys, vecs)
    tier.force_demote()
    assert tier.hot_docs() == 0 and tier.cold_docs() == 4000
    got = tier.search_batch(qs, 10)
    recall = np.mean(
        [len(truth[i] & {k for k, _ in got[i]}) / 10 for i in range(len(qs))]
    )
    assert recall >= 0.95, f"recall@10 {recall:.3f} under forced demotion"


def test_promotion_restores_hot_residency():
    """After force_demote, queries hitting cold clusters drive the
    rebalance loop to promote them back while shard room lasts."""
    rng = np.random.default_rng(6)
    vecs, qs = _clustered(rng, 120, dim=16, n_centers=6, n_queries=4)
    tier = TieredKnnIndex(
        dim=16,
        metric="cos",
        reserved_space=128,
        tiers=_full_recall_cfg(n_clusters=6, n_probe=6, promote_every=4),
    )
    tier.add_batch_arrays(list(range(120)), vecs)
    tier.force_demote()
    assert tier.cold_docs() == 120
    for _ in range(12):
        tier.search_batch(qs, 5)
    tier.maybe_rebalance(force=True)
    assert tier.hot_docs() > 0, "no cluster promoted despite hits + room"
    snap = INDEX_METRICS.snapshot()["indexes"][tier.name]["tiers"]
    assert snap["promotions"] >= 1 and snap["demotions"] >= 1


# ------------------------------------------------------- snapshot/restore


def test_snapshot_restore_preserves_tier_assignment():
    rng = np.random.default_rng(8)
    vecs, qs = _clustered(rng, 90, dim=16, n_centers=8, n_queries=5)
    src = TieredKnnIndex(
        dim=16, metric="cos", reserved_space=48, tiers=_full_recall_cfg(hot_rows=48)
    )
    src.add_batch_arrays(list(range(90)), vecs, [{"i": i} for i in range(90)])
    src.force_demote([0, 1])  # mixed residency, not all-hot / all-cold
    want_hot = set(src.hot._slot_of)
    want_cluster = dict(src._cluster_of)
    ref = src.search_batch(qs, 5)

    state = src.tier_state()
    dst = TieredKnnIndex(
        dim=16, metric="cos", reserved_space=48, tiers=_full_recall_cfg(hot_rows=48)
    )
    dst.restore_tier_state(state)
    # replay the engine's restore order: bulk re-add, then tier fixup
    dst.add_batch_arrays(
        list(range(90)), vecs, [{"i": i} for i in range(90)]
    )
    dst.finish_tier_restore()

    assert dict(dst._cluster_of) == want_cluster
    assert set(dst.hot._slot_of) == want_hot
    assert dst.cold_docs() == src.cold_docs()
    assert _rows(dst.search_batch(qs, 5)) == _rows(ref)


# ------------------------------------------------------------------ chaos


def test_chaos_mid_promotion_no_loss_no_dups():
    """Kill the promotion between its two hot-insert chunks: every key
    stays findable exactly once (the cold listing is only cleared after
    the hot copy lands, and the merge dedups hot-resident keys)."""
    rng = np.random.default_rng(9)
    vecs, qs = _clustered(rng, 100, dim=16, n_centers=4, n_queries=4)
    tier = TieredKnnIndex(
        dim=16,
        metric="cos",
        reserved_space=128,
        tiers=_full_recall_cfg(n_clusters=4, n_probe=4),
    )
    tier.add_batch_arrays(list(range(100)), vecs)
    tier.force_demote()
    for _ in range(8):
        tier.search_batch(qs, 5)
    chaos.activate([{"site": "index.tier.promote", "hit": 2, "action": "raise"}])
    try:
        with pytest.raises(ChaosInjected):
            tier.maybe_rebalance(force=True)
    finally:
        chaos.deactivate()
    # torn state is allowed (some keys live in BOTH tiers) but answers
    # must cover every key exactly once
    assert 0 < tier.hot_docs() < 100, "chaos window missed the promotion"
    got = tier.search_batch(
        np.asarray(vecs, np.float32), 1
    )  # each doc's own vector must find exactly itself at k=1
    found = [row[0][0] for row in got if row]
    assert sorted(found) == list(range(100))
    seen: set = set()
    for row in tier.search_batch(qs, 100):
        keys = [k for k, _ in row]
        assert len(keys) == len(set(keys)), "duplicate key in one answer"
        seen.update(keys)
    assert seen == set(range(100))
    # the next rebalance completes the torn promotion idempotently
    tier.maybe_rebalance(force=True)
    assert tier.hot_docs() + tier.cold_docs() == 100


# ------------------------------------------------------- metrics plumbing


def test_flat_metrics_stream_untouched():
    """With no tiered index in the process the metrics text contains no
    tier series and tiered_active() stays False — flat deployments get
    byte-identical scrape output."""
    from pathway_tpu.internals.http_monitoring import MonitoringHttpServer

    INDEX_METRICS.reset()
    rng = np.random.default_rng(10)
    idx = DeviceKnnIndex(dim=8, metric="cos", reserved_space=32, name="flatonly")
    for i in range(10):
        idx.add(i, rng.normal(size=8).astype(np.float32))
    idx.search_batch(rng.normal(size=(2, 8)).astype(np.float32), 3)
    assert not INDEX_METRICS.tiered_active()
    text = "\n".join(MonitoringHttpServer._index_lines())
    assert "pathway_index_docs" in text
    assert "pathway_index_tier" not in text
    assert "tiers" not in INDEX_METRICS.snapshot()["indexes"]["flatonly"]


def test_tier_metrics_rendered_and_imbalance_counts_cold():
    from pathway_tpu.internals import flight_recorder
    from pathway_tpu.internals.http_monitoring import MonitoringHttpServer

    INDEX_METRICS.reset()
    flight_recorder.RECORDER.clear()
    rng = np.random.default_rng(11)
    vecs, qs = _clustered(rng, 80, dim=16, n_centers=4, n_queries=3)
    tier = TieredKnnIndex(
        dim=16,
        metric="cos",
        reserved_space=96,
        tiers=_full_recall_cfg(n_clusters=4, n_probe=4),
        name="tiered",
    )
    tier.add_batch_arrays(list(range(80)), vecs)
    tier.force_demote()
    tier.search_batch(qs, 5)

    snap = INDEX_METRICS.snapshot()
    tiers = snap["indexes"]["tiered"]["tiers"]
    assert tiers["hot_docs"] == 0 and tiers["cold_docs"] == 80
    assert tiers["demotions"] >= 1
    assert tiers["cold_bytes"] == 80 * cold_row_bytes(16, "f32")
    assert 0.0 <= tiers["hot_hit_ratio"] <= 1.0
    assert snap["cold_fetch_seconds"]["count"] >= 1
    # a fully demoted single-shard index still reports its docs: the
    # docs gauge and imbalance count BOTH tiers
    assert snap["indexes"]["tiered"]["docs"] == 80
    assert tiers["cold_docs_shard"] == [80]

    text = "\n".join(MonitoringHttpServer._index_lines())
    for needle in (
        'pathway_index_tier_docs{index="tiered",shard="0",tier="cold"}',
        "pathway_index_tier_bytes",
        "pathway_index_tier_promotions_total",
        "pathway_index_tier_demotions_total",
        "pathway_index_tier_hot_hit_ratio",
        "pathway_index_tier_cold_fetch_seconds_bucket",
    ):
        assert needle in text

    kinds = [e["kind"] for e in flight_recorder.RECORDER.events()]
    assert "index.tier.demote" in kinds
    reb = [
        e
        for e in flight_recorder.RECORDER.events()
        if e["kind"] == "index.rebalance"
    ]
    assert reb and reb[-1]["docs"] == [80], "rebalance event ignored cold docs"
    assert reb[-1]["docs_cold"] == [80] and reb[-1]["docs_hot"] == [0]


# ---------------------------------------------------------- pw.run wiring


def _knn_pipeline(docs_v, qs_v, reserved=32):
    from pathway_tpu.stdlib.ml.index import KNNIndex

    docs = pw.debug.table_from_rows(
        pw.schema_from_types(i=int), [(i,) for i in range(len(docs_v))]
    )
    docs = docs.select(
        docs.i,
        emb=pw.apply_with_type(
            lambda i: tuple(map(float, docs_v[i])), pw.ANY, docs.i
        ),
    )
    queries = pw.debug.table_from_rows(
        pw.schema_from_types(i=int), [(i,) for i in range(len(qs_v))]
    )
    queries = queries.select(
        emb=pw.apply_with_type(
            lambda i: tuple(map(float, qs_v[i])), pw.ANY, queries.i
        )
    )
    index = KNNIndex(docs.emb, docs, n_dimensions=16, reserved_space=reserved)
    return index.get_nearest_items(
        queries.emb, k=3, collapse_rows=True, with_distances=True
    )


def _collect(res, **run_kwargs):
    rows = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[int(key)] = (tuple(row["i"]), tuple(row["dist"]))

    pw.io.subscribe(res, on_change=on_change)
    pw.run(**run_kwargs)
    return rows


def test_pw_run_index_tiers_end_to_end():
    """pw.run(index_tiers=...) serves the same answers as the flat run
    with zero query-API change, and the run-scoped config never leaks."""
    rng = np.random.default_rng(12)
    docs_v = rng.normal(size=(20, 16)).astype(np.float32)
    qs_v = rng.normal(size=(5, 16)).astype(np.float32)

    out_flat = _collect(_knn_pipeline(docs_v, qs_v))
    pw.clear_graph()
    out_tier = _collect(
        _knn_pipeline(docs_v, qs_v), index_tiers="hot=64,clusters=4,probe=4"
    )
    assert active_tiers() is None, "run-scoped tier config leaked"
    assert out_tier == out_flat
    assert len(out_tier) == 5


def test_pathway_index_tiers_env_and_run_context(monkeypatch):
    rng = np.random.default_rng(13)
    docs_v = rng.normal(size=(20, 16)).astype(np.float32)
    qs_v = rng.normal(size=(4, 16)).astype(np.float32)

    out_flat = _collect(_knn_pipeline(docs_v, qs_v))
    pw.clear_graph()
    # a hot tier smaller than the corpus: overflow serves from the f32
    # cold tier at full probe, answers still identical
    monkeypatch.setenv("PATHWAY_INDEX_TIERS", "hot=8,clusters=4,probe=4,cold=f32")
    out_env = _collect(_knn_pipeline(docs_v, qs_v))
    assert {k: v[0] for k, v in out_env.items()} == {
        k: v[0] for k, v in out_flat.items()
    }
    from pathway_tpu.internals.parse_graph import G

    assert G.run_context.get("index_tiers", {}).get("n_probe") == 4
