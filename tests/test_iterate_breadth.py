"""pw.iterate fixpoint breadth (reference internals tests for iterate:
collatz, connected components, iteration_limit, multi-table bodies)."""

from __future__ import annotations

import pytest

import pathway_tpu as pw

from .utils import T, run_table


def test_iterate_collatz_steps():
    """The reference's doc example: steps to reach 1."""

    def step(t):
        return t.select(
            n=pw.if_else(
                pw.this.n == 1,
                1,
                pw.if_else(pw.this.n % 2 == 0, pw.this.n // 2, 3 * pw.this.n + 1),
            ),
            steps=pw.if_else(pw.this.n == 1, pw.this.steps, pw.this.steps + 1),
        )

    t = T(
        """
      | n  | steps
    1 | 6  | 0
    2 | 27 | 0
    3 | 1  | 0
    """
    )
    res = pw.iterate(step, t=t)
    rows = list(run_table(res).values())
    assert all(r[0] == 1 for r in rows)  # every chain reached 1
    assert sorted(r[1] for r in rows) == [0, 8, 111]  # 6 -> 8, 27 -> 111


def test_iterate_min_propagation_components():
    """Connected components by min-label propagation over an edge list
    (constant within the fixpoint)."""

    def step(labels, edges):
        joined = edges.join(labels, edges.dst == labels.id_val).select(
            src=edges.src, lbl=labels.lbl
        )
        best = joined.groupby(pw.this.src).reduce(
            src=pw.this.src, m=pw.reducers.min(pw.this.lbl)
        )
        m = best.ix_ref(pw.this.id_val, optional=True).m
        cand = pw.coalesce(m, pw.this.lbl)
        updated = labels.select(
            id_val=pw.this.id_val,
            lbl=pw.if_else(cand < pw.this.lbl, cand, pw.this.lbl),
        )
        return dict(labels=updated)

    labels = T(
        """
      | id_val | lbl
    1 | 1      | 1
    2 | 2      | 2
    3 | 3      | 3
    4 | 4      | 4
    """
    )
    edges = T(
        """
      | src | dst
    7 | 2   | 1
    8 | 3   | 2
    9 | 1   | 2
    """
    )
    res = pw.iterate(step, labels=labels, edges=edges).labels
    rows = sorted(run_table(res).values())
    # component {1,2,3} converges to label 1; node 4 isolated
    assert rows == [(1, 1), (2, 1), (3, 1), (4, 4)]


def test_iterate_iteration_limit():
    def step(t):
        return t.select(n=pw.this.n * 2)

    t = T(
        """
      | n
    1 | 1
    """
    )
    res = pw.iterate(step, iteration_limit=3, t=t)
    ((n,),) = run_table(res).values()
    assert n == 8  # exactly 3 doublings, no fixpoint


def test_iterate_rejects_mismatched_columns():
    def step(t):
        return t.select(other=pw.this.n)

    t = T(
        """
      | n
    1 | 1
    """
    )
    with pytest.raises(ValueError, match="column"):
        pw.iterate(step, t=t)


def test_iterate_streamed_input_refixes():
    """A later epoch's input change re-runs the fixpoint incrementally."""

    def step(t):
        # saturate at 10: value grows toward the cap
        return t.select(n=pw.if_else(pw.this.n < 10, pw.this.n + 1, pw.this.n))

    t = T(
        """
      | n | __time__ | __diff__
    1 | 1 | 2        | 1
    2 | 3 | 4        | 1
    1 | 1 | 6        | -1
    """
    )
    res = pw.iterate(step, t=t)
    rows = sorted(run_table(res).values())
    assert rows == [(10,)]  # only row 2 remains, saturated
