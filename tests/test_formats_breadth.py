"""Parser/formatter breadth — the reference's Rust integration suites
(tests/integration/test_dsv.rs, test_jsonlines.rs, test_debezium.rs,
test_bson.rs) applied to io/_formats.py: round trips, malformed
payloads, envelope op coverage."""

from __future__ import annotations

import json

import pytest

from pathway_tpu.io._formats import (
    BsonFormatter,
    DebeziumMessageParser,
    DsvFormatter,
    DsvParser,
    IdentityParser,
    JsonLinesFormatter,
    JsonLinesParser,
    NullFormatter,
    PsqlSnapshotFormatter,
    PsqlUpdatesFormatter,
    SingleColumnFormatter,
    jsonable_value,
)


# ---- DSV -----------------------------------------------------------------


def test_dsv_header_then_rows():
    p = DsvParser()
    assert p.parse("a,b,c") == []  # header consumed
    assert p.parse("1,2,3") == [("insert", {"a": "1", "b": "2", "c": "3"})]
    assert p.parse(b"4,5,6\r\n") == [("insert", {"a": "4", "b": "5", "c": "6"})]


def test_dsv_explicit_fields_and_separator():
    p = DsvParser(field_names=["x", "y"], separator="|")
    assert p.parse("1|2") == [("insert", {"x": "1", "y": "2"})]


def test_dsv_field_count_mismatch_raises():
    p = DsvParser(field_names=["x", "y"])
    with pytest.raises(ValueError, match="fields"):
        p.parse("1,2,3".replace(",", ","))


def test_dsv_formatter_roundtrip():
    f = DsvFormatter(["a", "b"], separator=";")
    assert f.header() == "a;b;time;diff"
    line = f.format({"a": 1, "b": "x"}, 4, -1)
    assert line == "1;x;4;-1"
    p = DsvParser(separator=";")
    p.parse(f.header())
    ((op, rec),) = p.parse(line)
    assert op == "insert" and rec["a"] == "1" and rec["diff"] == "-1"


# ---- JsonLines -----------------------------------------------------------


def test_jsonlines_parser_field_projection():
    p = JsonLinesParser(field_names=["a", "b"])
    ((op, rec),) = p.parse('{"a": 1, "b": 2, "junk": 3}')
    assert op == "insert" and rec == {"a": 1, "b": 2}
    ((_, rec2),) = p.parse('{"a": 7}')
    assert rec2 == {"a": 7, "b": None}


def test_jsonlines_parser_rejects_non_object():
    p = JsonLinesParser()
    with pytest.raises(ValueError):
        p.parse("[1, 2, 3]")
    with pytest.raises(json.JSONDecodeError):
        p.parse("{not json")


def test_jsonlines_formatter_roundtrip():
    f = JsonLinesFormatter(["a", "s"])
    line = f.format({"a": 1, "s": "x"}, 2, 1)
    back = json.loads(line)
    assert back == {"a": 1, "s": "x", "time": 2, "diff": 1}
    p = JsonLinesParser()
    ((_, rec),) = p.parse(line)
    assert rec["a"] == 1


# ---- Identity ------------------------------------------------------------


def test_identity_parser_bytes_and_str():
    pb = IdentityParser(as_bytes=True)
    ((_, r1),) = pb.parse("abc")
    assert r1 == {"data": b"abc"}
    ps = IdentityParser(as_bytes=False, column="text")
    ((_, r2),) = ps.parse(b"xyz")
    assert r2 == {"text": "xyz"}


# ---- Debezium ------------------------------------------------------------


def _dbz(op, before=None, after=None):
    return json.dumps({"payload": {"op": op, "before": before, "after": after}})


def test_debezium_create_update_delete_postgres():
    p = DebeziumMessageParser()
    assert p.parse(None, _dbz("c", after={"id": 1, "v": "a"})) == [
        ("insert", {"id": 1, "v": "a"}, None)
    ]
    got = p.parse(None, _dbz("u", before={"id": 1, "v": "a"}, after={"id": 1, "v": "b"}))
    assert got == [
        ("delete", {"id": 1, "v": "a"}, None),
        ("insert", {"id": 1, "v": "b"}, None),
    ]
    assert p.parse(None, _dbz("d", before={"id": 1, "v": "b"})) == [
        ("delete", {"id": 1, "v": "b"}, None)
    ]


def test_debezium_snapshot_read_and_tombstone():
    p = DebeziumMessageParser()
    assert p.parse(None, _dbz("r", after={"id": 2})) == [("insert", {"id": 2}, None)]
    assert p.parse(None, None) == []  # Kafka tombstone


def test_debezium_mongodb_upserts():
    p = DebeziumMessageParser(db_type="mongodb")
    assert p.session_type == "upsert"
    got = p.parse(None, _dbz("u", after={"id": 1, "v": "new"}))
    assert got == [("upsert", {"id": 1, "v": "new"}, None)]
    # key payloads route through: the envelope key becomes key_values
    got = p.parse(json.dumps({"payload": {"id": 1}}), _dbz("d"))
    assert got == [("upsert", None, {"id": 1})]


# ---- Psql formatters -----------------------------------------------------


def test_psql_updates_formatter_sql_shape():
    f = PsqlUpdatesFormatter("tbl", ["a", "b"])
    sql, params = f.format({"a": 1, "b": "x"}, 3, 1)
    assert sql.startswith("INSERT INTO tbl (a,b,time,diff)")
    assert params == (1, "x")


def test_psql_snapshot_formatter_upsert_and_delete():
    f = PsqlSnapshotFormatter("tbl", primary_key=["id"], field_names=["id", "v"])
    up = f.format({"id": 1, "v": "x"}, 2, 1)
    assert any("CONFLICT" in s.upper() or "UPDATE" in s.upper() for s, _ in [up])
    dl = f.format({"id": 1, "v": "x"}, 4, -1)
    assert "DELETE" in dl[0].upper()


# ---- Bson / SingleColumn / Null -----------------------------------------


def test_bson_formatter_document():
    f = BsonFormatter(["a", "s"])
    doc = f.format({"a": 1, "s": "x"}, 5, 1)
    assert doc["a"] == 1 and doc["s"] == "x"
    assert doc["time"] == 5 and doc["diff"] == 1


def test_single_column_and_null():
    s = SingleColumnFormatter("data")
    assert s.format({"data": b"zz"}, 0, 1) == b"zz"
    n = NullFormatter()
    assert n.format({"x": 1}, 0, 1) is None


def test_jsonable_value_covers_engine_types():
    import numpy as np

    from pathway_tpu.engine.value import Json, Pointer

    assert jsonable_value(np.int64(3)) == 3
    assert jsonable_value(np.float32(1.5)) == 1.5
    assert jsonable_value(Json({"a": 1})) == {"a": 1}
    assert isinstance(jsonable_value(Pointer(123)), (str, int))
    assert jsonable_value((1, 2)) == [1, 2]
    assert jsonable_value(b"ab") is not None
