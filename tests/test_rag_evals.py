"""RAG quality evaluation (reference integration_tests/rag_evals): a
corpus + question set run through DocumentStore retrieval with the REAL
JAX sentence encoder (seeded init, CPU), scoring hit-rate@k / MRR /
answer term coverage.  This is the regression gate no throughput test
provides — a broken tokenizer, pooling, normalization, or index path
shows up as a hit-rate drop (demonstrated below with a degenerate
embedder)."""

from __future__ import annotations

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.stdlib.indexing import BruteForceKnnFactory
from pathway_tpu.xpacks.llm import DocumentStore
from pathway_tpu.xpacks.llm.rag_evals import (
    EvalCase,
    evaluate_document_store,
    extractive_answerer,
)

from .mocks import make_docs_table

CORPUS = [
    (
        "The systolic array in a TPU multiplies matrices by streaming weights"
        " diagonally through a grid of multiply-accumulate cells.",
        "/corpus/tpu_systolic.txt",
    ),
    (
        "Kafka consumer groups rebalance partitions whenever a member joins"
        " or leaves the group.",
        "/corpus/kafka_rebalance.txt",
    ),
    (
        "Sourdough bread rises because wild yeast and lactobacilli ferment"
        " the dough overnight.",
        "/corpus/sourdough.txt",
    ),
    (
        "The Amazon river discharges more fresh water than the next seven"
        " largest rivers combined.",
        "/corpus/amazon_river.txt",
    ),
    (
        "Rust's borrow checker enforces aliasing rules at compile time"
        " preventing data races.",
        "/corpus/rust_borrow.txt",
    ),
    (
        "Honeybees communicate the direction of flowers with a waggle dance"
        " inside the hive.",
        "/corpus/honeybee.txt",
    ),
    (
        "A total solar eclipse occurs when the moon completely covers the"
        " solar disk.",
        "/corpus/eclipse.txt",
    ),
    (
        "Chess engines prune the game tree with alpha-beta search and"
        " evaluate leaf positions.",
        "/corpus/chess.txt",
    ),
    (
        "Photosynthesis converts carbon dioxide and water into glucose using"
        " sunlight in chloroplasts.",
        "/corpus/photosynthesis.txt",
    ),
    (
        "The Eiffel tower grows about fifteen centimetres taller in summer"
        " as iron expands.",
        "/corpus/eiffel.txt",
    ),
]

CASES = [
    EvalCase(
        "what happens when a kafka consumer joins a group?",
        "kafka_rebalance",
        ("rebalance", "partitions"),
    ),
    EvalCase(
        "why does sourdough bread rise overnight?",
        "sourdough",
        ("yeast", "ferment"),
    ),
    EvalCase(
        "which river discharges the most fresh water?",
        "amazon_river",
        ("Amazon",),
    ),
    EvalCase(
        "how does the rust borrow checker prevent data races?",
        "rust_borrow",
        ("aliasing", "compile time"),
    ),
    EvalCase(
        "how do honeybees communicate the direction of flowers?",
        "honeybee",
        ("waggle dance",),
    ),
    EvalCase(
        "when does a total solar eclipse occur?",
        "eclipse",
        ("moon", "solar disk"),
    ),
    EvalCase(
        "how do chess engines prune the game tree?",
        "chess",
        ("alpha-beta",),
    ),
    EvalCase(
        "what does photosynthesis convert sunlight into?",
        "photosynthesis",
        ("glucose",),
    ),
    EvalCase(
        "why is the eiffel tower taller in summer?",
        "eiffel",
        ("iron expands",),
    ),
    EvalCase(
        "how does the systolic array in a tpu multiply matrices?",
        "tpu_systolic",
        ("multiply-accumulate",),
    ),
]


def _store(embedder) -> DocumentStore:
    docs = make_docs_table(CORPUS)
    return DocumentStore(
        docs, retriever_factory=BruteForceKnnFactory(embedder=embedder)
    )


def _real_embedder():
    from pathway_tpu.xpacks.llm.embedders import SentenceTransformerEmbedder

    return SentenceTransformerEmbedder(max_batch_size=32)


@pytest.fixture(scope="module")
def real_report():
    report = evaluate_document_store(_store(_real_embedder()), CASES, k=3)
    pw.clear_graph()
    return report


def test_real_encoder_retrieval_quality(real_report):
    """The JAX encoder stack (tokenize → transformer → pool → normalize
    → index) must retrieve the right sources.  Deterministic: seeded
    init, CPU backend."""
    d = real_report.as_dict()
    assert real_report.n_cases == len(CASES)
    assert real_report.hit_rate >= 0.7, d
    assert real_report.mrr >= 0.5, d


def test_real_encoder_answer_term_coverage(real_report):
    """With the extractive answerer, term coverage measures whether the
    retrieved passages actually carry the facts the answer needs."""
    hits = [o for o in real_report.outcomes if o.hit]
    assert hits
    # every case whose source was retrieved must surface its facts
    assert all(o.term_coverage == 1.0 for o in hits), [
        (o.case.question, o.term_coverage) for o in hits
    ]


def test_eval_catches_broken_embedder(real_report):
    """The regression-gate property: a degenerate embedder (all texts
    embed almost identically — e.g. a normalization or pooling bug)
    must score clearly worse than the healthy stack."""

    @pw.udf
    def broken_embedder(x: str) -> np.ndarray:
        v = np.ones(8, dtype=np.float32)
        v[0] += 1e-3 * (len(x or "") % 7)  # barely distinguishable
        return v / np.linalg.norm(v)

    broken = evaluate_document_store(_store(broken_embedder), CASES, k=3)
    pw.clear_graph()
    # with ~identical embeddings, top-3 of 10 docs is essentially
    # arbitrary; the healthy encoder must dominate it
    assert broken.hit_rate <= 0.5
    assert real_report.hit_rate > broken.hit_rate
    assert real_report.mrr > broken.mrr


def test_report_shape_and_misses_listed(real_report):
    d = real_report.as_dict()
    assert set(d) == {"n_cases", "k", "hit_rate", "mrr", "term_coverage", "misses"}
    assert all(isinstance(q, str) for q in d["misses"])
    # outcomes carry the evidence needed to debug a miss
    out = real_report.outcomes[0]
    assert out.retrieved_files and isinstance(out.retrieved_files[0], str)
    assert extractive_answerer("q", ["a", "b"]) == "a\nb"
