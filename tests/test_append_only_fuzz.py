"""Append-only fast-path equivalence fuzz: random insert-only pipelines
run twice — once with the append-only proof wired through (sources skip
upsert state, sinks skip consolidation) and once with every fast-path
flag forced off — must produce byte-identical sink streams. The plan
analysis itself is also fuzzed: pipelines containing a retraction-capable
stage must never claim is_append_only."""

from __future__ import annotations

import numpy as np
import pytest

import pathway_tpu as pw


def _rand_rows(rng, n):
    return [
        {
            "k": int(i),
            "grp": f"g{int(rng.integers(0, 5))}",
            "x": int(rng.integers(-100, 100)),
            "s": "".join(rng.choice(list("abcdef"), size=4)),
        }
        for i in range(n)
    ]


def _rand_pipeline(rng, t):
    """Random chain of append-only-preserving row-wise stages."""
    n_stages = int(rng.integers(1, 4))
    for _ in range(n_stages):
        choice = int(rng.integers(0, 4))
        if choice == 0:
            t = t.filter(pw.this.x > int(rng.integers(-60, 30)))
        elif choice == 1:
            t = t.select(
                k=pw.this.k, grp=pw.this.grp, x=pw.this.x * 2, s=pw.this.s
            )
        elif choice == 2:
            t = t.with_columns(y=pw.this.x + 1)
        else:
            t = t.filter(pw.this.s < "e").select(
                k=pw.this.k, grp=pw.this.grp, x=pw.this.x, s=pw.this.s + "!"
            )
    return t


def _run_once(rows, seed, disable_fast_path):
    class S(pw.Schema, append_only=True):
        k: int = pw.column_definition(primary_key=True)
        grp: str
        x: int
        s: str

    class Src(pw.io.python.ConnectorSubject):
        def run(self):
            for i, r in enumerate(rows):
                self.next(**r)
                if i % 7 == 6:
                    self.commit()

    rng = np.random.default_rng(seed)
    t = pw.io.python.read(Src(), schema=S)
    out = _rand_pipeline(rng, t)
    assert out.is_append_only

    events = []
    pw.io.subscribe(
        out,
        on_change=lambda key, row, time, is_addition: events.append(
            (tuple(sorted(row.items())), is_addition)
        ),
    )
    if disable_fast_path:
        # force every append-only shortcut off at the engine layer (the
        # flags are set during lowering; flip them before running): the
        # general consolidating path must agree with the fast path
        from pathway_tpu.internals.graph_runner import GraphRunner
        from pathway_tpu.internals.parse_graph import G

        runner = GraphRunner()
        for spec in list(G.subscriptions):
            runner.subscribe(
                spec["table"],
                on_change=spec.get("on_change"),
                on_time_end=spec.get("on_time_end"),
                on_end=spec.get("on_end"),
            )
        for eng in [runner.engine] + [r.engine for r in runner._replicas]:
            for node in eng.nodes:
                node.append_only = False
        runner.run()
    else:
        pw.run()
    pw.clear_graph()
    return sorted(events)


@pytest.mark.parametrize("seed", [7, 23, 99])
def test_fast_path_equals_consolidating_path(seed):
    rng = np.random.default_rng(seed)
    rows = _rand_rows(rng, 60)
    fast = _run_once(rows, seed, disable_fast_path=False)
    slow = _run_once(rows, seed, disable_fast_path=True)
    assert fast == slow
    assert all(add for _, add in fast)  # append-only: inserts only


@pytest.mark.parametrize("seed", range(12))
def test_retraction_stages_never_claim_append_only(seed):
    """Soundness of the plan analysis: splice one retraction-capable
    stage into a random row-wise chain — is_append_only must be False."""
    rng = np.random.default_rng(1000 + seed)
    t = pw.debug.table_from_rows(
        pw.schema_from_types(k=int, grp=str, x=int, s=str),
        [(r["k"], r["grp"], r["x"], r["s"]) for r in _rand_rows(rng, 20)],
    )
    t = _rand_pipeline(rng, t)
    assert t.is_append_only  # row-wise chain over static rows

    breaker = int(rng.integers(0, 3))
    if breaker == 0:
        broken = t.groupby(pw.this.grp).reduce(
            grp=pw.this.grp, total=pw.reducers.sum(pw.this.x)
        )
        downstream = broken.filter(pw.this.total > -(10**9))
    elif breaker == 1:
        broken = t.deduplicate(value=pw.this.x)
        downstream = broken.filter(pw.this.x > -(10**9))
    else:
        broken = t.difference(t.filter(pw.this.x > 0))
        downstream = broken.filter(pw.this.x > -(10**9))
    assert not broken.is_append_only
    # and anything built on top stays non-append-only
    assert not downstream.is_append_only
