"""Graph algorithms + statistical/ordered/stateful stdlib.

Mirrors reference stdlib tests: graphs (pagerank, bellman_ford,
louvain), statistical interpolate, ordered diff, stateful deduplicate."""

from __future__ import annotations

import math

import pathway_tpu as pw
from .utils import T, run_table


def test_pagerank_star():
    # everybody links to hub
    edges = T(
        """
          | u | v
        1 | a | hub
        2 | b | hub
        3 | c | hub
        4 | hub | a
        """
    )
    edges = edges.select(
        u=edges.u, v=edges.v
    )
    ranks = pw.stdlib.graphs.pagerank(edges, steps=10)
    state = run_table(ranks)
    vals = sorted(r[0] for r in state.values())
    assert len(vals) == 4
    assert vals[-1] > vals[0]  # hub outranks the leaves
    pw.clear_graph()


def test_bellman_ford_shortest_paths():
    verts = T(
        """
          | name | is_source
        1 | s    | True
        2 | a    | False
        3 | b    | False
        4 | unreachable | False
        """
    )
    keyed = verts.with_id_from(pw.this.name)
    e0 = T(
        """
          | u | v | dist
        1 | s | a | 1.0
        2 | a | b | 2.0
        3 | s | b | 10.0
        """
    )
    edges = e0.select(
        u=keyed.pointer_from(e0.u),
        v=keyed.pointer_from(e0.v),
        dist=e0.dist,
    )
    res = pw.stdlib.graphs.bellman_ford(keyed, edges)
    state = run_table(res)
    names = run_table(keyed.select(name=pw.this.name))
    by_name = {names[k][0]: state[k][0] for k in names}
    assert by_name["s"] == 0.0
    assert by_name["a"] == 1.0
    assert by_name["b"] == 3.0  # via a, not the direct 10.0 edge
    assert math.isinf(by_name["unreachable"])
    pw.clear_graph()


def test_interpolate_linear():
    t = T(
        """
          | t | v
        1 | 0 | 0.0
        2 | 2 |
        3 | 4 | 4.0
        """
    )
    res = pw.stdlib.statistical.interpolate(
        t, pw.this.t, pw.this.v
    )
    state = run_table(res)
    vals = sorted((row[0], row[1]) for row in state.values())
    assert vals == [(0, 0.0), (2, 2.0), (4, 4.0)]
    pw.clear_graph()


def test_ordered_diff():
    t = T(
        """
          | t | v
        1 | 1 | 10
        2 | 2 | 15
        3 | 3 | 21
        """
    )
    res = pw.stdlib.ordered.diff(t, pw.this.t, pw.this.v)
    state = run_table(res)
    diffs = sorted(
        (row[0] for row in state.values()), key=lambda v: (v is None, repr(v))
    )
    assert diffs == [5, 6, None]
    pw.clear_graph()


def test_stateful_deduplicate():
    t = pw.debug.table_from_markdown(
        """
          | v  | __time__
        1 | 1  | 0
        2 | 1  | 2
        3 | 5  | 4
        4 | 4  | 6
        5 | 10 | 8
        """
    )
    # accept only values at least 2 greater than the last accepted
    res = pw.stdlib.stateful.deduplicate(
        t, col=pw.this.v, acceptor=lambda new, old: new >= old + 2
    )
    state = run_table(res)
    assert [row[0] for row in state.values()] == [10]
    pw.clear_graph()
