"""SharePoint connector (P30) — scanner diffs, size limits, retries,
static + streaming modes, all on an injectable fake Office365 client.

Mirrors the reference connector's behavior
(/root/reference/python/pathway/xpacks/connectors/sharepoint/__init__.py:84-229):
snapshot diffing against stored metadata, deletion retraction,
STATUS_SIZE_LIMIT_EXCEEDED payload skipping, bounded retry on scan
failure.
"""

from __future__ import annotations

import threading
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner
from pathway_tpu.xpacks.connectors.sharepoint import (
    STATUS_DOWNLOADED,
    STATUS_SIZE_LIMIT_EXCEEDED,
    _EntryMeta,
    _Scanner,
)


class FakeFile:
    def __init__(self, path, content, modified_at=100, created_at=50):
        self.path = path
        self._content = content
        self.size = len(content)
        self.created_at = created_at
        self.modified_at = modified_at
        self.reads = 0

    def read(self):
        self.reads += 1
        return self._content


class FakeContext:
    def __init__(self, files):
        self.files = list(files)
        self.scans = 0

    def list_files(self, root_path, recursive):
        self.scans += 1
        return list(self.files)


@pytest.fixture(autouse=True)
def _enterprise_license():
    pw.set_license_key("enterprise-test")
    yield
    pw.set_license_key(None)


def test_sharepoint_gated_by_license():
    pw.set_license_key(None)
    with pytest.raises(pw.LicenseError):
        pw.xpacks.connectors.sharepoint.read(
            "https://example.sharepoint.com/sites/S", root_path="Docs"
        )


def test_scanner_snapshot_diff_and_deletions():
    f1 = FakeFile("/sites/S/Docs/a.txt", b"alpha")
    f2 = FakeFile("/sites/S/Docs/b.txt", b"beta")
    ctx = FakeContext([f1, f2])
    stored: dict = {}
    scanner = _Scanner(ctx, "Docs", True, stored)

    updated, deleted = scanner.get_snapshot_diff()
    assert sorted(m.path for _, m in updated) == [f1.path, f2.path]
    assert deleted == []

    # unchanged second scan: nothing re-downloaded
    updated, deleted = scanner.get_snapshot_diff()
    assert updated == [] and deleted == []
    assert f1.reads == 1 and f2.reads == 1

    # modify one, delete the other
    f1.modified_at = 200
    ctx.files = [f1]
    updated, deleted = scanner.get_snapshot_diff()
    assert [m.path for _, m in updated] == [f1.path]
    assert deleted == [f2.path]
    assert f1.reads == 2


def test_scanner_partial_failure_does_not_lose_updates():
    """A payload fetch failing mid-scan must not mark earlier files of
    the same scan as ingested — the retry must re-emit them."""

    class FlakyFile(FakeFile):
        def __init__(self, *a):
            super().__init__(*a)
            self.fail_next = True

        def read(self):
            if self.fail_next:
                self.fail_next = False
                raise ConnectionError("transient")
            return super().read()

    good = FakeFile("/s/a", b"A")
    flaky = FlakyFile("/s/b", b"B")
    stored: dict = {}
    scanner = _Scanner(FakeContext([good, flaky]), "s", True, stored)
    with pytest.raises(ConnectionError):
        scanner.get_snapshot_diff()
    assert stored == {}, "failed scan leaked metadata"
    updated, deleted = scanner.get_snapshot_diff()
    assert sorted(m.path for _, m in updated) == ["/s/a", "/s/b"]


def test_scanner_size_limit_skips_payload():
    small = FakeFile("/s/a", b"ok")
    big = FakeFile("/s/b", b"x" * 1000)
    scanner = _Scanner(FakeContext([small, big]), "s", True, {}, object_size_limit=10)
    updated, _ = scanner.get_snapshot_diff()
    by_path = {m.path: (payload, m) for payload, m in updated}
    assert by_path["/s/a"][0] == b"ok"
    assert by_path["/s/a"][1].status == STATUS_DOWNLOADED
    assert by_path["/s/b"][0] == b""
    assert by_path["/s/b"][1].status == STATUS_SIZE_LIMIT_EXCEEDED
    assert big.reads == 0  # oversized content never fetched


def test_entry_meta_url_and_dict():
    f = FakeFile("/sites/S/Docs/a b.txt", b"x")
    meta = _EntryMeta(f, base_url="https://company.sharepoint.com")
    d = meta.as_dict()
    assert d["url"] == "https://company.sharepoint.com/sites/S/Docs/a%20b.txt"
    assert d["path"] == f.path and d["size"] == 1
    assert d["status"] == STATUS_DOWNLOADED
    # equality ignores seen_at/status (change detection key)
    meta2 = _EntryMeta(f)
    assert meta == meta2
    f.modified_at = 999
    assert meta != _EntryMeta(f)


def test_sharepoint_static_read_e2e():
    files = [
        FakeFile("/sites/S/Docs/a.txt", b"alpha"),
        FakeFile("/sites/S/Docs/b.txt", b"beta"),
    ]
    t = pw.xpacks.connectors.sharepoint.read(
        "https://company.sharepoint.com/sites/S",
        root_path="Shared Documents/Docs",
        mode="static",
        with_metadata=True,
        _context_factory=lambda: FakeContext(files),
    )
    rows = []
    pw.io.subscribe(
        t,
        on_change=lambda key, row, time, is_addition: rows.append(
            (row["data"], row["_metadata"].value["path"], is_addition)
        ),
    )
    pw.run(monitoring_level="none")
    assert sorted(rows) == [
        (b"alpha", "/sites/S/Docs/a.txt", True),
        (b"beta", "/sites/S/Docs/b.txt", True),
    ]


def test_sharepoint_static_retries_then_succeeds():
    calls = {"n": 0}
    good = FakeContext([FakeFile("/s/a", b"data")])

    def factory():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise ConnectionError("auth flake")
        return good

    t = pw.xpacks.connectors.sharepoint.read(
        "https://x.sharepoint.com/sites/S",
        root_path="Docs",
        mode="static",
        refresh_interval=0,
        max_failed_attempts_in_row=5,
        _context_factory=factory,
    )
    rows = []
    pw.io.subscribe(
        t, on_change=lambda key, row, time, is_addition: rows.append(row["data"])
    )
    pw.run(monitoring_level="none")
    assert rows == [b"data"]
    assert calls["n"] == 3


def test_sharepoint_abort_after_max_failures():
    """A reader that exhausts max_failed_attempts_in_row must fail the
    run (EngineError), not end as a clean empty table."""
    from pathway_tpu.engine.dataflow import EngineError

    def factory():
        raise ConnectionError("bad credentials")

    t = pw.xpacks.connectors.sharepoint.read(
        "https://x.sharepoint.com/sites/S",
        root_path="Docs",
        mode="static",
        refresh_interval=0,
        max_failed_attempts_in_row=3,
        _context_factory=factory,
    )
    pw.io.subscribe(t, on_change=lambda key, row, time, is_addition: None)
    with pytest.raises(EngineError, match="sharepoint.*failed"):
        pw.run(monitoring_level="none")


def test_sharepoint_recovery_retracts_downtime_deletions(tmp_path):
    """Restart from a checkpoint: unchanged files are not re-downloaded,
    files deleted while the pipeline was down are retracted."""
    f1 = FakeFile("/s/a.txt", b"one")
    f2 = FakeFile("/s/b.txt", b"two")

    def run_once(files, events):
        ctx = FakeContext(files)
        t = pw.xpacks.connectors.sharepoint.read(
            "https://x.sharepoint.com/sites/S",
            root_path="Docs",
            mode="static",
            persistent_id="sp1",
            _context_factory=lambda: ctx,
        )
        pw.io.subscribe(
            t,
            on_change=lambda key, row, time, is_addition: events.append(
                (row["data"], is_addition)
            ),
        )
        pw.run(
            monitoring_level="none",
            persistence_config=pw.persistence.Config.simple_config(
                pw.persistence.Backend.filesystem(str(tmp_path / "snap"))
            ),
        )
        pw.clear_graph()
        return ctx

    ev1: list = []
    run_once([f1, f2], ev1)
    assert sorted(ev1) == [(b"one", True), (b"two", True)]
    assert f1.reads == 1 and f2.reads == 1

    # b.txt deleted during downtime; restart
    ev2: list = []
    run_once([f1], ev2)
    assert f1.reads == 1, "unchanged file was re-downloaded after recovery"
    assert (b"two", False) in ev2, "downtime deletion was not retracted"
    assert (b"one", True) not in ev2, "recovered row was re-delivered"


def test_sharepoint_streaming_updates_and_deletions():
    f1 = FakeFile("/s/a.txt", b"one")
    ctx = FakeContext([f1])
    t = pw.xpacks.connectors.sharepoint.read(
        "https://x.sharepoint.com/sites/S",
        root_path="Docs",
        mode="streaming",
        refresh_interval=0.05,
        autocommit_duration_ms=50,
        _context_factory=lambda: ctx,
    )
    events = []
    pw.io.subscribe(
        t,
        on_change=lambda key, row, time, is_addition: events.append(
            (row["data"], is_addition)
        ),
    )

    runner = GraphRunner()
    for spec in list(pw.parse_graph.subscriptions):
        runner.subscribe(spec["table"], on_change=spec.get("on_change"))

    def mutate():
        time.sleep(0.6)
        ctx.files = [FakeFile("/s/b.txt", b"two")]  # add b, delete a
        time.sleep(0.6)
        runner.engine.stop()

    th = threading.Thread(target=mutate, daemon=True)
    th.start()
    runner.run()
    th.join(timeout=10)

    assert (b"one", True) in events
    assert (b"two", True) in events
    assert (b"one", False) in events  # deletion retracts
    assert (b"two", False) not in events
