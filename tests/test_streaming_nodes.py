"""Every engine Node class driven by a streaming (multi-epoch, with
retractions) test — the reference's `_stream`-variant strategy
(python/pathway/tests, e.g. temporal/test_windows_stream.py) applied to
the whole operator vocabulary (VERDICT r2 Weak #7: nothing exercised
several nodes under retraction until now)."""

from __future__ import annotations

import pathway_tpu as pw

from .utils import (
    T,
    assert_stream_equality,
    assert_table_equality_wo_index,
    run_table,
)


def _vals(rows: dict) -> list:
    return sorted(rows.values())


# ---- ExprMapNode / FilterNode -------------------------------------------


def test_select_stream_retraction():
    t = T(
        """
      | a | __time__ | __diff__
    1 | 1 | 2        | 1
    2 | 2 | 2        | 1
    1 | 1 | 4        | -1
    3 | 5 | 4        | 1
    """
    )
    r = t.select(b=pw.this.a * 10)
    assert_stream_equality(
        r,
        [((10,), 2, 1), ((20,), 2, 1), ((10,), 4, -1), ((50,), 4, 1)],
    )


def test_filter_stream_row_crosses_predicate():
    # an updated row leaves the filter when its new value fails the test
    t = T(
        """
      | a | __time__ | __diff__
    1 | 5 | 2        | 1
    1 | 5 | 4        | -1
    1 | 1 | 4        | 1
    """
    )
    r = t.filter(pw.this.a > 3)
    assert_stream_equality(r, [((5,), 2, 1), ((5,), 4, -1)])


# ---- ConcatNode / ReindexNode -------------------------------------------


def test_concat_reindex_stream():
    a = T(
        """
      | x | __time__ | __diff__
    1 | 1 | 2        | 1
    """
    )
    b = T(
        """
      | x | __time__ | __diff__
    1 | 9 | 4        | 1
    1 | 9 | 6        | -1
    """
    )
    r = a.concat_reindex(b)
    assert_stream_equality(r, [((1,), 2, 1), ((9,), 4, 1), ((9,), 6, -1)])


# ---- FlattenNode ---------------------------------------------------------


def test_flatten_stream_retracts_children():
    t = T(
        """
      | n | __time__ | __diff__
    1 | 2 | 2        | 1
    1 | 2 | 4        | -1
    1 | 3 | 4        | 1
    """
    )
    t = t.select(parts=pw.apply_with_type(lambda n: tuple(range(n)), pw.ANY, pw.this.n))
    r = t.flatten(pw.this.parts)
    # same-valued children consolidate within the epoch: replacing the
    # n=2 row with n=3 nets out to a single (2,) insertion
    assert_stream_equality(
        r,
        [((0,), 2, 1), ((1,), 2, 1), ((2,), 4, 1)],
    )


# ---- UpdateRowsNode / UpdateCellsNode -----------------------------------


def test_update_rows_stream():
    base = T(
        """
      | v | __time__ | __diff__
    1 | 1 | 2        | 1
    2 | 2 | 2        | 1
    """
    )
    patch = T(
        """
      | v | __time__ | __diff__
    2 | 9 | 4        | 1
    3 | 7 | 4        | 1
    2 | 9 | 6        | -1
    """
    )
    r = base.update_rows(patch)
    rows = run_table(r)
    assert _vals(rows) == [(1,), (2,), (7,)]


def test_update_cells_stream():
    base = T(
        """
      | v | w | __time__ | __diff__
    1 | 1 | a | 2        | 1
    2 | 2 | b | 2        | 1
    """
    )
    patch = T(
        """
      | v | __time__ | __diff__
    2 | 9 | 4        | 1
    """
    )
    r = base.update_cells(patch)
    rows = run_table(r)
    assert _vals(rows) == [(1, "a"), (9, "b")]


# ---- IntersectNode / SubtractNode / HavingNode / restrict ----------------


def test_intersect_difference_stream():
    a = T(
        """
      | v | __time__ | __diff__
    1 | 1 | 2        | 1
    2 | 2 | 2        | 1
    3 | 3 | 2        | 1
    """
    )
    b = T(
        """
      | w | __time__ | __diff__
    2 | 0 | 4        | 1
    3 | 0 | 4        | 1
    2 | 0 | 6        | -1
    """
    )
    inter = a.intersect(b)
    diff = a.difference(b)
    assert _vals(run_table(inter)) == [(3,)]
    assert _vals(run_table(diff)) == [(1,), (2,)]


def test_having_and_restrict_stream():
    a = T(
        """
      | v | __time__ | __diff__
    1 | 1 | 2        | 1
    2 | 2 | 2        | 1
    """
    )
    # same markdown keys produce the same row ids across tables, so
    # keys.id indexes into a's universe
    keys = T(
        """
      | z | __time__ | __diff__
    1 | 0 | 4        | 1
    """
    )
    h = a.having(keys.id)
    assert _vals(run_table(h)) == [(1,)]
    # restrict against a shrinking subset
    sub = a.filter(pw.this.v > 1)
    r = a.restrict(sub)
    assert _vals(run_table(r)) == [(2,)]


# ---- GroupByNode: every reducer under retraction ------------------------


def test_reducers_under_retraction():
    t = T(
        """
      | g | v | __time__ | __diff__
    1 | a | 1 | 2        | 1
    2 | a | 5 | 2        | 1
    3 | a | 3 | 4        | 1
    2 | a | 5 | 6        | -1
    """
    )
    r = t.groupby(pw.this.g).reduce(
        pw.this.g,
        s=pw.reducers.sum(pw.this.v),
        n=pw.reducers.count(),
        mn=pw.reducers.min(pw.this.v),
        mx=pw.reducers.max(pw.this.v),
        av=pw.reducers.avg(pw.this.v),
        tup=pw.reducers.sorted_tuple(pw.this.v),
    )
    rows = run_table(r)
    assert list(rows.values()) == [("a", 4, 2, 1, 3, 2.0, (1, 3))]


def test_argmin_argmax_under_retraction():
    t = T(
        """
      | g | v | __time__ | __diff__
    1 | a | 9 | 2        | 1
    2 | a | 1 | 2        | 1
    2 | a | 1 | 4        | -1
    """
    )
    r = t.groupby(pw.this.g).reduce(
        pw.this.g,
        lo=pw.reducers.argmin(pw.this.v),
        hi=pw.reducers.argmax(pw.this.v),
    )
    rows = run_table(r)
    ((g, lo, hi),) = rows.values()
    assert g == "a" and lo == hi  # only row 1 remains


def test_groupby_group_vanishes():
    t = T(
        """
      | g | v | __time__ | __diff__
    1 | a | 1 | 2        | 1
    2 | b | 2 | 2        | 1
    1 | a | 1 | 4        | -1
    """
    )
    r = t.groupby(pw.this.g).reduce(pw.this.g, n=pw.reducers.count())
    assert_stream_equality(
        r,
        [(("a", 1), 2, 1), (("b", 1), 2, 1), (("a", 1), 4, -1)],
    )


# ---- DeduplicateNode -----------------------------------------------------


def test_deduplicate_stream():
    t = T(
        """
      | v | __time__ | __diff__
    1 | 1 | 2        | 1
    2 | 3 | 4        | 1
    3 | 2 | 6        | 1
    """
    )
    r = t.deduplicate(
        value=pw.this.v, acceptor=lambda new, old: new > old
    )
    rows = run_table(r)
    assert _vals(rows) == [(3,)]  # 1 -> 3 accepted, 2 rejected


# ---- JoinNode: all four kinds under retraction --------------------------


def test_joins_under_retraction():
    left = T(
        """
      | k | l | __time__ | __diff__
    1 | a | 1 | 2        | 1
    2 | b | 2 | 2        | 1
    1 | a | 1 | 6        | -1
    """
    )
    right = T(
        """
      | k | r | __time__ | __diff__
    7 | a | 10 | 4       | 1
    8 | c | 30 | 4       | 1
    """
    )
    inner = left.join(right, left.k == right.k).select(
        left.l, right.r
    )
    assert _vals(run_table(inner)) == []  # a retracted at t=6

    louter = left.join_left(right, left.k == right.k).select(
        left.l, r=pw.coalesce(right.r, 0)
    )
    assert _vals(run_table(louter)) == [(2, 0)]

    router = left.join_right(right, left.k == right.k).select(
        l=pw.coalesce(left.l, 0), r=right.r
    )
    assert _vals(run_table(router)) == [(0, 10), (0, 30)]

    outer = left.join_outer(right, left.k == right.k).select(
        l=pw.coalesce(left.l, 0), r=pw.coalesce(right.r, 0)
    )
    assert _vals(run_table(outer)) == [(0, 10), (0, 30), (2, 0)]


# ---- AsofNowJoinNode -----------------------------------------------------


def test_asof_now_join_no_retro_update():
    queries = T(
        """
      | k | __time__ | __diff__
    1 | a | 2        | 1
    2 | a | 6        | 1
    """
    )
    data = T(
        """
      | k | v | __time__ | __diff__
    7 | a | 1 | 0        | 1
    7 | a | 1 | 4        | -1
    8 | a | 2 | 4        | 1
    """
    )
    r = queries.asof_now_join(data, queries.k == data.k).select(
        queries.k, data.v
    )
    # first query saw v=1 and must NOT be revised when data changes
    assert sorted(run_table(r).values()) == [("a", 1), ("a", 2)]


# ---- SortNode ------------------------------------------------------------


def test_sort_stream_prev_next():
    t = T(
        """
      | v | __time__ | __diff__
    1 | 30 | 2       | 1
    2 | 10 | 2       | 1
    3 | 20 | 4       | 1
    2 | 10 | 6       | -1
    """
    )
    s = t.sort(key=pw.this.v)
    joined = t.select(pw.this.v) + s
    rows = run_table(joined)
    by_id = dict(rows.items())
    heads = [k for k, (v, prev, nxt) in rows.items() if prev is None]
    assert len(heads) == 1
    chain, cur = [], heads[0]
    while cur is not None:
        chain.append(by_id[cur][0])
        cur = by_id[cur][2]
    assert chain == [20, 30]


# ---- GradualBroadcastNode ------------------------------------------------


def test_gradual_broadcast_threshold_updates():
    import pathway_tpu.internals.graph_runner as gr

    rows = T(
        """
      | v | __time__ | __diff__
    1 | 10 | 2       | 1
    2 | 20 | 4       | 1
    """
    )
    thresh = T(
        """
      | lo | val | hi | __time__ | __diff__
    9 | 1  | 5   | 9  | 0        | 1
    """
    )
    r = rows._gradual_broadcast(thresh, thresh.lo, thresh.val, thresh.hi)
    rows_out = run_table(r)
    # every row receives the (single) apx value column
    assert len(rows_out) == 2


# ---- AsyncApplyNode ------------------------------------------------------


def test_async_apply_stream():
    t = T(
        """
      | v | __time__ | __diff__
    1 | 1 | 2        | 1
    2 | 2 | 4        | 1
    """
    )

    @pw.udf
    async def double(x: int) -> int:
        import asyncio

        await asyncio.sleep(0.01)
        return x * 2

    r = t.select(d=double(pw.this.v))
    assert _vals(run_table(r)) == [(2,), (4,)]


def test_batch_udf_runs_columnar_batch_apply():
    """A bare batch-executor UDF lowers to BatchApplyNode: ONE call per
    epoch chunk, no per-row coroutines (r4 streaming hot path)."""
    import pathway_tpu as pw
    from pathway_tpu.engine.dataflow import BatchApplyNode
    from pathway_tpu.internals.graph_runner import GraphRunner

    calls = []

    def double_all(xs):
        calls.append(len(xs))
        return [x * 2 for x in xs]

    udf = pw.udfs.udf(double_all, executor=pw.udfs.batch_executor(max_batch_size=1024))
    t = pw.debug.table_from_rows(
        schema=pw.schema_from_types(a=int), rows=[(i,) for i in range(100)]
    )
    res = t.select(b=udf(pw.this.a))
    runner = GraphRunner()
    cap, _ = runner.capture(res)
    assert any(
        isinstance(n, BatchApplyNode) for n in runner.engine.nodes
    ), "batch UDF did not lower to BatchApplyNode"
    runner.run()
    pw.clear_graph()
    assert sorted(v[0] for v in cap.state.values()) == [i * 2 for i in range(100)]
    assert calls == [100], calls  # one columnar call for the whole epoch


def test_batch_apply_retraction_and_chunking():
    """BatchApplyNode memoizes rows for retractions and chunks oversized
    epochs to max_batch_size."""
    import pathway_tpu as pw
    from pathway_tpu.engine import dataflow as df
    from pathway_tpu.internals.graph_runner import GraphRunner

    calls = []

    def tag(xs):
        calls.append(len(xs))
        return [f"v{x}" for x in xs]

    udf = pw.udfs.udf(tag, executor=pw.udfs.batch_executor(max_batch_size=3))
    t = pw.debug.table_from_markdown(
        """
          | a | __time__ | __diff__
        1 | 1 | 2        | 1
        2 | 2 | 2        | 1
        3 | 3 | 2        | 1
        4 | 4 | 2        | 1
        1 | 1 | 4        | -1
        """
    )
    res = t.select(b=udf(pw.this.a))
    runner = GraphRunner()
    cap, _ = runner.capture(res)
    runner.run()
    pw.clear_graph()
    assert sorted(v[0] for v in cap.state.values()) == ["v2", "v3", "v4"]
    # epoch of 4 rows chunked as 3 + 1
    assert calls == [3, 1], calls


def test_batch_apply_error_routes_per_row():
    """A failing batch chunk yields ERROR cells + error-log entries with
    terminate_on_error=False (same contract as the async batcher)."""
    import pathway_tpu as pw
    from pathway_tpu.engine.value import Error
    from pathway_tpu.internals.graph_runner import GraphRunner

    def boom(xs):
        raise RuntimeError("batch failed")

    udf = pw.udfs.udf(boom, executor=pw.udfs.batch_executor(max_batch_size=8))
    t = pw.debug.table_from_rows(
        schema=pw.schema_from_types(a=int), rows=[(1,), (2,)]
    )
    res = t.select(b=udf(pw.this.a))
    runner = GraphRunner()
    runner.engine.terminate_on_error = False
    cap, _ = runner.capture(res)
    runner.run()
    pw.clear_graph()
    vals = [v[0] for v in cap.state.values()]
    assert all(isinstance(v, Error) for v in vals) and len(vals) == 2


def test_next_batch_columnar_emit_matches_per_row():
    """ConnectorSubject.next_batch: same rows, keys, and recovery seq as
    per-row next()."""
    import pathway_tpu as pw
    from pathway_tpu.internals.graph_runner import GraphRunner

    class Batchy(pw.io.python.ConnectorSubject):
        def run(self):
            self.next_batch(w=["a", "b"], n=[1, 2])
            self.commit()
            self.next(w="c", n=3)  # mixing APIs keeps the seq consistent
            self.commit()

    class S(pw.Schema):
        w: str
        n: int

    t = pw.io.python.read(Batchy(), schema=S)
    rows = []
    pw.io.subscribe(
        t, on_change=lambda key, row, time, is_addition: rows.append((row["w"], row["n"]))
    )
    pw.run(monitoring_level="none")
    pw.clear_graph()
    assert sorted(rows) == [("a", 1), ("b", 2), ("c", 3)]


def test_next_batch_coerces_and_validates():
    import pytest

    import pathway_tpu as pw

    class Bad(pw.io.python.ConnectorSubject):
        def run(self):
            self.next_batch(w=["a"], n=[1, 2])  # mismatched lengths
            self.commit()

    class S(pw.Schema):
        w: str
        n: int

    t = pw.io.python.read(Bad(), schema=S)
    pw.io.subscribe(t, on_change=lambda key, row, time, is_addition: None)
    from pathway_tpu.engine.dataflow import EngineError

    with pytest.raises(EngineError, match="failed"):
        pw.run(monitoring_level="none")
    pw.clear_graph()
