"""Airbyte protocol reader, sharepoint gating, LiveTable, chats/parsers.

Covers P29 (airbyte full-refresh/incremental), P30 (sharepoint
enterprise stub), P9 (interactive LiveTable), P20/P22 (chat + parser
UDF surfaces with fakes)."""

from __future__ import annotations

import json

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner
from .utils import T, run_table


def _airbyte_source(records_by_sync):
    """Fake Airbyte connector: each sync yields RECORD msgs + a STATE."""
    calls = {"n": 0}

    def source(config, state):
        sync_no = int(state["sync"]) + 1 if state else 0
        calls["n"] += 1
        msgs = []
        for rec in records_by_sync.get(sync_no, []):
            msgs.append({"type": "RECORD", "record": {"stream": "users", "data": rec}})
        msgs.append({"type": "STATE", "state": {"sync": sync_no}})
        return msgs

    return source, calls


def test_airbyte_static_sync():
    source, _calls = _airbyte_source({0: [{"id": 1}, {"id": 2}]})
    t = pw.io.airbyte.read(
        config={"k": "v"}, streams=["users"], source=source, mode="static"
    )
    state = run_table(t)
    ids = sorted(row[1].value["id"] for row in state.values())
    assert ids == [1, 2]
    pw.clear_graph()


def test_airbyte_incremental_resumes_from_state(tmp_path, monkeypatch):
    """Restart passes the persisted STATE back to the connector: sync 1
    only emits the delta."""
    monkeypatch.setenv("PATHWAY_TPU_FS_ONESHOT", "1")
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))
    cfg = pw.persistence.Config.simple_config(backend)
    data = {0: [{"id": 1}], 1: [{"id": 2}]}

    def run_once():
        source, calls = _airbyte_source(data)
        t = pw.io.airbyte.read(
            config={}, source=source, mode="streaming", persistent_id="ab"
        )
        runner = GraphRunner()
        runner.engine.persistence_config = cfg
        cap, names = runner.capture(t)
        runner.run()
        pw.clear_graph()
        return sorted(r[1].value["id"] for r in cap.state.values())

    assert run_once() == [1]
    assert run_once() == [1, 2]  # sync 1 appended on top of recovered state


def test_airbyte_stream_filter():
    def source(config, state):
        return [
            {"type": "RECORD", "record": {"stream": "users", "data": {"id": 1}}},
            {"type": "RECORD", "record": {"stream": "orders", "data": {"id": 9}}},
        ]

    t = pw.io.airbyte.read(config={}, streams=["users"], source=source, mode="static")
    state = run_table(t)
    assert [row[0] for row in state.values()] == ["users"]
    pw.clear_graph()


def test_airbyte_requires_runtime_or_source():
    # no source/executable AND no resolvable docker_image in the config
    with pytest.raises(ValueError, match="docker_image"):
        pw.io.airbyte.read(config={})


def test_sharepoint_gated_by_license():
    with pytest.raises(pw.LicenseError):
        pw.xpacks.connectors.sharepoint.read(
            "https://example.sharepoint.com/site", root_path="Docs"
        )


def test_live_table_snapshot():
    t = pw.debug.table_from_markdown(
        """
          | a | __time__ | __diff__
        1 | 1 | 0        | 1
        2 | 2 | 0        | 1
        1 | 1 | 2        | -1
        """
    )
    live = pw.LiveTable.from_table(t)
    pw.run()
    assert len(live) == 1
    assert live.to_pandas()["a"].tolist() == [2]
    pw.clear_graph()


def test_fake_chat_udf():
    from tests.mocks import FakeChatModel

    chat = FakeChatModel()
    t = T(
        """
          | q
        1 | hello
        """
    )
    res = t.select(a=chat(pw.this.q))
    (row,) = run_table(res).values()
    assert isinstance(row[0], str) and row[0]
    pw.clear_graph()


def test_parse_utf8_udf():
    from pathway_tpu.xpacks.llm.parsers import ParseUtf8

    parser = ParseUtf8()
    t = pw.debug.table_from_rows(_bytes_schema(), [(b"hello world",)])
    res = t.select(parsed=parser(pw.this.data))
    (row,) = run_table(res).values()
    # parser contract: list of (text, metadata) pairs
    assert row[0][0][0] == "hello world"
    pw.clear_graph()


def _bytes_schema():
    class S(pw.Schema):
        data: bytes

    return S


def test_object_cache_zero_redownloads_across_restart(tmp_path):
    """Cached object storage (reference cached_object_storage.rs:1-377):
    a restart re-lists but never re-downloads unchanged objects; a
    changed object is fetched once; deletions evict."""
    import pathway_tpu as pw
    from pathway_tpu.io._object_store import ObjectCache

    class CountingDrive:
        def __init__(self, objects):
            self.objects = dict(objects)
            self.gets = 0

        def list_objects(self):
            return [(k, f"v{len(v)}") for k, v in self.objects.items()]

        def get_object(self, key):
            self.gets += 1
            return self.objects[key]

    cache_dir = str(tmp_path / "objcache")
    objs = {"a.txt": b"alpha\n", "b.txt": b"beta\n"}

    def run_once(client):
        t = pw.io.gdrive.read(
            "folder", mode="static", format="plaintext", _client=client,
            object_cache=cache_dir,
        )
        out = []
        pw.io.subscribe(t, on_change=lambda key, row, time, is_addition: out.append(row["data"]))
        pw.run(monitoring_level="none")
        pw.clear_graph()
        return sorted(out)

    c1 = CountingDrive(objs)
    assert run_once(c1) == ["alpha", "beta"]
    assert c1.gets == 2  # cold cache: both fetched

    # restart: fresh client + fresh graph, same cache dir
    c2 = CountingDrive(objs)
    assert run_once(c2) == ["alpha", "beta"]
    assert c2.gets == 0, "unchanged objects were re-downloaded"

    # changed object: exactly one fetch
    c3 = CountingDrive({**objs, "b.txt": b"beta2!\n"})
    assert run_once(c3) == ["alpha", "beta2!"]
    assert c3.gets == 1

    # eviction drops the cached blob
    cache = ObjectCache(cache_dir)
    cache.drop("a.txt")
    c4 = CountingDrive(objs)
    run_once(c4)
    assert c4.gets == 2  # a.txt refetched (evicted) + b.txt (version changed back)


def test_airbyte_serverless_docker_resolution(tmp_path, monkeypatch):
    """Serverless runtime (reference third_party/airbyte_serverless):
    a config naming source.docker_image resolves to `docker run --rm -i
    --volume <tmp>:<tmp> <image>` and drives the protocol end-to-end —
    verified with a fake docker binary emitting RECORD/STATE lines."""
    import os
    import stat

    import pathway_tpu as pw

    fake = tmp_path / "docker"
    fake.write_text(
        "#!/bin/sh\n"
        "# swallow docker-run flags until the image, then expect: read --config <path>\n"
        'echo \'{"type": "RECORD", "record": {"stream": "users", "data": {"id": 1}}}\'\n'
        'echo \'{"type": "STATE", "state": {"cursor": "2024"}}\'\n'
        'echo \'{"type": "RECORD", "record": {"stream": "users", "data": {"id": 2}}}\'\n'
    )
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{tmp_path}:{os.environ['PATH']}")

    t = pw.io.airbyte.read(
        config={
            "source": {
                "docker_image": "airbyte/source-faker:6.2.10",
                "config": {"count": 2},
            }
        },
        streams=["users"],
        mode="static",
    )
    got = []
    pw.io.subscribe(
        t,
        on_change=lambda key, row, time, is_addition: got.append(
            (row["stream"], row["data"].value["id"])
        ),
    )
    pw.run(monitoring_level="none")
    assert sorted(got) == [("users", 1), ("users", 2)]


def test_airbyte_docker_argv_shape():
    from pathway_tpu.io.airbyte import _docker_argv

    argv = _docker_argv("airbyte/source-github", "/tmp/x", {"TOKEN": "t"})
    assert argv[:6] == ["docker", "run", "--rm", "-i", "--volume", "/tmp/x:/tmp/x"]
    assert "-e" in argv and "TOKEN=t" in argv
    assert argv[-1] == "airbyte/source-github"
