"""Whole-layer pallas kernel (ops/fused_layer.py): numerics vs the flax
module, gradient path, packing round-trip, and the CLIP YUV420 wire
format.  Kernels run in interpret mode on the CPU mesh."""

from __future__ import annotations

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from pathway_tpu.models.batching import DEFAULT_SEQ_BUCKETS
from pathway_tpu.models.encoder import EncoderConfig, TextEncoder, init_params
from pathway_tpu.ops.fused_layer import (
    encoder_forward,
    pack_tokens,
    supports_fused_encoder,
    unpack_tokens,
)


@pytest.fixture(scope="module")
def minilm():
    cfg = EncoderConfig.minilm_l6()
    module = TextEncoder(cfg)
    return cfg, module, init_params(module, cfg)


def _batch(rng, b, s):
    ids = rng.integers(999, 29000, (b, s)).astype(np.int32)
    lens = rng.integers(max(1, s // 2), s + 1, (b,))
    mask = np.arange(s)[None, :] < lens[:, None]
    return jnp.asarray(ids), jnp.asarray(mask)


@pytest.mark.parametrize("b,s", [(8, 32), (5, 96), (3, 160), (2, 224), (2, 256)])
def test_fused_encoder_matches_module(minilm, b, s):
    cfg, module, params = minilm
    ids, mask = _batch(np.random.default_rng(s), b, s)
    ref = np.asarray(module.apply(params, ids, mask))
    got = np.asarray(encoder_forward(params, cfg, ids, mask, interpret=True))
    assert got.shape == ref.shape
    err = np.abs(ref - got).max()
    cos = (ref * got).sum(axis=1).min()
    assert err < 3e-2 and cos > 0.999, (err, cos)


@pytest.fixture(scope="module")
def tiny():
    """Miniature geometry for the full bucket sweep: parity is a
    property of the kernel's (seq, pack-factor) tiling, not the model
    size, so every bucket runs at a width that keeps interpret mode
    cheap."""
    cfg = EncoderConfig(
        vocab_size=1000, hidden_size=64, num_layers=2, num_heads=2,
        intermediate_size=128, max_position=512,
    )
    module = TextEncoder(cfg)
    return cfg, module, init_params(module, cfg)


@pytest.mark.parametrize("s", list(DEFAULT_SEQ_BUCKETS))
def test_every_bucket_parity_with_all_padding_rows(tiny, s):
    """Every seq bucket, every pack factor: the ragged kernel matches
    the per-op XLA module on live rows, and an all-padding row riding in
    the batch (its block may be dead-skipped) comes back exactly zero —
    the batch spills into a second, partly-dead block on purpose."""
    from pathway_tpu.ops.fused_layer import _pack_rows

    cfg, module, params = tiny
    rng = np.random.default_rng(s)
    b = _pack_rows(s) + 2
    ids = rng.integers(5, 999, (b, s)).astype(np.int32)
    lens = rng.integers(1, s + 1, (b,))
    lens[-1] = 0  # all-padding row in the tail (length-sorted contract)
    mask = np.arange(s)[None, :] < lens[:, None]
    ids_j, mask_j = jnp.asarray(ids), jnp.asarray(mask)
    got = np.asarray(encoder_forward(params, cfg, ids_j, mask_j, interpret=True))
    ref = np.asarray(module.apply(params, ids_j, mask_j))
    live = lens > 0
    err = np.abs(ref[live] - got[live]).max()
    assert err < 3e-2, (s, err)
    assert np.all(got[~live] == 0.0), "all-padding row must embed to zero"


def test_fused_encoder_cls_pooling(minilm):
    _, _, params = minilm
    cfg = EncoderConfig.cross_encoder_l6()
    module = TextEncoder(cfg)
    p = init_params(module, cfg)
    ids, mask = _batch(np.random.default_rng(0), 4, 32)
    ref = np.asarray(module.apply(p, ids, mask))
    got = np.asarray(encoder_forward(p, cfg, ids, mask, interpret=True))
    # cls outputs are unnormalized (scale ~3), so bound the error
    # relative to the output scale (a few bf16 ulps) plus direction
    err = np.abs(ref - got).max()
    assert err < 3e-2 * max(1.0, np.abs(ref).max()), err
    rn = ref / np.linalg.norm(ref, axis=1, keepdims=True)
    gn = got / np.linalg.norm(got, axis=1, keepdims=True)
    assert (rn * gn).sum(axis=1).min() > 0.999


def test_fused_encoder_gradient_flows(minilm):
    """custom_vjp backward recomputes through the flax path — grads
    must match the module's own within bf16 noise."""
    cfg, module, params = minilm
    ids, mask = _batch(np.random.default_rng(1), 2, 32)

    def loss_fused(p):
        return encoder_forward(p, cfg, ids, mask, interpret=True).sum()

    def loss_ref(p):
        return module.apply(p, ids, mask).sum()

    g_fused = jax.grad(loss_fused)(params)
    g_ref = jax.grad(loss_ref)(params)
    leaf_f = jax.tree_util.tree_leaves(g_fused)
    leaf_r = jax.tree_util.tree_leaves(g_ref)
    assert len(leaf_f) == len(leaf_r)
    for a, b in zip(leaf_f, leaf_r):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), atol=2e-2, rtol=2e-2
        )


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(5, 32, 8)).astype(np.float32))
    mask = jnp.ones((5, 32), bool)
    tokens, lens, b0 = pack_tokens(x, mask)
    assert tokens.shape[0] % (256 // 32 * 32) == 0
    # per-block lengths: one row per packed block, one entry per sequence
    assert lens.shape[1] == 256 // 32 and np.asarray(lens)[0, 0] == 32
    back = unpack_tokens(tokens, b0, 32)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))


def test_supports_fused_encoder_gates():
    cfg = EncoderConfig.minilm_l6()
    assert supports_fused_encoder(cfg, 160)
    assert not supports_fused_encoder(cfg, 1024)  # beyond packing range


def test_layer_impl_policy_is_honored():
    import dataclasses

    from pathway_tpu.ops.fused_layer import use_fused_encoder

    cfg = EncoderConfig.minilm_l6()
    assert not use_fused_encoder(dataclasses.replace(cfg, layer_impl="xla"), 160)
    assert use_fused_encoder(dataclasses.replace(cfg, layer_impl="fused"), 160)
    # auto on CPU backend: stays on the XLA path
    assert not use_fused_encoder(cfg, 160)


def test_clip_yuv420_wire_format_close_to_rgb():
    from pathway_tpu.models.clip import CLIPEncoder, CLIPConfig

    cfg = CLIPConfig(
        image_size=32, patch_size=8, vision_layers=1, vision_width=64,
        vision_heads=2, text_layers=1, text_width=64, text_heads=2,
        embed_dim=32,
    )
    enc = CLIPEncoder(cfg, max_batch=8)
    rng = np.random.default_rng(0)
    imgs = (rng.random((4, 32, 32, 3)) * 255).astype(np.uint8)
    enc.transport = "rgb"
    ref = enc.encode_image(imgs)
    enc.transport = "yuv420"
    got = enc.encode_image(imgs)
    cos = (ref * got).sum(axis=1)
    assert cos.min() > 0.99, cos
    # packed wire rows are half the size of RGB rows
    packed = enc._pack_yuv420(imgs)
    assert packed.shape[1] * 2 == imgs[0].size
