"""Table-op + expression breadth, modeled on the reference's
test_common.py / test_expressions coverage style: many small
assertions over the whole DSL surface, each comparing against a
directly-constructed expected table."""

from __future__ import annotations

import pytest

import pathway_tpu as pw

from .utils import (
    T,
    assert_table_equality,
    assert_table_equality_wo_index,
    run_table,
)


def _t3():
    return T(
        """
      | a | b | s
    1 | 1 | 1.5 | x
    2 | 2 | 2.5 | yy
    3 | 3 | 3.5 | zzz
    """
    )


# ---- arithmetic / comparison / boolean expressions ----------------------


def test_arithmetic_operators():
    t = _t3()
    r = t.select(
        add=pw.this.a + 1,
        sub=pw.this.a - 1,
        mul=pw.this.a * 3,
        div=pw.this.b / 0.5,
        fdiv=pw.this.a // 2,
        mod=pw.this.a % 2,
        pow_=pw.this.a**2,
        neg=-pw.this.a,
    )
    rows = sorted(run_table(r).values())
    assert rows == [
        (2, 0, 3, 3.0, 0, 1, 1, -1),
        (3, 1, 6, 5.0, 1, 0, 4, -2),
        (4, 2, 9, 7.0, 1, 1, 9, -3),
    ]


def test_comparison_and_boolean():
    t = _t3()
    r = t.select(
        lt=pw.this.a < 2,
        le=pw.this.a <= 2,
        eq=pw.this.a == 2,
        ne=pw.this.a != 2,
        both=(pw.this.a > 1) & (pw.this.a < 3),
        either=(pw.this.a == 1) | (pw.this.a == 3),
        inv=~(pw.this.a == 1),
    )
    rows = sorted(run_table(r).values())
    assert rows == [
        (False, False, False, True, False, True, True),
        (False, True, True, False, True, False, True),
        (True, True, False, True, False, True, False),
    ]


def test_if_else_coalesce_require():
    t = T(
        """
      | a | b
    1 | 1 |
    2 |   | 5
    """
    ).select(
        a=pw.if_else(pw.this.a == 0, None, pw.this.a),
        b=pw.if_else(pw.this.b == 0, None, pw.this.b),
    )
    r = t.select(
        pick=pw.coalesce(pw.this.a, pw.this.b, 0),
        gated=pw.require(pw.this.a, pw.this.b),  # None unless b non-null
        branch=pw.if_else(pw.this.a.is_none(), -1, 1),
    )
    assert sorted(run_table(r).values(), key=repr) == sorted(
        [(1, None, 1), (5, None, -1)], key=repr
    )


def test_str_namespace_breadth():
    t = _t3()
    r = t.select(
        up=pw.this.s.str.upper(),
        ln=pw.this.s.str.len(),
        rev=pw.this.s.str.reversed(),
        sub=pw.this.s.str.slice(0, 2),
        has=pw.this.s.str.count("z"),
        rep=pw.this.s.str.replace("y", "Y"),
        sw=pw.this.s.str.startswith("z"),
    )
    rows = sorted(run_table(r).values())
    assert rows == [
        ("X", 1, "x", "x", 0, "x", False),
        ("YY", 2, "yy", "yy", 0, "YY", False),
        ("ZZZ", 3, "zzz", "zz", 3, "zzz", True),
    ]


def test_num_namespace():
    t = T(
        """
      | x
    1 | -2.7
    2 | 3.2
    """
    )
    r = t.select(
        ab=pw.this.x.num.abs(),
        rd=pw.this.x.num.round(),
        fl=pw.apply_with_type(lambda v: int(v // 1), int, pw.this.x),
    )
    rows = sorted(run_table(r).values())
    assert rows == [(2.7, -3.0, -3), (3.2, 3.0, 3)]


def test_cast_and_as():
    t = _t3()
    r = t.select(
        f=pw.cast(float, pw.this.a),
        i=pw.cast(int, pw.this.b),
        s2=pw.apply_with_type(str, str, pw.this.a),
    )
    rows = sorted(run_table(r).values())
    assert rows == [(1.0, 1, "1"), (2.0, 2, "2"), (3.0, 3, "3")]  # cast truncates


# ---- table ops ----------------------------------------------------------


def test_rename_and_without():
    t = _t3()
    r = t.rename(aa=pw.this.a).without(pw.this.s)
    state = run_table(r)
    assert sorted(state.values()) == [(1, 1.5), (2, 2.5), (3, 3.5)]


def test_ix_and_ix_ref():
    t = _t3()
    idx = T(
        """
      | n
    9 | 1
    """
    )
    # ix by explicit pointer column is covered in indexing tests; here
    # ix_ref addresses by value-derived keys
    keyed = t.with_id_from(pw.this.a)
    r = idx.select(got=keyed.ix_ref(pw.this.n).s)
    assert list(run_table(r).values()) == [("x",)]


def test_with_id_from_and_reindex():
    t = _t3()
    k = t.with_id_from(pw.this.s)
    rows = run_table(k)
    assert len(rows) == 3
    # deterministic: same derivation yields identical ids
    k2 = t.with_id_from(pw.this.s)
    assert set(run_table(k2).keys()) == set(rows.keys())


def test_concat_duplicate_keys_raises_at_run():
    t = _t3()
    dup = t.concat(t.select(pw.this.a, pw.this.b, pw.this.s))
    with pytest.raises(Exception, match="duplicate key"):
        run_table(dup)


def test_groupby_multiple_keys():
    t = T(
        """
      | g | h | v
    1 | a | 1 | 10
    2 | a | 2 | 20
    3 | a | 1 | 30
    4 | b | 1 | 40
    """
    )
    r = t.groupby(pw.this.g, pw.this.h).reduce(
        pw.this.g, pw.this.h, s=pw.reducers.sum(pw.this.v)
    )
    assert sorted(run_table(r).values()) == [
        ("a", 1, 40),
        ("a", 2, 20),
        ("b", 1, 40),
    ]


def test_join_select_this_disambiguation():
    left = T(
        """
      | k | v
    1 | a | 1
    """
    )
    right = T(
        """
      | k | v
    7 | a | 2
    """
    )
    j = left.join(right, left.k == right.k).select(
        lv=left.v, rv=right.v, k=left.k
    )
    assert list(run_table(j).values()) == [(1, 2, "a")]


def test_flatten_preserves_other_columns():
    t = T(
        """
      | tag
    1 | ab
    """
    ).select(tag=pw.this.tag, parts=pw.apply_with_type(lambda s: tuple(s), pw.ANY, pw.this.tag))
    r = t.flatten(pw.this.parts)
    assert sorted(run_table(r.select(pw.this.parts, pw.this.tag)).values()) == [
        ("a", "ab"),
        ("b", "ab"),
    ]


def test_difference_update_rows_roundtrip():
    t = _t3()
    sub = t.filter(pw.this.a >= 2)
    rest = t.difference(sub)
    back = rest.concat(sub)
    assert_table_equality_wo_index(back.select(pw.this.a), t.select(pw.this.a))


def test_empty_table_ops():
    t = _t3().filter(pw.this.a > 100)
    r = t.select(b=pw.this.a + 1)
    assert run_table(r) == {}
    g = t.groupby(pw.this.s).reduce(pw.this.s, n=pw.reducers.count())
    assert run_table(g) == {}


# ---- error routing ------------------------------------------------------


def test_division_by_zero_routes_error():
    t = T(
        """
      | a | d
    1 | 1 | 0
    2 | 4 | 2
    """
    )
    r = t.select(q=pw.fill_error(pw.this.a // pw.this.d, -1))
    rows = sorted(run_table(r).values())
    assert rows == [(-1,), (2,)]


def test_apply_exception_is_error_value():
    t = T(
        """
      | a
    1 | 0
    2 | 2
    """
    )

    def boom(x):
        if x == 0:
            raise ValueError("zero")
        return 10 // x

    r = t.select(v=pw.fill_error(pw.apply_with_type(boom, int, pw.this.a), -7))
    assert sorted(run_table(r).values()) == [(-7,), (5,)]
