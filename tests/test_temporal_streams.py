"""Temporal operators under scripted streams — the reference's
`_stream` test variants (python/pathway/tests/temporal/
test_windows_stream.py, test_interval_join_stream.py): every window
kind and temporal join exercised with multi-epoch arrival, late data,
retractions, and behavior cutoffs."""

from __future__ import annotations

import pathway_tpu as pw
from pathway_tpu.stdlib import temporal

from .utils import T, assert_stream_equality, run_table


def _by(rows, names, *cols):
    idx = [names.index(c) for c in cols]
    return sorted(tuple(r[i] for i in idx) for r in rows.values())


def _state(table):
    from pathway_tpu.debug import _run_capture

    cap, names = _run_capture(table)
    return cap.state, names


# ---- windows under streaming arrival ------------------------------------


def test_tumbling_window_updates_across_epochs():
    t = T(
        """
      | t | v  | __time__ | __diff__
    1 | 1 | 10 | 2        | 1
    2 | 2 | 20 | 4        | 1
    3 | 5 | 30 | 6        | 1
    2 | 2 | 20 | 8        | -1
    """
    )
    res = t.windowby(pw.this.t, window=temporal.tumbling(duration=4)).reduce(
        start=pw.this._pw_window_start,
        total=pw.reducers.sum(pw.this.v),
        n=pw.reducers.count(),
    )
    state, names = _state(res)
    assert _by(state, names, "start", "total", "n") == [(0, 10, 1), (4, 30, 1)]


def test_tumbling_window_stream_emits_revisions():
    t = T(
        """
      | t | v  | __time__ | __diff__
    1 | 1 | 10 | 2        | 1
    2 | 2 | 20 | 4        | 1
    """
    )
    res = t.windowby(pw.this.t, window=temporal.tumbling(duration=4)).reduce(
        start=pw.this._pw_window_start,
        total=pw.reducers.sum(pw.this.v),
    )
    assert_stream_equality(
        res,
        [((0, 10), 2, 1), ((0, 10), 4, -1), ((0, 30), 4, 1)],
    )


def test_sliding_window_membership_stream():
    t = T(
        """
      | t | v | __time__ | __diff__
    1 | 3 | 1 | 2        | 1
    1 | 3 | 4 | -1
    """.replace("1 | 3 | 4 | -1", "1 | 3 | 1 | 4        | -1")
    )
    res = t.windowby(
        pw.this.t, window=temporal.sliding(hop=2, duration=4)
    ).reduce(
        start=pw.this._pw_window_start,
        n=pw.reducers.count(),
    )
    # t=3 belongs to windows starting at 0 and 2; both retract fully
    assert_stream_equality(
        res,
        [((0, 1), 2, 1), ((2, 1), 2, 1), ((0, 1), 4, -1), ((2, 1), 4, -1)],
    )


def test_session_window_merge_on_late_bridge():
    """Two separate sessions MERGE when a bridging row arrives later —
    the hardest session-window update case."""
    t = T(
        """
      | t  | v | __time__ | __diff__
    1 | 1  | 1 | 2        | 1
    2 | 10 | 2 | 2        | 1
    3 | 5  | 4 | 4        | 1
    """
    )
    res = t.windowby(
        pw.this.t, window=temporal.session(max_gap=5)
    ).reduce(
        n=pw.reducers.count(),
        total=pw.reducers.sum(pw.this.v),
    )
    state, names = _state(res)
    # after the bridge at t=5: one session [1,10] with all three rows
    assert _by(state, names, "n", "total") == [(3, 7)]


def test_session_window_splits_on_retraction():
    t = T(
        """
      | t  | v | __time__ | __diff__
    1 | 1  | 1 | 2        | 1
    2 | 5  | 2 | 2        | 1
    3 | 9  | 4 | 2        | 1
    2 | 5  | 2 | 4        | -1
    """
    )
    res = t.windowby(
        pw.this.t, window=temporal.session(max_gap=5)
    ).reduce(n=pw.reducers.count())
    state, names = _state(res)
    # bridge retracted: 1 and 9 stay one session only if gap <= 5 (8 > 5)
    assert _by(state, names, "n") == [(1,), (1,)]


def test_intervals_over_stream():
    t = T(
        """
      | t | v | __time__ | __diff__
    1 | 1 | 1 | 2        | 1
    2 | 3 | 2 | 2        | 1
    3 | 7 | 4 | 4        | 1
    """
    )
    probes = T(
        """
      | at | __time__ | __diff__
    7 | 4  | 2        | 1
    """
    )
    res = t.windowby(
        pw.this.t,
        window=temporal.intervals_over(
            at=probes.at, lower_bound=-3, upper_bound=0
        ),
    ).reduce(
        at=pw.this._pw_window_end,  # upper_bound=0: end == probe location
        total=pw.reducers.sum(pw.this.v),
    )
    state, names = _state(res)
    # probe at 4 covers [1, 4]: rows t=1 and t=3
    assert _by(state, names, "at", "total") == [(4, 3)]


# ---- behaviors: Buffer/Forget/Freeze under late data --------------------


def test_common_behavior_delay_buffers_emission():
    """delay=d holds rows until the watermark passes start+d (BufferNode)."""
    t = T(
        """
      | t | v  | __time__
    1 | 1 | 10 | 0
    2 | 2 | 20 | 2
    3 | 9 | 30 | 4
    """
    )
    res = t.windowby(
        pw.this.t,
        window=temporal.tumbling(duration=4),
        behavior=temporal.common_behavior(delay=4),
    ).reduce(
        start=pw.this._pw_window_start,
        total=pw.reducers.sum(pw.this.v),
    )
    state, names = _state(res)
    got = dict(_by(state, names, "start", "total"))
    assert got.get(0) == 30  # both rows arrived before release: one emission


def test_common_behavior_keep_results_false_drops_closed_windows():
    t = T(
        """
      | t  | v  | __time__
    1 | 1  | 10 | 0
    2 | 9  | 20 | 2
    3 | 20 | 30 | 4
    """
    )
    res = t.windowby(
        pw.this.t,
        window=temporal.tumbling(duration=4),
        behavior=temporal.common_behavior(cutoff=2, keep_results=False),
    ).reduce(
        start=pw.this._pw_window_start,
        total=pw.reducers.sum(pw.this.v),
    )
    state, names = _state(res)
    starts = [s for s, _ in _by(state, names, "start", "total")]
    assert 0 not in starts  # closed window swept from output
    assert 20 in starts


# ---- temporal join edge cases -------------------------------------------


def test_interval_join_boundary_inclusive():
    left = T(
        """
      | t | __time__ | __diff__
    1 | 5 | 2        | 1
    """
    )
    right = T(
        """
      | t | v | __time__ | __diff__
    7 | 3 | 1 | 2        | 1
    8 | 7 | 2 | 2        | 1
    9 | 2 | 3 | 2        | 1
    """
    )
    r = left.interval_join(
        right, left.t, right.t, temporal.interval(-2, 2)
    ).select(lt=left.t, rv=right.v)
    rows = run_table(r)
    # [-2, 2] inclusive: right at 3 and 7 match, 2 does not
    assert sorted(rows.values()) == [(5, 1), (5, 2)]


def test_interval_join_late_right_revises():
    left = T(
        """
      | t | __time__ | __diff__
    1 | 5 | 2        | 1
    """
    )
    right = T(
        """
      | t | v | __time__ | __diff__
    7 | 4 | 1 | 6        | 1
    """
    )
    r = temporal.interval_join_left(
        left, right, left.t, right.t, temporal.interval(-1, 1)
    ).select(lt=left.t, rv=right.v)
    assert_stream_equality(
        r,
        [((5, None), 2, 1), ((5, None), 6, -1), ((5, 1), 6, 1)],
    )


def test_asof_join_direction_and_retraction():
    left = T(
        """
      | t | __time__ | __diff__
    1 | 5 | 2        | 1
    """
    )
    right = T(
        """
      | t | v | __time__ | __diff__
    7 | 3 | 1 | 2        | 1
    8 | 4 | 2 | 4        | 1
    8 | 4 | 2 | 6        | -1
    """
    )
    r = left.asof_join(right, left.t, right.t).select(lt=left.t, rv=right.v)
    rows = run_table(r)
    # after the t=4 retraction the nearest earlier right row is t=3 again
    assert sorted(rows.values()) == [(5, 1)]


def test_window_join_streamed():
    left = T(
        """
      | t | a | __time__ | __diff__
    1 | 1 | x | 2        | 1
    """
    )
    right = T(
        """
      | t | b | __time__ | __diff__
    7 | 2 | y | 4        | 1
    8 | 6 | z | 4        | 1
    """
    )
    r = left.window_join(
        right, left.t, right.t, temporal.tumbling(duration=4)
    ).select(a=left.a, b=right.b)
    rows = run_table(r)
    assert sorted(rows.values()) == [("x", "y")]
