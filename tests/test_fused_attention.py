"""Fused attention kernel + native tokenizer + encode fast paths.

The pallas kernel runs in interpret mode on CPU (tests/conftest.py
forces the CPU platform); numerics must match the XLA reference chain
bit-for-bit up to bf16 rounding, including padding masks and gradients
(the custom_vjp recompute path used by ContrastiveTrainer)."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pathway_tpu.ops.fused_attention import attention


def _rand_qkv(rng, b, s, d):
    return jnp.asarray(
        rng.standard_normal((b, s, 3 * d)).astype(np.float32)
    ).astype(jnp.bfloat16)


@pytest.mark.parametrize(
    "b,s,h,d",
    [
        (10, 32, 12, 384),  # MiniLM geometry (4 sequences packed per block)
        (7, 32, 12, 384),  # batch not divisible by pack factor
        (33, 64, 4, 128),
        (256, 16, 8, 256),
        (3, 200, 8, 256),  # seq > 128: single-sequence blocks
    ],
)
def test_kernel_matches_xla(b, s, h, d):
    rng = np.random.default_rng(0)
    qkv = _rand_qkv(rng, b, s, d)
    mask = np.ones((b, s), bool)
    mask[0, s // 2 :] = False
    mask[-1, 1:] = False
    mask = jnp.asarray(mask)
    got = attention(qkv, mask, n_heads=h, impl="interpret")
    want = attention(qkv, mask, n_heads=h, impl="xla")
    # compare only unmasked positions: padded query rows are garbage on
    # both paths and excluded by pooling
    m = np.asarray(mask)[:, :, None]
    err = np.max(np.abs(np.float32(got) - np.float32(want)) * m)
    assert err < 0.05, err


@pytest.mark.parametrize(
    "b,s,h,d",
    [
        (10, 32, 4, 128),  # 8 rows packed per block
        (5, 64, 12, 384),  # MiniLM width, 4 rows per block
        (3, 200, 8, 256),  # seq > 128: one row per block
    ],
)
def test_segment_packed_kernel_matches_xla(b, s, h, d):
    """SEQUENCE PACKING mode: several independent chunks share one row;
    the seg kernel must match the XLA packed reference on every
    non-padding position. Segment ids are unique across rows (the
    caller contract: row * stride + local), tails stay -1 padding."""
    rng = np.random.default_rng(3)
    qkv = _rand_qkv(rng, b, s, d)
    segs = np.full((b, s), -1, np.int32)
    for r in range(b):
        pos, local = 0, 0
        while pos < s - 2:
            ln = int(rng.integers(3, max(4, s // 3)))
            segs[r, pos : min(pos + ln, s - 1)] = r * 1000 + local
            pos += ln
            local += 1
    segs = jnp.asarray(segs)
    got = attention(qkv, None, n_heads=h, impl="interpret", segment_ids=segs)
    want = attention(qkv, None, n_heads=h, impl="xla", segment_ids=segs)
    # -1 pads of different rows may attend each other inside a packed
    # block (documented garbage): compare real positions only
    m = (np.asarray(segs) >= 0)[:, :, None]
    err = np.max(np.abs(np.float32(got) - np.float32(want)) * m)
    assert err < 0.05, err


def test_kernel_grad_matches_xla():
    rng = np.random.default_rng(1)
    b, s, h, d = 6, 32, 12, 384
    qkv = _rand_qkv(rng, b, s, d)
    mask = jnp.asarray(np.ones((b, s), bool))

    def loss(impl):
        def f(t):
            out = attention(t, mask, n_heads=h, impl=impl).astype(jnp.float32)
            return jnp.sum(out * out)

        return f

    ga = jax.grad(loss("interpret"))(qkv)
    gb = jax.grad(loss("xla"))(qkv)
    assert np.max(np.abs(np.float32(ga) - np.float32(gb))) < 0.2


def test_auto_impl_selects_xla_off_tpu():
    # conftest forces CPU: auto must not route into the TPU kernel
    rng = np.random.default_rng(2)
    qkv = _rand_qkv(rng, 4, 32, 96)
    mask = jnp.asarray(np.ones((4, 32), bool))
    out = attention(qkv, mask, n_heads=4, impl="auto")
    assert out.shape == (4, 32, 96)


def test_native_tokenizer_parity_hash_mode():
    from pathway_tpu import native
    from pathway_tpu.models.tokenizer import WordPieceTokenizer

    if not native.is_available():
        pytest.skip("native lib unavailable")
    tok = WordPieceTokenizer()
    texts = [
        "Hello, World! 123 foo-bar",
        "the quick brown fox",
        "",
        "a" * 300,
        "punct!!! ??? ,,,",
    ] + [f"text {i} borp{i}" for i in range(20)]
    assert tok.batch_encode(texts, max_len=32) == [
        tok.encode(t, max_len=32) for t in texts
    ]


def test_native_tokenizer_parity_vocab_mode(tmp_path):
    from pathway_tpu import native
    from pathway_tpu.models.tokenizer import WordPieceTokenizer

    if not native.is_available():
        pytest.skip("native lib unavailable")
    vf = tmp_path / "vocab.txt"
    vf.write_text(
        "\n".join(
            ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "the", "quick", "brown",
             "fox", "##s", "he", "##llo", "hello", "wor", "##ld", "!", ",",
             "123", "a", "##a"]
        )
        + "\n"
    )
    tok = WordPieceTokenizer(vocab_file=str(vf))
    texts = ["Hello, worlds!", "the quick foxs", "unknownword", "a" * 150]
    assert tok.batch_encode(texts, max_len=16) == [
        tok.encode(t, max_len=16) for t in texts
    ]


def test_native_tokenizer_non_ascii_fallback():
    from pathway_tpu.models.tokenizer import WordPieceTokenizer

    tok = WordPieceTokenizer()
    mix = ["héllo wörld", "plain ascii", "汉字 test"]
    assert tok.batch_encode(mix, 16) == [tok.encode(t, 16) for t in mix]


def test_encode_device_matches_encode():
    from pathway_tpu.models.encoder import EncoderConfig
    from pathway_tpu.models.sentence_encoder import SentenceEncoder

    cfg = EncoderConfig(
        vocab_size=30000,
        hidden_size=32,
        num_layers=1,
        num_heads=2,
        intermediate_size=64,
        max_position=64,
        pooling="mean",
    )
    enc = SentenceEncoder(
        config=cfg, checkpoint_dir="/nonexistent", max_seq_len=32, max_batch=16
    )
    # 64 rows = 4 uniform groups -> packed single-dispatch path
    texts = [f"hello world document {i} words" for i in range(64)]
    a = np.asarray(enc.encode_device(texts))
    b = enc.encode(texts)
    np.testing.assert_allclose(a, b, atol=2e-5)
    # ragged sizes -> per-group path
    texts2 = ["short", "a bit longer text here", "x " * 30] * 7
    a2 = np.asarray(enc.encode_device(texts2))
    b2 = enc.encode(texts2)
    np.testing.assert_allclose(a2, b2, atol=2e-5)
