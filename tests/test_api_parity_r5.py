"""Round-5 API parity closure: CSV dialect settings, wall-clock temporal
helpers, ml utils, and export-name aliases — each with a semantic check,
not just an import."""

from __future__ import annotations

import datetime
import time

import pathway_tpu as pw

from .utils import T, run_table


def test_csv_parser_settings_quoting(tmp_path):
    (tmp_path / "data.csv").write_text(
        'a|b\n"x|y"|1\n# a comment line\n"he said ""hi"""|2\n'
    )

    class S(pw.Schema):
        a: str
        b: int

    settings = pw.io.CsvParserSettings(delimiter="|", comment_character="#")
    t = pw.io.csv.read(
        str(tmp_path), schema=S, mode="static", csv_settings=settings
    )
    state = run_table(t)
    rows = sorted(state.values())
    assert rows == [('he said "hi"', 2), ("x|y", 1)]


def test_dsv_parser_with_settings():
    from pathway_tpu.io._formats import CsvParserSettings, DsvParser

    p = DsvParser(settings=CsvParserSettings(delimiter=";", comment_character="#"))
    assert p.parse("a;b") == []  # header
    assert p.parse("# skip me") == []
    assert p.parse('"x;y";2') == [("insert", {"a": "x;y", "b": "2"})]


def test_csv_comment_char_inside_quoted_field(tmp_path):
    """A quoted multi-line field whose continuation line starts with the
    comment character is data, not a comment (review finding r5)."""
    (tmp_path / "d.csv").write_text('a,b\n1,"line1\n#line2"\n2,z\n')

    class S(pw.Schema):
        a: int
        b: str

    t = pw.io.csv.read(
        str(tmp_path),
        schema=S,
        mode="static",
        csv_settings=pw.io.CsvParserSettings(comment_character="#"),
    )
    state = run_table(t)
    rows = sorted(state.values())
    assert rows == [(1, "line1\n#line2"), (2, "z")]


def test_csv_settings_drive_schema_inference(tmp_path):
    (tmp_path / "d.csv").write_text("# header comment\nx;y\n1;2.5\n3;4.5\n")
    t = pw.io.csv.read(
        str(tmp_path),
        mode="static",
        csv_settings=pw.io.CsvParserSettings(delimiter=";", comment_character="#"),
    )
    assert set(t.column_names()) == {"x", "y"}
    state = run_table(t)
    assert len(state) == 2


def test_csv_settings_on_object_store_path():
    """s3/s3_csv/minio decode path honors csv_settings too (review
    finding r5)."""
    from pathway_tpu.io._object_store import rows_from_payload

    payload = b'a|b\n# comment\n"x|1"|2\n'
    rows = rows_from_payload(
        payload,
        "csv",
        False,
        None,
        csv_settings=pw.io.CsvParserSettings(delimiter="|", comment_character="#"),
    )
    assert rows == [{"a": "x|1", "b": "2"}]


def test_csv_comment_skip_with_quoting_disabled(tmp_path):
    """Under QUOTE_NONE a stray quote char must not disable comment
    skipping (review finding r5)."""
    (tmp_path / "d.csv").write_text('a,b\n1,5" pipe\n# note\n2,z\n')

    class S(pw.Schema):
        a: int
        b: str

    t = pw.io.csv.read(
        str(tmp_path),
        schema=S,
        mode="static",
        csv_settings=pw.io.CsvParserSettings(
            enable_quoting=False, comment_character="#"
        ),
    )
    state = run_table(t)
    assert sorted(state.values()) == [(1, '5" pipe'), (2, "z")]


def test_utc_now_cache_invalidates_on_clear_graph():
    a = pw.temporal.utc_now(refresh_rate=datetime.timedelta(seconds=5))
    pw.clear_graph()
    b = pw.temporal.utc_now(refresh_rate=datetime.timedelta(seconds=5))
    assert a is not b
    from pathway_tpu.internals.parse_graph import G

    assert b in G.tables  # the fresh clock belongs to the NEW program
    pw.clear_graph()


def test_subscribe_callback_protocols():
    # the exported names are typing.Protocols matching subscribe's API
    def cb(key, row, time, is_addition):
        return None

    assert isinstance(cb, pw.io.OnChangeCallback)
    assert isinstance(lambda: None, pw.io.OnFinishCallback)


def test_utc_now_ticks(monkeypatch):
    monkeypatch.setenv("PATHWAY_TPU_CLOCK_MAX_TICKS", "3")
    received = []
    clock = pw.temporal.utc_now(refresh_rate=datetime.timedelta(milliseconds=50))
    pw.io.subscribe(
        clock,
        on_change=lambda key, row, time, is_addition: received.append(
            row["timestamp_utc"]
        ),
    )
    pw.run()
    assert len(received) == 3
    assert all(ts.tzinfo is not None for ts in received)
    assert sorted(received) == received
    pw.clear_graph()


def test_utc_now_shared_per_rate():
    a = pw.temporal.utc_now(refresh_rate=datetime.timedelta(seconds=5))
    b = pw.temporal.utc_now(refresh_rate=datetime.timedelta(seconds=5))
    c = pw.temporal.utc_now(refresh_rate=datetime.timedelta(seconds=9))
    assert a is b
    assert a is not c
    pw.clear_graph()


def test_classifier_accuracy():
    labels = T(
        """
          | label
        1 | 1
        2 | 0
        3 | 1
        """
    )
    predicted = T(
        """
          | predicted_label
        1 | 1
        2 | 1
        3 | 1
        """
    )
    acc = pw.ml.utils.classifier_accuracy(predicted, labels)
    state = run_table(acc)
    by_match = {bool(v[1]): v[0] for v in state.values()}
    assert by_match == {True: 2, False: 1}


def test_predict_asof_now_wrapper():
    @pw.ml.utils._predict_asof_now
    def pipeline(col):
        return col.table.select(out=col * 2)

    t = T(
        """
          | x
        1 | 3
        """
    )
    res = pipeline(t.x)
    state = run_table(res)
    assert list(state.values()) == [(6,)]


def test_sorted_index_and_usearch_aliases():
    assert pw.indexing.USearchKnn is pw.indexing.UsearchKnn
    assert set(pw.indexing.SortedIndex.__annotations__) == {"index", "oracle"}
    assert pw.temporal.AsofJoinResult is not None
    assert pw.temporal.IntervalJoinResult is pw.temporal.WindowJoinResult
    iv = pw.temporal.Interval(-1, 1)
    assert (iv.lower_bound, iv.upper_bound) == (-1, 1)


def test_inactivity_detection_builds():
    # graph-construction check (full wall-clock behavior needs minutes);
    # the pipeline must build with and without instance and return two
    # tables with the documented columns
    class S(pw.Schema):
        t: datetime.datetime
        sensor: str

    events = pw.io.python.read(_NullSubject(), schema=S)
    inact, resumed = pw.temporal.inactivity_detection(
        events.t,
        allowed_inactivity_period=datetime.timedelta(seconds=2),
        refresh_rate=datetime.timedelta(seconds=1),
        instance=events.sensor,
    )
    assert "inactive_t" in inact.column_names()
    assert "instance" in inact.column_names()
    assert "resumed_t" in resumed.column_names()

    inact2, resumed2 = pw.temporal.inactivity_detection(
        events.t,
        allowed_inactivity_period=datetime.timedelta(seconds=2),
    )
    assert "instance" not in inact2.column_names()
    assert "instance" not in resumed2.column_names()
    pw.clear_graph()


class _NullSubject(pw.io.python.ConnectorSubject):
    def run(self):
        pass


def test_multiapply_all_rows_keeps_keys():
    t = T(
        """
          | colA | colB
        1 | 1    | 10
        2 | 2    | 20
        3 | 3    | 30
        """
    )

    def add_total_sum(c1, c2):
        s = sum(c1) + sum(c2)
        return [x + s for x in c1], [x + s for x in c2]

    r = pw.stdlib.utils.col.multiapply_all_rows(
        t.colA, t.colB, fun=add_total_sum, result_col_names=["res1", "res2"]
    )
    assert sorted(run_table(r).values()) == [(67, 76), (68, 86), (69, 96)]
    # original keys preserved: restrict back onto the source universe
    joined = run_table(t.select(a=t.colA, r1=r.restrict(t).res1))
    assert sorted(joined.values()) == [(1, 67), (2, 68), (3, 69)]
    pw.clear_graph()


def test_apply_all_rows_single_column():
    t = T(
        """
          | v
        1 | 5
        2 | 7
        """
    )
    r = pw.stdlib.utils.col.apply_all_rows(
        t.v, fun=lambda vs: [x - min(vs) for x in vs], result_col_name="rel"
    )
    assert sorted(run_table(r).values()) == [(0,), (2,)]
    pw.clear_graph()


def test_unpack_col_dict():
    t = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=pw.Json),
        rows=[
            ({"field_a": 13, "field_b": "foo", "field_c": False},),
            ({"field_a": 17, "field_c": True, "field_d": 3.4},),
        ],
    )

    class DS(pw.Schema):
        field_a: int
        field_b: str | None
        field_c: bool
        field_d: float | None

    r = pw.stdlib.utils.col.unpack_col_dict(t.data, schema=DS)
    assert sorted(run_table(r).values()) == [
        (13, "foo", False, None),
        (17, None, True, 3.4),
    ]
    pw.clear_graph()


def test_filtering_bucketing_flatten_column():
    import warnings

    t = T(
        """
          | g | v
        1 | a | 5
        2 | a | 9
        3 | b | 2
        """
    )
    mx = pw.stdlib.utils.argmax_rows(t, t.g, what=t.v)
    assert sorted(run_table(mx).values()) == [("a", 9), ("b", 2)]
    pw.clear_graph()
    t2 = T(
        """
          | g | v
        1 | a | 5
        2 | a | 9
        """
    )
    mn = pw.stdlib.utils.argmin_rows(t2, t2.g, what=t2.v)
    assert sorted(run_table(mn).values()) == [("a", 5)]
    pw.clear_graph()

    assert pw.stdlib.utils.bucketing.truncate_to_minutes(
        datetime.datetime(2026, 7, 31, 12, 34, 56, 789)
    ) == datetime.datetime(2026, 7, 31, 12, 34)

    t3 = T(
        """
          | pet | age
        1 | Dog | 2
        """
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        f = pw.stdlib.utils.col.flatten_column(t3.pet)
    vals = sorted(run_table(f).values())
    assert len(vals) == 3 and {v[0] for v in vals} == {"D", "o", "g"}
    pw.clear_graph()


def test_unpack_col_dict_non_object_cells_yield_none():
    t = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=pw.Json),
        rows=[({"field_a": 1},), ([1, 2],), ("plain",)],
    )

    class DS(pw.Schema):
        field_a: int | None

    r = pw.stdlib.utils.col.unpack_col_dict(t.data, schema=DS)
    assert sorted(run_table(r).values(), key=str) == [(1,), (None,), (None,)]
    pw.clear_graph()


def test_kafka_simple_read():
    import pytest as _pytest

    msgs = [(b"k1", b"hello"), (b"k2", b"world")]
    t = pw.io.kafka.simple_read(
        "srv:9092", "t", format="plaintext", _consumer=iter(msgs)
    )
    state = run_table(t)
    vals = sorted(v[-1] for v in state.values())
    assert vals == ["hello", "world"]
    pw.clear_graph()
    # anonymous groups cannot shard partitions: the footgun combination
    # is refused (a silent every-process-reads-everything would follow)
    with _pytest.raises(ValueError, match="group.id"):
        pw.io.kafka.simple_read("srv:9092", "t", parallel_readers=True)
    pw.clear_graph()


def test_persistence_engine_config_ctx():
    with pw.persistence.get_persistence_engine_config(None) as c:
        assert c is None
    cfg = pw.persistence.Config.simple_config(pw.persistence.Backend.mock([]))
    with pw.persistence.get_persistence_engine_config(cfg) as c:
        assert c is cfg


def test_rag_client_list_documents_keys_filter(monkeypatch):
    from pathway_tpu.xpacks.llm import question_answering as qa

    sent = {}

    def fake_post(url, data, headers=None, timeout=None):
        sent["url"] = url
        return [
            {"path": "/a", "size": 3, "owner": "x"},
            {"path": "/b", "size": 7, "owner": "y"},
        ]

    monkeypatch.setattr(qa, "send_post_request", fake_post)
    c = qa.RAGClient(host="127.0.0.1", port=12345)
    docs = c.pw_list_documents(keys=["path", "size"])
    assert docs == [{"path": "/a", "size": 3}, {"path": "/b", "size": 7}]
    assert sent["url"].endswith("/v1/pw_list_documents")


def test_udfs_deprecated_aliases():
    import warnings

    @pw.udfs.async_options(capacity=2)
    async def double(x):
        return x * 2

    t = T(
        """
          | a
        1 | 3
        """
    )
    state = run_table(t.select(b=double(pw.this.a)))
    assert list(state.values()) == [(6,)]
    pw.clear_graph()

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")

        @pw.udfs.udf_async
        async def trip(x):
            return x * 3

        assert any("deprecated" in str(x.message) for x in w)
    t2 = T(
        """
          | a
        1 | 3
        """
    )
    state2 = run_table(t2.select(b=trip(pw.this.a)))
    assert list(state2.values()) == [(9,)]
    assert pw.udfs.UDFFunction is pw.udfs.UDF
    pw.clear_graph()


# ---- debug utilities (reference debug/__init__.py parity) ----


class _W(pw.Schema):
    w: str


def test_stream_generator_batches_become_epochs():
    sg = pw.debug.StreamGenerator()
    t = sg.table_from_list_of_batches([[{"w": "a"}, {"w": "b"}], [{"w": "a"}]], _W)
    counts = t.groupby(pw.this.w).reduce(w=pw.this.w, n=pw.reducers.count())
    stream, _names = pw.debug.table_to_stream(counts)
    assert len({s[2] for s in stream}) >= 2  # two distinct epochs
    keys, cols = pw.debug.table_to_dicts(counts)
    assert {cols["w"][k]: cols["n"][k] for k in keys} == {"a": 2, "b": 1}
    pw.clear_graph()


def test_stream_generator_by_workers_and_validation():
    import pytest as _pytest

    sg = pw.debug.StreamGenerator()
    t = sg.table_from_list_of_batches_by_workers([{0: [{"w": "x"}], 1: [{"w": "y"}]}], _W)
    keys, cols = pw.debug.table_to_dicts(t)
    assert sorted(cols["w"].values()) == ["x", "y"]
    pw.clear_graph()
    with _pytest.raises(ValueError, match="negative"):
        sg._table_from_dict({-2: {0: [(1, 1, ["x"])]}}, _W)
    with _pytest.warns(UserWarning, match="doubl"):
        sg._table_from_dict({3: {0: [(1, 1, ["x"])]}}, _W)
    pw.clear_graph()


def test_stream_generator_pandas_scripted_retraction():
    import pandas as pd

    sg = pw.debug.StreamGenerator()
    df = pd.DataFrame({"w": ["a", "b", "a"], "_time": [2, 2, 4], "_diff": [1, 1, -1]})
    t = sg.table_from_pandas(df, schema=_W)
    keys, cols = pw.debug.table_to_dicts(t)
    assert sorted(cols["w"].values()) == ["b"]
    pw.clear_graph()


def test_parquet_round_trip(tmp_path):
    t = pw.debug.table_from_markdown(
        """
          | a | b
        1 | 1 | x
        2 | 2 | y
        """
    )
    f = str(tmp_path / "t.parquet")
    pw.debug.table_to_parquet(t, f)
    pw.clear_graph()
    t2 = pw.debug.table_from_parquet(f)
    keys, cols = pw.debug.table_to_dicts(t2.select(a=pw.this.a, b=pw.this.b))
    assert sorted((cols["a"][k], cols["b"][k]) for k in keys) == [(1, "x"), (2, "y")]
    pw.clear_graph()
