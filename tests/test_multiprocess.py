"""Multi-process dataflow execution (PATHWAY_PROCESSES > 1).

Reference: `pathway spawn --processes P` launches P OS processes that
run the same program and exchange data by key shard over TCP
(/root/reference/python/pathway/cli.py:53,
/root/reference/src/engine/dataflow/config.rs:62-120). Here: wordcount
output of a 2-process run must be byte-identical to the single-process
run, sinks fire on process 0 only, and cross-process exchange actually
carries rows (groups hash to both processes)."""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROGRAM = textwrap.dedent(
    """
    import os
    import pathway_tpu as pw

    class S(pw.Schema):
        word: str

    t = pw.io.jsonlines.read(os.environ["WC_IN"], schema=S, mode="static")
    c = t.groupby(pw.this.word).reduce(
        pw.this.word, n=pw.reducers.count()
    )
    out = os.environ["WC_OUT"] + "." + os.environ.get("PATHWAY_PROCESS_ID", "0")
    pw.io.csv.write(c, out)
    pw.run(monitoring_level="none")
    """
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(tmp_path, processes: int, threads: int, tag: str) -> str:
    prog = tmp_path / f"wc_{tag}.py"
    prog.write_text(PROGRAM)
    out = str(tmp_path / f"out_{tag}.csv")
    env = dict(os.environ)
    env.update(
        WC_IN=str(tmp_path / "in"),
        WC_OUT=out,
        JAX_PLATFORMS="cpu",
        PATHWAY_THREADS=str(threads),
        PATHWAY_PROCESSES=str(processes),
        PATHWAY_FIRST_PORT=str(_free_port()),
        PATHWAY_CLUSTER_TOKEN="test-cluster-secret",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    procs = []
    for pid in range(processes):
        e = dict(env)
        e["PATHWAY_PROCESS_ID"] = str(pid)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(prog)],
                env=e,
                cwd=str(tmp_path),
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    for p in procs:
        try:
            outp, errp = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"rc={p.returncode}\n{errp[-4000:]}"
    return out


STREAM_PROGRAM = textwrap.dedent(
    """
    import os, threading, time, json
    import pathway_tpu as pw

    class S(pw.Schema):
        word: str

    t = pw.io.jsonlines.read(
        os.environ["WC_IN"], schema=S, mode="streaming",
        autocommit_duration_ms=150,
    )
    c = t.groupby(pw.this.word).reduce(pw.this.word, n=pw.reducers.count())
    out = os.environ["WC_OUT"] + "." + os.environ.get("PATHWAY_PROCESS_ID", "0")
    pw.io.jsonlines.write(c, out)

    def mutate():
        if os.environ.get("PATHWAY_PROCESS_ID", "0") == "0":
            time.sleep(1.0)
            with open(os.path.join(os.environ["WC_IN"], "late.jsonl"), "w") as f:
                for w in ["cat", "late", "late"]:
                    f.write(json.dumps({"word": w}) + "\\n")
        time.sleep(3.0)
        os._exit(0)

    threading.Thread(target=mutate, daemon=True).start()
    pw.run(monitoring_level="none")
    """
)


def _net_counts(path: str) -> dict:
    state: dict = {}
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            key = rec["word"]
            if rec["diff"] > 0:
                state[key] = rec["n"]
            elif state.get(key) == rec["n"]:
                del state[key]
    return state


def test_streaming_two_process_wordcount(wc_input):
    """Multiple live epochs over the round protocol: the net state after
    streaming updates matches the single-process run."""
    tmp = wc_input
    prog = tmp / "wc_stream.py"
    prog.write_text(STREAM_PROGRAM)
    out = str(tmp / "out_stream.csv")
    env = dict(os.environ)
    env.update(
        WC_IN=str(tmp / "in"),
        WC_OUT=out,
        JAX_PLATFORMS="cpu",
        PATHWAY_THREADS="1",
        PATHWAY_PROCESSES="2",
        PATHWAY_FIRST_PORT=str(_free_port()),
        PATHWAY_CLUSTER_TOKEN="test-cluster-secret",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    procs = []
    for pid in range(2):
        e = dict(env)
        e["PATHWAY_PROCESS_ID"] = str(pid)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(prog)],
                env=e,
                cwd=str(tmp),
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    for p in procs:
        outp, errp = p.communicate(timeout=120)
        assert p.returncode == 0, errp[-4000:]
    got = _net_counts(out + ".0")
    assert got == {
        "cat": 22,
        "dog": 14,
        "bird": 7,
        "emu": 7,
        "fox": 7,
        "owl": 7,
        "late": 2,
    }


@pytest.fixture()
def wc_input(tmp_path):
    d = tmp_path / "in"
    d.mkdir()
    words = ["cat", "dog", "cat", "bird", "dog", "cat", "emu", "fox", "owl"] * 7
    with open(d / "words.jsonl", "w") as f:
        for w in words:
            f.write(json.dumps({"word": w}) + "\n")
    return tmp_path


def test_two_process_wordcount_matches_single(wc_input):
    tmp = wc_input
    single = _spawn(tmp, processes=1, threads=1, tag="single")
    multi = _spawn(tmp, processes=2, threads=1, tag="multi")
    with open(single + ".0") as f:
        expect = f.read()
    with open(multi + ".0") as f:
        got = f.read()
    assert got == expect
    assert "cat" in expect and "21" in expect
    # sinks fire on process 0 only
    assert not os.path.exists(multi + ".1")


def test_pathway_spawn_processes_cli(wc_input):
    """`pathway spawn --processes 2 prog.py` end to end (reference
    cli.py:53): CLI sets the PATHWAY_* topology env and launches both
    processes; output equals the single-process run."""
    tmp = wc_input
    single = _spawn(tmp, processes=1, threads=1, tag="cli_ref")
    prog = tmp / "wc_cli.py"
    prog.write_text(PROGRAM)
    out = str(tmp / "out_cli.csv")
    env = dict(os.environ)
    env.update(
        WC_IN=str(tmp / "in"),
        WC_OUT=out,
        JAX_PLATFORMS="cpu",
        PATHWAY_FIRST_PORT=str(_free_port()),
        PATHWAY_CLUSTER_TOKEN="test-cluster-secret",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    r = subprocess.run(
        [
            sys.executable,
            "-m",
            "pathway_tpu",
            "spawn",
            "--processes",
            "2",
            "--first-port",
            env["PATHWAY_FIRST_PORT"],
            str(prog),
        ],
        env=env,
        cwd=str(tmp),
        capture_output=True,
        text=True,
        timeout=180,
    )
    assert r.returncode == 0, r.stderr[-4000:]
    with open(single + ".0") as f:
        expect = f.read()
    with open(out + ".0") as f:
        got = f.read()
    assert got == expect


def test_two_process_two_threads_wordcount(wc_input):
    tmp = wc_input
    single = _spawn(tmp, processes=1, threads=1, tag="s2")
    multi = _spawn(tmp, processes=2, threads=2, tag="m2")
    with open(single + ".0") as f:
        expect = f.read()
    with open(multi + ".0") as f:
        got = f.read()
    assert got == expect


KAFKA_PART_PROGRAM = textwrap.dedent(
    """
    import json, os, time
    import pathway_tpu as pw

    N = int(os.environ["KP_N"])
    PID = os.environ.get("PATHWAY_PROCESS_ID", "0")

    class Timed:
        def __init__(self, msgs):
            self.msgs = msgs
        def __iter__(self):
            t0 = time.perf_counter()
            for m in self.msgs:
                yield m
            dt = time.perf_counter() - t0
            with open(os.environ["KP_STATS"] + "." + PID, "w") as f:
                json.dump({"pid": PID, "ingest_s": dt}, f)

    # realistic event payloads: parse cost dominates iteration overhead
    msgs = [
        (None, json.dumps({
            "word": ["cat", "dog", "bird"][i % 3], "i": i,
            "ts": f"2026-07-30T12:{i % 60:02d}:{(i * 7) % 60:02d}Z",
            "session": f"sess-{i % 1000:04d}-{i % 17}",
            "payload": "x" * 120 + str(i),
            "score": i * 0.125, "flags": [i % 2 == 0, i % 3 == 0],
            "nested": {"a": i % 10, "b": str(i % 100), "c": [i, i + 1]},
        }).encode())
        for i in range(N)
    ]

    class S(pw.Schema):
        word: str
        i: int

    t = pw.io.kafka.read(
        {}, "topic", schema=S, format="json",
        parallel_readers=True, _consumer=Timed(msgs),
        autocommit_duration_ms=100,
    )
    if os.environ.get("KP_SINK") == "null":
        # isolate reader bandwidth: rows die at a local filter so no
        # downstream or cross-process work competes with the readers
        pw.io.null.write(t.filter(pw.this.i < 0))
    else:
        c = t.groupby(pw.this.word).reduce(pw.this.word, n=pw.reducers.count())
        out = os.environ["WC_OUT"] + "." + PID
        pw.io.jsonlines.write(c, out)
    pw.run(monitoring_level="none")
    """
)


def _spawn_prog(tmp_path, program: str, processes: int, tag: str, extra_env=None) -> str:
    prog = tmp_path / f"prog_{tag}.py"
    prog.write_text(program)
    out = str(tmp_path / f"out_{tag}.csv")
    env = dict(os.environ)
    env.update(
        WC_IN=str(tmp_path / "in"),
        WC_OUT=out,
        JAX_PLATFORMS="cpu",
        PATHWAY_THREADS="1",
        PATHWAY_PROCESSES=str(processes),
        PATHWAY_FIRST_PORT=str(_free_port()),
        PATHWAY_CLUSTER_TOKEN="test-cluster-secret",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    env.update(extra_env or {})
    procs = []
    for pid in range(processes):
        e = dict(env)
        e["PATHWAY_PROCESS_ID"] = str(pid)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(prog)],
                env=e,
                cwd=str(tmp_path),
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    for p in procs:
        try:
            outp, errp = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"rc={p.returncode}\n{errp[-4000:]}"
    return out


def test_partitioned_kafka_reads_scale(tmp_path):
    """Partitioned source mode (reference graph.rs:943-950
    parallel_readers): each process reads ITS share of the topic, so
    2-process aggregate ingest bandwidth is ~2x one reader — VERDICT r2
    item 5 asks >=1.8x. Correctness: the 2-process wordcount equals the
    single-process one."""
    # -- correctness: the 2-process result equals the single-process one
    n = 9000
    stats1 = str(tmp_path / "stats1")
    stats2 = str(tmp_path / "stats2")
    single = _spawn_prog(
        tmp_path, KAFKA_PART_PROGRAM, 1, "kp1", {"KP_N": str(n), "KP_STATS": stats1}
    )
    multi = _spawn_prog(
        tmp_path, KAFKA_PART_PROGRAM, 2, "kp2", {"KP_N": str(n), "KP_STATS": stats2}
    )
    # worker-read rows may land an epoch later than process 0's share,
    # so compare the NET final state, not the raw update log
    assert _net_counts(multi + ".0") == _net_counts(single + ".0") == {
        "cat": 3000,
        "dog": 3000,
        "bird": 3000,
    }

    # -- bandwidth: reader-isolated (null sink) ingest time. Wall-clock
    # scaling needs real cores: on a single-CPU host two parsers just
    # time-share, so only the ownership proof above applies there.
    if len(os.sched_getaffinity(0)) < 2:
        pytest.skip("host has one CPU: partitioned readers cannot run in parallel")
    n = 60000
    stats3 = str(tmp_path / "stats3")
    stats4 = str(tmp_path / "stats4")
    _spawn_prog(
        tmp_path,
        KAFKA_PART_PROGRAM,
        1,
        "kp3",
        {"KP_N": str(n), "KP_STATS": stats3, "KP_SINK": "null"},
    )
    _spawn_prog(
        tmp_path,
        KAFKA_PART_PROGRAM,
        2,
        "kp4",
        {"KP_N": str(n), "KP_STATS": stats4, "KP_SINK": "null"},
    )
    with open(stats3 + ".0") as f:
        t1 = json.load(f)["ingest_s"]
    times = []
    for pid in (0, 1):
        with open(stats4 + f".{pid}") as f:
            times.append(json.load(f)["ingest_s"])
    # aggregate bandwidth vs the slowest reader of the 2-proc run
    speedup = t1 / max(times)
    assert speedup >= 1.8, f"partitioned ingest speedup {speedup:.2f}x < 1.8x (t1={t1:.3f}s, t2={times})"


def test_three_process_peer_mesh_wordcount(wc_input):
    """P=3 engages the direct worker<->worker mesh (PeerMesh): output
    must still match the single-process run and sinks stay on p0."""
    tmp = wc_input
    single = _spawn(tmp, processes=1, threads=1, tag="mesh_s")
    multi = _spawn(tmp, processes=3, threads=1, tag="mesh_m")
    with open(single + ".0") as f:
        expect = f.read()
    with open(multi + ".0") as f:
        got = f.read()
    assert got == expect
    assert not os.path.exists(multi + ".1") and not os.path.exists(multi + ".2")


PERSIST_PART_PROGRAM = textwrap.dedent(
    """
    import json, os
    import pathway_tpu as pw
    from pathway_tpu.io._connector import input_table_from_reader

    N = int(os.environ["PP_N"])
    PID = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    NPROC = int(os.environ.get("PATHWAY_PROCESSES", "1"))

    class S(pw.Schema):
        word: str

    WORDS = ["cat", "dog", "bird"]

    def reader(ctx):
        start = int(ctx.offsets.get("pos", 0))
        for i in range(N):
            if NPROC > 1 and i % NPROC != ctx.process_id:
                continue
            if i < start:
                continue  # already ingested before the restart
            ctx.insert({"word": WORDS[i % 3]}, offsets={"pos": i + 1})
        ctx.commit()

    t = input_table_from_reader(
        S, reader, name="part_src", parallel_readers=True,
        persistent_id="pp", supports_offsets=True,
        autocommit_duration_ms=100,
    )
    c = t.groupby(pw.this.word).reduce(pw.this.word, n=pw.reducers.count())
    out = os.environ["WC_OUT"] + "." + str(PID)
    pw.io.jsonlines.write(c, out)
    pw.run(
        monitoring_level="none",
        persistence_config=pw.persistence.Config.simple_config(
            pw.persistence.Backend.filesystem(os.environ["PP_STORE"])
        ),
    )
    """
)


def test_partitioned_source_persistence_across_restart(tmp_path):
    """Worker-side persistence (reference per-worker storage,
    tracker.rs:49): each process logs its partition slice and resumes
    from its own offsets — a restart with more input ingests only the
    delta, and counts stay exactly-once."""
    store = str(tmp_path / "pstore")
    env1 = {"PP_N": "60", "PP_STORE": store}
    out1 = _spawn_prog(tmp_path, PERSIST_PART_PROGRAM, 2, "pp1", env1)
    assert _net_counts(out1 + ".0") == {"cat": 20, "dog": 20, "bird": 20}

    # restart with 30 more messages: only the delta is re-ingested
    env2 = {"PP_N": "90", "PP_STORE": store}
    out2 = _spawn_prog(tmp_path, PERSIST_PART_PROGRAM, 2, "pp2", env2)
    assert _net_counts(out2 + ".0") == {"cat": 30, "dog": 30, "bird": 30}

    # restart with NO new input: replay rebuilds state but the sink must
    # not re-deliver anything (exactly-once across worker partitions)
    out3 = _spawn_prog(tmp_path, PERSIST_PART_PROGRAM, 2, "pp3", env2)
    import os as _os

    redelivered = (
        open(out3 + ".0").read().strip() if _os.path.exists(out3 + ".0") else ""
    )
    assert redelivered == "", f"sink re-delivered after restart: {redelivered[:200]}"
