"""IO connector coverage: fs/csv/jsonlines/plaintext read+write,
streaming watch semantics, python write observer, demo streams.

Mirrors reference io tests (python/pathway/tests/test_io.py)."""

from __future__ import annotations

import csv
import json
import os
import threading
import time

import pytest

import pathway_tpu as pw
from .utils import run_table


class WordSchema(pw.Schema):
    word: str
    n: int


def test_csv_read_static_with_schema_inference(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("word,n\nfoo,1\nbar,2\n")
    t = pw.io.csv.read(str(p), mode="static")
    state = run_table(t)
    assert sorted(state.values()) == [("bar", 2), ("foo", 1)]
    pw.clear_graph()


def test_jsonlines_static_roundtrip(tmp_path):
    src = tmp_path / "in.jsonl"
    with open(src, "w") as f:
        f.write(json.dumps({"word": "x", "n": 7}) + "\n")
        f.write(json.dumps({"word": "y", "n": 8}) + "\n")
    t = pw.io.jsonlines.read(str(src), schema=WordSchema, mode="static")
    out = tmp_path / "out.jsonl"
    pw.io.jsonlines.write(t, str(out))
    pw.run()
    pw.clear_graph()
    recs = [json.loads(l) for l in open(out) if l.strip()]
    assert sorted((r["word"], r["n"], r["diff"]) for r in recs) == [
        ("x", 7, 1),
        ("y", 8, 1),
    ]


def test_csv_write_includes_time_diff(tmp_path):
    src = tmp_path / "in.csv"
    src.write_text("word,n\nfoo,1\n")
    t = pw.io.csv.read(str(src), mode="static")
    out = tmp_path / "out.csv"
    pw.io.csv.write(t, str(out))
    pw.run()
    pw.clear_graph()
    rows = list(csv.DictReader(open(out)))
    assert rows[0]["word"] == "foo"
    assert rows[0]["diff"] == "1"


def test_plaintext_read(tmp_path):
    p = tmp_path / "doc.txt"
    p.write_text("hello\nworld\n")
    t = pw.io.plaintext.read(str(p), mode="static")
    state = run_table(t)
    assert sorted(r[0] for r in state.values()) == ["hello", "world"]
    pw.clear_graph()


def test_fs_streaming_watches_additions_and_deletions(tmp_path):
    in_dir = tmp_path / "watch"
    in_dir.mkdir()
    (in_dir / "a.txt").write_text("one\n")

    events = []
    t = pw.io.plaintext.read(str(in_dir), mode="streaming", autocommit_duration_ms=50)
    pw.io.subscribe(
        t,
        on_change=lambda key, row, time, is_addition: events.append(
            (row["data"], is_addition)
        ),
    )

    from pathway_tpu.internals.graph_runner import GraphRunner

    runner = GraphRunner()
    for spec in list(pw.parse_graph.subscriptions):
        runner.subscribe(spec["table"], on_change=spec.get("on_change"))

    def mutate():
        time.sleep(1.0)
        (in_dir / "b.txt").write_text("two\n")
        time.sleep(1.0)
        os.remove(in_dir / "a.txt")
        time.sleep(1.0)
        runner.engine.stop()

    th = threading.Thread(target=mutate, daemon=True)
    th.start()
    runner.run()
    th.join(timeout=10)
    pw.clear_graph()

    assert ("one", True) in events
    assert ("two", True) in events
    assert ("one", False) in events  # deletion retracts
    assert ("two", False) not in events


def test_python_write_observer(tmp_path):
    src = tmp_path / "in.jsonl"
    src.write_text(json.dumps({"word": "z", "n": 1}) + "\n")
    t = pw.io.jsonlines.read(str(src), schema=WordSchema, mode="static")

    seen = []

    class Observer(pw.io.python.ConnectorObserver):
        def on_change(self, key, row, time, is_addition):
            seen.append((row["word"], is_addition))

        def on_end(self):
            seen.append(("END", None))

    pw.io.python.write(t, Observer())
    pw.run()
    pw.clear_graph()
    assert ("z", True) in seen and ("END", None) in seen


def test_demo_range_stream():
    t = pw.demo.range_stream(nb_rows=5, autocommit_duration_ms=10)
    state = run_table(t)
    assert sorted(r[0] for r in state.values()) == [0.0, 1.0, 2.0, 3.0, 4.0]
    pw.clear_graph()


def test_null_write():
    src = pw.debug.table_from_markdown(
        """
          | a
        1 | 1
        """
    )
    pw.io.null.write(src)
    pw.run()
    pw.clear_graph()
