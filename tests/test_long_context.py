"""Ring attention / sequence-parallel long-context encoding on the
virtual 8-device mesh: must match single-device full attention exactly
(same math, online-softmax accumulation)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pathway_tpu.models.encoder import EncoderConfig, TextEncoder, init_params
from pathway_tpu.models.long_context import ring_attention, ring_encode
from pathway_tpu.parallel.sharding import make_mesh, shard_map


def _cfg():
    return EncoderConfig(
        vocab_size=512,
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        intermediate_size=128,
        max_position=128,
        dtype=jnp.float32,
        pooling="mean",
    )


def test_ring_attention_matches_full_attention():
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(model_parallel=1)  # 8-way sequence ring
    rng = np.random.default_rng(0)
    B, H, S, D = 2, 4, 64, 16
    q = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    mask = np.ones((B, S), bool)
    mask[:, 50:] = False  # ragged tail
    mask = jnp.asarray(mask)

    ringed = jax.jit(
        shard_map(
            lambda q, k, v, m: ring_attention(q, k, v, m, "data"),
            mesh=mesh,
            in_specs=(P(None, None, "data"), P(None, None, "data"), P(None, None, "data"), P(None, "data")),
            out_specs=P(None, None, "data"),
            check_vma=False,
        )
    )(q, k, v, mask)

    # reference: plain full attention
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    scores = jnp.where(mask[:, None, None, :], scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    full = jnp.einsum("bhqk,bhkd->bhqd", probs, v)

    np.testing.assert_allclose(np.asarray(ringed), np.asarray(full), rtol=2e-5, atol=2e-5)


def test_ring_encode_matches_single_device():
    cfg = _cfg()
    module = TextEncoder(cfg)
    params = init_params(module, cfg)
    mesh = make_mesh(model_parallel=1)

    rng = np.random.default_rng(1)
    B, S = 2, 64  # 8 tokens per shard
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, S)), jnp.int32)
    mask = np.ones((B, S), bool)
    mask[1, 40:] = False
    mask = jnp.asarray(mask)

    ringed = ring_encode(params, cfg, ids, mask, mesh, axis="data")
    direct = module.apply(params, ids, mask)
    np.testing.assert_allclose(np.asarray(ringed), np.asarray(direct), rtol=3e-4, atol=3e-4)


def test_ring_encode_long_sequence_beyond_single_block():
    """S=128 over 8 shards: positions are global, pooling is psum'd."""
    cfg = _cfg()
    module = TextEncoder(cfg)
    params = init_params(module, cfg)
    mesh = make_mesh(model_parallel=1)
    rng = np.random.default_rng(2)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 128)), jnp.int32)
    mask = jnp.ones((1, 128), bool)
    ringed = ring_encode(params, cfg, ids, mask, mesh)
    direct = module.apply(params, ids, mask)
    np.testing.assert_allclose(np.asarray(ringed), np.asarray(direct), rtol=3e-4, atol=3e-4)
