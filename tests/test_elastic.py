"""Elastic mesh: live grow/shrink/reshard under traffic.

Covers the reshard plane end to end: config parsing, the serve-through
handle (delta mirroring + dual-window dedup), byte-identical migration
for all three index families, chaos raise/kill at every protocol
boundary (rollback or idempotent completion), the durable reshard
intent + SIGKILL recovery, generation fencing, the watermark
controller, and the admission/Retry-After integration.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu import elastic
from pathway_tpu.elastic import ElasticConfig
from pathway_tpu.elastic.config import parse_elastic_spec
from pathway_tpu.elastic.controller import ElasticController, _dedup_rows
from pathway_tpu.elastic.metrics import ELASTIC_METRICS
from pathway_tpu.engine.persistence import EnginePersistence
from pathway_tpu.ops.knn import DeviceKnnIndex, StaleGeneration
from pathway_tpu.ops.tiered_knn import TieredKnnIndex
from pathway_tpu.parallel.mesh import parse_mesh_spec, resolve_mesh
from pathway_tpu.resilience import chaos
from pathway_tpu.resilience.cluster import ClusterHealth
from pathway_tpu.tenancy.packed import TenantPackedIndex

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_elastic():
    from pathway_tpu.tracing import TRACING_METRICS
    from pathway_tpu.tracing.store import TRACE_STORE

    elastic.reset_registry()
    ELASTIC_METRICS.reset()
    chaos.deactivate()
    yield
    elastic.reset_registry()
    ELASTIC_METRICS.reset()
    chaos.deactivate()
    TRACE_STORE.reset()
    TRACING_METRICS.reset()


def _rows(n, dim, seed=0):
    rng = np.random.default_rng(seed)
    keys = [f"k{i}" for i in range(n)]
    vecs = rng.normal(size=(n, dim)).astype("float32")
    return keys, vecs


def _queries(n, dim, seed=99):
    return np.random.default_rng(seed).normal(size=(n, dim)).astype("float32")


# ---------------------------------------------------------------------------
# config parsing


def test_parse_elastic_spec_forms():
    assert parse_elastic_spec(None) is None
    assert parse_elastic_spec("off") is None
    assert parse_elastic_spec("") is None
    assert parse_elastic_spec(False) is None
    assert parse_elastic_spec(True) == ElasticConfig()
    assert parse_elastic_spec("on") == ElasticConfig()
    assert parse_elastic_spec("auto") == ElasticConfig(auto=True)
    assert parse_elastic_spec(4) == ElasticConfig(shards=4)
    assert parse_elastic_spec("4") == ElasticConfig(shards=4)
    cfg = parse_elastic_spec("min=2,max=8,chunk=512,hbm_frac=0.85")
    assert cfg == ElasticConfig(
        min_shards=2, max_shards=8, chunk_rows=512, hbm_frac=0.85
    )
    cfg = parse_elastic_spec({"shards": 4, "cooldown_s": 5})
    assert cfg.shards == 4 and cfg.cooldown_s == 5.0
    assert parse_elastic_spec("auto,stranded_frac=0.5").auto
    roundtrip = parse_elastic_spec(ElasticConfig(oom_warn_s=30))
    assert roundtrip.oom_warn_s == 30
    d = ElasticConfig(hbm_frac=0.9).as_dict()
    assert d["hbm_frac"] == 0.9 and d["max_shards"] == 8


def test_parse_elastic_spec_rejects_malformed():
    for bad in ("wat", "shards=x", "nope=1", {"nope": 1}, 3.5, [4]):
        with pytest.raises(ValueError):
            parse_elastic_spec(bad)
    with pytest.raises(ValueError):
        ElasticConfig(shards=0)
    with pytest.raises(ValueError):
        ElasticConfig(min_shards=4, max_shards=2)
    with pytest.raises(ValueError):
        ElasticConfig(hbm_frac=1.5)


def test_watermarks_armed():
    assert not ElasticConfig().watermarks_armed()
    assert not ElasticConfig(shards=4).watermarks_armed()
    assert ElasticConfig(auto=True).watermarks_armed()
    assert ElasticConfig(hbm_frac=0.8).watermarks_armed()
    assert ElasticConfig(oom_warn_s=60).watermarks_armed()
    assert ElasticConfig(stranded_frac=0.5).watermarks_armed()


def test_mesh_auto_spec():
    axes = parse_mesh_spec("auto")
    assert axes.get("auto") and axes["data"] == 1
    mesh = resolve_mesh(axes)
    assert mesh.devices.size == len(__import__("jax").devices())


# ---------------------------------------------------------------------------
# dedup merge


def test_dedup_rows_new_generation_wins():
    new = [[("a", 0.9), ("b", 0.5)]]
    old = [[("a", 0.7), ("c", 0.6)]]
    rows, dropped = _dedup_rows(new, old, 3)
    assert rows == [[("a", 0.9), ("c", 0.6), ("b", 0.5)]]
    assert dropped == 1
    rows, dropped = _dedup_rows(new, old, 2)
    assert rows == [[("a", 0.9), ("c", 0.6)]]


# ---------------------------------------------------------------------------
# byte-identical migration, all three index families


def test_reshard_flat_grow_shrink_byte_identical():
    keys, vecs = _rows(300, 16)
    q = _queries(7, 16)
    base = DeviceKnnIndex(16, mesh=resolve_mesh(2), reserved_space=64)
    base.add_batch_arrays(keys, vecs)
    ref = base.search_batch(q, 5)

    idx = DeviceKnnIndex(16, mesh=resolve_mesh(2), reserved_space=64)
    idx.add_batch_arrays(keys, vecs)
    h = elastic.register_handle(idx)
    summary = elastic.reshard(4, chunk_rows=64)
    assert summary["from_shards"] == 2 and summary["to_shards"] == 4
    assert summary["rows_migrated"] == 300 and summary["indexes"] == 1
    assert summary["mttr_s"] > 0
    assert h.index.n_shards == 4
    assert h.search_batch(q, 5) == ref

    elastic.reshard(2, chunk_rows=64)
    assert h.index.n_shards == 2
    assert h.search_batch(q, 5) == ref

    snap = ELASTIC_METRICS.snapshot()
    assert snap["reshards_total"] == 2
    assert snap["cutovers_total"] == 2
    assert snap["rows_migrated"] == 600
    assert snap["generation"] == 2
    assert snap["migration"] is None


@pytest.mark.parametrize("dtype", ["float32", "int8"])
def test_reshard_tiered_byte_identical(dtype):
    keys, vecs = _rows(400, 16, seed=1)
    q = _queries(5, 16)
    base = TieredKnnIndex(16, mesh=resolve_mesh(2), reserved_space=128, dtype=dtype)
    base.add_batch_arrays(keys, vecs)
    ref = base.search_batch(q, 5)

    idx = TieredKnnIndex(16, mesh=resolve_mesh(2), reserved_space=128, dtype=dtype)
    idx.add_batch_arrays(keys, vecs)
    h = elastic.register_handle(idx)
    elastic.reshard(4, chunk_rows=64)
    assert h.search_batch(q, 5) == ref
    # hot/cold membership transplants exactly
    assert set(h.index.hot._slot_of) == set(base.hot._slot_of)
    assert h.index._cold_total == base._cold_total
    elastic.reshard(2, chunk_rows=64)
    assert h.search_batch(q, 5) == ref


def test_reshard_packed_byte_identical():
    keys, vecs = _rows(120, 16, seed=2)
    q = _queries(5, 16)
    tenants = ("alpha", "beta", "gamma")

    def build():
        idx = TenantPackedIndex(16, mesh=resolve_mesh(2), reserved_space=256)
        for t in tenants:
            idx.add_tenant_batch(t, [f"{t}-{k}" for k in keys], vecs)
        return idx

    base = build()
    refs = {t: base.search_tenant_batch(t, q, 5) for t in tenants}
    h = elastic.register_handle(build())
    elastic.reshard(4, chunk_rows=64)
    for t in tenants:
        assert h.search_tenant_batch(t, q, 5) == refs[t]
    elastic.reshard(2, chunk_rows=64)
    for t in tenants:
        assert h.search_tenant_batch(t, q, 5) == refs[t]


def test_reshard_packed_cold_tenant_stays_cold():
    keys, vecs = _rows(80, 8, seed=3)
    idx = TenantPackedIndex(8, mesh=resolve_mesh(2), reserved_space=128)
    idx.add_tenant_batch("hot", [f"h-{k}" for k in keys], vecs)
    idx.add_tenant_batch("cold", [f"c-{k}" for k in keys], vecs)
    idx._demote("cold")
    q = _queries(3, 8)
    ref_hot = idx.search_tenant_batch("hot", q, 4)
    ref_cold = idx.search_tenant_batch("cold", q, 4)
    h = elastic.register_handle(idx)
    elastic.reshard(4, chunk_rows=32)
    assert "cold" in h.index._cold
    assert h.search_tenant_batch("hot", q, 4) == ref_hot
    assert h.search_tenant_batch("cold", q, 4) == ref_cold


def test_reshard_multiple_indexes_one_generation():
    keys, vecs = _rows(100, 8, seed=4)
    a = DeviceKnnIndex(8, mesh=resolve_mesh(2), reserved_space=64)
    a.add_batch_arrays(keys, vecs)
    b = TieredKnnIndex(8, mesh=resolve_mesh(2), reserved_space=64)
    b.add_batch_arrays(keys, vecs)
    ha = elastic.register_handle(a)
    hb = elastic.register_handle(b)
    summary = elastic.reshard(4, chunk_rows=32)
    assert summary["indexes"] == 2
    assert ha.generation == hb.generation == summary["generation"]
    assert ha.index.n_shards == hb.index.n_shards == 4


def test_reshard_noop_and_validation():
    keys, vecs = _rows(20, 8)
    idx = DeviceKnnIndex(8, mesh=resolve_mesh(2), reserved_space=32)
    idx.add_batch_arrays(keys, vecs)
    elastic.register_handle(idx)
    summary = elastic.reshard(2)
    assert summary["indexes"] == 0 and summary["rows_migrated"] == 0
    with pytest.raises(ValueError):
        elastic.reshard(0)
    # no handles at all: also a no-op
    elastic.reset_registry()
    assert elastic.reshard(4)["indexes"] == 0


def test_register_handle_idempotent_and_weakref():
    idx = DeviceKnnIndex(8, reserved_space=16)
    h = elastic.register_handle(idx)
    assert elastic.register_handle(h) is h
    assert elastic.handles() == [h]
    assert elastic.current_shards() == 1
    del h
    assert elastic.handles() == []


def test_handle_delegates_like_an_index():
    keys, vecs = _rows(10, 8)
    idx = DeviceKnnIndex(8, reserved_space=16)
    h = elastic.register_handle(idx)
    h.add_batch_arrays(keys, vecs)
    assert len(h) == 10
    assert h.dim == 8  # __getattr__ delegation
    h.remove("k0")
    assert len(h) == 9
    assert h.index is idx


# ---------------------------------------------------------------------------
# writes under migration + fencing


def test_writes_during_migration_survive_cutover():
    keys, vecs = _rows(200, 8, seed=5)
    idx = DeviceKnnIndex(8, mesh=resolve_mesh(2), reserved_space=64)
    idx.add_batch_arrays(keys, vecs)
    h = elastic.register_handle(idx)

    import threading

    stop = threading.Event()
    wrote = []

    def writer():
        rng = np.random.default_rng(6)
        i = 0
        while not stop.is_set():
            h.add(f"w{i}", rng.normal(size=(8,)).astype("float32"))
            wrote.append(f"w{i}")
            i += 1

    t = threading.Thread(target=writer)
    t.start()
    try:
        elastic.reshard(4, chunk_rows=16)
    finally:
        stop.set()
        t.join()
    # every write that happened before reshard returned must be present
    # in the new generation (late ones raced the return, also present)
    missing = [k for k in wrote if k not in h.index._slot_of]
    assert not missing, f"dropped writes: {missing[:5]}"
    assert len(h.index) == 200 + len(wrote)


def test_removes_during_migration_do_not_abort():
    # the export generator advances under the handle lock — a remove()
    # racing the chunk walk must never KeyError (and abort the reshard)
    keys, vecs = _rows(400, 8, seed=21)
    idx = DeviceKnnIndex(8, mesh=resolve_mesh(2), reserved_space=64)
    idx.add_batch_arrays(keys, vecs)
    h = elastic.register_handle(idx)

    import threading

    errors: list[BaseException] = []

    def remover():
        for _ in range(3):
            for i in range(200):
                try:
                    h.remove(f"k{i}")
                except BaseException as exc:  # pragma: no cover
                    errors.append(exc)
                    return

    t = threading.Thread(target=remover)
    t.start()
    try:
        summary = elastic.reshard(4, chunk_rows=16)
    finally:
        t.join(timeout=30.0)
    assert not errors, f"writer died: {errors[0]!r}"
    assert summary["to_shards"] == 4
    # every remove landed: skipped at export, replayed from the delta,
    # or applied straight to the new generation after cutover
    assert len(h) == 200
    got = {k for row in h.search_batch(_queries(4, 8), 200) for k, _ in row}
    assert not {f"k{i}" for i in range(200)} & got


def test_fence_raises_stale_generation():
    keys, vecs = _rows(50, 8, seed=7)
    idx = DeviceKnnIndex(8, mesh=resolve_mesh(2), reserved_space=64)
    idx.add_batch_arrays(keys, vecs)
    h = elastic.register_handle(idx)
    old = h.index
    elastic.reshard(4, chunk_rows=32)
    with pytest.raises(StaleGeneration):
        old.add_batch_arrays(["zz"], np.zeros((1, 8), dtype="float32"))
    with pytest.raises(StaleGeneration):
        old.remove("k0")
    assert ELASTIC_METRICS.snapshot()["fenced_writes_total"] >= 1
    # reads against the fenced generation still work (drain-in-flight)
    assert old.search_batch(_queries(1, 8), 3)


def test_fence_tiered_and_dedup_window():
    keys, vecs = _rows(60, 8, seed=8)
    idx = TieredKnnIndex(8, mesh=resolve_mesh(2), reserved_space=32)
    idx.add_batch_arrays(keys, vecs)
    h = elastic.register_handle(idx)
    old = h.index
    elastic.reshard(4, chunk_rows=32)
    with pytest.raises(StaleGeneration):
        old.add_batch_arrays(["zz"], np.zeros((1, 8), dtype="float32"))
    # dual-serve window dedups; after end_cutover the handle serves new only
    assert h._dual is None
    q = _queries(2, 8)
    h._dual = old  # simulate the cutover window
    rows = h.search_batch(q, 4)
    assert [len(r) <= 4 for r in rows]
    keys_seen = [k for row in rows for k, _ in row]
    assert len(keys_seen) == len(set(keys_seen)), "double answer leaked"
    h._dual = None


# ---------------------------------------------------------------------------
# chaos at every boundary


def _built_handle(n=150, dim=8, seed=9):
    keys, vecs = _rows(n, dim, seed=seed)
    idx = DeviceKnnIndex(dim, mesh=resolve_mesh(2), reserved_space=64)
    idx.add_batch_arrays(keys, vecs)
    return elastic.register_handle(idx)


def test_chaos_raise_at_every_chunk_boundary():
    h = _built_handle()
    q = _queries(4, 8)
    ref = h.search_batch(q, 5)
    n_chunks = -(-150 // 32)
    for hit in range(1, n_chunks + 1):
        chaos.activate(
            [{"site": "elastic.migrate_chunk", "action": "raise", "hit": hit}]
        )
        with pytest.raises(chaos.ChaosInjected):
            elastic.reshard(4, chunk_rows=32)
        chaos.deactivate()
        # rollback: old generation untouched, still serving, not migrating
        assert h.index.n_shards == 2
        assert h.search_batch(q, 5) == ref
        assert not h._migrating and h._dual is None
    assert ELASTIC_METRICS.snapshot()["rollbacks_total"] == n_chunks
    # retried reshard completes byte-identically
    elastic.reshard(4, chunk_rows=32)
    assert h.index.n_shards == 4
    assert h.search_batch(q, 5) == ref


def test_chaos_raise_at_cutover_rolls_back():
    h = _built_handle(seed=10)
    q = _queries(4, 8)
    ref = h.search_batch(q, 5)
    chaos.activate([{"site": "elastic.cutover", "action": "raise"}])
    with pytest.raises(chaos.ChaosInjected):
        elastic.reshard(4, chunk_rows=32)
    chaos.deactivate()
    assert h.index.n_shards == 2
    assert h.search_batch(q, 5) == ref
    elastic.reshard(4, chunk_rows=32)
    assert h.search_batch(q, 5) == ref


def test_chaos_raise_during_abort_does_not_mask():
    h = _built_handle(seed=11)
    chaos.activate(
        [
            {"site": "elastic.cutover", "action": "raise"},
            {"site": "elastic.abort", "action": "raise"},
        ]
    )
    with pytest.raises(chaos.ChaosInjected):
        elastic.reshard(4, chunk_rows=32)
    chaos.deactivate()
    assert h.index.n_shards == 2
    assert not h._migrating


# ---------------------------------------------------------------------------
# durable intent + SIGKILL recovery (subprocess)


def _mk_persistence(tmp_path):
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstore"))
    cfg = pw.persistence.Config.simple_config(backend)
    return EnginePersistence(cfg)


def test_reshard_intent_roundtrip(tmp_path):
    p = _mk_persistence(tmp_path)
    assert p.reshard_intent() is None
    p.record_reshard_intent(4, 7)
    assert p.reshard_intent() == (4, 7)
    p.record_reshard_intent(2, 9)  # single-record log: last wins
    p.close()
    p2 = _mk_persistence(tmp_path)
    assert p2.reshard_intent() == (2, 9)
    p2.clear_reshard_intent()
    assert p2.reshard_intent() is None
    p2.close()
    p3 = _mk_persistence(tmp_path)
    assert p3.reshard_intent() is None


def test_reshard_clears_intent_and_bumps_generation(tmp_path):
    p = _mk_persistence(tmp_path)
    elastic.register_persistence(p)
    h = _built_handle(n=60, seed=12)
    gen0 = p.cluster_generation()
    summary = elastic.reshard(4, chunk_rows=32)
    assert summary["generation"] == gen0 + 1
    assert p.cluster_generation() == gen0 + 1
    assert p.reshard_intent() is None
    assert h.generation == gen0 + 1


def test_rollback_clears_intent(tmp_path):
    p = _mk_persistence(tmp_path)
    elastic.register_persistence(p)
    h = _built_handle(n=60, seed=13)
    chaos.activate([{"site": "elastic.cutover", "action": "raise"}])
    with pytest.raises(chaos.ChaosInjected):
        elastic.reshard(4, chunk_rows=32)
    chaos.deactivate()
    assert p.reshard_intent() is None
    assert h.index.n_shards == 2


def test_recover_pending_reshard_completes(tmp_path):
    p = _mk_persistence(tmp_path)
    elastic.register_persistence(p)
    h = _built_handle(n=60, seed=14)
    q = _queries(3, 8)
    ref = h.search_batch(q, 4)
    # simulate a crash that left the intent behind
    p.record_reshard_intent(4, p.cluster_generation() + 1)
    out = elastic.recover_pending_reshard(complete=True)
    assert out is not None and out["to_shards"] == 4
    assert h.index.n_shards == 4
    assert h.search_batch(q, 4) == ref
    assert p.reshard_intent() is None
    # idempotent: nothing pending now
    assert elastic.recover_pending_reshard() is None


def test_recover_pending_reshard_rollback(tmp_path):
    p = _mk_persistence(tmp_path)
    elastic.register_persistence(p)
    h = _built_handle(n=40, seed=15)
    p.record_reshard_intent(4, p.cluster_generation() + 1)
    out = elastic.recover_pending_reshard(complete=False)
    assert out is None
    assert h.index.n_shards == 2  # formally rolled back
    assert p.reshard_intent() is None
    assert ELASTIC_METRICS.snapshot()["rollbacks_total"] == 1


ELASTIC_KILL_PROGRAM = textwrap.dedent(
    """
    import json, os, sys
    import numpy as np
    import pathway_tpu as pw
    from pathway_tpu import elastic
    from pathway_tpu.engine.persistence import EnginePersistence
    from pathway_tpu.ops.knn import DeviceKnnIndex
    from pathway_tpu.parallel.mesh import resolve_mesh
    from pathway_tpu.resilience import chaos

    root = os.environ["EL_STORE"]
    backend = pw.persistence.Backend.filesystem(root)
    cfg = pw.persistence.Config.simple_config(backend)
    p = EnginePersistence(cfg)
    elastic.register_persistence(p)

    rng = np.random.default_rng(42)
    keys = [f"k{i}" for i in range(120)]
    vecs = rng.normal(size=(120, 8)).astype("float32")
    q = rng.normal(size=(4, 8)).astype("float32")

    idx = DeviceKnnIndex(8, mesh=resolve_mesh(2), reserved_space=64)
    idx.add_batch_arrays(keys, vecs)
    h = elastic.register_handle(idx)

    phase = os.environ["EL_PHASE"]
    out = {}
    if phase == "crash":
        # chaos kill fires mid-migration; we never reach the dump
        elastic.reshard(4, chunk_rows=32)
        out = {"unreachable": True}
    else:
        # restart: indexes rebuilt (here: re-added above), resolve intent
        out["intent"] = p.reshard_intent()
        summary = elastic.recover_pending_reshard(complete=True)
        out["recovered"] = summary is not None
        out["n_shards"] = h.index.n_shards
        out["results"] = h.search_batch(q, 5)
        out["generation"] = h.generation
    with open(os.environ["EL_OUT"], "w") as f:
        json.dump(out, f)
    """
)


@pytest.mark.parametrize(
    "site,hit",
    [("elastic.migrate_chunk", 1), ("elastic.migrate_chunk", 3), ("elastic.cutover", 1)],
)
def test_sigkill_at_boundary_recovers_byte_identical(tmp_path, site, hit):
    """Chaos SIGKILL at a chunk/cutover boundary; a restarted process
    finds the durable intent and completes the reshard idempotently,
    byte-identical to a run that was never killed."""
    prog = tmp_path / "prog.py"
    prog.write_text(ELASTIC_KILL_PROGRAM)
    env = dict(os.environ)
    env.update(
        EL_STORE=str(tmp_path / "pstore"),
        EL_OUT=str(tmp_path / "out.json"),
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    # control: same program, no chaos, straight recovery path with no
    # pending intent — gives the never-killed reference answer
    control_env = dict(env, EL_PHASE="recover", EL_OUT=str(tmp_path / "control.json"))
    subprocess.run(
        [sys.executable, str(prog)], env=control_env, check=True, timeout=240
    )
    control = json.loads((tmp_path / "control.json").read_text())
    assert control["intent"] is None and not control["recovered"]

    crash_env = dict(
        env,
        EL_PHASE="crash",
        PATHWAY_CHAOS=json.dumps(
            [{"site": site, "action": "kill", "hit": hit}]
        ),
    )
    r = subprocess.run(
        [sys.executable, str(prog)], env=crash_env, timeout=240
    )
    assert r.returncode != 0, "chaos kill did not fire"
    assert not (tmp_path / "out.json").exists()

    recover_env = dict(env, EL_PHASE="recover")
    subprocess.run(
        [sys.executable, str(prog)], env=recover_env, check=True, timeout=240
    )
    out = json.loads((tmp_path / "out.json").read_text())
    assert out["intent"] is not None, "durable intent lost in the crash"
    assert out["recovered"] and out["n_shards"] == 4
    # byte-identical to the never-resharded control
    control2 = json.loads((tmp_path / "control.json").read_text())
    # control never resharded (no intent), so compare against a clean
    # in-process reference at the ORIGINAL shard count: results must
    # be identical regardless of layout
    keys, vecs = _rows(120, 8, seed=42)
    rng = np.random.default_rng(42)
    keys = [f"k{i}" for i in range(120)]
    vecs = rng.normal(size=(120, 8)).astype("float32")
    q = rng.normal(size=(4, 8)).astype("float32")
    ref_idx = DeviceKnnIndex(8, mesh=resolve_mesh(2), reserved_space=64)
    ref_idx.add_batch_arrays(keys, vecs)
    ref = ref_idx.search_batch(q, 5)
    got = [[(k, s) for k, s in row] for row in out["results"]]
    ref_cmp = [[(k, pytest.approx(s, abs=0)) for k, s in row] for row in ref]
    assert got == ref_cmp


# ---------------------------------------------------------------------------
# watermark controller


def test_controller_fixed_target_reshards_once():
    h = _built_handle(n=60, seed=16)
    ctl = ElasticController(ElasticConfig(shards=4, cooldown_s=0, chunk_rows=32))
    assert ctl.evaluate_once() == "target"
    assert h.index.n_shards == 4
    assert ctl.evaluate_once() is None  # at target now


def test_controller_hbm_watermark_grows(monkeypatch):
    h = _built_handle(n=60, seed=17)
    from pathway_tpu.internals import ledger as ledger_mod

    monkeypatch.setattr(
        ledger_mod.LEDGER,
        "snapshot",
        lambda: {"total_bytes": 950, "budget_bytes": 1000},
    )
    ctl = ElasticController(ElasticConfig(hbm_frac=0.9, cooldown_s=0, chunk_rows=32))
    assert ctl.evaluate_once() == "hbm_watermark"
    assert h.index.n_shards == 4


def test_controller_time_to_oom_grows(monkeypatch):
    h = _built_handle(n=60, seed=18)
    from pathway_tpu.internals import ledger as ledger_mod

    readings = iter([100, 500_000])
    monkeypatch.setattr(
        ledger_mod.LEDGER,
        "snapshot",
        lambda: {"total_bytes": next(readings), "budget_bytes": 1_000_000},
    )
    ctl = ElasticController(
        ElasticConfig(oom_warn_s=10_000.0, cooldown_s=0, chunk_rows=32)
    )
    assert ctl.evaluate_once() is None  # first sample only primes the rate
    assert ctl.evaluate_once() == "time_to_oom"
    assert h.index.n_shards == 4


def test_controller_stranded_shrinks(monkeypatch):
    h = _built_handle(n=60, seed=19)
    from pathway_tpu.internals import chip_ledger as chip_mod
    from pathway_tpu.internals import ledger as ledger_mod

    monkeypatch.setattr(
        ledger_mod.LEDGER,
        "snapshot",
        lambda: {"total_bytes": 10, "budget_bytes": 1000},
    )
    monkeypatch.setattr(
        chip_mod.CHIP_LEDGER, "snapshot", lambda: {"stranded_fraction": 0.9}
    )
    ctl = ElasticController(
        ElasticConfig(stranded_frac=0.5, cooldown_s=0, chunk_rows=32)
    )
    assert ctl.evaluate_once() == "stranded_chip_time"
    assert h.index.n_shards == 1


def test_controller_auto_shrinks_on_low_footprint(monkeypatch):
    h = _built_handle(n=60, seed=20)
    from pathway_tpu.internals import ledger as ledger_mod

    monkeypatch.setattr(
        ledger_mod.LEDGER,
        "snapshot",
        lambda: {"total_bytes": 1, "budget_bytes": 1000},
    )
    ctl = ElasticController(ElasticConfig(auto=True, cooldown_s=0, chunk_rows=32))
    assert ctl.evaluate_once() == "footprint_shrunk"
    assert h.index.n_shards == 1


def test_controller_cooldown_throttles(monkeypatch):
    h = _built_handle(n=40, seed=21)
    from pathway_tpu.internals import ledger as ledger_mod

    monkeypatch.setattr(
        ledger_mod.LEDGER,
        "snapshot",
        lambda: {"total_bytes": 950, "budget_bytes": 1000},
    )
    ctl = ElasticController(
        ElasticConfig(hbm_frac=0.9, max_shards=8, cooldown_s=3600, chunk_rows=32)
    )
    assert ctl.evaluate_once() == "hbm_watermark"
    assert h.index.n_shards == 4
    assert ctl.evaluate_once() is None  # cooldown holds the second grow


def test_controller_idle_without_handles():
    ctl = ElasticController(ElasticConfig(auto=True))
    assert ctl.evaluate_once() is None
    ctl.start()
    ctl.start()  # idempotent
    time.sleep(0.05)
    ctl.stop()
    assert ctl._thread is None


def test_controller_reshard_failure_is_contained(monkeypatch):
    h = _built_handle(n=40, seed=22)
    ctl = ElasticController(ElasticConfig(shards=4, cooldown_s=0))
    monkeypatch.setattr(
        "pathway_tpu.elastic.controller.reshard",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")),
    )
    assert ctl.evaluate_once() is None  # swallowed, recorded, no raise
    assert h.index.n_shards == 2


# ---------------------------------------------------------------------------
# admission + Retry-After integration (satellite: ETA-derived backoff)


def test_retry_after_precedence():
    ch = ClusterHealth()
    # legacy constant fallback
    assert ch.retry_after_s() == 1.0
    # declared ETA decays with elapsed time
    ch.mark_down([0], eta_s=5.0)
    assert 4.0 < ch.retry_after_s() <= 5.0
    ch.mark_all_up()
    # learned outage duration while down without a declared ETA
    ch.mark_down([0])
    ra = ch.retry_after_s()
    assert 0.1 <= ra <= 1.0  # the outage above was short
    ch.mark_all_up()
    # live eta source wins over everything
    ch.set_eta_source(lambda: 7.5)
    assert ch.retry_after_s() == 7.5
    ch.set_eta_source(lambda: None)  # source declines -> fallback
    assert ch.retry_after_s() >= 0.1


def test_retry_after_uses_migration_eta():
    ch = ClusterHealth()
    ch.set_eta_source(ELASTIC_METRICS.migration_eta_s)
    ELASTIC_METRICS.migration_begin(10, 2, 4)
    for _ in range(5):
        ELASTIC_METRICS.record_chunk(10)
    eta = ch.retry_after_s()
    assert eta >= 0.1  # five chunks left at the observed pace
    ELASTIC_METRICS.record_cutover(1, 0.5, "test")
    assert ELASTIC_METRICS.migration_eta_s() is None


def test_admission_degrades_during_migration():
    from pathway_tpu.serving import ServingConfig
    from pathway_tpu.serving.admission import AdmissionController

    ac = AdmissionController(ServingConfig(shed="degrade", max_queue=8))
    ELASTIC_METRICS.migration_begin(4, 2, 4)
    try:
        ticket = ac.admit()
        assert ticket.degraded, "migration in flight must degrade, not reject"
        ac.release(ticket)
    finally:
        ELASTIC_METRICS.record_cutover(1, 0.1, "test")
    ticket = ac.admit()
    assert not ticket.degraded
    ac.release(ticket)


# ---------------------------------------------------------------------------
# metrics / status surfaces


def test_elastic_metrics_scrape_appears_after_first_reshard():
    from pathway_tpu.internals.http_monitoring import MonitoringHttpServer

    assert not ELASTIC_METRICS.active()
    h = _built_handle(n=40, seed=23)
    elastic.reshard(4, chunk_rows=32)
    assert h.index.n_shards == 4
    assert ELASTIC_METRICS.active()
    text = MonitoringHttpServer._elastic_lines()
    body = "\n".join(text)
    assert "pathway_elastic_reshards_total" in body
    assert "pathway_elastic_cutovers_total" in body
    assert "pathway_elastic_generation" in body
    assert 'reason="manual"' in body


def test_flight_events_for_reshard():
    from pathway_tpu.internals import flight_recorder

    flight_recorder.RECORDER.clear()
    h = _built_handle(n=40, seed=24)
    elastic.reshard(4, chunk_rows=32)
    assert h.index.n_shards == 4
    kinds = [e.get("kind") for e in flight_recorder.RECORDER.events()]
    assert "elastic.reshard_begin" in kinds
    assert "elastic.cutover" in kinds
    assert "elastic.reshard_done" in kinds


def test_reshard_span_recorded():
    from pathway_tpu.tracing.store import TRACE_STORE, set_tracing_enabled

    prev = set_tracing_enabled(True)
    TRACE_STORE.reset()
    try:
        h = _built_handle(n=40, seed=25)
        elastic.reshard(4, chunk_rows=32)
        assert h.index.n_shards == 4
        spans = [
            s
            for s in TRACE_STORE.recent_spans()
            if s.get("stage") == "elastic.reshard"
        ]
        assert spans, "no elastic.reshard span"
        assert spans[-1]["attrs"]["to_shards"] == 4
    finally:
        set_tracing_enabled(prev)
        TRACE_STORE.reset()
