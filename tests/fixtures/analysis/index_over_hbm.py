"""Analysis fixture: a device-backed KNN index whose reserved capacity
(20M x 384 f32 ~= 28.6 GiB) cannot fit one device's 16 GiB HBM budget,
in a run with no mesh — the verifier must flag PWL010 (warning): shard
it with pw.run(mesh=...) / PATHWAY_MESH. Analyze-only never builds the
index, so the huge reserved_space allocates nothing."""

import pathway_tpu as pw
from pathway_tpu.stdlib.ml.index import KNNIndex

docs = pw.debug.table_from_markdown(
    """
    | x   | y
  1 | 1.0 | 0.0
  2 | 0.0 | 1.0
    """
)
docs = docs.select(
    emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, docs.x, docs.y)
)

queries = pw.debug.table_from_markdown(
    """
    | x   | y
  9 | 1.0 | 1.0
    """
)
queries = queries.select(
    emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, queries.x, queries.y)
)

index = KNNIndex(
    docs.emb,
    docs,
    n_dimensions=384,
    reserved_space=20_000_000,
    distance_type="cosine",
)
res = index.get_nearest_items(queries.emb, k=3)

pw.io.null.write(res)

pw.run()
