"""Analysis fixture: groupby over a streaming source with no window —
the verifier must flag PWL002 (unbounded state) and exit nonzero."""

import pathway_tpu as pw

events = pw.demo.range_stream(nb_rows=5, input_rate=1000.0)

per_key = events.groupby(pw.this.value).reduce(
    pw.this.value, n=pw.reducers.count()
)

pw.io.null.write(per_key)

pw.run(monitoring_level=pw.MonitoringLevel.NONE)
