"""Analysis fixture: a REST endpoint with admission control and a
per-request deadline budget (``default_deadline_ms``), but a run where
tracing and the profiler are both off — a missed deadline sheds as a
bare 429/503 with no record of which stage spent the budget. The
verifier must flag PWL014 (warning). ``serving=`` is set so PWL008
stays quiet, and monitoring is on so PWL007 stays quiet too."""

import pathway_tpu as pw


class QuerySchema(pw.Schema):
    value: int


queries, response_writer = pw.io.http.rest_connector(
    host="127.0.0.1",
    port=0,
    schema=QuerySchema,
    delete_completed_queries=False,
    serving=pw.ServingConfig(max_queue=32, default_deadline_ms=250.0),
)
response_writer(queries.select(result=pw.this.value * 2))

pw.run(monitoring_level="in_out")
