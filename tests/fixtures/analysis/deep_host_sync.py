"""Deep-analysis fixture (PWL017 positive): a UDF on the staging path
into a device-backed KNN index calls ``jax.device_get`` — a synchronous
device->host transfer paid on every epoch's staged batch. The deep pass
(``--deep``) must flag PWL017 (warning); the plain pass stays silent
about it."""

import jax
import jax.numpy as jnp

import pathway_tpu as pw
from pathway_tpu.stdlib.ml.index import KNNIndex


def embed_on_device(x, y):
    # a device round trip inside host-side staging: the readback blocks
    # dispatch pipelining — exactly the hazard PWL017 exists for
    vec = jnp.asarray([x, y])
    host = jax.device_get(vec / (jnp.linalg.norm(vec) + 1e-6))
    return (float(host[0]), float(host[1]))


docs = pw.debug.table_from_markdown(
    """
    | x   | y
  1 | 1.0 | 0.0
  2 | 0.0 | 1.0
    """
)
docs = docs.select(emb=pw.apply_with_type(embed_on_device, pw.ANY, docs.x, docs.y))

queries = pw.debug.table_from_markdown(
    """
    | x   | y
  9 | 1.0 | 1.0
    """
)
queries = queries.select(
    emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, queries.x, queries.y)
)

index = KNNIndex(
    docs.emb,
    docs,
    n_dimensions=2,
    reserved_space=100,
    distance_type="cosine",
)
res = index.get_nearest_items(queries.emb, k=2)

pw.io.null.write(res)

pw.run()
