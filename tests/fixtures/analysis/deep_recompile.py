"""Deep-analysis fixture (PWL018 positive): a device-backed KNN index
under a deliberately tight compile budget. The predictor counts the
index's kernel families (scatter/grow/empty + one top-k fetch bucket =
4 distinct compiles) against PATHWAY_COMPILE_BUDGET=2 and must flag
PWL018 (warning) with the per-target breakdown."""

import os

os.environ["PATHWAY_COMPILE_BUDGET"] = "2"

import pathway_tpu as pw
from pathway_tpu.stdlib.ml.index import KNNIndex

docs = pw.debug.table_from_markdown(
    """
    | x   | y
  1 | 1.0 | 0.0
  2 | 0.0 | 1.0
    """
)
docs = docs.select(
    emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, docs.x, docs.y)
)

queries = pw.debug.table_from_markdown(
    """
    | x   | y
  9 | 1.0 | 1.0
    """
)
queries = queries.select(
    emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, queries.x, queries.y)
)

index = KNNIndex(
    docs.emb,
    docs,
    n_dimensions=2,
    reserved_space=100,
    distance_type="cosine",
)
res = index.get_nearest_items(queries.emb, k=2)

pw.io.null.write(res)

pw.run()
