"""Deep-analysis fixture (PWL020 clean): the same recovery run with the
hazards fixed — the tag comes from the row itself (deterministic under
replay) and the async notifier routes failures to the dead-letter table
(``on_error="dead_letter"``), making its retry idempotent from the
graph's perspective. ``--deep`` reports nothing."""

import pathway_tpu as pw


def stamp(word: str) -> str:
    return f"{word}@epoch"


@pw.udf(on_error="dead_letter")
async def notify(word: str) -> str:
    return f"notified:{word}"


t = pw.debug.table_from_markdown(
    """
    | word
  1 | cat
  2 | dog
    """
)

tagged = t.select(
    tagged=pw.apply_with_type(stamp, str, t.word),
    sent=notify(t.word),
)

pw.io.null.write(tagged)

pw.run(recovery=True, monitoring_level="auto")
