"""Analysis fixture: a RAG pipeline — a device-backed KNN index feeding
retrieval in the same program — whose run configures the decode plane
with prefix caching off. The verifier must flag PWL023 (warning):
retrieved-context prompts share the system/template prefix, and
decode="cache=1" would serve it from refcounted COW pages at ~zero cost
instead of re-prefilling it per request. The index is small enough to
fit HBM (PWL010/PWL012 stay silent) and the run is single-tenant, so
the RAG arm alone carries the diagnostic."""

import pathway_tpu as pw
from pathway_tpu.stdlib.ml.index import KNNIndex

docs = pw.debug.table_from_markdown(
    """
    | x   | y
  1 | 1.0 | 0.0
  2 | 0.0 | 1.0
    """
)
docs = docs.select(
    emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, docs.x, docs.y)
)

queries = pw.debug.table_from_markdown(
    """
    | x   | y
  9 | 1.0 | 1.0
    """
)
queries = queries.select(
    emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, queries.x, queries.y)
)

index = KNNIndex(
    docs.emb,
    docs,
    n_dimensions=384,
    reserved_space=10_000,
    distance_type="cosine",
)
res = index.get_nearest_items(queries.emb, k=3)

pw.io.null.write(res)

pw.run(decode="pages=128,page=16,max_new=32")
