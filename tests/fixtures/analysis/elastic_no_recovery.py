"""Analysis fixture: elastic reshard watermarks armed (auto mode with
an HBM pressure threshold) but no persistence backend — a crash
mid-migration loses the durable cluster-generation fence and the
reshard intent, so zombie writes are not fenced across restart and the
pending reshard cannot be recovered. The verifier must flag PWL022
(warning). The table is finite (PWL002 quiet) and single-process
(PWL009 quiet); this fixture is about durability, not cluster shape."""

import pathway_tpu as pw

t = pw.debug.table_from_markdown(
    """
    | word
  1 | cat
  2 | dog
    """
)

counts = t.groupby(pw.this.word).reduce(pw.this.word, n=pw.reducers.count())

pw.io.null.write(counts)

pw.run(elastic={"auto": True, "hbm_frac": 0.85, "max_shards": 4})
