"""Deep-analysis fixture (PWL019 clean): the index's mesh and the run
mesh agree (``data=2`` on both sides), so staging is mesh-aware and no
resharding or host bounce happens — ``--deep`` reports nothing."""

import pathway_tpu as pw
from pathway_tpu.stdlib.ml.index import KNNIndex

docs = pw.debug.table_from_markdown(
    """
    | x   | y
  1 | 1.0 | 0.0
  2 | 0.0 | 1.0
    """
)
docs = docs.select(
    emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, docs.x, docs.y)
)

queries = pw.debug.table_from_markdown(
    """
    | x   | y
  9 | 1.0 | 1.0
    """
)
queries = queries.select(
    emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, queries.x, queries.y)
)

index = KNNIndex(
    docs.emb,
    docs,
    n_dimensions=2,
    reserved_space=100,
    distance_type="cosine",
    mesh="data=2",
)
res = index.get_nearest_items(queries.emb, k=2)

pw.io.null.write(res)

pw.run(mesh="data=2")
