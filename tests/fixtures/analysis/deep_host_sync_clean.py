"""Deep-analysis fixture (PWL017 clean): the same pipeline shape as
deep_host_sync.py but the staging UDF is pure host Python — no jax
references, no device readback — so ``--deep`` reports nothing."""

import math

import pathway_tpu as pw
from pathway_tpu.stdlib.ml.index import KNNIndex


def embed_on_host(x, y):
    norm = math.sqrt(x * x + y * y) + 1e-6
    return (x / norm, y / norm)


docs = pw.debug.table_from_markdown(
    """
    | x   | y
  1 | 1.0 | 0.0
  2 | 0.0 | 1.0
    """
)
docs = docs.select(emb=pw.apply_with_type(embed_on_host, pw.ANY, docs.x, docs.y))

queries = pw.debug.table_from_markdown(
    """
    | x   | y
  9 | 1.0 | 1.0
    """
)
queries = queries.select(
    emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, queries.x, queries.y)
)

index = KNNIndex(
    docs.emb,
    docs,
    n_dimensions=2,
    reserved_space=100,
    distance_type="cosine",
)
res = index.get_nearest_items(queries.emb, k=2)

pw.io.null.write(res)

pw.run()
