"""Analysis fixture: the multi-tenant serving plane is configured
(``pw.run(tenancy=True)``) but no per-tenant quotas and no default
quota exist — tenants get routed and labeled yet nothing throttles
them, so one flooding tenant still monopolizes chip time and HBM. The
verifier must flag PWL016 (warning). ``serving=`` is set so PWL008
stays quiet, and monitoring is on so PWL007 stays quiet too."""

import pathway_tpu as pw


class QuerySchema(pw.Schema):
    value: int


queries, response_writer = pw.io.http.rest_connector(
    host="127.0.0.1",
    port=0,
    schema=QuerySchema,
    delete_completed_queries=False,
    serving=pw.ServingConfig(max_queue=32),
)
response_writer(queries.select(result=pw.this.value * 2))

pw.run(monitoring_level="in_out", tenancy=True)
