"""Analysis fixture: a device-backed KNN index (20k x 384 f32 ~= 29.4
MiB) and a decode KV page pool (256 pages x 16 ~= 32 MiB at nominal
decoder geometry) that each fit the HBM budget alone but jointly
oversubscribe it — with PATHWAY_HBM_BYTES=48M the verifier must flag
PWL015 (warning) while PWL010/PWL012 stay silent. Analyze-only never
builds either plane, so nothing allocates."""

import pathway_tpu as pw
from pathway_tpu.stdlib.ml.index import KNNIndex

docs = pw.debug.table_from_markdown(
    """
    | x   | y
  1 | 1.0 | 0.0
  2 | 0.0 | 1.0
    """
)
docs = docs.select(
    emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, docs.x, docs.y)
)

queries = pw.debug.table_from_markdown(
    """
    | x   | y
  9 | 1.0 | 1.0
    """
)
queries = queries.select(
    emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, queries.x, queries.y)
)

index = KNNIndex(
    docs.emb,
    docs,
    n_dimensions=384,
    reserved_space=20_000,
    distance_type="cosine",
)
res = index.get_nearest_items(queries.emb, k=3)

pw.io.null.write(res)

pw.run(decode="pages=256,page=16")
