"""Analysis fixture: a REST query endpoint with no ``serving=`` config
(no admission control, deadlines, or shed policy) in a run configured
for sustained pressure (recovery + overlapped pipeline) — the verifier
must flag PWL008 (warning). Monitoring is on, so PWL007 stays quiet."""

import pathway_tpu as pw


class QuerySchema(pw.Schema):
    value: int


queries, response_writer = pw.io.http.rest_connector(
    host="127.0.0.1", port=0, schema=QuerySchema, delete_completed_queries=False
)
response_writer(queries.select(result=pw.this.value * 2))

pw.run(recovery=True, monitoring_level="in_out", pipeline_depth=2)
