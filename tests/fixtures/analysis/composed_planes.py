"""Interaction fixture: every HBM plane composed in one run — a
data-sharded mesh, a tiered device index, a multi-tenant serving plane
with quotas on every tenant plus a default, and a small decode KV pool.
Each plane is sized to fit and every rule's fix is in place, so the
whole composition must lint clean (zero findings) under the full deep
pass: PWL010/012 see the tier bound, PWL015 sees the combined
footprint fit, PWL016 sees the quotas, PWL023 sees prefix caching on
for the multi-tenant+RAG traffic, PWL017-020 see clean device
callables and placement that follows the run mesh."""

import pathway_tpu as pw
from pathway_tpu.stdlib.ml.index import KNNIndex

docs = pw.debug.table_from_markdown(
    """
    | x   | y
  1 | 1.0 | 0.0
  2 | 0.0 | 1.0
    """
)
docs = docs.select(
    emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, docs.x, docs.y)
)

queries = pw.debug.table_from_markdown(
    """
    | x   | y
  9 | 1.0 | 1.0
    """
)
queries = queries.select(
    emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, queries.x, queries.y)
)

# no per-index mesh: the index follows the run mesh, so staging and
# search shards agree (PWL019's fix in place)
index = KNNIndex(
    docs.emb,
    docs,
    n_dimensions=384,
    reserved_space=20_000,
    distance_type="cosine",
)
res = index.get_nearest_items(queries.emb, k=3)

pw.io.null.write(res)

pw.run(
    mesh="data=2",
    index_tiers="hot=10000",
    decode="pages=64,page=16,cache=1",
    tenancy={
        "quotas": {
            "acme": {"qps": 100.0, "hbm": "8M"},
            "globex": {"qps": 50.0, "hbm": "8M"},
        },
        "default": {"qps": 10.0},
    },
)
