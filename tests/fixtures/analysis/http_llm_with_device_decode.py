"""Analysis fixture: a RAG pipeline that reranks candidates through an
HTTP chat-completion endpoint (LLMReranker) while the run configures
the device decode plane (pw.run(decode=...)) — the verifier must flag
PWL013 (warning): the rerank hop can run on-chip via
KNNIndex(rerank=...) and generation via decode.DecodeService, keeping
embed->retrieve->rerank->generate in one device dispatch. Analyze-only
never executes the UDF, so no HTTP call is ever made."""

import pathway_tpu as pw
from pathway_tpu.xpacks.llm.llms import BaseChat
from pathway_tpu.xpacks.llm.rerankers import LLMReranker


class StubChat(BaseChat):
    """Deterministic stand-in for an HTTP chat endpoint."""

    def __init__(self):
        super().__init__()
        self.kwargs = {"model": "gpt-x"}

    def __wrapped__(self, messages, **kwargs) -> str:
        return "3"

    def _accepts_call_arg(self, arg_name: str) -> bool:
        return False


pairs = pw.debug.table_from_markdown(
    """
    | doc          | query
  1 | relevant-doc | what is relevant
  2 | other-doc    | what is relevant
    """
)

reranker = LLMReranker(StubChat())
scored = pairs.select(score=reranker(pairs.doc, pairs.query))

pw.io.null.write(scored)

pw.run(decode="pages=128,page=16,max_new=32")
