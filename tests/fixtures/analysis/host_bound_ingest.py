"""Analysis fixture: a streaming connector feeding a device-backed KNN
index with the strict serial epoch loop (pipeline_depth defaults to 1)
and no collaborative ingest stage configured — the verifier must flag
PWL011 (warning): host prep runs in line with device dispatch, starving
the chip; fix with pw.run(ingest_workers=N) / PATHWAY_INGEST_WORKERS or
pipeline_depth>=2."""

import pathway_tpu as pw
from pathway_tpu.stdlib.ml.index import KNNIndex

docs = pw.demo.range_stream(nb_rows=5, input_rate=1000.0)
docs = docs.select(
    emb=pw.apply_with_type(lambda v: (float(v), 1.0), pw.ANY, docs.value)
)

queries = pw.debug.table_from_markdown(
    """
    | x   | y
  9 | 1.0 | 1.0
    """
)
queries = queries.select(
    emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, queries.x, queries.y)
)

index = KNNIndex(
    docs.emb,
    docs,
    n_dimensions=2,
    reserved_space=100,
    distance_type="cosine",
)
res = index.get_nearest_items(queries.emb, k=2)

pw.io.null.write(res)

pw.run()
