"""Analysis fixture: a REST endpoint with admission control and a
per-request deadline budget plus a health watchdog, but chip-time
accounting off — a breach leaves no record of where the device-seconds
went. The verifier must flag PWL021 (warning). ``tracing=True`` keeps
PWL014 quiet (this fixture is about the chip ledger, not tracing),
``serving=`` keeps PWL008 quiet, and monitoring is on for PWL007."""

import pathway_tpu as pw


class QuerySchema(pw.Schema):
    value: int


queries, response_writer = pw.io.http.rest_connector(
    host="127.0.0.1",
    port=0,
    schema=QuerySchema,
    delete_completed_queries=False,
    serving=pw.ServingConfig(max_queue=32, default_deadline_ms=250.0),
)
response_writer(queries.select(result=pw.this.value * 2))

pw.run(monitoring_level="in_out", tracing=True, watchdog=True)
