"""Analysis fixture: a streaming run arms the watchdog's
freshness_warn/freshness_critical thresholds while the freshness plane
(``pw.run(freshness=)`` / PATHWAY_FRESHNESS) is off — the freshness_slo
watch rule reads the plane's visibility-lag EWMA, so with no watermarks
ever measured it can never fire. The verifier must flag PWL024
(warning). ``chip_ledger=True`` keeps PWL021 quiet (this fixture is
about the freshness plane, not chip-time accounting); the stream feeds
no stateful operator (PWL002 quiet) and no device index (PWL011
quiet)."""

import pathway_tpu as pw

docs = pw.demo.range_stream(nb_rows=5, input_rate=1000.0)

out = docs.select(doubled=pw.this.value * 2)

pw.io.null.write(out)

pw.run(
    watchdog="interval=1,freshness_warn=0.8,freshness_critical=1.0",
    chip_ledger=True,
)
