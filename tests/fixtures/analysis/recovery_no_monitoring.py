"""Analysis fixture: supervised run with monitoring fully off — the
verifier must flag PWL007 (warning): restarts and escalations would be
invisible, no dashboard and no /metrics to scrape."""

import pathway_tpu as pw

t = pw.debug.table_from_markdown(
    """
    | word
  1 | cat
  2 | dog
    """
)

counts = t.groupby(pw.this.word).reduce(pw.this.word, n=pw.reducers.count())

pw.io.null.write(counts)

pw.run(recovery=True, monitoring_level="none")
