"""Analysis fixture: a 2-process sharded run with the cluster fault
domain hollowed out — the verifier must flag PWL009 (warning) twice:
once for ``recovery=`` off (one worker crash kills the whole run, no
partial restart) and once for heartbeats disabled
(``cluster_lease_ms=0``: a hung or partitioned worker stalls the epoch
barrier forever)."""

import os

os.environ["PATHWAY_PROCESSES"] = "2"

import pathway_tpu as pw

t = pw.debug.table_from_markdown(
    """
    | word
  1 | cat
  2 | dog
    """
)

counts = t.groupby(pw.this.word).reduce(pw.this.word, n=pw.reducers.count())

pw.io.null.write(counts)

pw.run(cluster_lease_ms=0)
