"""Analysis fixture: a device-backed KNN index whose reserved capacity
(20M x 384 f32 ~= 28.6 GiB) cannot fit the 16 GiB per-device HBM budget
and no cold tier is configured — the verifier must flag PWL012
(warning): demote the cold corpus with pw.run(index_tiers=...) /
PATHWAY_INDEX_TIERS. (PWL010 co-fires with the other lever, sharding —
the two rules advise complementary fixes for the same footprint.)
Analyze-only never builds the index, so nothing is allocated."""

import pathway_tpu as pw
from pathway_tpu.stdlib.ml.index import KNNIndex

docs = pw.debug.table_from_markdown(
    """
    | x   | y
  1 | 1.0 | 0.0
  2 | 0.0 | 1.0
    """
)
docs = docs.select(
    emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, docs.x, docs.y)
)

queries = pw.debug.table_from_markdown(
    """
    | x   | y
  9 | 1.0 | 1.0
    """
)
queries = queries.select(
    emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, queries.x, queries.y)
)

index = KNNIndex(
    docs.emb,
    docs,
    n_dimensions=384,
    reserved_space=20_000_000,
    distance_type="cosine",
)
res = index.get_nearest_items(queries.emb, k=3)

pw.io.null.write(res)

pw.run()
