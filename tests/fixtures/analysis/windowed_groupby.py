"""Analysis fixture: the same aggregation as unbounded_groupby.py but
windowed — the verifier must pass it clean (exit 0)."""

import pathway_tpu as pw

events = pw.demo.range_stream(nb_rows=5, input_rate=1000.0)

per_window = events.windowby(
    pw.this.value,
    window=pw.temporal.tumbling(duration=10),
).reduce(
    n=pw.reducers.count(),
)

pw.io.null.write(per_window)

pw.run(monitoring_level=pw.MonitoringLevel.NONE)
