"""Deep-analysis fixture (PWL019 positive): an index pinned to its own
``mesh="data=2"`` in a run with *no* mesh — DeviceRing staging lands
each epoch's payload on the default device and the engine bounces it
through host onto the index shards. ``--deep`` must flag PWL019
(warning) and suggest passing the same mesh to pw.run()."""

import pathway_tpu as pw
from pathway_tpu.stdlib.ml.index import KNNIndex

docs = pw.debug.table_from_markdown(
    """
    | x   | y
  1 | 1.0 | 0.0
  2 | 0.0 | 1.0
    """
)
docs = docs.select(
    emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, docs.x, docs.y)
)

queries = pw.debug.table_from_markdown(
    """
    | x   | y
  9 | 1.0 | 1.0
    """
)
queries = queries.select(
    emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, queries.x, queries.y)
)

index = KNNIndex(
    docs.emb,
    docs,
    n_dimensions=2,
    reserved_space=100,
    distance_type="cosine",
    mesh="data=2",
)
res = index.get_nearest_items(queries.emb, k=2)

pw.io.null.write(res)

pw.run()
