"""Deep-analysis fixture (PWL020 positive): a recovery run whose
persisted output depends on a default-deterministic UDF that reads the
wall clock — replay after a crash recomputes a *different* value than
the one the crashed epoch persisted. ``--deep`` must flag PWL020
(warning). A second hazard rides along: an async UDF with the default
``on_error="raise"`` (no dead-letter route), whose replayed side
effects are not idempotent."""

import time

import pathway_tpu as pw


def stamp(word: str) -> str:
    # nondeterministic under replay: the recomputed timestamp differs
    # from the one the pre-crash epoch persisted
    return f"{word}@{time.time():.0f}"


async def notify(word: str) -> str:
    return f"notified:{word}"


t = pw.debug.table_from_markdown(
    """
    | word
  1 | cat
  2 | dog
    """
)

tagged = t.select(
    tagged=pw.apply_with_type(stamp, str, t.word),
    sent=pw.apply_async(notify, t.word),
)

pw.io.null.write(tagged)

pw.run(recovery=True, monitoring_level="auto")
