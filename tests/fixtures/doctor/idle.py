"""Doctor fixture: a tiny static pipeline that allocates nothing on
device and breaches nothing — ``pathway doctor`` must come back green
(exit 0) with at least one watchdog sample taken."""

import pathway_tpu as pw

rows = pw.debug.table_from_markdown(
    """
    | x
  1 | 1.0
  2 | 2.0
    """
)
out = rows.select(y=rows.x + 1.0)
pw.io.null.write(out)

pw.run()
