"""Doctor fixture: a run whose HBM ledger ramps steadily toward a tiny
PATHWAY_HBM_BYTES budget. The health watchdog's ingest-rate EWMA
forecasts time-to-OOM well under the critical threshold, so ``pathway
doctor`` must come back red with a flight-recorder dump. Each row of
the pipeline commits ~4 MiB of "hot index" growth and sleeps long
enough for the watchdog thread to sample the ramp."""

import os
import time

os.environ.setdefault("PATHWAY_HBM_BYTES", str(64 * 1024 * 1024))

import pathway_tpu as pw
from pathway_tpu.internals.ledger import LEDGER

_ramp = {"bytes": 0}


def _grow(x: float) -> float:
    _ramp["bytes"] += 4 * 1024 * 1024
    LEDGER.update("index.hot", "ramp", _ramp["bytes"])
    time.sleep(0.1)
    return x


rows = pw.debug.table_from_markdown(
    """
     | x
   1 | 1.0
   2 | 2.0
   3 | 3.0
   4 | 4.0
   5 | 5.0
   6 | 6.0
   7 | 7.0
   8 | 8.0
   9 | 9.0
  10 | 10.0
    """
)
out = rows.select(y=pw.apply_with_type(_grow, float, rows.x))
pw.io.null.write(out)

pw.run()
