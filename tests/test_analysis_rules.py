"""Static verifier (pathway_tpu.analysis): one positive and one negative
fixture pipeline per rule PWL001..PWL006, the suppression API, the
pw.run(analysis=...) gate, the EngineError trace payload, and a golden
test pinning the JSON output format."""

from __future__ import annotations

import json

import numpy as np
import jax.numpy as jnp
import pytest

import pathway_tpu as pw
from pathway_tpu.analysis import Diagnostic, Severity, render_json
from pathway_tpu.internals.trace import Frame


@pytest.fixture(autouse=True)
def _fresh_graph():
    pw.clear_graph()
    yield
    pw.clear_graph()


def _rules(diags):
    return {d.rule for d in diags}


def _static(md: str):
    return pw.debug.table_from_markdown(md)


def _stream():
    return pw.demo.range_stream(nb_rows=5, input_rate=1000.0)


# ---------------------------------------------------------------- PWL001


def test_pwl001_filter_predicate_not_bool():
    t = _static("""
        | x
      1 | 1
    """)
    pw.io.null.write(t.filter(pw.this.x))
    diags = pw.analysis.analyze()
    hits = [d for d in diags if d.rule == "PWL001"]
    assert hits and hits[0].severity is Severity.ERROR
    assert "BOOL" in hits[0].message


def test_pwl001_concat_dtype_conflict():
    a = _static("""
        | x
      1 | 1
    """)
    b = _static("""
        | x
      1 | s
    """)
    pw.io.null.write(pw.Table.concat_reindex(a, b))
    diags = pw.analysis.analyze()
    assert any(d.rule == "PWL001" and "'x'" in d.message for d in diags)


def test_pwl001_negative_clean_filter_and_concat():
    a = _static("""
        | x
      1 | 1
    """)
    b = _static("""
        | x
      1 | 2
    """)
    pw.io.null.write(pw.Table.concat_reindex(a, b).filter(pw.this.x > 0))
    assert "PWL001" not in _rules(pw.analysis.analyze())


# ---------------------------------------------------------------- PWL002


def test_pwl002_unbounded_streaming_groupby():
    agg = _stream().groupby(pw.this.value).reduce(
        pw.this.value, n=pw.reducers.count()
    )
    pw.io.null.write(agg)
    diags = pw.analysis.analyze()
    hits = [d for d in diags if d.rule == "PWL002"]
    assert hits and hits[0].severity is Severity.ERROR
    assert hits[0].op_kind == "groupby_reduce"
    assert hits[0].trace is not None  # anchored to the user call site


def test_pwl002_windowed_groupby_is_clean():
    win = _stream().windowby(
        pw.this.value, window=pw.temporal.tumbling(duration=10)
    ).reduce(n=pw.reducers.count())
    pw.io.null.write(win)
    assert "PWL002" not in _rules(pw.analysis.analyze())


def test_pwl002_static_groupby_is_clean():
    t = _static("""
        | k | v
      1 | a | 1
    """)
    pw.io.null.write(t.groupby(pw.this.k).reduce(pw.this.k, n=pw.reducers.count()))
    assert "PWL002" not in _rules(pw.analysis.analyze())


def test_pwl002_streaming_join_warns_or_errors():
    s = _stream()
    t = _static("""
        | value | label
      1 | 1     | a
    """)
    j = s.join(t, s.value == t.value).select(s.value, t.label)
    pw.io.null.write(j)
    diags = pw.analysis.analyze()
    hits = [d for d in diags if d.rule == "PWL002"]
    assert hits and hits[0].severity is Severity.WARNING  # one side streaming


# ---------------------------------------------------------------- PWL003


def test_pwl003_mutable_capture():
    cache: dict = {}

    def slot(x: int) -> int:
        return cache.setdefault(x, len(cache))

    t = _static("""
        | x
      1 | 1
    """)
    pw.io.null.write(t.select(k=pw.apply_with_type(slot, int, pw.this.x)))
    diags = pw.analysis.analyze()
    assert any(
        d.rule == "PWL003" and "mutable state" in d.message for d in diags
    )


def test_pwl003_nondeterministic_grouping_key():
    import random

    @pw.udf
    def bucket(x: int) -> int:
        return x + random.randint(0, 1)

    t = _static("""
        | x | v
      1 | 1 | 2
    """)
    pw.io.null.write(
        t.groupby(bucket(pw.this.x)).reduce(total=pw.reducers.sum(pw.this.v))
    )
    diags = pw.analysis.analyze()
    assert any(
        d.rule == "PWL003" and "non-deterministic" in d.message for d in diags
    )


def test_pwl003_noncommutative_reducer():
    t = _static("""
        | k | v
      1 | a | 2
    """)
    pw.io.null.write(
        t.groupby(pw.this.k).reduce(first=pw.reducers.earliest(pw.this.v))
    )
    diags = pw.analysis.analyze()
    assert any(d.rule == "PWL003" and "commutative" in d.message for d in diags)


def test_pwl003_negative_pure_udf_and_sum():
    @pw.udf(deterministic=True)
    def double(x: int) -> int:
        return 2 * x

    t = _static("""
        | k | v
      1 | a | 2
    """)
    pw.io.null.write(
        t.groupby(double(pw.this.v)).reduce(total=pw.reducers.sum(pw.this.v))
    )
    assert "PWL003" not in _rules(pw.analysis.analyze())


# ---------------------------------------------------------------- PWL004


def test_pwl004_numpy_and_side_effect_in_batched_udf():
    @pw.udf(executor=pw.udfs.BatchExecutor(max_batch_size=8))
    def embed(xs: list[float]) -> list[float]:
        arr = np.asarray(xs)  # host numpy on traced values
        out = jnp.tanh(arr)
        print("batch", len(xs))  # side effect under jit
        return list(np.asarray(out))

    t = _static("""
        | x
      1 | 1.0
    """)
    pw.io.null.write(t.select(y=embed(pw.this.x)))
    diags = [d for d in pw.analysis.analyze() if d.rule == "PWL004"]
    assert any("numpy" in d.message for d in diags)
    assert any("print" in d.message for d in diags)


def test_pwl004_negative_pure_jnp_batch():
    @pw.udf(executor=pw.udfs.BatchExecutor(max_batch_size=8))
    def embed(xs: list[float]) -> list[float]:
        return [float(v) for v in jnp.tanh(jnp.asarray(xs))]

    t = _static("""
        | x
      1 | 1.0
    """)
    pw.io.null.write(t.select(y=embed(pw.this.x)))
    assert "PWL004" not in _rules(pw.analysis.analyze())


# ---------------------------------------------------------------- PWL005


def test_pwl005_dead_column_reported_at_origin():
    t = _static("""
        | owner | pet | age
      1 | Alice | dog | 2
    """)
    pw.io.null.write(t.filter(pw.this.age >= 3).select(pw.this.owner))
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL005"]
    # one finding, at the source that materializes 'pet' — not echoed by
    # the filter that merely carries it
    assert len(hits) == 1
    assert "'pet'" in hits[0].message and hits[0].op_kind == "static"


def test_pwl005_negative_all_columns_used():
    t = _static("""
        | owner | age
      1 | Alice | 2
    """)
    pw.io.null.write(t.filter(pw.this.age >= 3).select(pw.this.owner, pw.this.age))
    assert "PWL005" not in _rules(pw.analysis.analyze())


# ---------------------------------------------------------------- PWL006


def test_pwl006_unconnected_table():
    t = _static("""
        | x
      1 | 1
    """)
    t.select(y=pw.this.x + 1)  # orphan: never consumed
    pw.io.null.write(t.select(pw.this.x))
    diags = pw.analysis.analyze()
    assert any(
        d.rule == "PWL006" and d.severity is Severity.INFO for d in diags
    )


def test_pwl006_negative_everything_connected():
    t = _static("""
        | x
      1 | 1
    """)
    mid = t.select(y=pw.this.x + 1)
    pw.io.null.write(mid.filter(pw.this.y > 0))
    assert "PWL006" not in _rules(pw.analysis.analyze())


# ----------------------------------------------------------- suppression


def test_suppress_context_manager():
    t = _static("""
        | k | v
      1 | a | 2
    """)
    with pw.analysis.suppress("PWL003"):
        g = t.groupby(pw.this.k).reduce(first=pw.reducers.earliest(pw.this.v))
    pw.io.null.write(g)
    assert "PWL003" not in _rules(pw.analysis.analyze())


def test_suppress_direct_and_unknown_rule():
    t = _static("""
        | k | v
      1 | a | 2
    """)
    g = t.groupby(pw.this.k).reduce(first=pw.reducers.earliest(pw.this.v))
    pw.analysis.suppress("pwl003", g)  # case-insensitive
    pw.io.null.write(g)
    assert "PWL003" not in _rules(pw.analysis.analyze())
    with pytest.raises(ValueError):
        pw.analysis.suppress("PWL999")


# --------------------------------------------------------- run() gate


def test_run_analysis_strict_raises_before_running():
    agg = _stream().groupby(pw.this.value).reduce(n=pw.reducers.count())
    pw.io.null.write(agg)
    with pytest.raises(pw.analysis.AnalysisError) as exc:
        pw.run(analysis="strict")
    assert any(d.rule == "PWL002" for d in exc.value.diagnostics)


def test_run_analysis_warn_prints_and_continues(capsys):
    t = _static("""
        | x
      1 | 1
    """)
    pw.io.null.write(t.select(pw.this.x))
    t.select(dead=pw.this.x)  # orphan -> PWL006 info, not an error
    pw.run(analysis="warn", monitoring_level=pw.MonitoringLevel.NONE)
    assert "PWL006" in capsys.readouterr().err


def test_run_analysis_rejects_unknown_mode():
    with pytest.raises(ValueError):
        pw.run(analysis="pedantic")


# ------------------------------------------------- engine-level rules


def test_analyze_engine_flags_uncaptured_node():
    from pathway_tpu.internals.graph_runner import GraphRunner

    t = _static("""
        | x
      1 | 1
    """)
    orphan = t.select(y=pw.this.x + 1)
    out = t.select(pw.this.x)
    runner = GraphRunner(n_workers=1)
    runner.lower(orphan)
    runner.capture(out)  # wired to a sink; the orphan is not
    diags = pw.analysis.analyze(engine=runner.engine)
    engine_hits = [
        d for d in diags if d.rule == "PWL006" and "engine node" in d.message
    ]
    assert engine_hits
    # captured path must not be flagged: exactly the orphan's node chain
    assert all("Select" in d.message for d in engine_hits)


# -------------------------------------------------- EngineError payload


def test_engine_error_carries_node_identity_and_trace():
    from pathway_tpu.engine.dataflow import EngineError

    frame = Frame(
        filename="pipe.py", line_number=7, line="x = y.z", function="<module>"
    )

    class FakeNode:
        name = "groupby_reduce"
        id = 42
        user_frame = frame

    err = EngineError("boom", node=FakeNode())
    assert err.node_name == "groupby_reduce"
    assert err.node_id == 42
    assert err.trace is frame


# ------------------------------------------------------- golden output


def test_json_output_is_stable():
    """The --json wire format is consumed by CI scripts — pin it."""
    frame = Frame(
        filename="pipe.py", line_number=12, line="bad = s.groupby(...)",
        function="<module>",
    )
    diags = [
        Diagnostic(
            rule="PWL002",
            severity=Severity.ERROR,
            message="unbounded state",
            table="s.reduce",
            table_id=3,
            op_kind="groupby_reduce",
            trace=frame,
        ),
        Diagnostic(
            rule="PWL005",
            severity=Severity.INFO,
            message="dead column",
            table="t",
            table_id=1,
            op_kind="static",
            trace=None,
        ),
    ]
    got = json.loads(render_json(diags))
    assert got == {
        "diagnostics": [
            {
                "location": {
                    "file": "pipe.py",
                    "function": "<module>",
                    "line": 12,
                },
                "message": "unbounded state",
                "op": "groupby_reduce",
                "rule": "PWL002",
                "severity": "error",
                "table": "s.reduce",
            },
            {
                "message": "dead column",
                "op": "static",
                "rule": "PWL005",
                "severity": "info",
                "table": "t",
            },
        ],
        "summary": {"error": 1, "info": 1, "suppressed": 0, "warning": 0},
    }


# ---------------------------------------------------------------- PWL007


def _describe_run(monkeypatch, **run_kwargs):
    """Record pw.run's configuration on the graph without executing it
    (the same analyze-only path `pathway analyze` uses)."""
    monkeypatch.setenv("PATHWAY_ANALYZE_ONLY", "1")
    assert pw.run(**run_kwargs) is None


def _null_sink():
    t = _static("""
        | x
      1 | 1
    """)
    pw.io.null.write(t.select(pw.this.x))


def test_pwl007_recovery_with_monitoring_off(monkeypatch):
    _null_sink()
    _describe_run(monkeypatch, recovery=True, monitoring_level="none")
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL007"]
    assert hits and hits[0].severity is Severity.WARNING
    assert "recovery" in hits[0].message


def test_pwl007_fires_on_bare_default_monitoring(monkeypatch):
    # MonitoringLevel.coerce(None) is NONE: the bare default IS off
    _null_sink()
    _describe_run(monkeypatch, recovery=pw.Recovery(max_restarts=2))
    assert "PWL007" in _rules(pw.analysis.analyze())


def test_pwl007_enum_none_counts_as_off(monkeypatch):
    _null_sink()
    _describe_run(
        monkeypatch, recovery=True, monitoring_level=pw.MonitoringLevel.NONE
    )
    assert "PWL007" in _rules(pw.analysis.analyze())


def test_pwl007_negative_http_server_silences(monkeypatch):
    _null_sink()
    _describe_run(
        monkeypatch, recovery=True, monitoring_level="none", with_http_server=True
    )
    assert "PWL007" not in _rules(pw.analysis.analyze())


def test_pwl007_negative_monitoring_configured(monkeypatch):
    _null_sink()
    _describe_run(
        monkeypatch, recovery=True, monitoring_level=pw.MonitoringLevel.IN_OUT
    )
    assert "PWL007" not in _rules(pw.analysis.analyze())


def test_pwl007_negative_no_recovery(monkeypatch):
    _null_sink()
    _describe_run(monkeypatch, monitoring_level="none")
    assert "PWL007" not in _rules(pw.analysis.analyze())


def test_pwl007_negative_without_run_context():
    # `pw.analysis.analyze()` before any pw.run: nothing recorded, no rule
    _null_sink()
    assert "PWL007" not in _rules(pw.analysis.analyze())


# ---------------------------------------------------------------- PWL008


class _RestQuerySchema(pw.Schema):
    value: int


def _rest_endpoint(serving=None):
    queries, writer = pw.io.http.rest_connector(
        host="127.0.0.1",
        port=0,
        schema=_RestQuerySchema,
        delete_completed_queries=False,
        serving=serving,
    )
    writer(queries.select(result=pw.this.value * 2))


def test_pwl008_unprotected_endpoint_under_recovery(monkeypatch):
    _rest_endpoint()
    _describe_run(monkeypatch, recovery=True, monitoring_level="in_out")
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL008"]
    assert hits and hits[0].severity is Severity.WARNING
    assert "overload" in hits[0].message


def test_pwl008_unprotected_endpoint_under_pipelining(monkeypatch):
    _rest_endpoint()
    _describe_run(monkeypatch, pipeline_depth=2, monitoring_level="in_out")
    assert "PWL008" in _rules(pw.analysis.analyze())


def test_pwl008_negative_serving_config_silences(monkeypatch):
    _rest_endpoint(serving=pw.ServingConfig(max_queue=8))
    _describe_run(monkeypatch, recovery=True, monitoring_level="in_out")
    assert "PWL008" not in _rules(pw.analysis.analyze())


def test_pwl008_negative_no_pressure(monkeypatch):
    # plain single-depth run without recovery: an unprotected endpoint
    # is fine for a dev loop, no warning
    _rest_endpoint()
    _describe_run(monkeypatch, monitoring_level="in_out")
    assert "PWL008" not in _rules(pw.analysis.analyze())


def test_pwl008_negative_no_endpoints(monkeypatch):
    _null_sink()
    _describe_run(monkeypatch, recovery=True, monitoring_level="in_out")
    assert "PWL008" not in _rules(pw.analysis.analyze())


# ---------------------------------------------------------------- PWL009


def test_pwl009_multiworker_without_recovery(monkeypatch):
    monkeypatch.setenv("PATHWAY_PROCESSES", "2")
    _null_sink()
    _describe_run(monkeypatch, monitoring_level="in_out")
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL009"]
    assert hits and hits[0].severity is Severity.WARNING
    assert "recovery" in hits[0].message
    assert hits[0].detail["world"] == 2


def test_pwl009_threads_count_toward_world(monkeypatch):
    # a single process with 4 engine threads is still a sharded run
    monkeypatch.setenv("PATHWAY_THREADS", "4")
    _null_sink()
    _describe_run(monkeypatch, monitoring_level="in_out")
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL009"]
    assert hits and hits[0].detail["world"] == 4


def test_pwl009_lease_zero_disables_heartbeats(monkeypatch):
    monkeypatch.setenv("PATHWAY_PROCESSES", "2")
    _null_sink()
    _describe_run(
        monkeypatch,
        recovery=True,
        monitoring_level="in_out",
        cluster_lease_ms=0,
    )
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL009"]
    # recovery= is on, so only the disabled-heartbeats arm fires
    assert len(hits) == 1
    assert "heartbeats disabled" in hits[0].message
    assert hits[0].detail["cluster_lease_ms"] == 0.0


def test_pwl009_both_arms_fire_together(monkeypatch):
    monkeypatch.setenv("PATHWAY_PROCESSES", "2")
    _null_sink()
    _describe_run(monkeypatch, monitoring_level="in_out", cluster_lease_ms=0)
    assert len([d for d in pw.analysis.analyze() if d.rule == "PWL009"]) == 2


def test_pwl009_negative_single_worker(monkeypatch):
    _null_sink()
    _describe_run(monkeypatch, monitoring_level="in_out", cluster_lease_ms=0)
    assert "PWL009" not in _rules(pw.analysis.analyze())


def test_pwl009_negative_fault_domain_intact(monkeypatch):
    monkeypatch.setenv("PATHWAY_PROCESSES", "2")
    _null_sink()
    _describe_run(
        monkeypatch,
        recovery=True,
        monitoring_level="in_out",
        cluster_lease_ms=2000,
    )
    assert "PWL009" not in _rules(pw.analysis.analyze())


def test_pwl009_negative_without_run_context():
    _null_sink()
    assert "PWL009" not in _rules(pw.analysis.analyze())


# ---------------------------------------------------------------- PWL010


def _knn_sink(reserved: int, dim: int = 384):
    from pathway_tpu.stdlib.ml.index import KNNIndex

    docs = _static("""
        | x
      1 | 1.0
      2 | 2.0
    """)
    docs = docs.select(emb=pw.apply_with_type(lambda x: (x, x), pw.ANY, docs.x))
    queries = _static("""
        | x
      9 | 1.5
    """)
    queries = queries.select(
        emb=pw.apply_with_type(lambda x: (x, x), pw.ANY, queries.x)
    )
    index = KNNIndex(docs.emb, docs, n_dimensions=dim, reserved_space=reserved)
    pw.io.null.write(index.get_nearest_items(queries.emb, k=2))


def test_pwl010_index_over_hbm_without_mesh(monkeypatch):
    # 20M x 384 f32 ~= 28.6 GiB resident against the 16 GiB default
    _knn_sink(reserved=20_000_000)
    _describe_run(monkeypatch, monitoring_level="in_out")
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL010"]
    assert hits and hits[0].severity is Severity.WARNING
    assert "mesh" in hits[0].message
    assert hits[0].detail["suggested_mesh"] == 2
    assert hits[0].detail["mesh_axes"] is None


def test_pwl010_mesh_arg_silences(monkeypatch):
    _knn_sink(reserved=20_000_000)
    _describe_run(monkeypatch, monitoring_level="in_out", mesh=2)
    assert "PWL010" not in _rules(pw.analysis.analyze())


def test_pwl010_pathway_mesh_env_silences(monkeypatch):
    monkeypatch.setenv("PATHWAY_MESH", "4x2")
    _knn_sink(reserved=20_000_000)
    _describe_run(monkeypatch, monitoring_level="in_out")
    assert "PWL010" not in _rules(pw.analysis.analyze())


def test_pwl010_undersized_mesh_still_fires(monkeypatch):
    # ~114 GiB index: a 2-way data mesh still leaves 57 GiB per device
    _knn_sink(reserved=80_000_000)
    _describe_run(monkeypatch, monitoring_level="in_out", mesh=2)
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL010"]
    assert hits and hits[0].detail["mesh_axes"] == {"data": 2, "model": 1}
    assert hits[0].detail["suggested_mesh"] >= 8


def test_pwl010_hbm_budget_env_override(monkeypatch):
    # a modest index trips a deliberately tiny budget
    monkeypatch.setenv("PATHWAY_HBM_BYTES", str(64 * 1024 * 1024))
    _knn_sink(reserved=200_000)
    _describe_run(monkeypatch, monitoring_level="in_out")
    assert "PWL010" in _rules(pw.analysis.analyze())


def test_pwl010_negative_small_index(monkeypatch):
    _knn_sink(reserved=100_000)
    _describe_run(monkeypatch, monitoring_level="in_out")
    assert "PWL010" not in _rules(pw.analysis.analyze())


def test_pwl010_negative_host_index_invisible(monkeypatch):
    # LSH tier is host-resident: no spec registered, no HBM rule
    from pathway_tpu.stdlib.indexing import LshKnnFactory

    docs = _static("""
        | x
      1 | 1.0
    """)
    docs = docs.select(emb=pw.apply_with_type(lambda x: (x, x), pw.ANY, docs.x))
    queries = _static("""
        | x
      9 | 1.5
    """)
    queries = queries.select(
        emb=pw.apply_with_type(lambda x: (x, x), pw.ANY, queries.x)
    )
    idx = LshKnnFactory(dimensions=2, reserved_space=50_000_000).build_index(
        docs.emb, docs
    )
    pw.io.null.write(idx.query_as_of_now(queries.emb))
    _describe_run(monkeypatch, monitoring_level="in_out")
    assert "PWL010" not in _rules(pw.analysis.analyze())


# ---------------------------------------------------------------- PWL011


def _streaming_knn_sink():
    from pathway_tpu.stdlib.ml.index import KNNIndex

    docs = _stream()
    docs = docs.select(
        emb=pw.apply_with_type(lambda v: (float(v), 1.0), pw.ANY, docs.value)
    )
    queries = _static("""
        | x   | y
      9 | 1.0 | 1.0
    """)
    queries = queries.select(
        emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, queries.x, queries.y)
    )
    index = KNNIndex(docs.emb, docs, n_dimensions=2, reserved_space=100)
    pw.io.null.write(index.get_nearest_items(queries.emb, k=2))


def test_pwl011_streaming_device_index_serial_ingest(monkeypatch):
    _streaming_knn_sink()
    _describe_run(monkeypatch, monitoring_level="in_out")
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL011"]
    assert len(hits) == 1 and hits[0].severity is Severity.WARNING
    assert "ingest_workers" in hits[0].message
    assert hits[0].detail["pipeline_depth"] == 1
    assert hits[0].detail["ingest_workers"] == 0
    assert hits[0].detail["indexes"], "device index specs missing from detail"


def test_pwl011_ingest_workers_arg_silences(monkeypatch):
    _streaming_knn_sink()
    _describe_run(monkeypatch, monitoring_level="in_out", ingest_workers=2)
    assert "PWL011" not in _rules(pw.analysis.analyze())


def test_pwl011_ingest_workers_env_silences(monkeypatch):
    monkeypatch.setenv("PATHWAY_INGEST_WORKERS", "3")
    _streaming_knn_sink()
    _describe_run(monkeypatch, monitoring_level="in_out")
    assert "PWL011" not in _rules(pw.analysis.analyze())


def test_pwl011_pipeline_depth_silences(monkeypatch):
    _streaming_knn_sink()
    _describe_run(monkeypatch, monitoring_level="in_out", pipeline_depth=2)
    assert "PWL011" not in _rules(pw.analysis.analyze())


def test_pwl011_negative_static_source(monkeypatch):
    # static docs: one epoch, nothing streams — no serial-ingest hazard
    _knn_sink(reserved=100_000)
    _describe_run(monkeypatch, monitoring_level="in_out")
    assert "PWL011" not in _rules(pw.analysis.analyze())


def test_pwl011_negative_without_run_context():
    _streaming_knn_sink()
    assert "PWL011" not in _rules(pw.analysis.analyze())


# ---------------------------------------------------------------- PWL012


def test_pwl012_beyond_hbm_without_cold_tier(monkeypatch):
    _knn_sink(reserved=20_000_000)
    _describe_run(monkeypatch, monitoring_level="in_out")
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL012"]
    assert hits and hits[0].severity is Severity.WARNING
    assert "index_tiers" in hits[0].message
    d = hits[0].detail
    assert d["bytes"] > d["hbm_budget_bytes"]
    split = d["suggested_tier_split"]
    assert split["hot_rows"] + split["cold_rows"] == 20_000_000
    assert 0 < split["hot_rows"] < 20_000_000
    # int8 cold estimate: dim bytes + one f32 scale per row
    assert d["quantized_cold_bytes"] == split["cold_rows"] * (384 + 4)
    # the sharding rule co-fires: PWL010 advises the other lever
    assert "PWL010" in _rules(pw.analysis.analyze())


def test_pwl012_index_tiers_arg_silences(monkeypatch):
    _knn_sink(reserved=20_000_000)
    _describe_run(monkeypatch, monitoring_level="in_out", index_tiers="hot=40000")
    assert "PWL012" not in _rules(pw.analysis.analyze())


def test_pwl012_env_knob_silences(monkeypatch):
    monkeypatch.setenv("PATHWAY_INDEX_TIERS", "auto")
    _knn_sink(reserved=20_000_000)
    _describe_run(monkeypatch, monitoring_level="in_out")
    assert "PWL012" not in _rules(pw.analysis.analyze())


def test_pwl012_tier_config_silences_pwl010_too(monkeypatch):
    # a tiered run bounds the resident set to the hot tier: neither the
    # sharding rule nor the tier rule has anything left to flag
    _knn_sink(reserved=20_000_000)
    _describe_run(monkeypatch, monitoring_level="in_out", index_tiers="auto")
    got = _rules(pw.analysis.analyze())
    assert "PWL010" not in got and "PWL012" not in got


def test_pwl012_fires_with_undersized_mesh(monkeypatch):
    # ~114 GiB over 2 shards leaves 57 GiB per device: tiering advice
    # still applies, with the hot split scaled by the mesh
    _knn_sink(reserved=80_000_000)
    _describe_run(monkeypatch, monitoring_level="in_out", mesh=2)
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL012"]
    assert hits and hits[0].detail["mesh_axes"] == {"data": 2, "model": 1}
    assert hits[0].detail["per_device_bytes"] > hits[0].detail["hbm_budget_bytes"]


def test_pwl012_negative_fits_hbm(monkeypatch):
    _knn_sink(reserved=100_000)
    _describe_run(monkeypatch, monitoring_level="in_out")
    assert "PWL012" not in _rules(pw.analysis.analyze())


def test_pwl012_hbm_budget_env_override(monkeypatch):
    monkeypatch.setenv("PATHWAY_HBM_BYTES", str(64 * 1024 * 1024))
    _knn_sink(reserved=200_000)
    _describe_run(monkeypatch, monitoring_level="in_out")
    assert "PWL012" in _rules(pw.analysis.analyze())


# ---------------------------------------------------------------- PWL013


def _llm_rerank_sink():
    """A pipeline whose rerank hop goes through an HTTP chat endpoint
    (LLMReranker records an llm_endpoints entry at expression build)."""
    from pathway_tpu.xpacks.llm.llms import BaseChat
    from pathway_tpu.xpacks.llm.rerankers import LLMReranker

    class StubChat(BaseChat):
        def __init__(self):
            super().__init__()
            self.kwargs = {"model": "gpt-x"}

        def __wrapped__(self, messages, **kwargs) -> str:
            return "3"

        def _accepts_call_arg(self, arg_name: str) -> bool:
            return False

    pairs = _static("""
        | doc | query
      1 | a   | q
      2 | b   | q
    """)
    reranker = LLMReranker(StubChat())
    pw.io.null.write(pairs.select(score=reranker(pairs.doc, pairs.query)))


def test_pwl013_http_llm_with_decode_plane(monkeypatch):
    _llm_rerank_sink()
    _describe_run(monkeypatch, decode="pages=64,page=16")
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL013"]
    assert len(hits) == 1 and hits[0].severity is Severity.WARNING
    assert "llm_reranker" in hits[0].message
    assert hits[0].detail["llm_endpoints"][0]["model"] == "gpt-x"
    assert hits[0].detail["decode"]["pages"] == 64


def test_pwl013_env_knob_counts_as_decode(monkeypatch):
    monkeypatch.setenv("PATHWAY_DECODE", "auto")
    _llm_rerank_sink()
    _describe_run(monkeypatch)
    assert "PWL013" in _rules(pw.analysis.analyze())


def test_pwl013_negative_no_decode_plane(monkeypatch):
    monkeypatch.delenv("PATHWAY_DECODE", raising=False)
    _llm_rerank_sink()
    _describe_run(monkeypatch)
    assert "PWL013" not in _rules(pw.analysis.analyze())


def test_pwl013_negative_decode_off_spec(monkeypatch):
    _llm_rerank_sink()
    _describe_run(monkeypatch, decode="off")
    assert "PWL013" not in _rules(pw.analysis.analyze())


def test_pwl013_negative_device_reranker_does_not_record(monkeypatch):
    # the on-chip cross-encoder IS the decode-plane-friendly path: a
    # pipeline already using it must not be told to migrate
    from pathway_tpu.xpacks.llm.rerankers import CrossEncoderReranker

    pairs = _static("""
        | doc | query
      1 | a   | q
    """)
    reranker = CrossEncoderReranker()
    pw.io.null.write(pairs.select(score=reranker(pairs.doc, pairs.query)))
    _describe_run(monkeypatch, decode=True)
    assert "PWL013" not in _rules(pw.analysis.analyze())


# ---------------------------------------------------------------- PWL014


def test_pwl014_slo_budget_without_observability(monkeypatch):
    monkeypatch.delenv("PATHWAY_TRACING", raising=False)
    monkeypatch.delenv("PATHWAY_PROFILE", raising=False)
    _rest_endpoint(serving=pw.ServingConfig(default_deadline_ms=250.0))
    _describe_run(monkeypatch, monitoring_level="in_out")
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL014"]
    assert len(hits) == 1 and hits[0].severity is Severity.WARNING
    assert "deadline" in hits[0].message
    assert hits[0].detail["endpoints"][0]["deadline_ms"] == 250.0
    assert hits[0].detail["tracing"] is False


def test_pwl014_tracing_arg_silences(monkeypatch):
    _rest_endpoint(serving=pw.ServingConfig(default_deadline_ms=250.0))
    _describe_run(monkeypatch, monitoring_level="in_out", tracing=True)
    assert "PWL014" not in _rules(pw.analysis.analyze())


def test_pwl014_tracing_env_silences(monkeypatch):
    monkeypatch.setenv("PATHWAY_TRACING", "1")
    _rest_endpoint(serving=pw.ServingConfig(default_deadline_ms=250.0))
    _describe_run(monkeypatch, monitoring_level="in_out")
    assert "PWL014" not in _rules(pw.analysis.analyze())


def test_pwl014_profiler_silences(monkeypatch):
    monkeypatch.delenv("PATHWAY_TRACING", raising=False)
    _rest_endpoint(serving=pw.ServingConfig(default_deadline_ms=250.0))
    _describe_run(monkeypatch, monitoring_level="in_out", profile="prof.json")
    assert "PWL014" not in _rules(pw.analysis.analyze())


def test_pwl014_negative_no_deadline_budget(monkeypatch):
    monkeypatch.delenv("PATHWAY_TRACING", raising=False)
    monkeypatch.delenv("PATHWAY_PROFILE", raising=False)
    # an endpoint without a deadline budget has no SLO to attribute
    _rest_endpoint(serving=pw.ServingConfig(default_deadline_ms=None))
    _describe_run(monkeypatch, monitoring_level="in_out")
    assert "PWL014" not in _rules(pw.analysis.analyze())


def test_pwl014_negative_without_run_context():
    _rest_endpoint(serving=pw.ServingConfig(default_deadline_ms=250.0))
    # unit-built graph, pw.run never described: rule stays quiet
    assert "PWL014" not in _rules(pw.analysis.analyze())


# ---------------------------------------------------------------- PWL021


def test_pwl021_deadline_budget_without_chip_accounting(monkeypatch):
    monkeypatch.delenv("PATHWAY_CHIP_LEDGER", raising=False)
    _rest_endpoint(serving=pw.ServingConfig(default_deadline_ms=250.0))
    # tracing on: PWL014 is satisfied yet PWL021 still fires — wall
    # attribution and device-second attribution are different planes
    _describe_run(monkeypatch, monitoring_level="in_out", tracing=True)
    diags = pw.analysis.analyze()
    hits = [d for d in diags if d.rule == "PWL021"]
    assert len(hits) == 1 and hits[0].severity is Severity.WARNING
    assert "chip-time accounting is off" in hits[0].message
    assert hits[0].detail["endpoints"][0]["deadline_ms"] == 250.0
    assert hits[0].detail["chip_ledger"] is False
    assert "PWL014" not in _rules(diags)


def test_pwl021_watchdog_without_chip_accounting(monkeypatch):
    monkeypatch.delenv("PATHWAY_CHIP_LEDGER", raising=False)
    _null_sink()
    _describe_run(monkeypatch, monitoring_level="in_out", watchdog=True)
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL021"]
    assert len(hits) == 1
    assert hits[0].detail["watchdog"] is True
    assert "watchdog is on" in hits[0].message


def test_pwl021_chip_ledger_arg_silences(monkeypatch):
    _rest_endpoint(serving=pw.ServingConfig(default_deadline_ms=250.0))
    _describe_run(
        monkeypatch, monitoring_level="in_out", tracing=True, chip_ledger=True
    )
    assert "PWL021" not in _rules(pw.analysis.analyze())


def test_pwl021_chip_ledger_env_silences(monkeypatch):
    monkeypatch.setenv("PATHWAY_CHIP_LEDGER", "1")
    _null_sink()
    _describe_run(monkeypatch, monitoring_level="in_out", watchdog=True)
    assert "PWL021" not in _rules(pw.analysis.analyze())


def test_pwl021_negative_no_contract(monkeypatch):
    monkeypatch.delenv("PATHWAY_CHIP_LEDGER", raising=False)
    # no deadline budget and no watchdog: nothing promised, no warning
    _rest_endpoint(serving=pw.ServingConfig(default_deadline_ms=None))
    _describe_run(monkeypatch, monitoring_level="in_out")
    assert "PWL021" not in _rules(pw.analysis.analyze())


def test_pwl021_negative_without_run_context():
    _rest_endpoint(serving=pw.ServingConfig(default_deadline_ms=250.0))
    assert "PWL021" not in _rules(pw.analysis.analyze())


# ---------------------------------------------------------------- PWL022


def test_pwl022_watermarks_without_persistence(monkeypatch):
    _null_sink()
    _describe_run(
        monkeypatch,
        monitoring_level="in_out",
        elastic={"auto": True, "hbm_frac": 0.85},
    )
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL022"]
    assert len(hits) == 1 and hits[0].severity is Severity.WARNING
    assert "watermarks are armed" in hits[0].message
    assert hits[0].detail["elastic"]["hbm_frac"] == 0.85
    assert hits[0].detail["persistence"] is False


def test_pwl022_mesh_auto_without_persistence(monkeypatch):
    _null_sink()
    _describe_run(monkeypatch, monitoring_level="in_out", mesh="auto")
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL022"]
    assert len(hits) == 1
    assert 'mesh="auto"' in hits[0].message
    assert hits[0].detail["mesh_auto"] is True


def test_pwl022_fixed_target_without_persistence(monkeypatch):
    _null_sink()
    _describe_run(monkeypatch, monitoring_level="in_out", elastic=4)
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL022"]
    assert len(hits) == 1
    assert "shards=4" in hits[0].message


def test_pwl022_persistence_silences(monkeypatch, tmp_path):
    _null_sink()
    _describe_run(
        monkeypatch,
        monitoring_level="in_out",
        elastic={"auto": True, "hbm_frac": 0.85},
        persistence_config=pw.persistence.Config.simple_config(
            pw.persistence.Backend.filesystem(str(tmp_path))
        ),
    )
    assert "PWL022" not in _rules(pw.analysis.analyze())


def test_pwl022_negative_no_elastic_plane(monkeypatch):
    # neither an elastic spec nor mesh="auto": nothing migrates,
    # nothing to fence
    _null_sink()
    _describe_run(monkeypatch, monitoring_level="in_out")
    assert "PWL022" not in _rules(pw.analysis.analyze())


def test_pwl022_negative_without_run_context():
    _null_sink()
    assert "PWL022" not in _rules(pw.analysis.analyze())


# ---------------------------------------------------------------- PWL015


def _combined_budget(monkeypatch):
    """48 MiB budget: a 20k x 384 f32 index (~29.4 MiB) and the default
    256x16 KV pool (~32 MiB at nominal decoder geometry) each fit alone
    but jointly oversubscribe."""
    monkeypatch.setenv("PATHWAY_HBM_BYTES", str(48 * 1024 * 1024))


def test_pwl015_combined_planes_oversubscribe(monkeypatch):
    _combined_budget(monkeypatch)
    _knn_sink(reserved=20_000)
    _describe_run(monkeypatch, monitoring_level="in_out", decode="pages=256,page=16")
    diags = pw.analysis.analyze()
    hits = [d for d in diags if d.rule == "PWL015"]
    assert len(hits) == 1 and hits[0].severity is Severity.WARNING
    fp = hits[0].detail["footprint"]
    budget = hits[0].detail["hbm_budget_bytes"]
    assert fp["index"] <= budget and fp["decode_kv"] <= budget
    assert fp["total"] > budget
    # the single-plane rules stay quiet in this window
    got = _rules(diags)
    assert "PWL010" not in got and "PWL012" not in got


def test_pwl015_negative_fits_together(monkeypatch):
    monkeypatch.setenv("PATHWAY_HBM_BYTES", str(256 * 1024 * 1024))
    _knn_sink(reserved=20_000)
    _describe_run(monkeypatch, monitoring_level="in_out", decode="pages=256,page=16")
    assert "PWL015" not in _rules(pw.analysis.analyze())


def test_pwl015_negative_without_decode_plane(monkeypatch):
    _combined_budget(monkeypatch)
    _knn_sink(reserved=20_000)
    _describe_run(monkeypatch, monitoring_level="in_out")
    assert "PWL015" not in _rules(pw.analysis.analyze())


def test_pwl015_negative_index_alone_over_budget(monkeypatch):
    # the index alone blows the budget: PWL010/PWL012 own that finding
    _combined_budget(monkeypatch)
    _knn_sink(reserved=200_000)
    _describe_run(monkeypatch, monitoring_level="in_out", decode="pages=256,page=16")
    diags = pw.analysis.analyze()
    assert "PWL015" not in _rules(diags)
    assert "PWL010" in _rules(diags)


def test_pwl015_mesh_sharding_silences(monkeypatch):
    # a 2-way data mesh halves the per-device index share: fits together
    _combined_budget(monkeypatch)
    _knn_sink(reserved=20_000)
    _describe_run(
        monkeypatch, monitoring_level="in_out", mesh=4, decode="pages=256,page=16"
    )
    assert "PWL015" not in _rules(pw.analysis.analyze())


def test_pwl015_index_tiers_silence(monkeypatch):
    # a configured cold tier bounds the resident hot set: PWL012's
    # territory, not PWL015's
    _combined_budget(monkeypatch)
    monkeypatch.setenv("PATHWAY_INDEX_TIERS", "auto")
    _knn_sink(reserved=20_000)
    _describe_run(monkeypatch, monitoring_level="in_out", decode="pages=256,page=16")
    assert "PWL015" not in _rules(pw.analysis.analyze())


def test_pwl015_negative_without_run_context(monkeypatch):
    _combined_budget(monkeypatch)
    _knn_sink(reserved=20_000)
    assert "PWL015" not in _rules(pw.analysis.analyze())


# ---------------------------------------------------------------- PWL016


def test_pwl016_tenancy_without_quotas(monkeypatch):
    _null_sink()
    _describe_run(monkeypatch, monitoring_level="in_out", tenancy=True)
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL016"]
    assert len(hits) == 1 and hits[0].severity is Severity.WARNING
    assert "quota" in hits[0].message
    assert hits[0].detail["tenancy"]["quotas"] == {}


def test_pwl016_env_knob_counts_as_tenancy(monkeypatch):
    monkeypatch.setenv("PATHWAY_TENANCY", "on")
    _null_sink()
    _describe_run(monkeypatch, monitoring_level="in_out")
    assert "PWL016" in _rules(pw.analysis.analyze())


def test_pwl016_default_quota_silences(monkeypatch):
    # quota knobs in the flat spec become the default quota: every
    # tenant is bounded, nothing to warn about
    _null_sink()
    _describe_run(monkeypatch, monitoring_level="in_out", tenancy="qps=50,inflight=8")
    assert "PWL016" not in _rules(pw.analysis.analyze())


def test_pwl016_named_quotas_silence(monkeypatch):
    _null_sink()
    _describe_run(
        monkeypatch,
        monitoring_level="in_out",
        tenancy={"quotas": {"acme": {"qps": 100, "hbm": "1M"}}},
    )
    assert "PWL016" not in _rules(pw.analysis.analyze())


def test_pwl016_quota_hbm_oversubscription(monkeypatch):
    # each tenant's HBM quota fits alone, but the three sum past the
    # 4 MiB budget: admission would book segments the device can't hold
    monkeypatch.setenv("PATHWAY_HBM_BYTES", str(4 * 1024 * 1024))
    _null_sink()
    _describe_run(
        monkeypatch,
        monitoring_level="in_out",
        tenancy={"quotas": {t: {"hbm": "2M"} for t in ("a", "b", "c")}},
    )
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL016"]
    assert len(hits) == 1
    assert hits[0].detail["total_bytes"] == 3 * 2 * 1024 * 1024
    assert hits[0].detail["total_bytes"] > hits[0].detail["hbm_budget_bytes"]


def test_pwl016_quota_hbm_fits(monkeypatch):
    monkeypatch.setenv("PATHWAY_HBM_BYTES", str(64 * 1024 * 1024))
    _null_sink()
    _describe_run(
        monkeypatch,
        monitoring_level="in_out",
        tenancy={"quotas": {t: {"hbm": "2M"} for t in ("a", "b", "c")}},
    )
    assert "PWL016" not in _rules(pw.analysis.analyze())


def test_pwl016_negative_tenancy_off(monkeypatch):
    monkeypatch.delenv("PATHWAY_TENANCY", raising=False)
    _null_sink()
    _describe_run(monkeypatch, monitoring_level="in_out")
    assert "PWL016" not in _rules(pw.analysis.analyze())


def test_pwl016_negative_without_run_context():
    _null_sink()
    # unit-built graph, pw.run never described: rule stays quiet
    assert "PWL016" not in _rules(pw.analysis.analyze())


# ---------------------------------------------------------------- PWL023


def test_pwl023_multi_tenant_without_prefix_cache(monkeypatch):
    _null_sink()
    _describe_run(
        monkeypatch,
        monitoring_level="in_out",
        decode="pages=64,page=16",
        tenancy="qps=50,inflight=8",
    )
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL023"]
    assert len(hits) == 1 and hits[0].severity is Severity.WARNING
    assert "multi-tenant" in hits[0].message
    assert "prefix caching off" in hits[0].message
    assert hits[0].detail["tenancy"] is True
    assert hits[0].detail["prefix_cache"] is False


def test_pwl023_rag_traffic_without_prefix_cache(monkeypatch):
    _knn_sink(reserved=20_000)
    _describe_run(monkeypatch, monitoring_level="in_out", decode="pages=64,page=16")
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL023"]
    assert len(hits) == 1
    assert "RAG" in hits[0].message
    assert hits[0].detail["rag_indexes"][0]["device_backed"]


def test_pwl023_prefix_cache_on_silences(monkeypatch):
    _knn_sink(reserved=20_000)
    _describe_run(
        monkeypatch,
        monitoring_level="in_out",
        decode="pages=64,page=16,cache=1",
        tenancy="qps=50",
    )
    assert "PWL023" not in _rules(pw.analysis.analyze())


def test_pwl023_negative_single_tenant_no_rag(monkeypatch):
    # decode alone — no tenancy, no device-backed index: nothing shares
    # a prefix across requests, nothing to warn about
    _null_sink()
    _describe_run(monkeypatch, monitoring_level="in_out", decode="pages=64,page=16")
    assert "PWL023" not in _rules(pw.analysis.analyze())


def test_pwl023_negative_no_decode_plane(monkeypatch):
    _knn_sink(reserved=20_000)
    _describe_run(monkeypatch, monitoring_level="in_out", tenancy="qps=50")
    assert "PWL023" not in _rules(pw.analysis.analyze())


def _spec_draft_budget(monkeypatch):
    """96 MiB budget: the 256x16 KV pool (~32 MiB at nominal geometry)
    plus the nominal target weights (~44 MiB) fit alone; a 32 MiB draft
    checkpoint is the straw."""
    monkeypatch.setenv("PATHWAY_HBM_BYTES", str(96 * 1024 * 1024))


def test_pwl023_draft_weights_overflow_hbm(monkeypatch):
    _spec_draft_budget(monkeypatch)
    _null_sink()
    _describe_run(
        monkeypatch,
        monitoring_level="in_out",
        decode="pages=256,page=16,cache=1,spec=4,draft_weights=32M",
    )
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL023"]
    assert len(hits) == 1 and hits[0].severity is Severity.WARNING
    assert "straw" in hits[0].message
    detail = hits[0].detail
    assert detail["draft_weights_bytes"] == 32 * 1024 * 1024
    base = detail["kv_pool_bytes"] + detail["target_weights_bytes"]
    assert base <= detail["hbm_budget_bytes"]
    assert detail["total_bytes"] > detail["hbm_budget_bytes"]


def test_pwl023_both_arms_fire_together(monkeypatch):
    _spec_draft_budget(monkeypatch)
    _null_sink()
    _describe_run(
        monkeypatch,
        monitoring_level="in_out",
        decode="pages=256,page=16,spec=4,draft_weights=32M",
        tenancy="qps=50",
    )
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL023"]
    assert len(hits) == 2


def test_pwl023_negative_draft_fits_budget(monkeypatch):
    monkeypatch.setenv("PATHWAY_HBM_BYTES", str(256 * 1024 * 1024))
    _null_sink()
    _describe_run(
        monkeypatch,
        monitoring_level="in_out",
        decode="pages=256,page=16,cache=1,spec=4,draft_weights=32M",
    )
    assert "PWL023" not in _rules(pw.analysis.analyze())


def test_pwl023_negative_self_draft_books_no_weights(monkeypatch):
    # the built-in layer-skip self-draft (spec= without draft_weights=)
    # adds zero weight bytes: never the straw
    _spec_draft_budget(monkeypatch)
    _null_sink()
    _describe_run(
        monkeypatch,
        monitoring_level="in_out",
        decode="pages=256,page=16,cache=1,spec=4,draft=1",
    )
    assert "PWL023" not in _rules(pw.analysis.analyze())


def test_pwl023_negative_base_already_over_budget(monkeypatch):
    # the plane overflows even without the draft: PWL015/decode budget
    # territory, the draft is not the straw
    monkeypatch.setenv("PATHWAY_HBM_BYTES", str(48 * 1024 * 1024))
    _null_sink()
    _describe_run(
        monkeypatch,
        monitoring_level="in_out",
        decode="pages=256,page=16,cache=1,spec=4,draft_weights=32M",
    )
    assert "PWL023" not in _rules(pw.analysis.analyze())


def test_pwl023_negative_without_run_context():
    _knn_sink(reserved=20_000)
    assert "PWL023" not in _rules(pw.analysis.analyze())


# ---------------------------------------------------------------- PWL024


def _stream_sink(autocommit_ms: int = 1000):
    docs = pw.demo.range_stream(
        nb_rows=5, input_rate=1000.0, autocommit_duration_ms=autocommit_ms
    )
    pw.io.null.write(docs.select(doubled=pw.this.value * 2))


def test_pwl024_watchdog_freshness_keys_with_plane_off(monkeypatch):
    monkeypatch.delenv("PATHWAY_FRESHNESS", raising=False)
    _stream_sink()
    _describe_run(
        monkeypatch,
        monitoring_level="in_out",
        watchdog="interval=1,freshness_warn=0.8,freshness_critical=1.0",
        chip_ledger=True,
    )
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL024"]
    assert len(hits) == 1 and hits[0].severity is Severity.WARNING
    assert "never" in hits[0].message
    assert hits[0].detail["watchdog_freshness"] is True
    assert hits[0].detail["freshness"] is None


def test_pwl024_slo_tighter_than_autocommit_floor(monkeypatch):
    _stream_sink(autocommit_ms=500)
    _describe_run(monkeypatch, monitoring_level="in_out", freshness="slo=100ms")
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL024"]
    assert len(hits) == 1 and hits[0].severity is Severity.WARNING
    assert "floor" in hits[0].message
    assert hits[0].detail["slo_ms"] == 100.0
    assert hits[0].detail["floor_ms"] == 500.0
    assert hits[0].detail["autocommit_duration_ms"] == 500.0


def test_pwl024_batcher_linger_folds_into_floor(monkeypatch):
    # the rest connector commits every 50ms; alone that clears a 60ms
    # SLO, but the serving batcher's 30ms linger pushes the floor to 80
    _rest_endpoint(serving=pw.ServingConfig(batch_window_ms=30.0))
    _describe_run(monkeypatch, monitoring_level="in_out", freshness="slo=60ms")
    hits = [d for d in pw.analysis.analyze() if d.rule == "PWL024"]
    assert len(hits) == 1
    assert hits[0].detail["autocommit_duration_ms"] == 50.0
    assert hits[0].detail["batch_window_ms"] == 30.0
    assert hits[0].detail["floor_ms"] == 80.0


def test_pwl024_freshness_env_silences(monkeypatch):
    # the fix the diagnostic suggests: PATHWAY_FRESHNESS turns the
    # plane on, so the watchdog's freshness rule has a signal
    monkeypatch.setenv("PATHWAY_FRESHNESS", "1")
    _stream_sink()
    _describe_run(
        monkeypatch,
        monitoring_level="in_out",
        watchdog="interval=1,freshness_critical=1.0",
        chip_ledger=True,
    )
    assert "PWL024" not in _rules(pw.analysis.analyze())


def test_pwl024_negative_slo_clears_floor(monkeypatch):
    _stream_sink(autocommit_ms=500)
    _describe_run(monkeypatch, monitoring_level="in_out", freshness="slo=2000ms")
    assert "PWL024" not in _rules(pw.analysis.analyze())


def test_pwl024_negative_plane_on_without_slo(monkeypatch):
    # plane on, no slo budget: nothing to grade against the floor, and
    # arm 1 is satisfied — the watchdog's freshness rule has a signal
    _stream_sink(autocommit_ms=500)
    _describe_run(
        monkeypatch,
        monitoring_level="in_out",
        watchdog="interval=1,freshness_critical=1.0",
        chip_ledger=True,
        freshness=True,
    )
    assert "PWL024" not in _rules(pw.analysis.analyze())


def test_pwl024_negative_bounded_run(monkeypatch):
    # no streaming connector: freshness is a no-op by design, the
    # watchdog keys are harmless dead config on a bounded run
    monkeypatch.delenv("PATHWAY_FRESHNESS", raising=False)
    _null_sink()
    _describe_run(
        monkeypatch,
        monitoring_level="in_out",
        watchdog="interval=1,freshness_critical=1.0",
        chip_ledger=True,
    )
    assert "PWL024" not in _rules(pw.analysis.analyze())


def test_pwl024_negative_without_run_context():
    _stream_sink()
    assert "PWL024" not in _rules(pw.analysis.analyze())
