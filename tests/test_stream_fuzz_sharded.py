"""Sharded-engine stream fuzz (round 5): the full brute-force oracle
battery from test_stream_fuzz_r4 re-run on a 4-shard engine, plus a
sink-event consolidation check — worker-invariance under retraction
churn for every core operator, not just groupby (VERDICT r4 Weak #8).
"""

from __future__ import annotations

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner

from .test_stream_fuzz_r4 import (
    FuzzSchema,
    _final_state,
    _random_stream,
    _scripted_table,
)

WORKERS = 4


def _run_sharded(res):
    runner = GraphRunner(n_workers=WORKERS)
    cap, _ = runner.capture(res)
    runner.run()
    pw.clear_graph()
    return cap


@pytest.mark.parametrize("seed", [0, 3, 7, 31])
def test_sharded_groupby_matches_bruteforce(seed):
    rng = np.random.default_rng(seed)
    rows = _random_stream(rng, n_keys=24, n_events=160)
    t = _scripted_table(rows, FuzzSchema)
    res = t.groupby(pw.this.g).reduce(
        g=pw.this.g,
        s=pw.reducers.sum(pw.this.v),
        n=pw.reducers.count(),
        mn=pw.reducers.min(pw.this.v),
    )
    cap = _run_sharded(res)
    live = _final_state(rows)
    want: dict[str, list[int]] = {}
    for g, v in live.values():
        want.setdefault(g, []).append(v)
    expect = {g: (sum(vs), len(vs), min(vs)) for g, vs in want.items()}
    got = {row[0]: (row[1], row[2], row[3]) for row in cap.state.values()}
    assert got == expect, f"seed {seed}"


@pytest.mark.parametrize("seed", [11, 13])
def test_sharded_filter_select_matches_bruteforce(seed):
    rng = np.random.default_rng(seed)
    rows = _random_stream(rng)
    t = _scripted_table(rows, FuzzSchema)
    res = t.filter(pw.this.v % 2 == 0).select(g=pw.this.g, h=pw.this.v - 3)
    cap = _run_sharded(res)
    live = _final_state(rows)
    expect = sorted((g, v - 3) for g, v in live.values() if v % 2 == 0)
    assert sorted(cap.state.values()) == expect, f"seed {seed}"


@pytest.mark.parametrize("seed", [21, 25])
def test_sharded_join_matches_bruteforce(seed):
    rng = np.random.default_rng(seed)
    left_rows = _random_stream(rng, n_keys=14, n_events=110)
    # right side churns too: re-priced groups mid-stream
    right_rows = []
    right_live: dict[str, int] = {}
    for i in range(12):
        g = f"g{int(rng.integers(0, 4))}"
        t = 2 * (1 + i)
        if g in right_live:
            right_rows.append((5000 + hash(g) % 100, (g, right_live.pop(g)), t, -1))
        w = int(rng.integers(1, 100))
        right_live[g] = w
        right_rows.append((5000 + hash(g) % 100, (g, w), t, 1))

    class RightSchema(pw.Schema):
        g: str
        w: int

    lt = _scripted_table(left_rows, FuzzSchema)
    rt = _scripted_table(right_rows, RightSchema)
    res = lt.join(rt, pw.left.g == pw.right.g).select(
        g=pw.left.g, prod=pw.left.v * pw.right.w
    )
    cap = _run_sharded(res)
    live = _final_state(left_rows)
    expect = sorted(
        (g, v * right_live[g]) for g, v in live.values() if g in right_live
    )
    assert sorted(cap.state.values()) == expect, f"seed {seed}"


@pytest.mark.parametrize("seed", [41, 43])
def test_sharded_groupby_then_join_chain(seed):
    """Two-stage pipeline: per-group aggregates joined back against a
    static dimension — exercises cross-shard mailbox routing twice."""
    rng = np.random.default_rng(seed)
    rows = _random_stream(rng, n_keys=18, n_events=140)
    dims = [(9000 + i, (f"g{i}", 10 ** i), 2, 1) for i in range(4)]

    class DimSchema(pw.Schema):
        g: str
        scale: int

    t = _scripted_table(rows, FuzzSchema)
    d = _scripted_table(dims, DimSchema)
    agg = t.groupby(pw.this.g).reduce(g=pw.this.g, s=pw.reducers.sum(pw.this.v))
    res = agg.join(d, pw.left.g == pw.right.g).select(
        g=pw.left.g, scaled=pw.left.s * pw.right.scale
    )
    cap = _run_sharded(res)
    live = _final_state(rows)
    sums: dict[str, int] = {}
    for g, v in live.values():
        sums[g] = sums.get(g, 0) + v
    expect = sorted(
        (g, s * 10 ** int(g[1])) for g, s in sums.items() if g in {f"g{i}" for i in range(4)}
    )
    assert sorted(cap.state.values()) == expect, f"seed {seed}"


def test_sharded_sink_events_consolidate_to_final_state():
    """The delivered event stream (insert/retract pairs across epochs)
    must net out to exactly the final captured state on the sharded
    engine — partial sweep states leaking to sinks would break this."""
    rng = np.random.default_rng(77)
    rows = _random_stream(rng, n_keys=16, n_events=130)
    t = _scripted_table(rows, FuzzSchema)
    res = t.groupby(pw.this.g).reduce(
        g=pw.this.g, s=pw.reducers.sum(pw.this.v), n=pw.reducers.count()
    )
    events: list = []
    pw.io.subscribe(
        res,
        on_change=lambda key, row, time, is_addition: events.append(
            (key, tuple(sorted(row.items())), 1 if is_addition else -1)
        ),
    )
    import os

    os.environ["PATHWAY_THREADS"] = str(WORKERS)
    try:
        pw.run(monitoring_level="none")
    finally:
        del os.environ["PATHWAY_THREADS"]
    pw.clear_graph()

    net: dict = {}
    for key, row, diff in events:
        net[(key, row)] = net.get((key, row), 0) + diff
        assert net[(key, row)] in (0, 1), "overlapping insert without retract"
    final = {k: row for (k, row), d in net.items() if d == 1}

    live = _final_state(rows)
    want: dict[str, list[int]] = {}
    for g, v in live.values():
        want.setdefault(g, []).append(v)
    expect = {
        g: tuple(sorted({"g": g, "s": sum(vs), "n": len(vs)}.items()))
        for g, vs in want.items()
    }
    assert sorted(final.values()) == sorted(expect.values())
