"""CLI tests: pathway spawn / spawn-from-env / record+replay.

Mirrors the reference's CLI coverage
(/root/reference/python/pathway/tests/cli/): worker-topology env wiring
and stream record/replay via env vars.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pathway_tpu as pw

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(args, cwd, extra_env=None):
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "pathway_tpu"] + args,
        cwd=cwd,
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )


def test_spawn_runs_n_processes_with_topology_env(tmp_path):
    prog = tmp_path / "prog.py"
    prog.write_text(
        "import os, json\n"
        "pid = os.environ['PATHWAY_PROCESS_ID']\n"
        "info = {k: os.environ.get(k) for k in\n"
        "        ('PATHWAY_THREADS', 'PATHWAY_PROCESSES', 'PATHWAY_FIRST_PORT')}\n"
        "open(f'out_{pid}.json', 'w').write(json.dumps(info))\n"
    )
    res = _run_cli(
        ["spawn", "--threads", "2", "--processes", "2", "--first-port", "11500", str(prog)],
        cwd=tmp_path,
    )
    assert res.returncode == 0, res.stderr
    for pid in (0, 1):
        info = json.loads((tmp_path / f"out_{pid}.json").read_text())
        assert info == {
            "PATHWAY_THREADS": "2",
            "PATHWAY_PROCESSES": "2",
            "PATHWAY_FIRST_PORT": "11500",
        }


def test_spawn_propagates_failure(tmp_path):
    prog = tmp_path / "prog.py"
    prog.write_text("import sys; sys.exit(3)\n")
    res = _run_cli(["spawn", str(prog)], cwd=tmp_path)
    assert res.returncode == 3


def test_spawn_from_env(tmp_path):
    prog = tmp_path / "prog.py"
    prog.write_text("open('ran.txt', 'w').write('yes')\n")
    res = _run_cli(
        ["spawn-from-env"],
        cwd=tmp_path,
        extra_env={"PATHWAY_SPAWN_ARGS": f"--processes=1 {prog}"},
    )
    assert res.returncode == 0, res.stderr
    assert (tmp_path / "ran.txt").read_text() == "yes"


class _WordSubject(pw.io.python.ConnectorSubject):
    def __init__(self, words):
        super().__init__()
        self.words = words

    def run(self):
        start = int(self.offsets.get("next", 0))
        for i in range(start, len(self.words)):
            self.next_with_offset("next", i + 1, word=self.words[i])
        self.commit()


class _WordSchema(pw.Schema):
    word: str


def _wordcount_events(words, storage, mode):
    """Run the wordcount pipeline with PATHWAY_REPLAY_* env set."""
    os.environ["PATHWAY_REPLAY_STORAGE"] = storage
    os.environ["PATHWAY_REPLAY_MODE"] = mode
    try:
        t = pw.io.python.read(
            _WordSubject(words), schema=_WordSchema, autocommit_duration_ms=None
        )
        counts = t.groupby(pw.this.word).reduce(
            word=pw.this.word, count=pw.reducers.count()
        )
        events: list = []
        pw.io.subscribe(
            counts,
            on_change=lambda key, row, time, is_addition: events.append(
                (row["word"], row["count"], is_addition)
            ),
        )
        pw.run()
        pw.clear_graph()
        return events
    finally:
        del os.environ["PATHWAY_REPLAY_STORAGE"]
        del os.environ["PATHWAY_REPLAY_MODE"]


def test_record_then_speedrun_replay(tmp_path):
    """--record captures the stream (auto persistent ids); speedrun
    replay recomputes identical sink output without running readers."""
    storage = str(tmp_path / "rec")
    recorded = _wordcount_events(["a", "b", "a"], storage, "record")
    assert ("a", 2, True) in recorded and ("b", 1, True) in recorded

    # speedrun: the subject would emit NOTHING new (offsets persisted),
    # and readers never even start; output comes purely from the log
    replayed = _wordcount_events(["a", "b", "a"], storage, "speedrun")
    assert sorted(replayed) == sorted(recorded)


def test_speedrun_replay_multi_worker(tmp_path):
    """A recorded run replays deterministically across N workers: the
    sharded engine's replay equals both the recording and a
    single-worker replay (reference PersistenceMode::SpeedrunReplay
    works under any worker config, src/connectors/mod.rs:108)."""
    storage = str(tmp_path / "rec")
    words = ["a", "b", "a", "c", "b", "a", "d", "c"]
    recorded = _wordcount_events(words, storage, "record")
    assert ("a", 3, True) in recorded

    replay_1w = _wordcount_events(words, storage, "speedrun")
    os.environ["PATHWAY_THREADS"] = "4"
    try:
        replay_4w = _wordcount_events(words, storage, "speedrun")
        # replay again: a sharded replay is itself reproducible
        replay_4w_again = _wordcount_events(words, storage, "speedrun")
    finally:
        del os.environ["PATHWAY_THREADS"]
    assert sorted(replay_4w) == sorted(recorded)
    assert sorted(replay_4w) == sorted(replay_1w)
    assert sorted(replay_4w_again) == sorted(replay_4w)


def test_speedrun_replay_multi_worker_sees_every_epoch(tmp_path):
    """Sharded replay must re-deliver intermediate epochs (retract/insert
    pairs), not just the final state — it is the debugging tool for
    multi-worker nondeterminism claims."""
    storage = str(tmp_path / "rec")

    class _EpochSubject(pw.io.python.ConnectorSubject):
        def run(self):
            import time as _time

            start = int(self.offsets.get("next", 0))
            for i in range(start, 4):
                self.next_with_offset("next", i + 1, word="w")
                self.commit()  # one epoch per row -> count 1,2,3,4
                _time.sleep(0.15)  # outlive the engine poll so commits
                # land in distinct epochs instead of coalescing

    def run_events(mode, threads=None):
        os.environ["PATHWAY_REPLAY_STORAGE"] = storage
        os.environ["PATHWAY_REPLAY_MODE"] = mode
        if threads:
            os.environ["PATHWAY_THREADS"] = str(threads)
        try:
            t = pw.io.python.read(
                _EpochSubject(), schema=_WordSchema, autocommit_duration_ms=None
            )
            counts = t.groupby(pw.this.word).reduce(
                word=pw.this.word, count=pw.reducers.count()
            )
            events: list = []
            pw.io.subscribe(
                counts,
                on_change=lambda key, row, time, is_addition: events.append(
                    (row["count"], is_addition)
                ),
            )
            pw.run()
            pw.clear_graph()
            return events
        finally:
            del os.environ["PATHWAY_REPLAY_STORAGE"]
            del os.environ["PATHWAY_REPLAY_MODE"]
            if threads:
                del os.environ["PATHWAY_THREADS"]

    recorded = run_events("record")
    replayed = run_events("speedrun", threads=4)
    assert replayed == recorded
    # the full incremental history: 1, then retract 1 / insert 2, ...
    assert (1, True) in replayed and (1, False) in replayed
    assert replayed[-1] == (4, True)
