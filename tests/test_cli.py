"""CLI tests: pathway spawn / spawn-from-env / record+replay.

Mirrors the reference's CLI coverage
(/root/reference/python/pathway/tests/cli/): worker-topology env wiring
and stream record/replay via env vars.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pathway_tpu as pw

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(args, cwd, extra_env=None):
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "pathway_tpu"] + args,
        cwd=cwd,
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )


def test_spawn_runs_n_processes_with_topology_env(tmp_path):
    prog = tmp_path / "prog.py"
    prog.write_text(
        "import os, json\n"
        "pid = os.environ['PATHWAY_PROCESS_ID']\n"
        "info = {k: os.environ.get(k) for k in\n"
        "        ('PATHWAY_THREADS', 'PATHWAY_PROCESSES', 'PATHWAY_FIRST_PORT')}\n"
        "open(f'out_{pid}.json', 'w').write(json.dumps(info))\n"
    )
    res = _run_cli(
        ["spawn", "--threads", "2", "--processes", "2", "--first-port", "11500", str(prog)],
        cwd=tmp_path,
    )
    assert res.returncode == 0, res.stderr
    for pid in (0, 1):
        info = json.loads((tmp_path / f"out_{pid}.json").read_text())
        assert info == {
            "PATHWAY_THREADS": "2",
            "PATHWAY_PROCESSES": "2",
            "PATHWAY_FIRST_PORT": "11500",
        }


def test_spawn_propagates_failure(tmp_path):
    prog = tmp_path / "prog.py"
    prog.write_text("import sys; sys.exit(3)\n")
    res = _run_cli(["spawn", str(prog)], cwd=tmp_path)
    assert res.returncode == 3


def test_spawn_from_env(tmp_path):
    prog = tmp_path / "prog.py"
    prog.write_text("open('ran.txt', 'w').write('yes')\n")
    res = _run_cli(
        ["spawn-from-env"],
        cwd=tmp_path,
        extra_env={"PATHWAY_SPAWN_ARGS": f"--processes=1 {prog}"},
    )
    assert res.returncode == 0, res.stderr
    assert (tmp_path / "ran.txt").read_text() == "yes"


class _WordSubject(pw.io.python.ConnectorSubject):
    def __init__(self, words):
        super().__init__()
        self.words = words

    def run(self):
        start = int(self.offsets.get("next", 0))
        for i in range(start, len(self.words)):
            self.next_with_offset("next", i + 1, word=self.words[i])
        self.commit()


class _WordSchema(pw.Schema):
    word: str


def _wordcount_events(words, storage, mode):
    """Run the wordcount pipeline with PATHWAY_REPLAY_* env set."""
    os.environ["PATHWAY_REPLAY_STORAGE"] = storage
    os.environ["PATHWAY_REPLAY_MODE"] = mode
    try:
        t = pw.io.python.read(
            _WordSubject(words), schema=_WordSchema, autocommit_duration_ms=None
        )
        counts = t.groupby(pw.this.word).reduce(
            word=pw.this.word, count=pw.reducers.count()
        )
        events: list = []
        pw.io.subscribe(
            counts,
            on_change=lambda key, row, time, is_addition: events.append(
                (row["word"], row["count"], is_addition)
            ),
        )
        pw.run()
        pw.clear_graph()
        return events
    finally:
        del os.environ["PATHWAY_REPLAY_STORAGE"]
        del os.environ["PATHWAY_REPLAY_MODE"]


def test_record_then_speedrun_replay(tmp_path):
    """--record captures the stream (auto persistent ids); speedrun
    replay recomputes identical sink output without running readers."""
    storage = str(tmp_path / "rec")
    recorded = _wordcount_events(["a", "b", "a"], storage, "record")
    assert ("a", 2, True) in recorded and ("b", 1, True) in recorded

    # speedrun: the subject would emit NOTHING new (offsets persisted),
    # and readers never even start; output comes purely from the log
    replayed = _wordcount_events(["a", "b", "a"], storage, "speedrun")
    assert sorted(replayed) == sorted(recorded)
