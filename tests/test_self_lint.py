"""Repo self-lint: the static verifier runs over the shipped demo
pipelines (pathway_tpu/debug/demos/) and an llm-xpack RAG template, and
fails this suite on any new error-severity finding. Also exercises the
``pathway analyze`` CLI end to end, including the nonzero exit + JSON
contract the CI hook relies on."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

import pathway_tpu as pw
from pathway_tpu.debug.demos import demo_programs

from .mocks import fake_embeddings_model, make_docs_table

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "analysis")


def _analyze_cli(program: str, *flags: str) -> subprocess.CompletedProcess:
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-m", "pathway_tpu.cli", "analyze", *flags, program],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=180,
    )


@pytest.mark.parametrize(
    "demo", demo_programs(), ids=[os.path.basename(p) for p in demo_programs()]
)
def test_demo_pipelines_lint_clean(demo):
    """Every shipped demo must pass the verifier with zero findings of
    error severity — this is the repo's own lint gate."""
    proc = _analyze_cli(demo)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)


def test_unbounded_fixture_fails_with_pwl002_human():
    proc = _analyze_cli(os.path.join(FIXTURES, "unbounded_groupby.py"))
    assert proc.returncode == 1, (proc.stdout, proc.stderr)
    assert "PWL002" in proc.stdout
    assert "error" in proc.stdout
    # the diagnostic cites the fixture's own source line
    assert "unbounded_groupby.py" in proc.stdout


def test_unbounded_fixture_fails_with_pwl002_json():
    proc = _analyze_cli(os.path.join(FIXTURES, "unbounded_groupby.py"), "--json")
    assert proc.returncode == 1, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    assert payload["summary"]["error"] >= 1
    (diag,) = [d for d in payload["diagnostics"] if d["rule"] == "PWL002"]
    assert diag["severity"] == "error"
    assert diag["location"]["file"].endswith("unbounded_groupby.py")
    assert diag["location"]["line"] > 0


def test_windowed_fixture_passes_clean():
    proc = _analyze_cli(os.path.join(FIXTURES, "windowed_groupby.py"))
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "no findings" in proc.stdout


def test_broken_program_exits_3():
    proc = _analyze_cli(os.path.join(FIXTURES, "does_not_exist.py"))
    assert proc.returncode == 3


def test_rag_template_lints_clean_in_process():
    """The llm-xpack vector store template must stay free of
    error-severity findings (warnings/info are reported, not fatal)
    and of ALL deep-pass findings (PWL017-PWL020): the template's
    device callables are ours end to end, so any host sync, compile
    storm, placement mismatch, or exactly-once hazard there is a
    regression, not an accepted risk."""
    from pathway_tpu.xpacks.llm import VectorStoreServer

    pw.clear_graph()
    try:
        docs = make_docs_table(
            [("pathway is a streaming dataflow framework", "/data/pathway.txt")]
        )
        VectorStoreServer(docs, embedder=fake_embeddings_model)
        diags = pw.analysis.analyze(deep=True)
        errors = [d for d in diags if d.severity is pw.analysis.Severity.ERROR]
        assert not errors, [d.render() for d in errors]
        deep = [d for d in diags if d.rule in pw.analysis.DEEP_RULE_IDS]
        assert not deep, [d.render() for d in deep]
    finally:
        pw.clear_graph()


def test_recovery_without_monitoring_warns_pwl007():
    """recovery= with monitoring fully off: a warning (exit 0), nonzero
    only under --fail-on=warn — the CLI sees the run configuration
    because pw.run records it before the analyze-only return."""
    fixture = os.path.join(FIXTURES, "recovery_no_monitoring.py")
    proc = _analyze_cli(fixture)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL007" in proc.stdout
    assert "warning" in proc.stdout

    proc = _analyze_cli(fixture, "--fail-on=warn")
    assert proc.returncode == 1, (proc.stdout, proc.stderr)


def test_pwl007_json_carries_run_context():
    proc = _analyze_cli(
        os.path.join(FIXTURES, "recovery_no_monitoring.py"), "--json"
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    (diag,) = [d for d in payload["diagnostics"] if d["rule"] == "PWL007"]
    assert diag["severity"] == "warning"
    assert diag["detail"]["run_context"]["recovery"] == "True"


def test_unprotected_serving_endpoint_warns_pwl008():
    """rest_connector without serving= in a recovery/pipelined run: a
    warning (exit 0), nonzero only under --fail-on=warn. The CLI
    sees the endpoint because rest_connector records it on the parse
    graph (serving_endpoints) at build time."""
    fixture = os.path.join(FIXTURES, "serving_unprotected.py")
    proc = _analyze_cli(fixture)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL008" in proc.stdout
    assert "warning" in proc.stdout

    proc = _analyze_cli(fixture, "--fail-on=warn")
    assert proc.returncode == 1, (proc.stdout, proc.stderr)


def test_pwl008_json_names_route_and_pressure():
    proc = _analyze_cli(
        os.path.join(FIXTURES, "serving_unprotected.py"), "--json"
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    (diag,) = [d for d in payload["diagnostics"] if d["rule"] == "PWL008"]
    assert diag["severity"] == "warning"
    assert diag["detail"]["endpoints"][0]["route"] == "/"
    assert diag["detail"]["recovery"] is True
    assert diag["detail"]["pipeline_depth"] == 2

def test_cluster_without_fault_domain_warns_pwl009():
    """A 2-process run with recovery= off and cluster_lease_ms=0: two
    PWL009 warnings (exit 0), nonzero only under --fail-on=warn.
    The fixture sets PATHWAY_PROCESSES itself, so the CLI sees the
    cluster shape through the recorded run configuration."""
    fixture = os.path.join(FIXTURES, "cluster_no_recovery.py")
    proc = _analyze_cli(fixture)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert proc.stdout.count("PWL009") == 2
    assert "warning" in proc.stdout

    proc = _analyze_cli(fixture, "--fail-on=warn")
    assert proc.returncode == 1, (proc.stdout, proc.stderr)


def test_pwl009_json_carries_world_and_lease():
    proc = _analyze_cli(
        os.path.join(FIXTURES, "cluster_no_recovery.py"), "--json"
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    diags = [d for d in payload["diagnostics"] if d["rule"] == "PWL009"]
    assert len(diags) == 2
    assert all(d["severity"] == "warning" for d in diags)
    assert {d["detail"]["world"] for d in diags} == {2}
    (lease_diag,) = [
        d for d in diags if "cluster_lease_ms" in d["detail"]
    ]
    assert lease_diag["detail"]["cluster_lease_ms"] == 0.0


def test_index_over_hbm_warns_pwl010():
    """A device-backed index bigger than one device's HBM with no mesh:
    a warning (exit 0), nonzero only under --fail-on=warn. The CLI
    sees the index because query building records its spec on the parse
    graph (external_indexes) — no device allocation happens."""
    fixture = os.path.join(FIXTURES, "index_over_hbm.py")
    proc = _analyze_cli(fixture)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL010" in proc.stdout
    assert "warning" in proc.stdout

    proc = _analyze_cli(fixture, "--fail-on=warn")
    assert proc.returncode == 1, (proc.stdout, proc.stderr)


def test_pwl010_json_carries_footprint_and_suggestion():
    proc = _analyze_cli(os.path.join(FIXTURES, "index_over_hbm.py"), "--json")
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    (diag,) = [d for d in payload["diagnostics"] if d["rule"] == "PWL010"]
    assert diag["severity"] == "warning"
    assert diag["detail"]["index"]["reserved_space"] == 20_000_000
    assert diag["detail"]["bytes"] > diag["detail"]["hbm_budget_bytes"]
    assert diag["detail"]["suggested_mesh"] == 2


def test_host_bound_ingest_warns_pwl011():
    """Streaming connector -> device KNN with the serial epoch loop and
    no ingest stage: a warning (exit 0), nonzero only under
    --fail-on=warn."""
    fixture = os.path.join(FIXTURES, "host_bound_ingest.py")
    proc = _analyze_cli(fixture)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL011" in proc.stdout
    assert "warning" in proc.stdout

    proc = _analyze_cli(fixture, "--fail-on=warn")
    assert proc.returncode == 1, (proc.stdout, proc.stderr)


def test_pwl011_json_carries_depth_and_workers():
    proc = _analyze_cli(os.path.join(FIXTURES, "host_bound_ingest.py"), "--json")
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    (diag,) = [d for d in payload["diagnostics"] if d["rule"] == "PWL011"]
    assert diag["severity"] == "warning"
    assert diag["detail"]["pipeline_depth"] == 1
    assert diag["detail"]["ingest_workers"] == 0
    assert diag["detail"]["indexes"]


def test_pwl011_env_knob_silences_cli(monkeypatch):
    """The fix the diagnostic suggests (PATHWAY_INGEST_WORKERS) makes
    the same program lint clean — env flows through _analyze_cli."""
    monkeypatch.setenv("PATHWAY_INGEST_WORKERS", "2")
    proc = _analyze_cli(os.path.join(FIXTURES, "host_bound_ingest.py"))
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL011" not in proc.stdout


def test_index_no_cold_tier_warns_pwl012():
    """A beyond-HBM device index with no cold tier: PWL012 warns (exit
    0), nonzero only under --fail-on=warn."""
    fixture = os.path.join(FIXTURES, "index_no_cold_tier.py")
    proc = _analyze_cli(fixture)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL012" in proc.stdout
    assert "warning" in proc.stdout

    proc = _analyze_cli(fixture, "--fail-on=warn")
    assert proc.returncode == 1, (proc.stdout, proc.stderr)


def test_pwl012_json_carries_tier_split():
    proc = _analyze_cli(
        os.path.join(FIXTURES, "index_no_cold_tier.py"), "--json"
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    (diag,) = [d for d in payload["diagnostics"] if d["rule"] == "PWL012"]
    assert diag["severity"] == "warning"
    assert diag["detail"]["bytes"] > diag["detail"]["hbm_budget_bytes"]
    split = diag["detail"]["suggested_tier_split"]
    assert split["hot_rows"] > 0 and split["cold_rows"] > 0
    assert split["hot_rows"] + split["cold_rows"] == 20_000_000
    assert diag["detail"]["quantized_cold_bytes"] < diag["detail"]["bytes"]


def test_pwl012_env_knob_silences_cli(monkeypatch):
    """The fix the diagnostic suggests (PATHWAY_INDEX_TIERS) makes the
    same program lint clean — and silences PWL010 too, since the hot
    tier now bounds the resident set."""
    monkeypatch.setenv("PATHWAY_INDEX_TIERS", "auto")
    proc = _analyze_cli(os.path.join(FIXTURES, "index_no_cold_tier.py"))
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL012" not in proc.stdout
    assert "PWL010" not in proc.stdout


def test_http_llm_with_decode_warns_pwl013():
    """An HTTP LLM rerank hop in a run that configures the device
    decode plane: PWL013 warns (exit 0), nonzero only under
    --fail-on=warn."""
    fixture = os.path.join(FIXTURES, "http_llm_with_device_decode.py")
    proc = _analyze_cli(fixture)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL013" in proc.stdout
    assert "warning" in proc.stdout

    proc = _analyze_cli(fixture, "--fail-on=warn")
    assert proc.returncode == 1, (proc.stdout, proc.stderr)


def test_pwl013_json_carries_endpoints_and_decode_config():
    proc = _analyze_cli(
        os.path.join(FIXTURES, "http_llm_with_device_decode.py"), "--json"
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    (diag,) = [d for d in payload["diagnostics"] if d["rule"] == "PWL013"]
    assert diag["severity"] == "warning"
    endpoints = diag["detail"]["llm_endpoints"]
    assert endpoints and endpoints[0]["kind"] == "llm_reranker"
    assert endpoints[0]["model"] == "gpt-x"
    assert diag["detail"]["decode"]["pages"] == 128


def test_pwl013_silent_without_decode_plane(monkeypatch):
    """A pipeline that never configures the decode plane is PWL013-clean
    even with HTTP LLM stages elsewhere in the suite's fixtures — the
    rule only fires when the on-chip alternative is actually set up."""
    monkeypatch.delenv("PATHWAY_DECODE", raising=False)
    proc = _analyze_cli(os.path.join(FIXTURES, "host_bound_ingest.py"))
    assert "PWL013" not in proc.stdout


def test_slo_without_tracing_warns_pwl014(monkeypatch):
    """A deadline-budgeted serving endpoint in a run with tracing and
    the profiler both off: PWL014 warns (exit 0), nonzero only under
    --fail-on=warn."""
    monkeypatch.delenv("PATHWAY_TRACING", raising=False)
    monkeypatch.delenv("PATHWAY_PROFILE", raising=False)
    fixture = os.path.join(FIXTURES, "slo_without_tracing.py")
    proc = _analyze_cli(fixture)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL014" in proc.stdout
    assert "warning" in proc.stdout

    proc = _analyze_cli(fixture, "--fail-on=warn")
    assert proc.returncode == 1, (proc.stdout, proc.stderr)


def test_pwl014_json_carries_budget_and_intent(monkeypatch):
    monkeypatch.delenv("PATHWAY_TRACING", raising=False)
    monkeypatch.delenv("PATHWAY_PROFILE", raising=False)
    proc = _analyze_cli(
        os.path.join(FIXTURES, "slo_without_tracing.py"), "--json"
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    (diag,) = [d for d in payload["diagnostics"] if d["rule"] == "PWL014"]
    assert diag["severity"] == "warning"
    assert diag["detail"]["endpoints"][0]["deadline_ms"] == 250.0
    assert diag["detail"]["tracing"] is False
    assert diag["detail"]["profile"] is False


def test_pwl014_tracing_env_silences_cli(monkeypatch):
    """The fix the diagnostic suggests (PATHWAY_TRACING=1) makes the
    same program lint clean."""
    monkeypatch.setenv("PATHWAY_TRACING", "1")
    fixture = os.path.join(FIXTURES, "slo_without_tracing.py")
    proc = _analyze_cli(fixture)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL014" not in proc.stdout


def test_slo_without_chip_accounting_warns_pwl021(monkeypatch):
    """A deadline-budgeted endpoint plus a watchdog with the chip
    ledger off: PWL021 warns (exit 0), nonzero only under
    --fail-on=warn — and PWL014 stays quiet (the fixture traces)."""
    monkeypatch.delenv("PATHWAY_CHIP_LEDGER", raising=False)
    fixture = os.path.join(FIXTURES, "slo_without_chip_accounting.py")
    proc = _analyze_cli(fixture)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL021" in proc.stdout
    assert "PWL014" not in proc.stdout
    assert "warning" in proc.stdout

    proc = _analyze_cli(fixture, "--fail-on=warn")
    assert proc.returncode == 1, (proc.stdout, proc.stderr)


def test_pwl021_json_carries_contract_and_intent(monkeypatch):
    monkeypatch.delenv("PATHWAY_CHIP_LEDGER", raising=False)
    proc = _analyze_cli(
        os.path.join(FIXTURES, "slo_without_chip_accounting.py"), "--json"
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    (diag,) = [d for d in payload["diagnostics"] if d["rule"] == "PWL021"]
    assert diag["severity"] == "warning"
    assert diag["detail"]["endpoints"][0]["deadline_ms"] == 250.0
    assert diag["detail"]["watchdog"] is True
    assert diag["detail"]["chip_ledger"] is False


def test_pwl021_chip_ledger_env_silences_cli(monkeypatch):
    """The fix the diagnostic suggests (PATHWAY_CHIP_LEDGER=1) makes
    the same program lint clean."""
    monkeypatch.setenv("PATHWAY_CHIP_LEDGER", "1")
    fixture = os.path.join(FIXTURES, "slo_without_chip_accounting.py")
    proc = _analyze_cli(fixture)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL021" not in proc.stdout


def test_elastic_no_recovery_warns_pwl022():
    """Elastic watermarks armed with no persistence backend: PWL022
    warns (exit 0), nonzero only under --fail-on=warn."""
    fixture = os.path.join(FIXTURES, "elastic_no_recovery.py")
    proc = _analyze_cli(fixture)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL022" in proc.stdout
    assert "warning" in proc.stdout

    proc = _analyze_cli(fixture, "--fail-on=warn")
    assert proc.returncode == 1, (proc.stdout, proc.stderr)


def test_pwl022_json_carries_elastic_intent():
    proc = _analyze_cli(
        os.path.join(FIXTURES, "elastic_no_recovery.py"), "--json"
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    (diag,) = [d for d in payload["diagnostics"] if d["rule"] == "PWL022"]
    assert diag["severity"] == "warning"
    assert diag["detail"]["elastic"]["auto"] is True
    assert diag["detail"]["elastic"]["hbm_frac"] == 0.85
    assert diag["detail"]["persistence"] is False


def test_decode_no_prefix_cache_warns_pwl023():
    """A RAG pipeline (device-backed index) whose run configures the
    decode plane with prefix caching off: PWL023 warns (exit 0),
    nonzero only under --fail-on=warn."""
    fixture = os.path.join(FIXTURES, "decode_no_prefix_cache.py")
    proc = _analyze_cli(fixture)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL023" in proc.stdout
    assert "warning" in proc.stdout

    proc = _analyze_cli(fixture, "--fail-on=warn")
    assert proc.returncode == 1, (proc.stdout, proc.stderr)


def test_pwl023_json_carries_traffic_and_cache_intent():
    proc = _analyze_cli(
        os.path.join(FIXTURES, "decode_no_prefix_cache.py"), "--json"
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    (diag,) = [d for d in payload["diagnostics"] if d["rule"] == "PWL023"]
    assert diag["severity"] == "warning"
    assert diag["detail"]["prefix_cache"] is False
    assert diag["detail"]["rag_indexes"][0]["device_backed"] is True
    assert diag["detail"]["decode"]["pages"] == 128


def test_pwl023_prefix_cache_on_silences_cli(monkeypatch):
    """The fix the diagnostic suggests (decode cache=1) makes the same
    RAG+decode shape lint clean — combined_over_hbm.py is that program
    with prefix caching on (and a budget big enough for both planes)."""
    monkeypatch.setenv("PATHWAY_HBM_BYTES", str(256 * 1024 * 1024))
    fixture = os.path.join(FIXTURES, "combined_over_hbm.py")
    proc = _analyze_cli(fixture)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL023" not in proc.stdout


def test_combined_over_hbm_warns_pwl015(monkeypatch):
    """An index plane and a decode KV pool that each fit the HBM budget
    alone but jointly oversubscribe it: PWL015 warns (exit 0), nonzero
    only under --fail-on=warn — and neither single-plane rule
    (PWL010/PWL012) fires."""
    monkeypatch.setenv("PATHWAY_HBM_BYTES", str(48 * 1024 * 1024))
    fixture = os.path.join(FIXTURES, "combined_over_hbm.py")
    proc = _analyze_cli(fixture)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL015" in proc.stdout
    assert "PWL010" not in proc.stdout
    assert "PWL012" not in proc.stdout
    assert "warning" in proc.stdout

    proc = _analyze_cli(fixture, "--fail-on=warn")
    assert proc.returncode == 1, (proc.stdout, proc.stderr)


def test_pwl015_json_carries_footprint(monkeypatch):
    monkeypatch.setenv("PATHWAY_HBM_BYTES", str(48 * 1024 * 1024))
    proc = _analyze_cli(
        os.path.join(FIXTURES, "combined_over_hbm.py"), "--json"
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    (diag,) = [d for d in payload["diagnostics"] if d["rule"] == "PWL015"]
    assert diag["severity"] == "warning"
    fp = diag["detail"]["footprint"]
    budget = diag["detail"]["hbm_budget_bytes"]
    assert budget == 48 * 1024 * 1024
    # the rule's defining window: each plane fits alone, not together
    assert fp["index"] <= budget
    assert fp["decode_kv"] <= budget
    assert fp["total"] > budget
    assert fp["total"] == fp["index"] + fp["decode_kv"]
    assert diag["detail"]["decode"]["pages"] == 256


def test_pwl015_silent_when_budget_fits_both(monkeypatch):
    """With enough HBM for both planes the same program lints clean."""
    monkeypatch.setenv("PATHWAY_HBM_BYTES", str(256 * 1024 * 1024))
    proc = _analyze_cli(os.path.join(FIXTURES, "combined_over_hbm.py"))
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL015" not in proc.stdout


def test_freshness_unmeasurable_warns_pwl024(monkeypatch):
    """A streaming run arming the watchdog's freshness thresholds with
    the freshness plane off: PWL024 warns (exit 0), nonzero only under
    --fail-on=warn — and PWL021 stays quiet (the fixture keeps the
    chip ledger on)."""
    monkeypatch.delenv("PATHWAY_FRESHNESS", raising=False)
    fixture = os.path.join(FIXTURES, "freshness_unmeasurable.py")
    proc = _analyze_cli(fixture)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL024" in proc.stdout
    assert "PWL021" not in proc.stdout
    assert "warning" in proc.stdout

    proc = _analyze_cli(fixture, "--fail-on=warn")
    assert proc.returncode == 1, (proc.stdout, proc.stderr)


def test_pwl024_json_carries_intent(monkeypatch):
    monkeypatch.delenv("PATHWAY_FRESHNESS", raising=False)
    proc = _analyze_cli(
        os.path.join(FIXTURES, "freshness_unmeasurable.py"), "--json"
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    (diag,) = [d for d in payload["diagnostics"] if d["rule"] == "PWL024"]
    assert diag["severity"] == "warning"
    assert diag["detail"]["watchdog_freshness"] is True
    assert diag["detail"]["freshness"] is None


def test_pwl024_freshness_env_silences_cli(monkeypatch):
    """The fix the diagnostic suggests (PATHWAY_FRESHNESS=1) makes the
    same program lint clean."""
    monkeypatch.setenv("PATHWAY_FRESHNESS", "1")
    proc = _analyze_cli(os.path.join(FIXTURES, "freshness_unmeasurable.py"))
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL024" not in proc.stdout


# ---------------------------------------------------------------------------
# pathway doctor (internals/ledger.py HealthWatchdog + cli.py doctor)
# ---------------------------------------------------------------------------

DOCTOR_FIXTURES = os.path.join(REPO, "tests", "fixtures", "doctor")


def _doctor_cli(program: str, *flags: str) -> subprocess.CompletedProcess:
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-m", "pathway_tpu.cli", "doctor", *flags, program],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=180,
    )


@pytest.mark.parametrize(
    "demo", demo_programs(), ids=[os.path.basename(p) for p in demo_programs()]
)
def test_demo_pipelines_doctor_green(demo):
    """Every shipped demo must come back green from the health
    watchdog — the doctor counterpart of the lint gate above."""
    proc = _doctor_cli(demo)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "overall: GREEN" in proc.stdout


def test_doctor_green_on_idle_pipeline():
    proc = _doctor_cli(os.path.join(DOCTOR_FIXTURES, "idle.py"))
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "overall: GREEN" in proc.stdout


def test_doctor_red_on_oom_ramp_with_dump():
    """The watchdog forecasts OOM under a synthetic ingest ramp: doctor
    exits 2 (red) and points at the one-shot flight-recorder dump."""
    proc = _doctor_cli(
        os.path.join(DOCTOR_FIXTURES, "oom_ramp.py"),
        "--watchdog",
        "interval=0.05,breach_for=1,oom_critical_s=3600",
    )
    assert proc.returncode == 2, (proc.stdout, proc.stderr)
    assert "overall: RED" in proc.stdout
    assert "time_to_oom_s" in proc.stdout
    assert "flight recorder dump:" in proc.stdout


def test_doctor_json_contract():
    """--json emits the machine-readable verdict: status, per-plane
    statuses with evidence, per-rule entries, and the ledger snapshot
    when accounts were live."""
    proc = _doctor_cli(
        os.path.join(DOCTOR_FIXTURES, "oom_ramp.py"),
        "--json",
        "--watchdog",
        "interval=0.05,breach_for=1,oom_critical_s=3600",
    )
    assert proc.returncode == 2, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    assert payload["status"] == "red"
    assert payload["planes"]["hbm"]["status"] == "red"
    assert payload["planes"]["hbm"]["evidence"]
    (oom_rule,) = [r for r in payload["rules"] if r["name"] == "hbm_headroom"]
    assert oom_rule["level"] == "critical"
    assert payload["breaches"] >= 1
    assert payload["dump_path"]
    assert payload["hbm"]["accounts"]["index.hot"]["bytes"] > 0


def test_doctor_broken_program_exits_3():
    proc = _doctor_cli(os.path.join(DOCTOR_FIXTURES, "does_not_exist.py"))
    assert proc.returncode == 3


# ---------------------------------------------------------------------------
# PWL016 — tenancy configured without per-tenant quotas
# ---------------------------------------------------------------------------


def test_tenancy_no_quotas_warns_pwl016(monkeypatch):
    """The tenancy plane on with nothing bounding any tenant: PWL016
    warns (exit 0), nonzero only under --fail-on=warn."""
    monkeypatch.delenv("PATHWAY_TENANCY", raising=False)
    fixture = os.path.join(FIXTURES, "tenancy_no_quotas.py")
    proc = _analyze_cli(fixture)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL016" in proc.stdout
    assert "warning" in proc.stdout

    proc = _analyze_cli(fixture, "--fail-on=warn")
    assert proc.returncode == 1, (proc.stdout, proc.stderr)


def test_pwl016_json_carries_tenancy_config(monkeypatch):
    monkeypatch.delenv("PATHWAY_TENANCY", raising=False)
    proc = _analyze_cli(
        os.path.join(FIXTURES, "tenancy_no_quotas.py"), "--json"
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    (diag,) = [d for d in payload["diagnostics"] if d["rule"] == "PWL016"]
    assert diag["severity"] == "warning"
    assert diag["detail"]["tenancy"]["quotas"] == {}
    assert diag["detail"]["tenancy"]["default"] is None


def test_pwl016_explicit_arg_wins_over_env_cli(monkeypatch):
    """The fixture passes tenancy=True explicitly, so a quota-carrying
    PATHWAY_TENANCY env spec does NOT silence it — explicit args win
    over env, same precedence as decode=/index_tiers=. The warning
    still fires."""
    monkeypatch.setenv("PATHWAY_TENANCY", "qps=50,inflight=8")
    fixture = os.path.join(FIXTURES, "tenancy_no_quotas.py")
    proc = _analyze_cli(fixture)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PWL016" in proc.stdout
