"""Behavioral coverage for public API names no other test touches —
every name in ``pw.__all__`` should have at least one semantic check
(not just an import), mirroring the reference's test_common.py breadth."""

from __future__ import annotations

import pytest

import pathway_tpu as pw

from .utils import T, run_table


def test_apply_async_and_fully_async():
    t = T(
        """
          | a
        1 | 2
        2 | 3
        """
    )

    async def double(x):
        return x * 2

    r = t.select(b=pw.apply_async(double, pw.this.a))
    state = run_table(r)
    assert sorted(v[0] for v in state.values()) == [4, 6]
    pw.clear_graph()

    t2 = T(
        """
          | a
        1 | 5
        """
    )
    r2 = t2.select(b=pw.apply_fully_async(double, pw.this.a))
    # fully-async columns hold futures until awaited; await_futures
    # materializes them
    state2 = run_table(r2.await_futures())
    vals = [v[0] for v in state2.values()]
    assert vals == [10]


def test_make_tuple_and_unpack_col():
    t = T(
        """
          | a | b
        1 | 1 | x
        """
    )
    packed = t.select(tup=pw.make_tuple(pw.this.a, pw.this.b))
    from pathway_tpu.stdlib.utils.col import unpack_col

    unpacked = unpack_col(packed.tup, "a", "b")
    state = run_table(unpacked)
    assert list(state.values()) == [(1, "x")]


def test_declare_type_and_cast():
    from pathway_tpu.internals import dtype as dt

    t = T(
        """
          | a
        1 | 1
        """
    )
    r = t.select(b=pw.declare_type(float, pw.this.a))
    assert r._columns["b"].dtype is dt.FLOAT
    r2 = t.select(c=pw.cast(float, pw.this.a))
    state = run_table(r2)
    assert list(state.values()) == [(1.0,)]


def test_unsafe_make_pointer_and_wrap_py_object():
    p = pw.unsafe_make_pointer(42)
    assert int(p) == 42
    obj = object()
    w = pw.wrap_py_object(obj)
    assert isinstance(w, pw.PyObjectWrapper)
    assert w.value is obj


def test_schema_from_csv(tmp_path):
    f = tmp_path / "s.csv"
    f.write_text("name,age,score\nada,30,1.5\n")
    schema = pw.schema_from_csv(str(f))
    hints = schema.typehints()
    assert hints["name"] is str
    assert hints["age"] is int
    assert hints["score"] is float


def test_assert_table_has_schema():
    class S(pw.Schema):
        a: int

    t = T(
        """
          | a
        1 | 1
        """
    )
    pw.assert_table_has_schema(t, S)

    class Wrong(pw.Schema):
        a: str

    with pytest.raises(AssertionError):
        pw.assert_table_has_schema(t, Wrong)


def test_iterate_universe_fixpoint():
    """pw.iterate_universe: iterate where the row set itself changes
    (reference iterate w/ universe changes)."""
    t = T(
        """
          | v
        1 | 16
        2 | 3
        """
    )

    def halve_big(t):
        # keys stay stable across iterations (filter/select preserve
        # them) so the fixpoint detector can converge
        big = t.filter(pw.this.v > 4).select(v=pw.this.v // 2)
        small = t.filter(pw.this.v <= 4)
        return small.concat(big)

    res = pw.iterate_universe(halve_big, t=t)
    state = run_table(res.t if hasattr(res, "t") else res)
    assert sorted(v[0] for v in state.values()) == [3, 4]


def test_datetime_constants_roundtrip():
    """DATE_TIME_NAIVE/UTC/DURATION type markers work in schemas and
    the .dt namespace consumes their columns."""
    import datetime

    class S(pw.Schema):
        ts: pw.DATE_TIME_NAIVE
        dur: pw.DURATION

    rows = [(datetime.datetime(2024, 5, 1, 12, 30), datetime.timedelta(hours=2))]
    t = pw.debug.table_from_rows(schema=S, rows=rows)
    r = t.select(
        h=pw.this.ts.dt.hour(),
        total_h=pw.this.dur.dt.hours(),
    )
    state = run_table(r)
    assert list(state.values()) == [(12, 2)]


def test_grouped_join_result_reduce():
    """JoinResult.groupby-style reduce (GroupedJoinResult surface)."""
    orders = T(
        """
          | item | qty
        1 | a    | 1
        2 | a    | 3
        3 | b    | 2
        """
    )
    prices = T(
        """
          | item | price
        1 | a    | 10
        2 | b    | 20
        """
    )
    total = (
        orders.join(prices, pw.left.item == pw.right.item)
        .select(rev=pw.left.qty * pw.right.price)
        .reduce(total=pw.reducers.sum(pw.this.rev))
    )
    state = run_table(total)
    assert list(state.values()) == [(80,)]


def test_pathway_config_and_monitoring_config():
    cfg = pw.pathway_config
    assert hasattr(cfg, "license_key")
    pw.set_monitoring_config(server_endpoint=None)  # accepts and no-ops


def test_udf_sync_async_aliases():
    @pw.udf
    def inc(x: int) -> int:
        return x + 1

    assert isinstance(inc, pw.UDFSync) or isinstance(inc, pw.UDF)
    t = T(
        """
          | a
        1 | 1
        """
    )
    state = run_table(t.select(b=inc(pw.this.a)))
    assert list(state.values()) == [(2,)]
