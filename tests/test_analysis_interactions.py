"""Cross-rule interaction contract for the HBM footprint family
(PWL010 index-over-HBM, PWL012 no-cold-tier, PWL015 combined
oversubscription, PWL016 tenancy quotas): all four price planes with
the same shared footprint model (``internals/ledger``) and the same
PATHWAY_HBM_BYTES budget, each owns a disjoint failure window (no
double-firing on one hazard), and the fully composed
mesh+tiers+tenancy+decode run lints clean when every fix is in place."""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

import pathway_tpu as pw
from pathway_tpu.analysis.rules import (
    check_combined_hbm_oversubscription,
    check_index_hbm_budget,
    check_index_tier_budget,
    check_tenancy_without_quotas,
)
from pathway_tpu.internals.parse_graph import G

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "analysis")


def _analyze_cli(program: str, *flags: str) -> subprocess.CompletedProcess:
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-m", "pathway_tpu.cli", "analyze", *flags, program],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=180,
    )


def _build_index_graph(reserved: int = 20_000_000, dim: int = 384):
    from pathway_tpu.stdlib.ml.index import KNNIndex

    docs = pw.debug.table_from_markdown(
        """
        | x   | y
      1 | 1.0 | 0.0
        """
    )
    docs = docs.select(
        emb=pw.apply_with_type(lambda x, y: (x, y), pw.ANY, docs.x, docs.y)
    )
    index = KNNIndex(
        docs.emb,
        docs,
        n_dimensions=dim,
        reserved_space=reserved,
        distance_type="cosine",
    )
    res = index.get_nearest_items(docs.emb, k=3)
    pw.io.null.write(res)
    return res


@pytest.fixture
def graph():
    pw.clear_graph()
    yield G
    pw.clear_graph()


def test_composed_planes_fixture_lints_clean_deep():
    """The all-planes composition (mesh + tiers + tenancy-with-quotas +
    decode) with every fix in place: zero findings even with warnings
    fatal and the deep pass on."""
    proc = _analyze_cli(
        os.path.join(FIXTURES, "composed_planes.py"), "--deep", "--fail-on=warn"
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "no findings" in proc.stdout


def test_pwl010_and_pwl012_agree_on_footprint(graph):
    """Both over-budget rules fire on the same untried index and price
    it identically: same total bytes, same per-device bytes, same
    budget — one shared footprint model, two different fixes."""
    _build_index_graph()
    view = pw.analysis.GraphView(graph)
    (d10,) = check_index_hbm_budget(view)
    (d12,) = check_index_tier_budget(view)
    assert d10.rule == "PWL010" and d12.rule == "PWL012"
    assert d10.detail["bytes"] == d12.detail["bytes"]
    assert d10.detail["per_device_bytes"] == d12.detail["per_device_bytes"]
    assert d10.detail["hbm_budget_bytes"] == d12.detail["hbm_budget_bytes"]
    # and both anchor to the same index spec (no diverging copies)
    assert d10.detail["index"] is d12.detail["index"]


def test_run_tiers_silence_both_hbm_rules(graph):
    """index_tiers= is the accepted fix for the resident-set hazard:
    with it configured neither PWL010 nor PWL012 fires — the fixed
    hazard is not re-reported under another rule id."""
    _build_index_graph()
    G.run_context = {"mesh_axes": None, "index_tiers": {"hot_rows": 10_000}}
    view = pw.analysis.GraphView(graph)
    assert check_index_hbm_budget(view) == []
    assert check_index_tier_budget(view) == []
    assert check_combined_hbm_oversubscription(view) == []


def test_pwl015_owns_the_each_fits_alone_window(graph, monkeypatch):
    """In the combined-oversubscription window (each plane fits alone)
    PWL015 fires and the single-plane rules stay silent — and PWL015's
    index term equals exactly what PWL010 would have priced."""
    monkeypatch.setenv("PATHWAY_HBM_BYTES", str(48 * 1024 * 1024))
    _build_index_graph(reserved=20_000, dim=384)
    G.run_context = {
        "mesh_axes": None,
        "decode": {"pages": 256, "page_size": 16},
    }
    view = pw.analysis.GraphView(graph)
    assert check_index_hbm_budget(view) == []
    assert check_index_tier_budget(view) == []
    (d15,) = check_combined_hbm_oversubscription(view)
    fp = d15.detail["footprint"]
    from pathway_tpu.analysis.rules import _index_hbm_bytes

    (spec,) = [s for s in G.external_indexes if s.get("device_backed")]
    assert fp["index"] == _index_hbm_bytes(spec)
    assert fp["total"] == fp["index"] + fp["decode_kv"]
    assert d15.detail["hbm_budget_bytes"] == 48 * 1024 * 1024
    assert fp["index"] <= d15.detail["hbm_budget_bytes"]
    assert fp["decode_kv"] <= d15.detail["hbm_budget_bytes"]


def test_mesh_sharding_scales_every_rules_per_device_term(graph):
    """PWL010/012/015 all divide the index footprint by the data axis —
    the mesh composes identically into each rule's arithmetic."""
    _build_index_graph(reserved=40_000_000)  # ~57 GiB: over budget even halved
    G.run_context = {"mesh_axes": {"data": 2, "model": 1}}
    view = pw.analysis.GraphView(graph)
    (d10,) = check_index_hbm_budget(view)
    (d12,) = check_index_tier_budget(view)
    assert d10.detail["per_device_bytes"] == d10.detail["bytes"] // 2
    assert d12.detail["per_device_bytes"] == d10.detail["per_device_bytes"]
    assert d10.detail["mesh_axes"] == {"data": 2, "model": 1}


def test_pwl016_prices_quotas_against_the_shared_budget(graph, monkeypatch):
    """Tenancy quota booking is gated by the same PATHWAY_HBM_BYTES
    knob the index rules use — overbooked quotas fire PWL016 with the
    identical budget value, and fitting quotas are silent."""
    monkeypatch.setenv("PATHWAY_HBM_BYTES", str(64 * 1024 * 1024))
    _build_index_graph(reserved=20_000, dim=384)
    quotas = {
        "acme": {"hbm_bytes": 40 * 1024 * 1024},
        "globex": {"hbm_bytes": 40 * 1024 * 1024},
    }
    G.run_context = {"mesh_axes": None, "tenancy": {"quotas": quotas}}
    view = pw.analysis.GraphView(graph)
    (d16,) = check_tenancy_without_quotas(view)
    assert d16.rule == "PWL016"
    assert d16.detail["hbm_budget_bytes"] == 64 * 1024 * 1024
    assert d16.detail["total_bytes"] == 80 * 1024 * 1024
    # the index rules read the same knob in the same run
    assert check_index_hbm_budget(view) == []  # 29 MiB index fits 64 MiB

    # shrink the booking into the budget: PWL016 goes silent
    quotas["globex"]["hbm_bytes"] = 16 * 1024 * 1024
    assert check_tenancy_without_quotas(view) == []


def test_composed_hazard_fires_exactly_one_rule_per_window(graph, monkeypatch):
    """All four planes composed with ONE hazard (overbooked tenant
    quotas): exactly one PWL016 finding, nothing else from the
    footprint family — composition never double-fires."""
    monkeypatch.setenv("PATHWAY_HBM_BYTES", str(64 * 1024 * 1024))
    _build_index_graph(reserved=20_000, dim=384)
    G.run_context = {
        "mesh_axes": {"data": 2, "model": 1},
        "index_tiers": {"hot_rows": 10_000},
        "decode": {"pages": 64, "page_size": 16},
        "tenancy": {
            "quotas": {
                "acme": {"hbm_bytes": 40 * 1024 * 1024},
                "globex": {"hbm_bytes": 40 * 1024 * 1024},
            }
        },
    }
    view = pw.analysis.GraphView(graph)
    fired = (
        check_index_hbm_budget(view)
        + check_index_tier_budget(view)
        + check_combined_hbm_oversubscription(view)
        + check_tenancy_without_quotas(view)
    )
    assert [d.rule for d in fired] == ["PWL016"]
