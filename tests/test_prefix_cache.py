"""Prefix cache over the paged-KV pool (decode/prefix_cache): the
hash-chain lookup/publish contract, refcounted page sharing with the
book-once ``decode.kv`` accounting invariant, leaf-only LRU eviction,
pool-pressure reclaim, and the two races the module docstring pins —
lookup-vs-eviction under the lock and eviction-vs-in-flight-decode
through the pool's immutable array snapshots."""

from __future__ import annotations

import threading

import numpy as np
import pytest

from pathway_tpu.decode import (
    DECODE_METRICS,
    DecodeConfig,
    DecodeEngine,
    DecoderConfig,
    PrefixCache,
    init_decoder_params,
)
from pathway_tpu.ops.paged_attention import PagedKvPool
from pathway_tpu.resilience import chaos

PAGE = 4


@pytest.fixture(autouse=True)
def _fresh_metrics():
    DECODE_METRICS.reset()
    yield
    DECODE_METRICS.reset()
    chaos.deactivate()


def _pool(n_pages=16):
    return PagedKvPool(layers=1, dim=8, n_pages=n_pages, page_size=PAGE)


def _cache(pool, version=""):
    return PrefixCache(pool, page_size=PAGE, model_version=version)


def _prefilled(pool, n):
    pages = pool.alloc(n)
    assert pages is not None
    return pages


# ------------------------------------------------------- lookup / publish


def test_cold_lookup_misses_and_takes_nothing():
    pool = _pool()
    cache = _cache(pool)
    assert cache.lookup(list(range(12))) == []
    assert pool.pages_in_use == 0
    assert cache.cached_pages == 0


def test_publish_then_lookup_maps_the_shared_pages():
    pool = _pool()
    cache = _cache(pool)
    prompt = list(range(10))  # 2 full pages + partial
    pages = _prefilled(pool, 3)
    assert cache.publish(prompt, pages, len(prompt)) == 2
    assert cache.cached_pages == 2
    # cache holds its own reference on top of the request's
    assert pool.refcount(pages[0]) == 2
    assert pool.refcount(pages[2]) == 1  # partial page never cached
    hit = cache.lookup(prompt)
    assert hit == pages[:2]
    assert pool.refcount(pages[0]) == 3  # lookup acquired for the caller


def test_only_full_pages_short_of_the_last_token_are_shareable():
    pool = _pool()
    cache = _cache(pool)
    # 8 tokens = 2 exact pages, but the last token must re-prefill to
    # produce first-token logits, so only 1 page (7 tokens span) shares
    pages = _prefilled(pool, 2)
    assert cache.publish(list(range(8)), pages, 8) == 1
    assert cache.lookup(list(range(8))) == pages[:1]
    pool.free(pages[:1])  # release the lookup hold


def test_lookup_walks_the_chain_to_the_first_miss():
    pool = _pool()
    cache = _cache(pool)
    a = list(range(20))
    pages = _prefilled(pool, 4)
    cache.publish(a, pages, len(a))  # 4 full pages cached... (19//4)
    # a prompt diverging inside page 2 maps only the agreeing prefix
    b = a[:6] + [77] * 14
    assert cache.lookup(b) == pages[:1]
    pool.free(pages[:1])


def test_model_version_keys_the_chain():
    pool = _pool()
    prompt = list(range(12))
    pages = _prefilled(pool, 2)
    _cache(pool, version="v1").publish(prompt, pages, len(prompt))
    assert _cache(pool, version="v2").lookup(prompt) == []


def test_publish_is_idempotent_for_cached_pages():
    pool = _pool()
    cache = _cache(pool)
    prompt = list(range(10))
    pages = _prefilled(pool, 3)
    assert cache.publish(prompt, pages, len(prompt)) == 2
    assert cache.publish(prompt, pages, len(prompt)) == 0
    assert pool.refcount(pages[0]) == 2  # no double cache-hold


# --------------------------------------------------- book-once accounting


def test_shared_pages_book_once_in_pages_in_use():
    """The ledger invariant: N holders of the same physical prefix are
    one booking — ``pages_in_use`` counts pages, not references."""
    pool = _pool()
    cache = _cache(pool)
    prompt = list(range(13))  # 3 full pages
    pages = _prefilled(pool, 4)
    cache.publish(prompt, pages, len(prompt))
    base = pool.pages_in_use
    holds = [cache.lookup(prompt) for _ in range(5)]
    assert all(h == pages[:3] for h in holds)
    assert pool.pages_in_use == base  # five sharers, zero new pages
    for h in holds:
        pool.free(h)
    assert pool.pages_in_use == base


# ----------------------------------------------------------- eviction


def test_reclaim_evicts_lru_leaves_first():
    pool = _pool()
    cache = _cache(pool)
    old = list(range(9))
    new = [50 + i for i in range(9)]
    p_old = _prefilled(pool, 2)
    p_new = _prefilled(pool, 2)
    cache.publish(old, p_old, 9)
    cache.publish(new, p_new, 9)
    pool.free(p_old)  # requests retire; cache holds remain
    pool.free(p_new)
    cache.lookup(new) and pool.free(p_new[:2])  # touch new (LRU = old)
    assert cache.reclaim(2) == 2
    assert cache.lookup(old) == []  # old evicted...
    hit = cache.lookup(new)
    assert hit == p_new[:2]  # ...new survived
    pool.free(hit)


def test_interior_pages_never_outlive_descendants():
    pool = _pool()
    cache = _cache(pool)
    prompt = list(range(13))  # pages: p0 -> p1 -> p2 chain
    pages = _prefilled(pool, 3)
    cache.publish(prompt, pages, len(prompt))
    pool.free(pages)  # only the cache holds now
    assert cache.reclaim(1) == 1  # evicts the leaf p2
    assert cache.lookup(prompt) == pages[:2]
    pool.free(pages[:2])
    # evicting everything walks leaf-by-leaf without breaking the chain
    assert cache.reclaim(10) == 2
    assert cache.cached_pages == 0
    assert pool.pages_in_use == 0


def test_held_pages_are_not_evictable():
    pool = _pool()
    cache = _cache(pool)
    prompt = list(range(9))
    pages = _prefilled(pool, 2)
    cache.publish(prompt, pages, 9)
    # the publishing request still holds its pages: refcount 2 > 1
    assert cache.reclaim(10) == 0
    pool.free(pages)
    assert cache.reclaim(10) == 2
    assert cache.cached_pages == 0


def test_clear_drops_only_idle_entries():
    pool = _pool()
    cache = _cache(pool)
    a, b = list(range(9)), [30 + i for i in range(9)]
    pa, pb = _prefilled(pool, 2), _prefilled(pool, 2)
    cache.publish(a, pa, 9)
    cache.publish(b, pb, 9)
    pool.free(pb)  # b idle, a still held
    assert cache.clear() == 2
    assert cache.cached_pages == 2
    hit = cache.lookup(a)
    assert hit == pa[:2]
    pool.free(hit)


# ------------------------------------------------------------- races


def test_lookup_racing_reclaim_never_yields_a_freed_page():
    """The lock contract: a concurrent lookup either acquires the page
    (reference taken before the lock drops, so eviction skips it) or
    misses cleanly — it can never hand out a page that reclaim freed."""
    pool = _pool(n_pages=64)
    cache = _cache(pool)
    prompt = list(range(21))
    pages = _prefilled(pool, 5)
    cache.publish(prompt, pages, len(prompt))
    pool.free(pages)  # idle: everything is fair game for reclaim
    stop = threading.Event()
    errors: list[Exception] = []

    def hammer_lookup():
        try:
            while not stop.is_set():
                hit = cache.lookup(prompt)
                # every page handed out is held (>= our ref) right now
                assert all(pool.refcount(p) >= 1 for p in hit)
                if hit:
                    pool.free(hit)
        except Exception as exc:  # pragma: no cover - failure path
            errors.append(exc)

    threads = [threading.Thread(target=hammer_lookup) for _ in range(4)]
    for t in threads:
        t.start()
    for _ in range(200):
        cache.reclaim(1)
        if cache.cached_pages == 0:
            break
    stop.set()
    for t in threads:
        t.join()
    assert not errors
    cache.clear()
    # all references eventually returned: the pool is fully reclaimed
    assert cache.cached_pages == 0
    assert pool.pages_in_use == 0


def test_eviction_between_compute_and_commit_leaves_streams_bitwise():
    """Satellite gate: pages evicted + reallocated while a decode tick
    is in flight must not tear KV out from under it. The tick computes
    against an immutable snapshot of the pool arrays, so we kill a step
    at the ``decode.step`` chaos site (after compute, before commit),
    evict the cached prefix, let a new prompt's prefill REUSE those
    physical pages, and then resume: the survivor's stream must be
    bitwise what an unchaosed engine produces."""
    model = DecoderConfig(
        vocab_size=97, hidden_size=16, num_layers=2, num_heads=2,
        intermediate_size=32, max_position=64,
    )
    params = init_decoder_params(model, seed=0)
    cfg = DecodeConfig(
        pages=16, page_size=4, lanes=2, max_new_tokens=6,
        degrade_max_new_tokens=2, max_seq=32, impl="xla",
        prefix_cache=True,
    )

    def fresh():
        return DecodeEngine(model, cfg, params=params)

    warm = [3, 1, 4, 1, 5, 9, 2, 6, 5]  # publishes 2 full pages
    victim_prompt = [2, 7, 1, 8, 2, 8]
    intruder_prompt = [41, 42, 43, 44, 45, 46, 47, 48, 49]

    ref_engine = fresh()
    ref_engine.generate([warm])
    ref = ref_engine.generate([victim_prompt])[0]

    eng = fresh()
    eng.generate([warm])  # cache now holds warm's full pages
    cached_before = eng.cache.cached_pages
    assert cached_before > 0
    victim = eng.submit(victim_prompt)
    chaos.activate([{"site": "decode.step", "time": eng.steps + 2, "action": "raise"}])
    with pytest.raises(chaos.ChaosInjected):
        eng.drain()
    chaos.deactivate()
    # mid-flight: evict the idle cached prefix and hand its physical
    # pages to a new prompt whose prefill overwrites their bytes
    assert eng.cache.reclaim(cached_before) == cached_before
    intruder = eng.submit(intruder_prompt)
    eng.drain()
    assert victim.result() == ref
    # the intruder decoded on the recycled pages without corruption
    assert intruder.result() == fresh().generate([intruder_prompt])[0]
