"""Chip-time attribution plane: the device-seconds ledger
(internals/chip_ledger.py), the persistent metrics journal + perf
snapshot/diff (pathway_tpu/perf/), and their surfaces (/metrics,
/status, `pathway top`, watchdog rule, flight-recorder ride-along).

House rules under test: accounting is opt-in and byte-identical-off
(scrapes must not change a byte until the first booking), booked
device-seconds must reconcile with wall time, nested dispatches must
never double-count, and per-tenant sub-accounts must reconcile with
the DRR weights."""

from __future__ import annotations

import json
import os
import time

import pytest

from pathway_tpu.internals.chip_ledger import (
    CHIP_LEDGER,
    PLANE_ACCOUNTS,
    STRANDED_CAUSES,
    chip_ledger_enabled,
    chip_peak_tflops,
)


@pytest.fixture()
def _chip(monkeypatch):
    """Ledger on for the test body, pristine before and after."""
    monkeypatch.delenv("PATHWAY_CHIP_LEDGER", raising=False)
    CHIP_LEDGER.reset()
    CHIP_LEDGER.set_enabled(True)
    yield CHIP_LEDGER
    CHIP_LEDGER.set_enabled(None)
    CHIP_LEDGER.reset()


@pytest.fixture()
def _chip_off(monkeypatch):
    monkeypatch.delenv("PATHWAY_CHIP_LEDGER", raising=False)
    CHIP_LEDGER.reset()
    CHIP_LEDGER.set_enabled(None)
    yield CHIP_LEDGER
    CHIP_LEDGER.set_enabled(None)
    CHIP_LEDGER.reset()


# ---------------------------------------------------------------------------
# gating
# ---------------------------------------------------------------------------


def test_default_off_and_env_opt_in(monkeypatch):
    monkeypatch.delenv("PATHWAY_CHIP_LEDGER", raising=False)
    assert chip_ledger_enabled() is False
    for v in ("1", "true", "on", "yes"):
        monkeypatch.setenv("PATHWAY_CHIP_LEDGER", v)
        assert chip_ledger_enabled() is True
    monkeypatch.setenv("PATHWAY_CHIP_LEDGER", "0")
    assert chip_ledger_enabled() is False


def test_override_wins_over_env(monkeypatch, _chip_off):
    monkeypatch.setenv("PATHWAY_CHIP_LEDGER", "1")
    assert CHIP_LEDGER.on() is True
    CHIP_LEDGER.set_enabled(False)  # pw.run(chip_ledger=False)
    assert CHIP_LEDGER.on() is False
    CHIP_LEDGER.set_enabled(None)
    assert CHIP_LEDGER.on() is True


def test_off_booking_is_noop(_chip_off):
    CHIP_LEDGER.book("encode", 1.0)
    CHIP_LEDGER.book_tenant("a", 1.0)
    CHIP_LEDGER.note_stall("host_prep", 1.0)
    with CHIP_LEDGER.timed("rerank"):
        pass
    assert CHIP_LEDGER.active() is False
    snap = CHIP_LEDGER.snapshot()
    assert snap["accounts"] == {} and snap["busy_seconds"] == 0.0


def test_run_kwarg_sets_and_restores_override(monkeypatch):
    import pathway_tpu as pw

    monkeypatch.delenv("PATHWAY_CHIP_LEDGER", raising=False)
    CHIP_LEDGER.reset()
    t = pw.debug.table_from_markdown("""
        | x
      1 | 1
    """)
    pw.io.null.write(t.select(pw.this.x))
    result = pw.run(monitoring_level="none", chip_ledger=True)
    assert result is not None
    from pathway_tpu.internals.parse_graph import G

    assert G.run_context["chip_ledger"] is True
    assert CHIP_LEDGER.on() is False  # restored to the env default
    CHIP_LEDGER.reset()


# ---------------------------------------------------------------------------
# booking model: sums-to-wall, nested dedup, stranded causes
# ---------------------------------------------------------------------------


def test_accounts_sum_to_wall_within_tolerance(_chip):
    """A staged run whose every phase books must reconcile: busy equals
    the sum of accounts, and accounted_fraction >= 0.95 of the measured
    wall (the bench gate, asserted here without jax). Best-of-3 windows:
    the window is only ~70ms, so a single scheduler stall between the
    staged blocks on a loaded CI box must not fail the claim."""
    best = 0.0
    for _ in range(3):
        CHIP_LEDGER.reset()
        t0 = time.perf_counter()
        for account, dur in (
            ("encode", 0.03),
            ("index.search", 0.02),
            ("index.merge", 0.01),
            ("rerank", 0.01),
        ):
            with CHIP_LEDGER.timed(account):
                time.sleep(dur)
        wall = time.perf_counter() - t0
        snap = CHIP_LEDGER.snapshot(wall)
        # snapshot rounds each figure to 6 decimals, so the sum of
        # rounded account rows can drift a few microseconds from busy
        assert snap["busy_seconds"] == pytest.approx(
            sum(a["seconds"] for a in snap["accounts"].values()), abs=5e-6
        )
        assert snap["wall_seconds"] == pytest.approx(wall, abs=1e-6)
        shares = sum(a["share"] for a in snap["accounts"].values())
        assert shares == pytest.approx(1.0, abs=0.01)
        best = max(best, snap["accounted_fraction"])
        if best >= 0.95:
            break
    assert best >= 0.95, best


def test_nested_booking_never_double_counts(_chip):
    """wrap_jit books `compile` inside an encode timed window: the
    window must book its wall MINUS the nested seconds, so the two
    accounts sum to the window wall, not above it."""
    with CHIP_LEDGER.timed("encode"):
        time.sleep(0.02)
        CHIP_LEDGER.book("compile", 0.015)  # what wrap_jit does
        time.sleep(0.01)
    snap = CHIP_LEDGER.snapshot()
    enc = snap["accounts"]["encode"]["seconds"]
    comp = snap["accounts"]["compile"]["seconds"]
    assert comp == pytest.approx(0.015, abs=1e-9)
    # encode booked ~0.03 of sleep, never the full 0.045 window
    assert enc == pytest.approx(0.03, abs=0.02)
    assert enc + comp <= snap["wall_seconds"] + 1e-6


def test_account_render_order_is_plane_order(_chip):
    CHIP_LEDGER.book("compile", 0.01)
    CHIP_LEDGER.book("decode", 0.01)
    CHIP_LEDGER.book("encode", 0.01)
    CHIP_LEDGER.book("zz_custom", 0.01)
    names = list(CHIP_LEDGER.snapshot()["accounts"])
    assert names == ["encode", "decode", "compile", "zz_custom"]
    assert [a for a in names if a in PLANE_ACCOUNTS] == [
        a for a in PLANE_ACCOUNTS if a in names
    ]


def test_stranded_residual_attributed_to_causes(_chip):
    """busy=0.05 against wall=0.2: 0.15 stranded; explicit stall notes
    claim their share in STRANDED_CAUSES order, remainder is
    unattributed — and causes never claim more than the residual."""
    CHIP_LEDGER.book("encode", 0.05)
    CHIP_LEDGER.note_stall("host_prep", 0.04)
    CHIP_LEDGER.note_stall("barrier", 0.02)
    snap = CHIP_LEDGER.snapshot(0.2)
    assert snap["stranded_seconds"] == pytest.approx(0.15, abs=1e-6)
    causes = snap["stranded_causes"]
    assert causes["host_prep"] == pytest.approx(0.04, abs=1e-6)
    assert causes["barrier"] == pytest.approx(0.02, abs=1e-6)
    assert causes["unattributed"] == pytest.approx(0.09, abs=1e-6)
    assert sum(causes.values()) == pytest.approx(0.15, abs=1e-6)
    assert list(causes)[:2] == [
        c for c in STRANDED_CAUSES if c in ("host_prep", "barrier")
    ]


def test_stranded_causes_capped_at_residual(_chip):
    CHIP_LEDGER.book("encode", 0.09)
    CHIP_LEDGER.note_stall("host_prep", 5.0)  # wildly over-reported
    snap = CHIP_LEDGER.snapshot(0.1)
    causes = snap["stranded_causes"]
    assert causes["host_prep"] == pytest.approx(0.01, abs=1e-6)
    assert "unattributed" not in causes


def test_chip_peak_tflops_env(monkeypatch):
    monkeypatch.delenv("PATHWAY_CHIP_PEAK_TFLOPS", raising=False)
    assert chip_peak_tflops() == 200.0
    monkeypatch.setenv("PATHWAY_CHIP_PEAK_TFLOPS", "130.7")
    assert chip_peak_tflops() == 130.7
    monkeypatch.setenv("PATHWAY_CHIP_PEAK_TFLOPS", "bogus")
    assert chip_peak_tflops() == 200.0


# ---------------------------------------------------------------------------
# per-tenant reconciliation with the DRR weights
# ---------------------------------------------------------------------------


def test_tenant_share_reconciles_with_drr_weights(_chip):
    from pathway_tpu.tenancy import TenancyConfig, TenantQuotas, set_active_tenancy

    set_active_tenancy(
        TenancyConfig(
            quotas={
                "gold": TenantQuotas(weight=3.0),
                "free": TenantQuotas(weight=1.0),
            }
        )
    )
    try:
        # chip time delivered exactly at the configured 3:1 split
        CHIP_LEDGER.book("encode", 0.09, tenant="gold")
        CHIP_LEDGER.book("encode", 0.03, tenant="free")
        tenants = CHIP_LEDGER.snapshot()["tenants"]
    finally:
        set_active_tenancy(None)
    assert tenants["gold"]["share"] == pytest.approx(0.75, abs=1e-3)
    assert tenants["free"]["share"] == pytest.approx(0.25, abs=1e-3)
    assert tenants["gold"]["weight_share"] == pytest.approx(0.75, abs=1e-3)
    assert tenants["free"]["weight_share"] == pytest.approx(0.25, abs=1e-3)
    # delivered share matches entitled share when work arrives at the
    # weight ratio — the reconciliation the snapshot exists to expose
    for t in ("gold", "free"):
        assert tenants[t]["share"] == pytest.approx(
            tenants[t]["weight_share"], abs=1e-3
        )


def test_tenant_overflow_folds_to_other(_chip):
    for i in range(60):
        CHIP_LEDGER.book_tenant(f"t{i:02d}", 0.001 * (i + 1))
    tenants = CHIP_LEDGER.snapshot()["tenants"]
    assert len(tenants) == 51  # 50 + "other"
    assert "other" in tenants
    assert sum(r["share"] for r in tenants.values()) == pytest.approx(
        1.0, abs=0.01
    )


# ---------------------------------------------------------------------------
# metrics journal: rotation, crash recovery, sampler
# ---------------------------------------------------------------------------


def test_journal_rotates_and_prunes_segments(tmp_path):
    from pathway_tpu.perf.journal import MetricsJournal

    j = MetricsJournal(str(tmp_path), seg_bytes=4096, segments=3)
    try:
        for i in range(400):
            j.append("sample", {"i": i, "pad": "x" * 64})
    finally:
        j.close()
    segs = j.segments()
    assert 1 < len(segs) <= 3
    # the newest record survived pruning; the oldest did not
    recs = j.read_all()
    assert recs[-1]["i"] == 399
    assert recs[0]["i"] > 0
    assert all(r["kind"] == "sample" for r in recs)


def test_journal_crash_recovery_skips_torn_line(tmp_path):
    """A crash mid-append leaves a torn trailing line; readers must
    return every intact record and drop the torn one."""
    from pathway_tpu.perf.journal import MetricsJournal

    j = MetricsJournal(str(tmp_path))
    j.append("sample", {"i": 1})
    j.append("sample", {"i": 2})
    j.close()
    seg = j.segments()[-1]
    with open(seg, "a", encoding="utf-8") as fh:
        fh.write('{"t": 3, "kind": "sample", "i": 3')  # no closing brace
    recs = j.read_all()
    assert [r["i"] for r in recs] == [1, 2]
    assert j.tail(1)[-1]["i"] == 2


def test_journal_sampler_writes_samples(tmp_path, monkeypatch, _chip):
    from pathway_tpu.perf.journal import JournalSampler, MetricsJournal

    CHIP_LEDGER.book("encode", 0.01)
    j = MetricsJournal(str(tmp_path))
    s = JournalSampler(j, interval_s=0.05)
    s.start()
    time.sleep(0.18)
    s.stop()
    j.close()
    recs = [r for r in j.read_all() if r["kind"] == "sample"]
    assert len(recs) >= 2  # ticks plus the final stop() sample
    assert recs[-1]["chip"]["accounts"]["encode"]["seconds"] > 0


def test_journal_inactive_without_dir(monkeypatch):
    from pathway_tpu.perf.journal import append_record, journal_active

    monkeypatch.delenv("PATHWAY_JOURNAL_DIR", raising=False)
    assert journal_active() is False
    assert append_record("bench", {"x": 1}) is False


# ---------------------------------------------------------------------------
# perf snapshot + diff gate math
# ---------------------------------------------------------------------------


def _snap(metrics):
    """BENCH_r*-shaped snapshot from (metric, value, unit[, extra])."""
    lines = []
    for m in metrics:
        rec = {"metric": m[0], "value": m[1], "unit": m[2]}
        if len(m) > 3:
            rec.update(m[3])
        lines.append(json.dumps(rec))
    return {
        "n": 1,
        "cmd": "test",
        "rc": 0,
        "tail": "=== FINAL SUMMARY (one line per metric) ===\n"
        + "\n".join(lines),
        "parsed": {},
    }


def test_perf_diff_direction_heuristics():
    from pathway_tpu.perf.snapshot import diff_snapshots

    a = _snap([
        ("ingest_eps", 1000.0, "rows/s"),
        ("p50_ms", 10.0, "ms"),
    ])
    b = _snap([
        ("ingest_eps", 800.0, "rows/s"),  # -20% on higher-better: regression
        ("p50_ms", 9.0, "ms"),  # lower-better improved
    ])
    result = diff_snapshots(a, b, gate=0.10)
    by_metric = {r["metric"]: r for r in result["rows"]}
    assert by_metric["ingest_eps"]["status"] == "regression"
    assert by_metric["ingest_eps"]["direction"] == "higher"
    assert by_metric["p50_ms"]["status"] in ("ok", "improved")
    assert result["rc"] == 1
    assert [r["metric"] for r in result["regressions"]] == ["ingest_eps"]


def test_perf_diff_within_gate_passes():
    from pathway_tpu.perf.snapshot import diff_snapshots

    a = _snap([("ingest_eps", 1000.0, "rows/s")])
    b = _snap([("ingest_eps", 950.0, "rows/s")])  # -5% within the 10% gate
    result = diff_snapshots(a, b, gate=0.10)
    assert result["rc"] == 0 and not result["regressions"]


def test_perf_diff_absolute_gate_field_wins():
    """A record carrying its own absolute `gate` (like
    chip_time_accounted_fraction's 0.95) fails when the candidate value
    drops below it, regardless of the relative gate."""
    from pathway_tpu.perf.snapshot import diff_snapshots

    a = _snap([("chip_time_accounted_fraction", 0.99, "fraction", {"gate": 0.95})])
    b = _snap([("chip_time_accounted_fraction", 0.93, "fraction", {"gate": 0.95})])
    result = diff_snapshots(a, b, gate=0.5)
    (row,) = result["regressions"]
    assert row["metric"] == "chip_time_accounted_fraction"
    assert result["rc"] == 1


def test_perf_diff_one_sided_metrics_reported_not_fatal():
    """A metric present in only one snapshot must not crash the diff:
    it reports as `new` (candidate only) / `removed` (baseline only)
    with the missing side None, and never fails the gate (rc 0)."""
    from pathway_tpu.perf.snapshot import diff_snapshots, render_diff

    a = _snap([("ingest_eps", 1000.0, "rows/s"), ("old_only_ms", 5.0, "ms")])
    b = _snap([("ingest_eps", 1000.0, "rows/s"), ("brand_new_qps", 50.0, "qps")])
    result = diff_snapshots(a, b, gate=0.10)
    by_metric = {r["metric"]: r for r in result["rows"]}
    assert by_metric["brand_new_qps"]["status"] == "new"
    assert by_metric["brand_new_qps"]["a"] is None
    assert by_metric["brand_new_qps"]["b"] == 50.0
    assert by_metric["old_only_ms"]["status"] == "removed"
    assert by_metric["old_only_ms"]["a"] == 5.0
    assert by_metric["old_only_ms"]["b"] is None
    assert by_metric["brand_new_qps"]["rel_change"] is None
    assert result["rc"] == 0 and not result["regressions"]
    # the rendered table must survive the None sides
    text = render_diff(result)
    assert "brand_new_qps" in text and "removed" in text and "new" in text


def test_perf_diff_disjoint_snapshots_exit_zero():
    from pathway_tpu.perf.snapshot import diff_snapshots, render_diff

    a = _snap([("alpha_ms", 1.0, "ms")])
    b = _snap([("beta_ms", 2.0, "ms")])
    result = diff_snapshots(a, b, gate=0.10)
    assert result["rc"] == 0
    assert {r["status"] for r in result["rows"]} == {"new", "removed"}
    assert "0 regression(s)" in render_diff(result)


def test_perf_snapshot_builds_from_journal(tmp_path, monkeypatch):
    from pathway_tpu.perf.snapshot import SUMMARY_MARKER, build_snapshot
    from pathway_tpu.perf.journal import MetricsJournal

    j = MetricsJournal(str(tmp_path))
    j.append(
        "bench",
        {
            "records": [{"metric": "ingest_eps", "value": 1234.5, "unit": "rows/s"}],
            "headline": {"metric": "rag_p50_ms", "value": 42.0, "unit": "ms"},
        },
    )
    j.close()
    snap = build_snapshot(str(tmp_path))
    assert SUMMARY_MARKER in snap["tail"]
    assert snap["parsed"]["metric"] == "rag_p50_ms"
    assert '"ingest_eps"' in snap["tail"]
    assert snap["rc"] == 0


def test_perf_snapshot_empty_journal_raises(tmp_path):
    from pathway_tpu.perf.snapshot import build_snapshot

    with pytest.raises(ValueError):
        build_snapshot(str(tmp_path))


# ---------------------------------------------------------------------------
# surfaces: /metrics + /status byte-identity both ways, pathway top
# ---------------------------------------------------------------------------


def test_chip_off_scrape_byte_identical_both_ways(_chip_off, monkeypatch):
    """Until the first booking, /metrics and /status must not change a
    single byte — in both directions: booking attempts while off leave
    the scrape at baseline, and turning accounting on without booking
    still leaves it at baseline (activity-gated, not config-gated)."""
    from pathway_tpu.internals.http_monitoring import MonitoringHttpServer
    from pathway_tpu.internals.monitoring import StatsMonitor

    server = MonitoringHttpServer(StatsMonitor(), port=0)

    def scrape():
        return "\n".join(
            line
            for line in server._prometheus().splitlines()
            if not line.startswith(
                ("pathway_input_latency_ms", "pathway_output_latency_ms")
            )
        )

    baseline_metrics = scrape()
    baseline_status = server._status()
    assert "pathway_chip_" not in baseline_metrics
    assert '"chip"' not in baseline_status

    monkeypatch.setenv("PATHWAY_CHIP_LEDGER", "0")
    CHIP_LEDGER.book("encode", 0.5)  # kill switch: booking is a no-op
    with CHIP_LEDGER.timed("rerank"):
        pass
    assert scrape() == baseline_metrics
    assert server._status() == baseline_status

    monkeypatch.setenv("PATHWAY_CHIP_LEDGER", "1")
    assert scrape() == baseline_metrics  # on but untouched: still silent
    assert server._status() == baseline_status

    CHIP_LEDGER.book("encode", 0.5)
    body = server._prometheus()
    assert 'pathway_chip_seconds_total{account="encode"} 0.500000' in body
    assert "pathway_chip_busy_seconds_total" in body
    assert '"chip"' in server._status()


def test_top_renders_empty_and_populated(_chip):
    from pathway_tpu.perf.top import render_top, verdict_state

    text, state = render_top({})
    assert state == "empty" and "no chip-time samples" in text

    CHIP_LEDGER.book("encode", 0.08, tenant="gold")
    CHIP_LEDGER.book("index.search", 0.02)
    snap = CHIP_LEDGER.snapshot(0.2)
    text, state = render_top({"chip": snap})
    assert state == verdict_state(snap)
    assert "encode" in text and "index.search" in text
    assert "stranded" in text and "gold" in text


def test_top_verdict_thresholds():
    from pathway_tpu.perf.top import verdict_state

    assert verdict_state(None) == "empty"
    assert verdict_state({"stranded_fraction": 0.1}) == "green"
    assert verdict_state({"stranded_fraction": 0.6}) == "yellow"
    assert verdict_state({"stranded_fraction": 0.85}) == "red"


def test_top_handles_both_hbm_shapes(_chip):
    """Journal samples store the flat LEDGER.accounts() dict; /status
    nests under snapshot()["accounts"] — both must render."""
    from pathway_tpu.perf.top import render_top

    CHIP_LEDGER.book("encode", 0.01)
    chip = CHIP_LEDGER.snapshot()
    flat = {"index.hot": {"bytes": 4096, "high_water_bytes": 8192}}
    nested = {"accounts": flat, "total_bytes": 4096}
    for hbm in (flat, nested):
        text, _ = render_top({"chip": chip, "hbm": hbm})
        assert "index.hot" in text and "4,096" in text


# ---------------------------------------------------------------------------
# watchdog rule + flight-recorder ride-along
# ---------------------------------------------------------------------------


def test_watchdog_stranded_rule_breach_and_clear(_chip):
    from pathway_tpu.internals.ledger import HealthWatchdog

    wd = HealthWatchdog(interval_s=0.01)
    # hysteresis: one bad sample is not a breach
    v = wd.evaluate_once({"t": 0.0, "stranded_fraction": 0.9})
    chip_rule = [r for r in v["rules"] if r["name"] == "stranded_chip_time"][0]
    assert chip_rule["level"] == "ok"
    v = wd.evaluate_once({"t": 1.0, "stranded_fraction": 0.9})
    chip_rule = [r for r in v["rules"] if r["name"] == "stranded_chip_time"][0]
    assert chip_rule["level"] == "critical"
    assert v["planes"]["chip"]["status"] == "red"
    # two good samples clear it
    wd.evaluate_once({"t": 2.0, "stranded_fraction": 0.1})
    v = wd.evaluate_once({"t": 3.0, "stranded_fraction": 0.1})
    chip_rule = [r for r in v["rules"] if r["name"] == "stranded_chip_time"][0]
    assert chip_rule["level"] == "ok"


def test_watchdog_spec_overrides_stranded_thresholds():
    from pathway_tpu.internals.ledger import parse_watchdog_spec

    cfg = parse_watchdog_spec("stranded_warn=0.3,stranded_critical=0.6")
    (rule,) = [r for r in cfg["rules"] if r.name == "stranded_chip_time"]
    assert rule.warn == 0.3 and rule.critical == 0.6


def test_watchdog_live_sample_carries_chip_fraction(_chip):
    from pathway_tpu.internals.ledger import HealthWatchdog

    CHIP_LEDGER.book("encode", 0.01)
    sample = HealthWatchdog(interval_s=0.01)._live_sample()
    assert "stranded_fraction" in sample
    assert 0.0 <= sample["stranded_fraction"] <= 1.0
    assert "chip_accounted_fraction" in sample


def test_doctor_verdict_renders_chip_rows(_chip):
    from pathway_tpu.internals.ledger import HealthWatchdog, render_verdict

    CHIP_LEDGER.book("encode", 0.05)
    CHIP_LEDGER.book("decode", 0.01)
    v = HealthWatchdog(interval_s=0.01).evaluate_once({"t": 0.0})
    assert v["chip"] is not None
    text = render_verdict(v)
    assert "chip-time:" in text
    assert "encode" in text and "decode" in text


def test_flight_recorder_dump_embeds_chip_and_journal(
    _chip, tmp_path, monkeypatch
):
    from pathway_tpu.internals import flight_recorder as fr
    from pathway_tpu.perf import journal as pj

    monkeypatch.setenv("PATHWAY_JOURNAL_DIR", str(tmp_path / "journal"))
    pj._JOURNALS.clear()
    CHIP_LEDGER.book("encode", 0.04)
    pj.get_journal().sample()
    fr.record("epoch.commit", epoch=7)
    path = fr.dump("test.chip", None)
    try:
        assert path is not None
        data = fr.load_dump(path)
        assert data["chip"]["accounts"]["encode"]["seconds"] > 0
        assert data["journal_tail"], "journal samples must ride along"
        text = fr.render(data)
        assert "chip time at dump:" in text
        assert "journal samples before dump" in text
    finally:
        pj._JOURNALS.clear()
        if path:
            os.unlink(path)
