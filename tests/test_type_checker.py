"""Build-time type checking: incompatible operand types are rejected when
the pipeline is constructed, not at runtime (reference behavior:
python/pathway/internals/type_interpreter.py raises TypeError from
eval_binary_op/eval_unary_op/eval_declare/eval_coalesce).

ANY stays lenient: schema-less sources and untyped UDF results defer to
runtime evaluation."""

import datetime

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import dtype as dt


def _t():
    return pw.debug.table_from_markdown(
        """
          | name  | amount | score
        1 | alice | 10     | 1.5
        2 | bob   | 20     | 2.5
        """
    )


# ---- binary operators ----


def test_str_plus_int_rejected_at_build_time():
    t = _t()
    with pytest.raises(TypeError, match=r"operator '\+'.*STR.*INT"):
        t.select(x=pw.this.name + pw.this.amount)


def test_str_lt_int_rejected():
    t = _t()
    with pytest.raises(TypeError, match="not defined"):
        t.select(x=pw.this.name < pw.this.amount)


def test_eq_between_str_and_int_rejected():
    t = _t()
    with pytest.raises(TypeError):
        t.select(x=pw.this.name == pw.this.amount)


def test_bool_and_on_str_rejected():
    t = _t()
    with pytest.raises(TypeError):
        t.select(x=pw.this.name & pw.this.name)


def test_valid_arithmetic_still_works():
    t = _t()
    out = t.select(
        a=pw.this.amount + 1,
        b=pw.this.amount / 2,
        c=pw.this.amount * pw.this.score,
        d=pw.this.name + "!",
        e=pw.this.amount == 10,
        f=pw.this.name < "zzz",
    )
    assert out._columns["a"].dtype is dt.INT
    assert out._columns["b"].dtype is dt.FLOAT
    assert out._columns["c"].dtype is dt.FLOAT
    assert out._columns["d"].dtype is dt.STR
    assert out._columns["e"].dtype is dt.BOOL
    assert out._columns["f"].dtype is dt.BOOL


def test_int_float_mix_comparison_ok():
    t = _t()
    out = t.select(x=pw.this.amount < pw.this.score)
    assert out._columns["x"].dtype is dt.BOOL


def test_datetime_minus_datetime_is_duration():
    t = pw.debug.table_from_rows(
        pw.schema_from_types(ts=datetime.datetime),
        [(1, datetime.datetime(2026, 1, 1)), (2, datetime.datetime(2026, 1, 2))],
    )
    out = t.select(d=pw.this.ts - pw.this.ts)
    assert out._columns["d"].dtype is dt.DURATION


def test_datetime_plus_datetime_rejected():
    t = pw.debug.table_from_rows(
        pw.schema_from_types(ts=datetime.datetime),
        [(1, datetime.datetime(2026, 1, 1))],
    )
    with pytest.raises(TypeError):
        t.select(d=pw.this.ts + pw.this.ts)


def test_any_operand_stays_lenient():
    t = _t()
    u = t.select(x=pw.apply(lambda v: v, pw.this.name))  # untyped UDF -> ANY
    out = u.select(y=pw.this.x + 1)  # ANY + INT defers to runtime
    assert out._columns["y"].dtype is dt.ANY


def test_error_raised_inside_select_with_this():
    # pw.this refs resolve at select() time; the error must still fire
    t = _t()
    with pytest.raises(TypeError, match="not defined"):
        t.select(x=pw.this.name * pw.this.name)


# ---- unary operators ----


def test_neg_str_rejected():
    t = _t()
    with pytest.raises(TypeError, match="unary"):
        t.select(x=-pw.this.name)


def test_invert_int_ok_neg_ok():
    t = _t()
    out = t.select(x=~pw.this.amount, y=-pw.this.amount)
    assert out._columns["x"].dtype is dt.INT
    assert out._columns["y"].dtype is dt.INT


def test_invert_str_rejected():
    t = _t()
    with pytest.raises(TypeError, match="unary"):
        t.select(x=~pw.this.name)


# ---- if_else / coalesce / fill_error ----


def test_if_else_non_bool_condition_rejected():
    t = _t()
    with pytest.raises(TypeError, match="condition"):
        t.select(x=pw.if_else(pw.this.amount, 1, 2))


def test_if_else_mismatched_branches_rejected():
    t = _t()
    with pytest.raises(TypeError, match="common type"):
        t.select(x=pw.if_else(pw.this.amount > 5, pw.this.name, pw.this.amount))


def test_if_else_int_float_branches_unify():
    t = _t()
    out = t.select(x=pw.if_else(pw.this.amount > 5, pw.this.amount, pw.this.score))
    assert out._columns["x"].dtype is dt.FLOAT


def test_coalesce_mismatched_rejected():
    t = _t()
    with pytest.raises(TypeError, match="coalesce"):
        t.select(x=pw.coalesce(pw.this.name, pw.this.amount))


def test_coalesce_compatible_ok():
    t = _t()
    out = t.select(x=pw.coalesce(pw.this.amount, 0))
    assert out._columns["x"].dtype is dt.INT


def test_fill_error_mismatched_replacement_rejected():
    t = _t()
    with pytest.raises(TypeError, match="fill_error"):
        t.select(x=pw.fill_error(pw.this.amount, "oops"))


# ---- declare_type ----


def test_declare_type_narrowing_ok():
    t = _t()
    u = t.select(x=pw.apply(lambda v: v, pw.this.amount))  # ANY
    out = u.select(y=pw.declare_type(int, pw.this.x))
    assert out._columns["y"].dtype is dt.INT


def test_declare_type_optional_narrowing_ok():
    t = _t()
    u = t.select(x=pw.cast(dt.Optional(dt.INT), pw.this.amount))
    out = u.select(y=pw.declare_type(int, pw.this.x))
    assert out._columns["y"].dtype is dt.INT


def test_declare_type_cross_cast_rejected():
    t = _t()
    with pytest.raises(TypeError, match="declare_type"):
        t.select(x=pw.declare_type(str, pw.this.amount))


# ---- sequence get ----


def test_tuple_str_index_rejected():
    t = _t()
    u = t.select(x=pw.make_tuple(pw.this.amount, pw.this.score))
    with pytest.raises(TypeError, match="sequence index"):
        u.select(y=pw.this.x["nope"])


def test_tuple_int_index_typed():
    t = _t()
    u = t.select(x=pw.make_tuple(pw.this.amount, pw.this.score))
    out = u.select(y=pw.this.x[0], z=pw.this.x[1])
    assert out._columns["y"].dtype is dt.INT
    assert out._columns["z"].dtype is dt.FLOAT


# ---- the checks don't break runtime evaluation ----


def test_checked_pipeline_still_computes():
    t = _t()
    out = t.select(
        n=pw.this.name,
        double=pw.this.amount * 2,
        label=pw.if_else(pw.this.amount > 15, "big", "small"),
    )
    keys, cols = pw.debug.table_to_dicts(out)
    rows = {cols["n"][k]: (cols["double"][k], cols["label"][k]) for k in keys}
    assert rows == {"alice": (20, "small"), "bob": (40, "big")}
