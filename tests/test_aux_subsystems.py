"""Auxiliary subsystems: license gating, telemetry, export/import,
AsyncTransformer, YAML loader, viz, monitoring dashboard.

Covers SURVEY.md §5's aux inventory (R27 telemetry, R28 license, R32
export/import, P8 AsyncTransformer, P9 YAML config)."""

from __future__ import annotations

import json

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.licensing import License, LicenseError, check_worker_count
from pathway_tpu.internals.telemetry import Telemetry
from .utils import T, run_table


def test_license_free_tier_worker_gate():
    lic = License.new(None)
    check_worker_count(lic, 8)  # at the limit: fine
    with pytest.raises(LicenseError):
        check_worker_count(lic, 9)
    ent = License.new("enterprise-abc123")
    check_worker_count(ent, 64)
    assert lic.telemetry_required and not ent.telemetry_required


def test_license_entitlements():
    with pytest.raises(LicenseError):
        License.new(None).check_entitlement("enterprise-connectors")
    License.new("enterprise-x").check_entitlement("enterprise-connectors")


def test_run_rejects_too_many_workers(monkeypatch):
    monkeypatch.setenv("PATHWAY_THREADS", "4")
    monkeypatch.setenv("PATHWAY_PROCESSES", "4")  # 16 > 8 free tier
    t = T(
        """
          | a
        1 | 1
        """
    )
    pw.io.subscribe(t, on_change=lambda **kw: None)
    with pytest.raises(LicenseError):
        pw.run()
    pw.clear_graph()


def test_telemetry_local_file_exporter(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    tel = Telemetry(endpoint=path)
    with tel.span("graph_runner.build", nodes=3):
        pass
    tel.gauge("rows_in", 42)
    tel.flush()
    rec = json.loads(open(path).read())
    assert rec["metrics"]["rows_in"] == 42.0
    assert rec["spans"][0]["name"] == "graph_runner.build"
    assert Telemetry(endpoint=None).enabled is False


def test_export_import_roundtrip():
    t = T(
        """
          | word | n
        1 | a    | 1
        2 | b    | 2
        """
    )
    agg = t.groupby(pw.this.word).reduce(word=pw.this.word, n=pw.reducers.sum(pw.this.n))
    exported = pw.export_table(agg)
    pw.clear_graph()

    # new graph: imported table joins against fresh data
    assert sorted(exported.rows.values()) == [("a", 1), ("b", 2)]
    imp = pw.import_table(exported)
    doubled = imp.select(word=pw.this.word, n2=pw.this.n * 2)
    state = run_table(doubled)
    assert sorted(state.values()) == [("a", 2), ("b", 4)]
    pw.clear_graph()


def test_export_import_with_history():
    t = pw.debug.table_from_markdown(
        """
          | v | __time__ | __diff__
        1 | 1 | 0        | 1
        1 | 1 | 2        | -1
        2 | 5 | 2        | 1
        """
    )
    exported = pw.export_table(t)
    pw.clear_graph()
    imp = pw.import_table(exported, with_history=True)
    from pathway_tpu.internals.graph_runner import GraphRunner

    runner = GraphRunner()
    cap, _ = runner.capture(imp)
    runner.run()
    assert sorted(r[0] for r in cap.state.values()) == [5]
    assert len(cap.stream) == 3  # full history replayed
    pw.clear_graph()


def test_async_transformer():
    class Upper(pw.AsyncTransformer, output_schema=_out_schema()):
        async def invoke(self, data: str) -> dict:
            return {"data": data.upper()}

    t = T(
        """
          | data
        1 | cat
        2 | dog
        """
    )
    res = Upper(input_table=t).successful
    state = run_table(res)
    assert sorted(r[0] for r in state.values()) == ["CAT", "DOG"]
    pw.clear_graph()


def _out_schema():
    class Out(pw.Schema):
        data: str

    return Out


def test_yaml_loader(tmp_path):
    cfg = tmp_path / "pipeline.yaml"
    cfg.write_text(
        """
$run_name: demo
splitter: !pw.xpacks.llm.splitters.TokenCountSplitter
  max_tokens: 100
name: $run_name
nested:
  k: 5
"""
    )
    loaded = pw.load_yaml(open(cfg))
    from pathway_tpu.xpacks.llm.splitters import TokenCountSplitter

    assert isinstance(loaded["splitter"], TokenCountSplitter)
    assert loaded["name"] == "demo"
    assert loaded["nested"]["k"] == 5


def test_viz_table_to_pandas_and_repr():
    t = T(
        """
          | a | b
        1 | 1 | x
        2 | 2 | y
        """
    )
    df = pw.debug.table_to_pandas(t, include_id=False)
    assert list(df.columns) == ["a", "b"]
    assert sorted(df["a"].tolist()) == [1, 2]
    pw.clear_graph()


def test_monitoring_dashboard_snapshot():
    from pathway_tpu.internals.graph_runner import GraphRunner
    from pathway_tpu.internals.monitoring import StatsMonitor

    t = T(
        """
          | a
        1 | 1
        """
    )
    res = t.select(b=pw.this.a + 1)
    monitor = StatsMonitor()
    runner = GraphRunner()
    cap, _ = runner.capture(res)
    runner.run(monitoring_callback=monitor.update)
    assert monitor.snapshot.rows_in > 0
    assert monitor.snapshot.operators
    pw.clear_graph()


def test_otlp_http_trace_export():
    """Telemetry exports OTel OTLP/HTTP JSON (reference telemetry.rs:37
    OTLP exporter; VERDICT r2 Missing #8): spans land at /v1/traces and
    gauges at /v1/metrics in collector-consumable shape."""
    import http.server
    import json as _json
    import threading

    from pathway_tpu.internals.telemetry import Telemetry

    received = {}

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            received[self.path] = _json.loads(body)
            self.send_response(200)
            self.end_headers()
            self.wfile.write(b"{}")

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        tel = Telemetry(endpoint=f"http://127.0.0.1:{port}")
        assert tel.enabled
        with tel.span("graph_runner.run", rows=42):
            pass
        tel.gauge("input_latency_ms", 1.5)
        tel.flush()
    finally:
        srv.shutdown()

    traces = received["/v1/traces"]
    span = traces["resourceSpans"][0]["scopeSpans"][0]["spans"][0]
    assert span["name"] == "graph_runner.run"
    assert len(span["traceId"]) == 32 and len(span["spanId"]) == 16
    assert int(span["endTimeUnixNano"]) >= int(span["startTimeUnixNano"])
    assert {"key": "rows", "value": {"intValue": "42"}} in span["attributes"]
    res_attrs = traces["resourceSpans"][0]["resource"]["attributes"]
    assert any(a["key"] == "service.name" for a in res_attrs)

    metrics = received["/v1/metrics"]
    m = metrics["resourceMetrics"][0]["scopeMetrics"][0]["metrics"][0]
    assert m["name"] == "input_latency_ms"
    assert m["gauge"]["dataPoints"][0]["asDouble"] == 1.5


def test_telemetry_file_exporter_still_works(tmp_path):
    from pathway_tpu.internals.telemetry import Telemetry

    path = str(tmp_path / "tel.jsonl")
    tel = Telemetry(endpoint=path)
    with tel.span("x"):
        pass
    tel.flush()
    import json as _json

    rec = _json.loads(open(path).read().strip())
    assert rec["spans"][0]["name"] == "x"


def test_table_show_and_plot_views():
    """Viz stack (reference stdlib/viz): Table.show renders HTML with
    formatted pointers; Table.plot drives a plotting callable over the
    snapshot and inlines the figure."""
    import pathway_tpu.stdlib.viz  # attaches Table.show / Table.plot

    t = pw.debug.table_from_markdown(
        """
      | a | b
    1 | 1 | x
    2 | 2 | y
    """
    )
    view = t.select(a=pw.this.a * 2, b=pw.this.b).show()
    h = view._repr_html_()
    assert "<table" in h and "<th>a</th>" in h and "4" in h
    assert "id" in view._header_cols()
    pw.clear_graph()

    def plot_fn(df):
        import matplotlib

        matplotlib.use("Agg")
        return df.plot(x="a", y="sq")

    t2 = pw.debug.table_from_markdown(
        """
      | a
    1 | 1
    2 | 3
    """
    )
    p = t2.select(a=pw.this.a, sq=pw.this.a * pw.this.a).plot(plot_fn)
    assert p._repr_html_().startswith("<img src='data:image/png")
    pw.clear_graph()


def test_table_show_streaming_updates_live():
    """Streaming graphs: the view's snapshot store fills as pw.run()
    processes epochs (auto-updating semantics)."""
    import pathway_tpu.stdlib.viz

    class S(pw.Schema):
        v: int

    rows = [{"v": 1}, {"v": 2}]
    t = pw.demo.generate_custom_stream(
        {"v": lambda i: i + 1}, schema=S, nb_rows=2, autocommit_duration_ms=50,
        input_rate=1000,
    ) if hasattr(pw.demo, "generate_custom_stream") else None
    if t is None:
        import pytest

        pytest.skip("demo stream builder unavailable")
    view = t.show()
    assert view.streaming
    pw.run(monitoring_level="none")
    assert len(view.rows) == 2
