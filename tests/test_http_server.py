"""REST connector end-to-end over real HTTP.

Mirrors /root/reference/python/pathway/tests/test_http_server.py:
rest_connector → pipeline → response_writer, with requests from a
helper thread; /_schema OpenAPI endpoint."""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.request

import pytest

import pathway_tpu as pw


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _post(url: str, payload: dict, timeout=20):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


class QuerySchema(pw.Schema):
    value: int


def test_rest_connector_roundtrip():
    port = _free_port()
    queries, response_writer = pw.io.http.rest_connector(
        host="127.0.0.1", port=port, schema=QuerySchema, delete_completed_queries=False
    )
    results = queries.select(result=pw.this.value * 2)
    response_writer(results)

    answers = {}
    errors = []

    def client():
        try:
            deadline = time.time() + 20
            while time.time() < deadline:
                try:
                    answers["a"] = _post(f"http://127.0.0.1:{port}/", {"value": 21})
                    break
                except Exception:
                    time.sleep(0.3)
            answers["b"] = _post(f"http://127.0.0.1:{port}/", {"value": 5})
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/_schema", timeout=5
            ) as resp:
                answers["schema"] = json.loads(resp.read().decode())
        except Exception as e:  # pragma: no cover
            errors.append(e)
        finally:
            stopper()

    def stopper():
        # end the run: the rest reader never closes, so stop the engine
        runner.engine.stop()

    from pathway_tpu.internals.graph_runner import GraphRunner

    runner = GraphRunner()
    for table, sink in list(pw.parse_graph.outputs):
        build = sink.get("build")
        if build is not None:
            build(runner, table)
    for spec in list(pw.parse_graph.subscriptions):
        runner.subscribe(
            spec["table"],
            on_change=spec.get("on_change"),
            on_time_end=spec.get("on_time_end"),
            on_end=spec.get("on_end"),
        )
    t = threading.Thread(target=client, daemon=True)
    t.start()
    runner.run()
    t.join(timeout=30)
    pw.clear_graph()

    assert not errors, errors
    assert answers["a"] == 42
    assert answers["b"] == 10
    assert "openapi" in json.dumps(answers["schema"]).lower() or "paths" in answers["schema"]


def _get(url: str, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _post_raw_status(url: str, payload: dict, timeout=20):
    """POST returning (status, body) without raising on 4xx."""
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


class DocumentedSchema(pw.Schema):
    value: int = pw.column_definition(
        description="the number to double", example=21
    )
    tag: str = pw.column_definition(default_value="none")


def test_rest_connector_docs_validation_and_logging(caplog):
    """EndpointDocumentation renders real per-route OpenAPI docs into
    /_schema; schema validation answers 400; every request emits one
    structured JSON access-log record (reference _server.py:89-166,
    403-420)."""
    import logging as _logging
    import urllib.error

    port = _free_port()
    docs = pw.io.http.EndpointDocumentation(
        summary="Double a number",
        description="Doubles `value`.",
        tags=["math"],
        examples=pw.io.http.EndpointExamples().add_example(
            "default", "double 21", {"value": 21}
        ),
    )
    queries, response_writer = pw.io.http.rest_connector(
        host="127.0.0.1",
        port=port,
        schema=DocumentedSchema,
        delete_completed_queries=False,
        documentation=docs,
    )
    response_writer(queries.select(result=pw.this.value * 2))

    answers = {}
    errors = []

    def client():
        try:
            deadline = time.time() + 20
            while time.time() < deadline:
                try:
                    answers["ok"] = _post_raw_status(
                        f"http://127.0.0.1:{port}/", {"value": 4}
                    )
                    break
                except Exception:
                    time.sleep(0.3)
            answers["missing"] = _post_raw_status(f"http://127.0.0.1:{port}/", {})
            answers["badtype"] = _post_raw_status(
                f"http://127.0.0.1:{port}/", {"value": "x"}
            )
            answers["schema"] = _get(f"http://127.0.0.1:{port}/_schema")
        except Exception as e:  # pragma: no cover
            errors.append(e)
        finally:
            runner.engine.stop()

    from pathway_tpu.internals.graph_runner import GraphRunner

    runner = GraphRunner()
    for spec in list(pw.parse_graph.subscriptions):
        runner.subscribe(spec["table"], on_change=spec.get("on_change"))
    t = threading.Thread(target=client, daemon=True)
    t.start()
    with caplog.at_level(_logging.INFO, logger="pathway_tpu.io.http._docs"):
        runner.run()
    t.join(timeout=30)
    pw.clear_graph()

    assert not errors, errors
    assert answers["ok"] == (200, 8)
    status, body = answers["missing"]
    assert status == 400 and "value" in body["error"]
    status, body = answers["badtype"]
    assert status == 400 and "INT" in body["error"]

    # per-route OpenAPI docs: summary/tags/examples/properties/required
    post_doc = answers["schema"]["paths"]["/"]["post"]
    assert post_doc["summary"] == "Double a number"
    assert post_doc["tags"] == ["math"]
    content = post_doc["requestBody"]["content"]["application/json"]
    assert content["examples"]["default"]["value"] == {"value": 21}
    props = content["schema"]["properties"]
    assert props["value"]["description"] == "the number to double"
    assert props["value"]["example"] == 21
    assert props["tag"]["default"] == "none"
    assert content["schema"]["required"] == ["value"]
    assert "400" in post_doc["responses"]

    # structured access log: one JSON record per request, 4xx at error
    records = [
        json.loads(r.message)
        for r in caplog.records
        if r.name == "pathway_tpu.io.http._docs"
    ]
    assert len(records) >= 3
    ok_recs = [r for r in records if r["status"] == 200]
    bad_recs = [r for r in records if r["status"] == 400]
    assert ok_recs and bad_recs
    rec = ok_recs[0]
    assert rec["_type"] == "http_access"
    assert rec["method"] == "POST"
    assert "time_elapsed" in rec and "session_id" in rec


def test_rest_connector_raw_format():
    """format='raw': the request body feeds the `query` column as text."""
    port = _free_port()

    class RawSchema(pw.Schema):
        query: str

    queries, response_writer = pw.io.http.rest_connector(
        host="127.0.0.1",
        port=port,
        schema=RawSchema,
        format="raw",
        delete_completed_queries=False,
    )
    response_writer(
        queries.select(result=pw.apply(lambda q: q.upper(), pw.this.query))
    )

    answers = {}
    errors = []

    def client():
        try:
            deadline = time.time() + 20
            while time.time() < deadline:
                try:
                    req = urllib.request.Request(
                        f"http://127.0.0.1:{port}/",
                        data=b"hello raw",
                        headers={"Content-Type": "text/plain"},
                        method="POST",
                    )
                    with urllib.request.urlopen(req, timeout=10) as resp:
                        answers["up"] = json.loads(resp.read().decode())
                    break
                except Exception:
                    time.sleep(0.3)
            answers["schema"] = _get(f"http://127.0.0.1:{port}/_schema")
        except Exception as e:  # pragma: no cover
            errors.append(e)
        finally:
            runner.engine.stop()

    from pathway_tpu.internals.graph_runner import GraphRunner

    runner = GraphRunner()
    for spec in list(pw.parse_graph.subscriptions):
        runner.subscribe(spec["table"], on_change=spec.get("on_change"))
    t = threading.Thread(target=client, daemon=True)
    t.start()
    runner.run()
    t.join(timeout=30)
    pw.clear_graph()

    assert not errors, errors
    assert answers["up"] == "HELLO RAW"
    # raw endpoints document a text/plain body
    post_doc = answers["schema"]["paths"]["/"]["post"]
    assert "text/plain" in post_doc["requestBody"]["content"]
