"""REST connector end-to-end over real HTTP.

Mirrors /root/reference/python/pathway/tests/test_http_server.py:
rest_connector → pipeline → response_writer, with requests from a
helper thread; /_schema OpenAPI endpoint."""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.request

import pytest

import pathway_tpu as pw


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _post(url: str, payload: dict, timeout=20):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


class QuerySchema(pw.Schema):
    value: int


def test_rest_connector_roundtrip():
    port = _free_port()
    queries, response_writer = pw.io.http.rest_connector(
        host="127.0.0.1", port=port, schema=QuerySchema, delete_completed_queries=False
    )
    results = queries.select(result=pw.this.value * 2)
    response_writer(results)

    answers = {}
    errors = []

    def client():
        try:
            deadline = time.time() + 20
            while time.time() < deadline:
                try:
                    answers["a"] = _post(f"http://127.0.0.1:{port}/", {"value": 21})
                    break
                except Exception:
                    time.sleep(0.3)
            answers["b"] = _post(f"http://127.0.0.1:{port}/", {"value": 5})
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/_schema", timeout=5
            ) as resp:
                answers["schema"] = json.loads(resp.read().decode())
        except Exception as e:  # pragma: no cover
            errors.append(e)
        finally:
            stopper()

    def stopper():
        # end the run: the rest reader never closes, so stop the engine
        runner.engine.stop()

    from pathway_tpu.internals.graph_runner import GraphRunner

    runner = GraphRunner()
    for table, sink in list(pw.parse_graph.outputs):
        build = sink.get("build")
        if build is not None:
            build(runner, table)
    for spec in list(pw.parse_graph.subscriptions):
        runner.subscribe(
            spec["table"],
            on_change=spec.get("on_change"),
            on_time_end=spec.get("on_time_end"),
            on_end=spec.get("on_end"),
        )
    t = threading.Thread(target=client, daemon=True)
    t.start()
    runner.run()
    t.join(timeout=30)
    pw.clear_graph()

    assert not errors, errors
    assert answers["a"] == 42
    assert answers["b"] == 10
    assert "openapi" in json.dumps(answers["schema"]).lower() or "paths" in answers["schema"]
