"""Unit coverage for the cluster fault domain: the shared registries
(`ClusterMetrics`/`ClusterHealth`), shard-aware admission shedding, the
cluster-channel chaos fault family, durable generation tokens, the
deterministic chaos seed, /metrics gating, and flight-recorder dump
retention. Multi-process integration (lease expiry, partial restart)
lives in tests/test_chaos_crash_window.py."""

from __future__ import annotations

import threading
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.engine import persistence as eng_persist
from pathway_tpu.internals.flight_recorder import FlightRecorder, list_dumps
from pathway_tpu.resilience import chaos
from pathway_tpu.resilience.cluster import (
    CLUSTER_HEALTH,
    CLUSTER_METRICS,
    ClusterHealth,
    ClusterMetrics,
    ClusterRegroup,
    WorkerLost,
)
from pathway_tpu.serving import (
    AdmissionController,
    ServingConfig,
    ShardUnavailable,
)
from pathway_tpu.serving.metrics import ServingMetrics


@pytest.fixture(autouse=True)
def _clean_registries():
    yield
    CLUSTER_METRICS.reset()
    CLUSTER_HEALTH.mark_all_up()
    chaos.deactivate()


# ---------------------------------------------------------- registries


def test_cluster_metrics_counts_and_snapshot():
    m = ClusterMetrics()
    assert not m.active()
    m.record_lease_expired(1)
    m.record_lease_expired(1)
    m.record_lease_expired(2)
    m.record_partial_restart(1)
    m.record_fenced_write(2)
    m.record_barrier(generation=3)
    snap = m.snapshot()
    assert snap["lease_expiries"] == {"1": 2, "2": 1}
    assert snap["lease_expiries_total"] == 3
    assert snap["partial_restarts_total"] == 1
    assert snap["fenced_writes_total"] == 1
    assert snap["barriers_total"] == 1
    assert snap["generation"] == 3
    assert m.active()
    m.reset()
    assert not m.active()


def test_cluster_metrics_barrier_without_generation_keeps_token():
    m = ClusterMetrics()
    m.record_barrier(generation=2)
    m.record_barrier()
    assert m.snapshot()["generation"] == 2
    assert m.snapshot()["barriers_total"] == 2


def test_cluster_health_down_and_recovery():
    h = ClusterHealth()
    assert not h.any_down()
    h.mark_down([2, 3], retry_after_s=4.5)
    assert h.is_down(2) and h.is_down(3) and not h.is_down(0)
    assert h.down_shards() == frozenset({2, 3})
    assert h.retry_after_s() == 4.5
    h.mark_down([5])  # accumulates until the next full formation
    assert h.down_shards() == frozenset({2, 3, 5})
    h.mark_all_up()
    assert not h.any_down()


def test_worker_lost_and_regroup_carry_identity():
    wl = WorkerLost(3, "lease expired (2s without a frame)")
    assert wl.pid == 3 and "lease expired" in str(wl)
    rg = ClusterRegroup([3, 1], 7, "lease expired")
    assert rg.dead_pids == [1, 3]
    assert rg.generation == 7
    assert "generation=7" in str(rg)
    # a leaked regroup must NOT be absorbed by the supervisor's default
    # restart_on classes — it is the partial-restart loop's signal
    from pathway_tpu.resilience.supervisor import _default_restart_on

    assert not isinstance(rg, _default_restart_on())


# ------------------------------------------------- shard-aware admission


def test_admit_sheds_down_shard_with_typed_503():
    CLUSTER_HEALTH.mark_down([1], retry_after_s=2.0)
    ctl = AdmissionController(
        ServingConfig(max_queue=8), metrics=ServingMetrics()
    )
    t = ctl.admit(shard=0)  # healthy shard unaffected
    ctl.release(t)
    with pytest.raises(ShardUnavailable) as ei:
        ctl.admit(shard=1)
    assert ei.value.status == 503
    assert ei.value.reason == "shard_unavailable"
    assert ei.value.retry_after_s == 2.0
    assert ctl.metrics.snapshot()["shed_total"]["shard_unavailable"] == 1


def test_admit_degrade_mode_serves_down_shard_degraded():
    CLUSTER_HEALTH.mark_down([1])
    ctl = AdmissionController(
        ServingConfig(max_queue=8, shed="degrade"), metrics=ServingMetrics()
    )
    t = ctl.admit(shard=1)
    assert t.degraded
    ctl.release(t)
    t = ctl.admit(shard=0)
    assert not t.degraded
    ctl.release(t)


def test_admit_without_shard_ignores_cluster_health():
    CLUSTER_HEALTH.mark_down([0, 1, 2])
    ctl = AdmissionController(
        ServingConfig(max_queue=8), metrics=ServingMetrics()
    )
    t = ctl.admit()  # not pinned to a shard: answered normally
    ctl.release(t)


def test_tenant_cap_beats_down_shard_deterministically():
    """A tenant at its inflight cap querying a down shard must always
    see 429 ``tenant_rate_limited``, never 503 ``shard_unavailable``:
    the tenant gates run BEFORE the shard-health check, so the client's
    typed reason does not depend on which internal check loses a race.
    Repeated to pin determinism."""
    from pathway_tpu.serving import TenantRateLimited
    from pathway_tpu.tenancy import use_tenancy

    CLUSTER_HEALTH.mark_down([1], retry_after_s=2.0)
    ctl = AdmissionController(
        ServingConfig(max_queue=8), metrics=ServingMetrics()
    )
    with use_tenancy({"quotas": {"acme": {"inflight": 1}}}):
        held = ctl.admit(shard=0, tenant="acme")  # cap reached
        for _ in range(10):
            with pytest.raises(TenantRateLimited) as ei:
                ctl.admit(shard=1, tenant="acme")
            assert ei.value.status == 429
            assert ei.value.reason == "tenant_rate_limited"
            assert ei.value.tenant == "acme"
        ctl.release(held)


def test_under_cap_tenant_still_sheds_down_shard():
    """The same tenant under its cap hitting the same down shard gets
    the shard verdict — 503 ``shard_unavailable`` — deterministically:
    the quota gate passes, so the shard-health check owns the refusal
    (and the failed admit must not leak quota inflight)."""
    from pathway_tpu.tenancy import use_tenancy

    CLUSTER_HEALTH.mark_down([1], retry_after_s=2.0)
    ctl = AdmissionController(
        ServingConfig(max_queue=8), metrics=ServingMetrics()
    )
    with use_tenancy({"quotas": {"acme": {"inflight": 1}}}):
        for _ in range(10):
            with pytest.raises(ShardUnavailable) as ei:
                ctl.admit(shard=1, tenant="acme")
            assert ei.value.status == 503
            assert ei.value.reason == "shard_unavailable"
        # shard-shed admits never consumed the tenant's inflight slot
        t = ctl.admit(shard=0, tenant="acme")
        ctl.release(t)


# ------------------------------------------- cluster-channel chaos family


def test_chaos_channel_drop_and_duplicate_verdicts():
    chaos.activate(
        [
            {"site": "cluster.send", "action": "drop", "hit": 2},
            {"site": "cluster.send", "action": "duplicate", "hit": 3},
        ]
    )
    # hit counters advance independently per rule
    assert chaos.channel("cluster.send") is None  # drop@1, dup@1
    v2 = chaos.channel("cluster.send")  # drop fires at its 2nd hit
    assert v2 == "drop"
    v3 = chaos.channel("cluster.send")  # duplicate fires at its 3rd
    assert v3 == "duplicate"
    assert chaos.channel("cluster.send") is None  # both one-shot


def test_chaos_channel_partition_is_sticky_until_expiry():
    chaos.activate(
        [
            {
                "site": "cluster.send",
                "action": "partition",
                "duration_s": 0.2,
            }
        ]
    )
    assert chaos.channel("cluster.send") == "drop"  # arms the partition
    assert chaos.channel("cluster.send") == "drop"  # sticky
    assert chaos.channel("other.site") is None  # per-site
    time.sleep(0.25)
    assert chaos.channel("cluster.send") is None  # healed


def test_chaos_channel_filters_on_process_and_generation(monkeypatch):
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "1")
    monkeypatch.setenv("PATHWAY_CLUSTER_GENERATION", "0")
    chaos.activate(
        [
            {
                "site": "cluster.send",
                "action": "drop",
                "process": 1,
                "generation": 0,
            }
        ]
    )
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "0")
    assert chaos.channel("cluster.send") is None  # wrong process
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "1")
    monkeypatch.setenv("PATHWAY_CLUSTER_GENERATION", "1")
    # generation moved on (partial restart happened): rule disarmed
    assert chaos.channel("cluster.send") is None
    monkeypatch.setenv("PATHWAY_CLUSTER_GENERATION", "0")
    assert chaos.channel("cluster.send") == "drop"


class _FakeSock:
    def __init__(self):
        self.data = b""

    def sendall(self, b):
        self.data += b


def test_send_frame_applies_channel_verdicts():
    from pathway_tpu.parallel.multiprocess import _HDR, _send_frame

    chaos.activate(
        [{"site": "cluster.send", "action": "drop", "hit": 1}]
    )
    s = _FakeSock()
    _send_frame(s, {"op": "poll"}, threading.Lock())
    assert s.data == b""  # dropped: nothing hit the wire
    chaos.activate(
        [{"site": "cluster.send", "action": "duplicate", "hit": 1}]
    )
    _send_frame(s, {"op": "poll"})
    (n,) = _HDR.unpack(s.data[: _HDR.size])
    assert len(s.data) == 2 * (_HDR.size + n)  # frame sent twice
    assert s.data[: _HDR.size + n] == s.data[_HDR.size + n :]


def test_deterministic_seed_stable_per_plan_and_process(monkeypatch):
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "1")
    chaos.activate([{"site": "cluster.send", "action": "drop"}])
    s1 = chaos.deterministic_seed()
    s2 = chaos.deterministic_seed()
    assert s1 is not None and s1 == s2
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "2")
    assert chaos.deterministic_seed() != s1
    chaos.deactivate()
    assert chaos.deterministic_seed() is None


def test_retry_policy_defaults_jitter_seed_from_chaos_plan(monkeypatch):
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "0")
    chaos.activate([{"site": "cluster.send", "action": "drop"}])
    def schedule():
        p = pw.RetryPolicy(
            first_delay_ms=10, backoff_factor=2, jitter_ms=100, max_retries=5
        )
        return [p.wait_duration_before_retry() for _ in range(5)]

    assert schedule() == schedule()  # chaos runs replay identically
    chaos.deactivate()


# ----------------------------------------------- durable generation token


def test_cluster_generation_bump_is_durable(tmp_path):
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))
    cfg = pw.persistence.Config.simple_config(backend)
    p = eng_persist.EnginePersistence(cfg)
    assert p.cluster_generation() == 0
    assert p.bump_cluster_generation() == 1
    assert p.bump_cluster_generation() == 2
    p.close()
    p2 = eng_persist.EnginePersistence(cfg)
    assert p2.cluster_generation() == 2
    p2.close()


def test_cluster_generation_visible_from_worker_namespace(
    tmp_path, monkeypatch
):
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))
    cfg = pw.persistence.Config.simple_config(backend)
    p0 = eng_persist.EnginePersistence(cfg)
    p0.bump_cluster_generation()
    p0.close()
    # a worker process namespaces its own logs under proc-<pid> but must
    # read the coordinator's generation from the base namespace
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "1")
    pw1 = eng_persist.EnginePersistence(cfg)
    assert pw1.cluster_generation() == 1
    pw1.close()


# ------------------------------------------------- metrics plane gating


def test_metrics_cluster_lines_gated_on_activity():
    from pathway_tpu.internals.http_monitoring import MonitoringHttpServer

    assert MonitoringHttpServer._cluster_lines() == []
    CLUSTER_METRICS.record_barrier(generation=1)
    CLUSTER_HEALTH.mark_down([3])
    lines = "\n".join(MonitoringHttpServer._cluster_lines())
    assert "pathway_cluster_barriers_total 1" in lines
    assert "pathway_cluster_generation 1" in lines
    assert 'pathway_cluster_shard_down{shard="3"} 1' in lines


def test_metrics_cluster_lines_render_per_process_counters():
    from pathway_tpu.internals.http_monitoring import MonitoringHttpServer

    CLUSTER_METRICS.record_lease_expired(1)
    CLUSTER_METRICS.record_partial_restart(1)
    CLUSTER_METRICS.record_fenced_write(2)
    CLUSTER_METRICS.record_fenced_write(2)
    lines = "\n".join(MonitoringHttpServer._cluster_lines())
    # lease expiries keep the per-process split; the rest are totals
    assert 'pathway_cluster_lease_expiries_total{process="1"} 1' in lines
    assert "pathway_cluster_partial_restarts_total 1" in lines
    assert "pathway_cluster_fenced_writes_total 2" in lines


# -------------------------------------------------- dump retention (KEEP)


def test_flight_recorder_keep_prunes_old_dumps(tmp_path, monkeypatch):
    monkeypatch.setenv("PATHWAY_FLIGHT_RECORDER_KEEP", "2")
    fr = FlightRecorder(size=16, enabled=True)
    d = str(tmp_path / "bb")
    paths = []
    for i in range(5):
        fr.record("epoch.begin", t=i)
        paths.append(fr.dump(f"r{i}", directory=d))
    remaining = list_dumps(d)
    assert len(remaining) == 2
    assert remaining == sorted(paths[-2:])


def test_flight_recorder_keep_zero_keeps_everything(tmp_path, monkeypatch):
    monkeypatch.delenv("PATHWAY_FLIGHT_RECORDER_KEEP", raising=False)
    fr = FlightRecorder(size=16, enabled=True)
    d = str(tmp_path / "bb")
    for i in range(4):
        fr.record("epoch.begin", t=i)
        fr.dump(f"r{i}", directory=d)
    assert len(list_dumps(d)) == 4
