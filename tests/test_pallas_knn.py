"""Fused pallas KNN top-k kernel vs the unfused XLA reference.

Runs in interpret mode on CPU (tests/conftest.py); on a real TPU the
same kernel lowers through Mosaic (verified there: exact index
agreement, ~6x faster than unfused at 1M docs)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pathway_tpu.ops.pallas_knn import NEG, knn_topk


def _ref(q, d, k, bias=None, factor=1.0):
    s = factor * (q @ d.T)
    if bias is not None:
        s = s + bias[None, :]
    return jax.lax.top_k(jnp.asarray(s), k)


def test_dot_topk_matches_xla():
    rng = np.random.default_rng(0)
    q = rng.normal(size=(13, 32)).astype(np.float32)
    d = rng.normal(size=(700, 32)).astype(np.float32)
    vals, idx = knn_topk(q, d, k=5, block_q=8, block_n=256, interpret=True)
    rv, ri = _ref(jnp.asarray(q), jnp.asarray(d), 5)
    np.testing.assert_allclose(np.asarray(vals), np.asarray(rv), rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(ri))


def test_bias_masks_invalid_slots():
    rng = np.random.default_rng(1)
    q = rng.normal(size=(4, 16)).astype(np.float32)
    d = rng.normal(size=(100, 16)).astype(np.float32)
    valid = np.ones(100, bool)
    valid[::3] = False  # a third of the slots are dead
    bias = np.where(valid, 0.0, NEG).astype(np.float32)
    vals, idx = knn_topk(q, d, k=8, bias=bias, block_q=8, block_n=64, interpret=True)
    assert not set(np.asarray(idx).ravel().tolist()) & set(np.nonzero(~valid)[0].tolist())


def test_l2_bias_and_factor():
    rng = np.random.default_rng(2)
    q = rng.normal(size=(6, 24)).astype(np.float32)
    d = rng.normal(size=(300, 24)).astype(np.float32)
    bias = -(d * d).sum(axis=1).astype(np.float32)
    vals, idx = knn_topk(q, d, k=4, bias=bias, factor=2.0, block_q=8, block_n=128, interpret=True)
    # nearest by L2 == argmax of 2q.d - |d|^2
    full = 2.0 * (q @ d.T) - (d * d).sum(axis=1)[None, :]
    ri = np.argsort(-full, axis=1)[:, :4]
    np.testing.assert_array_equal(np.asarray(idx), ri)


def test_padding_never_surfaces():
    rng = np.random.default_rng(3)
    q = rng.normal(size=(3, 8)).astype(np.float32)
    d = -np.abs(rng.normal(size=(37, 8))).astype(np.float32)  # all-negative scores likely
    vals, idx = knn_topk(q, d, k=40, block_q=8, block_n=64, interpret=True)
    got = np.asarray(idx)
    assert got.max() < 37  # padded rows (zero vectors, score 0) excluded
    # only 37 real docs: the tail of k=40 is sentinel
    assert (np.asarray(vals)[:, 37:] <= NEG / 2).all()


def test_device_index_parity_with_pallas_formula():
    """DeviceKnnIndex result parity: the pallas path computes the same
    (key, score) lists as the unfused path (CPU uses unfused; this
    pins the shared formula via _pallas_topk in interpret mode)."""
    from pathway_tpu.ops import knn as knn_mod

    rng = np.random.default_rng(4)
    idx = knn_mod.DeviceKnnIndex(dim=16, metric="cos")
    for i in range(50):
        idx.add(f"k{i}", rng.normal(size=16).astype(np.float32))
    idx.remove("k7")
    q = rng.normal(size=(2, 16)).astype(np.float32)
    expected = idx.search_batch(q, 5)

    idx._sync()
    qn = q / np.linalg.norm(q, axis=1, keepdims=True)
    vals, ids = knn_mod._pallas_topk("cos", idx._dev_matrix, idx._dev_valid, qn, 8)
    got = []
    for row_v, row_i in zip(np.asarray(vals), np.asarray(ids)):
        out = []
        for s, slot in zip(row_v, row_i):
            if s <= NEG / 2 or idx._keys[slot] is None:
                continue
            out.append((idx._keys[slot], float(s)))
            if len(out) == 5:
                break
        got.append(out)
    for e_row, g_row in zip(expected, got):
        assert [k for k, _ in e_row] == [k for k, _ in g_row]
        np.testing.assert_allclose(
            [s for _, s in e_row], [s for _, s in g_row], rtol=1e-5
        )


def test_large_k_fori_merge_matches_xla():
    """k > 64 takes the fori_loop extraction merge (flat compile time)."""
    rng = np.random.default_rng(5)
    q = rng.normal(size=(5, 16)).astype(np.float32)
    d = rng.normal(size=(900, 16)).astype(np.float32)
    vals, idx = knn_topk(q, d, k=128, block_q=8, block_n=256, interpret=True)
    rv, ri = _ref(jnp.asarray(q), jnp.asarray(d), 128)
    np.testing.assert_allclose(np.asarray(vals), np.asarray(rv), rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(ri))


def test_sharded_kernel_cross_device_merge():
    """Shard-local kernels + ICI candidate merge == global top-k
    (virtual 8-device CPU mesh, kernel in interpret mode)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from pathway_tpu.ops.pallas_knn import knn_topk_sharded

    devices = np.array(jax.devices()[:8])
    mesh = Mesh(devices, ("data",))
    rng = np.random.default_rng(6)
    q = rng.normal(size=(7, 32)).astype(np.float32)
    d = rng.normal(size=(1024, 32)).astype(np.float32)
    valid = np.ones(1024, bool)
    valid[5] = valid[700] = False
    bias = np.where(valid, 0.0, NEG).astype(np.float32)
    dd = jax.device_put(d, NamedSharding(mesh, P("data", None)))
    bb = jax.device_put(bias, NamedSharding(mesh, P("data")))
    vals, idx = knn_topk_sharded(
        jnp.asarray(q), dd, bb, k=9, mesh=mesh, block_q=8, block_n=64,
        interpret=True,
    )
    rv, ri = _ref(jnp.asarray(q), jnp.asarray(d), 9, bias=jnp.asarray(bias))
    np.testing.assert_allclose(np.asarray(vals), np.asarray(rv), rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(ri))


def test_device_index_sharded_pallas_parity(monkeypatch):
    """DeviceKnnIndex on a mesh with the pallas path forced: results
    match the unsharded unfused reference."""
    from jax.sharding import Mesh

    from pathway_tpu.ops import knn as knn_mod

    rng = np.random.default_rng(7)
    vecs = [rng.normal(size=24).astype(np.float32) for _ in range(200)]
    q = rng.normal(size=(3, 24)).astype(np.float32)

    ref_idx = knn_mod.DeviceKnnIndex(dim=24, metric="l2")
    for i, v in enumerate(vecs):
        ref_idx.add(f"k{i}", v)
    ref_idx.remove("k11")
    expected = ref_idx.search_batch(q, 6)

    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    monkeypatch.setenv("PATHWAY_TPU_FORCE_PALLAS", "1")
    # interpret mode on CPU: knn_topk auto-interprets off-TPU
    sh_idx = knn_mod.DeviceKnnIndex(dim=24, metric="l2", mesh=mesh)
    for i, v in enumerate(vecs):
        sh_idx.add(f"k{i}", v)
    sh_idx.remove("k11")
    got = sh_idx.search_batch(q, 6)
    for e_row, g_row in zip(expected, got):
        assert [k for k, _ in e_row] == [k for k, _ in g_row]
        np.testing.assert_allclose(
            [s for _, s in e_row], [s for _, s in g_row], rtol=1e-4
        )
