"""CLIP image staging: the pack-ahead loop actually overlaps.

Regression for the serialized staging loop in ``models/clip.py``: the
old ``_image_batches`` packed batch i+1 only *after* dispatching batch
i, so on a synchronous backend (CPU jit) host packing and device
compute strictly alternated and nothing overlapped. The rewritten loop
packs batch i+1 between stage(i) — the non-blocking device put into the
donated ring — and dispatch(i). The ``_pipeline_events`` hook records
the loop's event order so the ordering is assertable without a real
device clock, and the DeviceRing counters pin the donation behavior.
"""

from __future__ import annotations

import numpy as np
import pytest

from pathway_tpu.models.clip import CLIPConfig, CLIPEncoder


@pytest.fixture(scope="module")
def enc():
    cfg = CLIPConfig(
        image_size=32, patch_size=8, vision_layers=1, vision_width=64,
        vision_heads=2, text_layers=1, text_width=64, text_heads=2,
        embed_dim=32,
    )
    return CLIPEncoder(cfg, max_batch=8)


def _images(n: int) -> np.ndarray:
    rng = np.random.default_rng(7)
    return (rng.random((n, 32, 32, 3)) * 255).astype(np.uint8)


def _events(enc, imgs) -> tuple[list[str], np.ndarray]:
    ev: list[str] = []
    enc._pipeline_events = ev
    try:
        out = enc.encode_image(imgs)
    finally:
        enc._pipeline_events = None
    return ev, out


def test_pack_ahead_precedes_dispatch(enc):
    """The event-order contract: pack(i+1) fires BEFORE dispatch(i) —
    i.e. host prep of the next batch is already done when the current
    batch's compute is submitted, even when the jit call itself blocks
    (CPU backend). The old loop emitted dispatch:0 before pack:1."""
    ev, _ = _events(enc, _images(20))  # max_batch=8 -> 3 batches
    assert ev.index("pack:1") < ev.index("dispatch:0"), ev
    assert ev.index("pack:2") < ev.index("dispatch:1"), ev
    # and each batch is staged (device put) before its own dispatch
    for i in range(3):
        assert ev.index(f"stage:{i}") < ev.index(f"dispatch:{i}"), ev
    # the single sync point stays at the end: every dispatch happens
    # before the first result is consumed
    assert ev.index("dispatch:2") < ev.index("complete:0"), ev


def test_single_batch_has_no_lookahead(enc):
    ev, out = _events(enc, _images(4))
    assert out.shape == (4, 32)
    assert "pack:1" not in ev
    assert ev.index("pack:0") < ev.index("stage:0") < ev.index("dispatch:0")


def test_staged_output_matches_unstaged_reference(enc):
    """Byte-identical: the ring-staged loop computes exactly what a
    direct pack+forward of each batch computes."""
    imgs = _images(20)
    got = enc.encode_image(imgs)
    ref = []
    for lo in range(0, len(imgs), 8):
        n, flat, fwd = enc._pack_image_batch(imgs[lo : lo + 8])
        ref.append(np.asarray(fwd(enc.vparams, flat))[:n])
    assert np.array_equal(got, np.concatenate(ref))


def test_repeat_encode_is_deterministic(enc):
    imgs = _images(12)
    a = enc.encode_image(imgs)
    b = enc.encode_image(imgs)
    assert np.array_equal(a, b)


def test_ring_donates_across_batches(enc):
    enc.encode_image(_images(24))  # 3 batches through the 2-deep ring
    ring = enc._ring
    assert ring is not None
    assert ring.staged >= 3
    # wrapping a 2-deep ring with >= 3 stages must have donated at
    # least one prior generation back to the device
    assert ring.donated >= 1
    # nothing left in flight after the final sync point
    assert ring.in_flight() == 0
