"""Core Table DSL tests (modeled on reference python/pathway/tests/test_common.py)."""

import pytest

import pathway_tpu as pw
from .utils import (
    T,
    assert_table_equality,
    assert_table_equality_wo_index,
    assert_stream_equality,
)


def test_select_arithmetic():
    t = T(
        """
        | a | b
      1 | 1 | 2
      2 | 3 | 4
    """
    )
    r = t.select(s=t.a + t.b, d=t.b - t.a, p=t.a * t.b, q=t.b / t.a, m=t.b % t.a)
    expected = T(
        """
        | s | d | p | q   | m
      1 | 3 | 1 | 2 | 2.0 | 0
      2 | 7 | 1 | 12| 1.333333333333333333 | 1
    """
    )
    s, names = None, None
    from .utils import _capture_state

    st, nm = _capture_state(r)
    rows = sorted(st.values())
    assert nm == ["s", "d", "p", "q", "m"]
    assert rows[0][:3] == (3, 1, 2) and abs(rows[0][3] - 2.0) < 1e-9
    assert rows[1][:3] == (7, 1, 12) and abs(rows[1][3] - 4 / 3) < 1e-9


def test_select_this_and_kwargs():
    t = T(
        """
        | a | b
      1 | 1 | 2
    """
    )
    r = t.select(pw.this.a, c=pw.this.b * 10)
    assert r.column_names() == ["a", "c"]
    from .utils import _capture_state

    st, _ = _capture_state(r)
    assert list(st.values()) == [(1, 20)]


def test_filter():
    t = T(
        """
        | v
      1 | 1
      2 | 2
      3 | 3
      4 | 4
    """
    )
    r = t.filter(t.v % 2 == 0).select(t.v)
    expected = T(
        """
        | v
      2 | 2
      4 | 4
    """
    )
    assert_table_equality(r, expected)


def test_with_columns_rename_without():
    t = T(
        """
        | a | b
      1 | 1 | 2
    """
    )
    r = t.with_columns(c=pw.this.a + pw.this.b)
    assert r.column_names() == ["a", "b", "c"]
    r2 = r.rename_columns(total=pw.this.c).without("a")
    assert set(r2.column_names()) == {"b", "total"}


def test_concat():
    t1 = T(
        """
        | v
      1 | 1
    """
    )
    t2 = T(
        """
        | v
      2 | 2
    """
    )
    r = t1.concat(t2)
    expected = T(
        """
        | v
      1 | 1
      2 | 2
    """
    )
    assert_table_equality(r, expected)


def test_concat_duplicate_keys_raises():
    t1 = T(
        """
        | v
      1 | 1
    """
    )
    t2 = T(
        """
        | v
      1 | 2
    """
    )
    from pathway_tpu.engine.dataflow import EngineError

    with pytest.raises(EngineError):
        pw.debug.compute_and_print(t1.concat(t2))


def test_concat_reindex():
    t1 = T(
        """
        | v
      1 | 1
    """
    )
    t2 = T(
        """
        | v
      1 | 2
    """
    )
    r = t1.concat_reindex(t2)
    assert_table_equality_wo_index(
        r,
        T(
            """
        | v
      7 | 1
      8 | 2
    """
        ),
    )


def test_update_rows():
    old = T(
        """
      | pet
    1 | dog
    2 | cat
    """
    )
    new = T(
        """
      | pet
    2 | tiger
    3 | fish
    """
    )
    r = old.update_rows(new)
    assert_table_equality_wo_index(
        r,
        T(
            """
      | pet
    1 | dog
    2 | tiger
    3 | fish
    """
        ),
    )


def test_update_cells():
    base = T(
        """
      | a | b
    1 | 1 | x
    2 | 2 | y
    """
    )
    patch = T(
        """
      | b
    2 | z
    """
    )
    r = base.update_cells(patch)
    assert_table_equality_wo_index(
        r,
        T(
            """
      | a | b
    1 | 1 | x
    2 | 2 | z
    """
        ),
    )


def test_intersect_difference():
    t1 = T(
        """
      | v
    1 | 1
    2 | 2
    3 | 3
    """
    )
    t2 = T(
        """
      | w
    2 | 0
    3 | 0
    """
    )
    assert_table_equality_wo_index(
        t1.intersect(t2),
        T(
            """
      | v
    2 | 2
    3 | 3
    """
        ),
    )
    assert_table_equality_wo_index(
        t1.difference(t2),
        T(
            """
      | v
    1 | 1
    """
        ),
    )


def test_ix_ref():
    t = T(
        """
      | name | v
    1 | a    | 10
    2 | b    | 20
    """
    )
    keyed = t.with_id_from(t.name)
    r = keyed.select(keyed.name, other=keyed.ix_ref("a").v)
    from .utils import _capture_state

    st, _ = _capture_state(r)
    assert sorted(st.values()) == [("a", 10), ("b", 10)]


def test_groupby_reducers():
    t = T(
        """
      | g | v
    1 | a | 1
    2 | a | 3
    3 | b | 5
    4 | a | 2
    """
    )
    r = t.groupby(t.g).reduce(
        t.g,
        cnt=pw.reducers.count(),
        s=pw.reducers.sum(t.v),
        mn=pw.reducers.min(t.v),
        mx=pw.reducers.max(t.v),
        av=pw.reducers.avg(t.v),
        st=pw.reducers.sorted_tuple(t.v),
    )
    from .utils import _capture_state

    st, names = _capture_state(r)
    rows = {row[0]: row for row in st.values()}
    assert rows["a"] == ("a", 3, 6, 1, 3, 2.0, (1, 2, 3))
    assert rows["b"] == ("b", 1, 5, 5, 5, 5.0, (5,))


def test_groupby_argmin_argmax():
    t = T(
        """
      | g | v
    1 | a | 1
    2 | a | 3
    """
    )
    r = t.groupby(t.g).reduce(
        lo=t.ix(pw.reducers.argmin(t.v)).v,
        hi=t.ix(pw.reducers.argmax(t.v)).v,
    )
    from .utils import _capture_state

    st, _ = _capture_state(r)
    assert list(st.values()) == [(1, 3)]


def test_global_reduce():
    t = T(
        """
      | v
    1 | 1
    2 | 2
    3 | 3
    """
    )
    r = t.reduce(s=pw.reducers.sum(t.v))
    from .utils import _capture_state

    st, _ = _capture_state(r)
    assert list(st.values()) == [(6,)]


def test_join_inner_left():
    t1 = T(
        """
      | a | k
    1 | 1 | x
    2 | 2 | y
    3 | 3 | w
    """
    )
    t2 = T(
        """
      | k | b
    1 | x | 10
    2 | y | 20
    3 | z | 30
    """
    )
    inner = t1.join(t2, t1.k == t2.k).select(t1.a, t2.b)
    assert_table_equality_wo_index(
        inner,
        T(
            """
      | a | b
    1 | 1 | 10
    2 | 2 | 20
    """
        ),
    )
    left = t1.join_left(t2, t1.k == t2.k).select(t1.a, b=pw.coalesce(t2.b, 0))
    assert_table_equality_wo_index(
        left,
        T(
            """
      | a | b
    1 | 1 | 10
    2 | 2 | 20
    3 | 3 | 0
    """
        ),
    )


def test_join_expressions_in_condition():
    t1 = T(
        """
      | a
    1 | 1
    2 | 2
    """
    )
    t2 = T(
        """
      | b
    1 | 2
    2 | 4
    """
    )
    r = t1.join(t2, t1.a * 2 == t2.b).select(t1.a, t2.b)
    assert_table_equality_wo_index(
        r,
        T(
            """
      | a | b
    1 | 1 | 2
    2 | 2 | 4
    """
        ),
    )


def test_flatten():
    t = T(
        """
      | w
    1 | 'a b'
    """
    )
    r = t.select(
        parts=pw.apply_with_type(lambda s: tuple(s.split()), tuple, t.w)
    ).flatten(pw.this.parts)
    assert_table_equality_wo_index(
        r,
        T(
            """
      | parts
    1 | a
    2 | b
    """
        ),
    )


def test_sort_prev_next():
    t = T(
        """
      | v
    1 | 30
    2 | 10
    3 | 20
    """
    )
    s = t.sort(t.v)
    r = t.select(
        t.v,
        p=t.ix(s.prev, optional=True).v,
        n=t.ix(s.next, optional=True).v,
    )
    from .utils import _capture_state

    st, _ = _capture_state(r)
    assert sorted(st.values()) == [(10, None, 20), (20, 10, 30), (30, 20, None)]


def test_apply_and_udf():
    t = T(
        """
      | v
    1 | 1
    2 | 2
    """
    )

    @pw.udf
    def sq(x: int) -> int:
        return x * x

    r = t.select(a=pw.apply(lambda x: x + 1, t.v), b=sq(t.v))
    assert_table_equality_wo_index(
        r,
        T(
            """
      | a | b
    1 | 2 | 1
    2 | 3 | 4
    """
        ),
    )


def test_async_udf():
    t = T(
        """
      | v
    1 | 1
    2 | 2
    """
    )

    @pw.udf
    async def double(x: int) -> int:
        import asyncio

        await asyncio.sleep(0.001)
        return x * 2

    r = t.select(d=double(t.v))
    assert_table_equality_wo_index(
        r,
        T(
            """
      | d
    1 | 2
    2 | 4
    """
        ),
    )


def test_if_else_coalesce_require():
    t = T(
        """
      | a | b
    1 | 1 |
    2 | 5 | 7
    """
    )
    r = t.select(
        x=pw.if_else(t.a > 2, t.a, 0),
        y=pw.coalesce(t.b, -1),
        z=pw.require(t.a, t.b),
    )
    from .utils import _capture_state

    st, _ = _capture_state(r)
    assert sorted(st.values(), key=repr) == sorted(
        [(0, -1, None), (5, 7, 5)], key=repr
    )


def test_update_stream_semantics():
    s = T(
        """
        | v | __time__ | __diff__
      1 | 1 | 2        | 1
      2 | 2 | 2        | 1
      1 | 1 | 4        | -1
    """
    )
    tot = s.reduce(total=pw.reducers.sum(s.v))
    stream, _ = pw.debug.table_to_stream(tot)
    seq = [(row[0], time, diff) for _, row, time, diff in stream]
    assert seq == [(3, 2, 1), (3, 4, -1), (2, 4, 1)]


def test_deduplicate():
    t = T(
        """
        | v | __time__
      1 | 1 | 2
      2 | 5 | 4
      3 | 3 | 6
      4 | 8 | 8
    """
    )
    r = t.deduplicate(value=pw.this.v, acceptor=lambda new, old: old is None or new > old)
    from .utils import _capture_state

    st, _ = _capture_state(r)
    assert sorted(st.values()) == [(8,)]


def test_cast_and_namespaces():
    t = T(
        """
      | s     | f
    1 | '12'  | 2.7
    """
    )
    r = t.select(
        i=t.s.str.parse_int(),
        up=pw.apply_with_type(str.upper, str, t.s),
        fl=t.f.num.floor(),
        ln=t.s.str.len(),
    )
    from .utils import _capture_state

    st, _ = _capture_state(r)
    assert list(st.values()) == [(12, "12", 2, 2)]


def test_groupby_incremental_updates():
    s = T(
        """
        | g | v | __time__ | __diff__
      1 | a | 1 | 2        | 1
      2 | a | 2 | 4        | 1
      3 | b | 5 | 4        | 1
      2 | a | 2 | 6        | -1
    """
    )
    r = s.groupby(s.g).reduce(s.g, total=pw.reducers.sum(s.v))
    from .utils import _capture_state

    st, _ = _capture_state(r)
    assert sorted(st.values()) == [("a", 1), ("b", 5)]
