"""UDF system: executors, caches, retries, timeouts.

Mirrors /root/reference/python/pathway/tests coverage of internals/udfs/
(executors.py, caches.py, retries.py)."""

from __future__ import annotations

import asyncio
import time

import pytest

import pathway_tpu as pw
from .utils import T, run_table


def test_sync_udf_with_kwargs_and_defaults():
    @pw.udf
    def scale(x: int, factor: int = 10) -> int:
        return x * factor

    t = T(
        """
          | x
        1 | 1
        2 | 2
        """
    )
    res = t.select(y=scale(pw.this.x))
    assert sorted(r[0] for r in run_table(res).values()) == [10, 20]


def test_async_udf_executor():
    calls = []

    @pw.udf(executor=pw.udfs.async_executor())
    async def slow_double(x: int) -> int:
        calls.append(x)
        await asyncio.sleep(0.01)
        return x * 2

    t = T(
        """
          | x
        1 | 3
        2 | 4
        """
    )
    res = t.select(y=slow_double(pw.this.x))
    assert sorted(r[0] for r in run_table(res).values()) == [6, 8]
    assert sorted(calls) == [3, 4]


def test_async_udf_retries():
    attempts = {"n": 0}

    @pw.udf(
        executor=pw.udfs.async_executor(
            retry_strategy=pw.udfs.FixedDelayRetryStrategy(max_retries=5, delay_ms=1)
        )
    )
    async def flaky(x: int) -> int:
        attempts["n"] += 1
        if attempts["n"] < 3:
            raise RuntimeError("transient")
        return x

    t = T(
        """
          | x
        1 | 7
        """
    )
    res = t.select(y=flaky(pw.this.x))
    assert [r[0] for r in run_table(res).values()] == [7]
    assert attempts["n"] == 3


def test_async_udf_timeout_produces_error_value():
    @pw.udf(executor=pw.udfs.async_executor(timeout=0.01))
    async def hang(x: int) -> int:
        await asyncio.sleep(5)
        return x

    t = T(
        """
          | x
        1 | 1
        """
    )
    res = t.select(y=hang(pw.this.x))
    from pathway_tpu.internals.graph_runner import GraphRunner

    runner = GraphRunner()
    runner.engine.terminate_on_error = False
    cap, _ = runner.capture(res)
    runner.run()
    from pathway_tpu.engine.value import Error

    (row,) = cap.state.values()
    assert isinstance(row[0], Error)
    pw.clear_graph()


def test_in_memory_cache_deduplicates_calls():
    calls = []

    @pw.udf(cache_strategy=pw.udfs.InMemoryCache())
    async def embed(x: str) -> str:
        calls.append(x)
        return x.upper()

    t = T(
        """
          | s
        1 | aa
        2 | aa
        3 | bb
        """
    )
    res = t.select(y=embed(pw.this.s))
    assert sorted(r[0] for r in run_table(res).values()) == ["AA", "AA", "BB"]
    assert sorted(calls) == ["aa", "bb"]  # second "aa" served from cache


def test_disk_cache_persists_across_runs(tmp_path, monkeypatch):
    monkeypatch.setenv("PATHWAY_PERSISTENT_STORAGE", str(tmp_path))
    calls = []

    def make_udf():
        @pw.udf(cache_strategy=pw.udfs.DiskCache(name="testcache"))
        async def embed(x: str) -> str:
            calls.append(x)
            return x + "!"

        return embed

    def run_once():
        embed = make_udf()
        t = T(
            """
              | s
            1 | q
            """
        )
        res = t.select(y=embed(pw.this.s))
        out = [r[0] for r in run_table(res).values()]
        pw.clear_graph()
        return out

    assert run_once() == ["q!"]
    assert run_once() == ["q!"]
    assert calls == ["q"]  # second run hit the disk cache


def test_batch_executor_receives_lists():
    seen = []

    @pw.udf(executor=pw.udfs.batch_executor(max_batch_size=8))
    def embed_many(xs: list[int]) -> list[int]:
        seen.append(list(xs))
        return [x + 1 for x in xs]

    t = T(
        """
          | x
        1 | 1
        2 | 2
        3 | 3
        """
    )
    res = t.select(y=embed_many(pw.this.x))
    assert sorted(r[0] for r in run_table(res).values()) == [2, 3, 4]
    assert len(seen) == 1 and sorted(seen[0]) == [1, 2, 3]  # one batch call


def test_udf_propagate_none():
    @pw.udf(propagate_none=True)
    def double(x: int) -> int:
        return x * 2

    t = T(
        """
          | x
        1 | 5
        2 |
        """
    )
    res = t.select(y=double(pw.this.x))
    assert sorted((r[0] for r in run_table(res).values()), key=repr) == [10, None]
