"""Collaborative CPU<->TPU host-ingest stage (pathway_tpu/ingest/).

The stage's contract is the one ``pipeline_depth`` already established:
parallelism may reorder *work* but never *commits* — N prep workers
feed a single ordered committer, so every output is byte-identical to
the strict inline path at any worker count, under chaos at
``ingest.worker`` (slow or dying workers), and with persistence
enabled. These tests pin that contract plus the observability plane:
``pathway_ingest_*`` metrics, ``ingest.enqueue/dequeue/autoscale``
flight events, queue-depth autoscaling, and the mixed-ASCII native
tokenizer split.
"""

from __future__ import annotations

import json
import time

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.ingest import (
    INGEST_METRICS,
    HostIngestStage,
    configure_stage,
    get_stage,
    route_by_length,
    shutdown_stage,
)
from pathway_tpu.internals import flight_recorder as fr
from pathway_tpu.io._connector import input_table_from_reader
from pathway_tpu.resilience import chaos


@pytest.fixture(autouse=True)
def _clean_stage():
    shutdown_stage()
    INGEST_METRICS.reset()
    yield
    shutdown_stage()
    INGEST_METRICS.reset()
    chaos.deactivate()


@pytest.fixture
def recorder(monkeypatch):
    rec = fr.FlightRecorder(size=512, enabled=True)
    monkeypatch.setattr(fr, "RECORDER", rec)
    return rec


def _kinds(rec):
    return [e["kind"] for e in rec.events()]


# ---------------------------------------------------------------------------
# stage core: ordering, chaos, autoscale
# ---------------------------------------------------------------------------


def test_map_ordered_preserves_submission_order():
    st = HostIngestStage(4)
    try:
        out = list(st.map_ordered(lambda x: x * x, range(200)))
    finally:
        st.shutdown()
    assert out == [x * x for x in range(200)]
    snap = INGEST_METRICS.snapshot()
    assert snap["committed"] == 200
    assert snap["host_workers"] == 4
    assert snap["enqueued"] == snap["dequeued"] == 200


def test_result_error_propagates_at_commit():
    st = HostIngestStage(2)

    def boom(x):
        if x == 3:
            raise ValueError("task 3 failed")
        return x

    try:
        with pytest.raises(ValueError, match="task 3 failed"):
            list(st.map_ordered(boom, range(6)))
    finally:
        st.shutdown()


def test_chaos_slow_worker_degrades_but_stays_ordered(recorder):
    """A delayed worker (chaos ``ingest.worker`` delay) slows the stage
    down but results still commit in submission order, losslessly."""
    chaos.activate(
        [{"site": "ingest.worker", "action": "delay", "delay_s": 0.02, "repeat": True}]
    )
    st = HostIngestStage(3)
    try:
        out = list(st.map_ordered(lambda x: x + 100, range(24)))
    finally:
        st.shutdown()
        chaos.deactivate()
    assert out == [x + 100 for x in range(24)]
    assert INGEST_METRICS.snapshot()["committed"] == 24


def test_chaos_dying_worker_never_drops_or_reorders(recorder):
    """``ingest.worker`` raise kills workers mid-stream; the committer
    re-executes their untouched tasks inline — every row survives, in
    order, and the retry is visible on the metrics."""
    chaos.activate(
        [{"site": "ingest.worker", "action": "raise", "repeat": True}]
    )
    st = HostIngestStage(2)
    try:
        out = list(st.map_ordered(lambda x: x * 2, range(40)))
    finally:
        st.shutdown()
        chaos.deactivate()
    assert out == [x * 2 for x in range(40)], "dying workers dropped/reordered rows"
    snap = INGEST_METRICS.snapshot()
    assert snap["committed"] == 40
    assert snap["retried"] >= 1, "no chaos-killed task was ever retried"


def test_autoscale_grows_on_backlog_and_shrinks_on_idle(recorder):
    st = HostIngestStage(1, autoscale=True, min_workers=1, max_workers=4, max_queue=64)
    try:
        # slow tasks pile the queue up past the per-worker watermark
        out = list(st.map_ordered(lambda x: (time.sleep(0.005), x)[1], range(48)))
        assert out == list(range(48))
        grown = st.workers
        assert grown > 1, "backlog never grew the pool"
        # idle observations shrink back toward min_workers
        for _ in range(40):
            st.submit(lambda: None).result()
            time.sleep(0.002)
        assert st.workers < grown, "idle never shrank the pool"
    finally:
        st.shutdown()
    snap = INGEST_METRICS.snapshot()
    assert snap["scale_up"] >= 1 and snap["scale_down"] >= 1
    assert "ingest.autoscale" in _kinds(recorder)


def test_attribution_feed_grows_host_bound_pool():
    st = HostIngestStage(1, autoscale=True, max_workers=4)
    try:
        st.observe_attribution(host_prep_s=1.0, device_wait_s=0.01)
        assert st.workers == 2
    finally:
        st.shutdown()


def test_route_by_length_splits_and_counts():
    short, long = route_by_length([3, 50, 4, 120, 7], threshold=32)
    assert short == [0, 2, 4] and long == [1, 3]
    snap = INGEST_METRICS.snapshot()
    assert snap["routed_short"] == 3 and snap["routed_long"] == 2


# ---------------------------------------------------------------------------
# flight events + blackbox render
# ---------------------------------------------------------------------------


def test_flight_events_enqueue_dequeue(recorder):
    st = HostIngestStage(2)
    try:
        list(st.map_ordered(lambda x: x, range(8)))
    finally:
        st.shutdown()
    kinds = _kinds(recorder)
    assert "ingest.enqueue" in kinds and "ingest.dequeue" in kinds


def test_ingest_events_visible_in_blackbox_show(tmp_path, recorder):
    from click.testing import CliRunner

    from pathway_tpu.cli import cli

    st = HostIngestStage(1, autoscale=True, max_workers=2, max_queue=4)
    try:
        list(st.map_ordered(lambda x: (time.sleep(0.005), x)[1], range(24)))
    finally:
        st.shutdown()
    path = recorder.dump("test", directory=str(tmp_path))
    assert path is not None
    res = CliRunner().invoke(cli, ["blackbox", "show", path])
    assert res.exit_code == 0, res.output
    assert "ingest.enqueue" in res.output
    assert "ingest.dequeue" in res.output


# ---------------------------------------------------------------------------
# env / pw.run wiring
# ---------------------------------------------------------------------------


def test_get_stage_honors_env(monkeypatch):
    shutdown_stage()
    monkeypatch.delenv("PATHWAY_INGEST_WORKERS", raising=False)
    assert get_stage() is None
    monkeypatch.setenv("PATHWAY_INGEST_WORKERS", "3")
    st = get_stage()
    assert st is not None and st.workers == 3
    shutdown_stage()


def test_configure_stage_zero_disables():
    assert configure_stage(2) is not None
    assert configure_stage(0) is None
    assert get_stage() is None


def test_run_records_ingest_workers_in_run_context(monkeypatch):
    monkeypatch.setenv("PATHWAY_ANALYZE_ONLY", "1")
    t = pw.debug.table_from_markdown(
        """
        | x
      1 | 1
        """
    )
    pw.io.null.write(t)
    assert pw.run(ingest_workers=4) is None
    from pathway_tpu.internals.parse_graph import G

    assert G.run_context["ingest_workers"] == 4
    pw.clear_graph()


# ---------------------------------------------------------------------------
# tokenizer: mixed-ASCII split + collaborative shards
# ---------------------------------------------------------------------------


def test_tokenizer_mixed_ascii_batch_keeps_native_path():
    """The old gate abandoned C++ for the whole batch on one non-ASCII
    text; now only the stragglers detour through Python, and every row
    still equals the per-text reference encoding."""
    from pathway_tpu import native
    from pathway_tpu.models.tokenizer import WordPieceTokenizer

    if not native.is_available():
        pytest.skip("native library unavailable")
    tok = WordPieceTokenizer()
    texts = ["plain ascii text"] * 5 + ["café au lait", "naïve übermut"] + [
        f"more ascii {i}" for i in range(20)
    ]
    m = tok.batch_encode_matrix(texts, 32)
    assert m is not None, "mixed batch abandoned the native path entirely"
    ids, lens = m
    for i, t in enumerate(texts):
        ref = tok.encode(t, max_len=32)
        assert lens[i] == len(ref)
        assert ids[i, : lens[i]].tolist() == ref
        assert (ids[i, lens[i] :] == tok.pad_id).all()


def test_tokenizer_staged_shards_byte_identical():
    from pathway_tpu import native
    from pathway_tpu.models.tokenizer import WordPieceTokenizer

    if not native.is_available():
        pytest.skip("native library unavailable")
    tok = WordPieceTokenizer()
    texts = [f"document {i} with {'extra words ' * (i % 5)}content" for i in range(300)]
    ref_ids, ref_lens = tok.batch_encode_matrix(texts, 48)
    st = HostIngestStage(4)
    try:
        ids, lens = tok.batch_encode_matrix(texts, 48, stage=st)
    finally:
        st.shutdown()
    assert np.array_equal(ids, ref_ids) and np.array_equal(lens, ref_lens)


# ---------------------------------------------------------------------------
# model paths: encoder + CLIP byte-identity at any worker count
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_encoder():
    from pathway_tpu.models.encoder import EncoderConfig
    from pathway_tpu.models.sentence_encoder import SentenceEncoder

    cfg = EncoderConfig(
        vocab_size=30000,
        hidden_size=32,
        num_layers=1,
        num_heads=2,
        intermediate_size=64,
        max_position=64,
        pooling="mean",
    )
    return SentenceEncoder(
        config=cfg, checkpoint_dir="/nonexistent", max_seq_len=32, max_batch=16
    )


def test_encoder_stage_byte_identical_any_worker_count(tiny_encoder):
    texts = [f"doc {i} {'long tail of words ' * (i % 4)}end" for i in range(80)]
    ref = np.asarray(tiny_encoder.encode(texts))  # inline, no stage
    for workers in (1, 4):
        configure_stage(workers)
        out = np.asarray(tiny_encoder.encode(texts))
        shutdown_stage()
        # tobytes: true byte-identity (array_equal trips on NaN rows the
        # random-init reference weights can produce)
        assert out.tobytes() == ref.tobytes(), f"{workers}-worker output diverged"


def test_encoder_stage_records_routing(tiny_encoder):
    configure_stage(2)
    texts = ["short"] * 30 + ["many words beyond the short bucket " * 4] * 10
    tiny_encoder.encode(texts)
    shutdown_stage()
    snap = INGEST_METRICS.snapshot()
    assert snap["routed_short"] >= 30
    assert snap["routed_long"] >= 10


def test_clip_stage_byte_identical():
    from pathway_tpu.models.clip import CLIPConfig, CLIPEncoder

    cfg = CLIPConfig(
        image_size=64,
        patch_size=32,
        vision_width=64,
        vision_layers=1,
        vision_heads=2,
        text_width=32,
        text_layers=1,
        text_heads=2,
        context_length=16,
        embed_dim=32,
    )
    enc = CLIPEncoder(cfg, max_batch=16)
    rng = np.random.default_rng(7)
    images = (rng.random((48, 64, 64, 3)) * 255).astype(np.uint8)
    ref = np.asarray(enc.encode_image(images))
    configure_stage(3)
    out = np.asarray(enc.encode_image(images))
    shutdown_stage()
    assert out.tobytes() == ref.tobytes(), "collaborative CLIP pack diverged"
    assert INGEST_METRICS.snapshot()["committed"] >= 3  # one pack per span


# ---------------------------------------------------------------------------
# engine path: stager hands resolve to the pool; byte-identical output
# with persistence + chaos at ingest.worker
# ---------------------------------------------------------------------------

WORDS = ["cat", "dog", "bird", "cat", "dog", "cat", "emu", "dog"]
FINAL = {"cat": 3, "dog": 3, "bird": 1, "emu": 1}


def _build_wordcount(out: str, store: str | None = None, pause: float = 0.04):
    class S(pw.Schema):
        word: str

    def reader(ctx):
        start = int(ctx.offsets.get("pos", 0))
        for i, w in enumerate(WORDS):
            if i < start:
                continue
            ctx.insert({"word": w}, offsets={"pos": i + 1})
            ctx.commit()
            time.sleep(pause)

    t = input_table_from_reader(
        S,
        reader,
        name="isrc",
        persistent_id="i" if store is not None else None,
        supports_offsets=True,
        autocommit_duration_ms=10,
    )
    c = t.groupby(pw.this.word).reduce(pw.this.word, n=pw.reducers.count())
    pw.io.jsonlines.write(c, out)
    if store is None:
        return None
    return pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(store)
    )


def _net(text: str) -> dict[str, int]:
    state: dict[str, int] = {}
    for line in text.splitlines():
        rec = json.loads(line)
        if rec["diff"] > 0:
            state[rec["word"]] = rec["n"]
        else:
            state.pop(rec["word"], None)
    return state


def test_pipeline_ingest_stage_net_identical(tmp_path, monkeypatch):
    """depth-2 run with the ingest stage resolving batches on workers
    == strict depth-1 inline run, in net sink state."""
    ref_out = str(tmp_path / "ref.jsonl")
    _build_wordcount(ref_out)
    pw.run(monitoring_level="none")
    pw.clear_graph()
    with open(ref_out) as f:
        ref = f.read()
    assert _net(ref) == FINAL

    monkeypatch.setenv("PATHWAY_INGEST_WORKERS", "3")
    shutdown_stage()  # force lazy re-read of the env knob
    out = str(tmp_path / "staged.jsonl")
    _build_wordcount(out)
    pw.run(monitoring_level="none", pipeline_depth=2)
    pw.clear_graph()
    shutdown_stage()
    with open(out) as f:
        assert _net(f.read()) == FINAL
    assert INGEST_METRICS.snapshot()["committed"] > 0, (
        "engine path never used the ingest stage"
    )


def test_pipeline_ingest_chaos_with_persistence_byte_identical(tmp_path, monkeypatch):
    """The acceptance bar: N-worker output == inline, under chaos at
    ``ingest.worker`` AND with persistence enabled (KIND_FEED logging
    stays serial on the committer, so the durable log is unchanged)."""
    cfg = _build_wordcount(str(tmp_path / "ref.jsonl"), str(tmp_path / "ref_store"))
    pw.run(monitoring_level="none", persistence_config=cfg)
    pw.clear_graph()
    with open(tmp_path / "ref.jsonl") as f:
        ref = f.read()
    assert _net(ref) == FINAL

    monkeypatch.setenv("PATHWAY_INGEST_WORKERS", "2")
    shutdown_stage()
    out = str(tmp_path / "chaos.jsonl")
    cfg = _build_wordcount(out, str(tmp_path / "chaos_store"))
    chaos.activate([{"site": "ingest.worker", "action": "raise", "repeat": True}])
    try:
        pw.run(monitoring_level="none", persistence_config=cfg, pipeline_depth=2)
    finally:
        chaos.deactivate()
        pw.clear_graph()
        shutdown_stage()
    with open(out) as f:
        assert _net(f.read()) == _net(ref) == FINAL
    snap = INGEST_METRICS.snapshot()
    assert snap["committed"] > 0
    assert snap["retried"] >= 1, "chaos never killed a worker on the engine path"


# ---------------------------------------------------------------------------
# observability surfaces
# ---------------------------------------------------------------------------


def test_metrics_inactive_renders_nothing():
    from pathway_tpu.internals.http_monitoring import MonitoringHttpServer

    assert not INGEST_METRICS.active()
    assert MonitoringHttpServer._ingest_lines() == []


def test_metrics_active_renders_family():
    from pathway_tpu.internals.http_monitoring import MonitoringHttpServer

    st = HostIngestStage(2)
    try:
        list(st.map_ordered(lambda x: x, range(5)))
    finally:
        st.shutdown()
    body = "\n".join(MonitoringHttpServer._ingest_lines())
    for metric in (
        "pathway_ingest_queue_depth",
        "pathway_ingest_host_workers 2",
        "pathway_ingest_host_stage_utilization",
        "pathway_ingest_enqueued_total 5",
        "pathway_ingest_committed_total 5",
        "pathway_ingest_routed_short_total",
        "pathway_ingest_routed_long_total",
    ):
        assert metric in body, f"{metric} missing from /metrics"


def test_snapshot_and_dashboard_ingest_column():
    from pathway_tpu.internals.monitoring import StatsSnapshot, StatsMonitor, _operators_table

    # inactive: snapshot fields stay zero (byte-identical rendering)
    snap = StatsSnapshot()
    assert snap.ingest_workers == 0 and snap.ingest_queue_depth == 0
    monitor = StatsMonitor()
    inactive = _operators_table(monitor, time.monotonic(), False)
    assert not any("ingest" in str(c.header) for c in inactive.columns)

    monitor.snapshot.ingest_workers = 3
    monitor.snapshot.ingest_utilization = 0.5
    monitor.snapshot.ingest_committed = 42
    active = _operators_table(monitor, time.monotonic(), False)
    assert any("ingest" in str(c.header) for c in active.columns)
