"""Streaming join/groupby revision semantics under multi-epoch arrival
and retraction — the reference's join `_stream` variants
(python/pathway/tests/test_joins.py + compute_and_print_update_stream
checks): every join mode must retract stale outputs and emit revised
ones when either side changes."""

from __future__ import annotations

import pathway_tpu as pw

from .utils import T, assert_stream_equality, run_table


def _orders():
    return T(
        """
          | item | qty | __time__ | __diff__
        1 | a    | 1   | 2        | 1
        2 | b    | 2   | 2        | 1
        3 | a    | 3   | 4        | 1
        """
    )


def _prices():
    return T(
        """
          | item | price | __time__ | __diff__
        1 | a    | 10    | 2        | 1
        2 | b    | 20    | 4        | 1
        1 | a    | 10    | 6        | -1
        1 | a    | 11    | 6        | 1
        """
    )


def test_inner_join_revises_on_right_update():
    res = _orders().join(
        _prices(), pw.left.item == pw.right.item
    ).select(item=pw.left.item, qty=pw.left.qty, price=pw.right.price)
    assert_stream_equality(
        res,
        [
            (("a", 1, 10), 2, 1),
            (("a", 3, 10), 4, 1),
            (("b", 2, 20), 4, 1),
            (("a", 1, 10), 6, -1),  # price revision retracts old outputs
            (("a", 3, 10), 6, -1),
            (("a", 1, 11), 6, 1),
            (("a", 3, 11), 6, 1),
        ],
    )


def test_left_join_fills_then_matches():
    """A left row emitted with a None pad must retract the pad when its
    match arrives later."""
    res = _orders().join_left(
        _prices(), pw.left.item == pw.right.item
    ).select(item=pw.left.item, price=pw.right.price)
    stream = [
        u
        for u in _capture_stream(res)
        if u[0][0] == "b"  # focus the late-matching key
    ]
    assert (("b", None), 2, 1) in stream
    assert (("b", None), 4, -1) in stream
    assert (("b", 20), 4, 1) in stream


def _capture_stream(table):
    from .utils import table_to_stream

    stream, _names = table_to_stream(table)
    return [(tuple(row), time, diff) for _k, row, time, diff in stream]


def test_groupby_count_revision_stream():
    t = T(
        """
          | w | __time__ | __diff__
        1 | x | 2        | 1
        2 | x | 4        | 1
        2 | x | 6        | -1
        """
    )
    res = t.groupby(pw.this.w).reduce(w=pw.this.w, n=pw.reducers.count())
    assert_stream_equality(
        res,
        [
            (("x", 1), 2, 1),
            (("x", 1), 4, -1),
            (("x", 2), 4, 1),
            (("x", 2), 6, -1),
            (("x", 1), 6, 1),
        ],
    )


def test_groupby_min_max_retraction_recomputes():
    """Retracting the current extremum must resurface the runner-up
    (full ReducerImpl path, not semigroup)."""
    t = T(
        """
          | g | v  | __time__ | __diff__
        1 | a | 5  | 2        | 1
        2 | a | 9  | 2        | 1
        2 | a | 9  | 4        | -1
        """
    )
    res = t.groupby(pw.this.g).reduce(
        g=pw.this.g, mx=pw.reducers.max(pw.this.v), mn=pw.reducers.min(pw.this.v)
    )
    state = run_table(res)
    assert list(state.values()) == [("a", 5, 5)]


def test_deduplicate_acceptor_streamed():
    """pw.Table.deduplicate with an acceptor: only increasing values
    replace the kept row (reference stdlib/stateful/deduplicate)."""
    t = T(
        """
          | v  | __time__ | __diff__
        1 | 5  | 2        | 1
        2 | 3  | 4        | 1
        3 | 8  | 6        | 1
        """
    )
    res = t.deduplicate(
        value=pw.this.v, acceptor=lambda new, old: new > old
    )
    assert_stream_equality(
        res,
        [
            ((5,), 2, 1),
            ((5,), 6, -1),  # 3 rejected at t=4; 8 replaces at t=6
            ((8,), 6, 1),
        ],
    )


def test_intersect_difference_streamed():
    a = T(
        """
          | v | __time__ | __diff__
        1 | 1 | 2        | 1
        2 | 2 | 2        | 1
        """
    )
    b = T(
        """
          | v | __time__ | __diff__
        1 | 0 | 4        | 1
        """
    )
    inter = a.intersect(b)
    diff = a.difference(b)
    inter_state = run_table(inter.copy())
    # key 1 is in both universes once b's row lands
    assert sorted(v[0] for v in inter_state.values()) == [1]
    pw.clear_graph()

    a2 = T(
        """
          | v | __time__ | __diff__
        1 | 1 | 2        | 1
        2 | 2 | 2        | 1
        """
    )
    b2 = T(
        """
          | v | __time__ | __diff__
        1 | 0 | 4        | 1
        """
    )
    diff_state = run_table(a2.difference(b2))
    assert sorted(v[0] for v in diff_state.values()) == [2]


def test_update_cells_streamed_revision():
    base = T(
        """
          | v  | __time__ | __diff__
        1 | 10 | 2        | 1
        2 | 20 | 2        | 1
        """
    )
    patch = T(
        """
          | v  | __time__ | __diff__
        1 | 99 | 4        | 1
        """
    )
    res = base.update_cells(patch)
    assert_stream_equality(
        res,
        [
            ((10,), 2, 1),
            ((20,), 2, 1),
            ((10,), 4, -1),
            ((99,), 4, 1),
        ],
    )
