"""Overlapped epoch pipeline: byte-identical outputs at depth 2.

The contract of ``pipeline_depth >= 2`` (engine/pipeline.py) is that
only epoch *formation* overlaps execution — epochs still execute
strictly in staged order on one thread — so every output, snapshot and
recovery artifact is byte-for-byte what the strict depth-1 loop
produces. These tests pin that equality on scripted streams, live
connector streams, the 4-way sharded runtime, and the PR-3 exactly-once
recovery window with KIND_FEED moved to staging-commit time, plus the
DeviceRing donation rules the model layer relies on.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.engine.device_ring import DeviceRing, active_rings, quiesce_all
from pathway_tpu.internals.graph_runner import GraphRunner
from pathway_tpu.io._connector import input_table_from_reader
from pathway_tpu.resilience import Recovery, RetryPolicy, chaos

STREAM = """
  | g | v | __time__ | __diff__
1 | a | 1 | 2        | 1
2 | b | 2 | 2        | 1
3 | a | 3 | 4        | 1
4 | c | 4 | 4        | 1
2 | b | 2 | 6        | -1
5 | a | 5 | 6        | 1
3 | a | 3 | 8        | -1
"""

WORDS = ["cat", "dog", "bird", "cat", "dog", "cat", "emu", "dog"]
FINAL = {"cat": 3, "dog": 3, "bird": 1, "emu": 1}


def _scripted_build():
    t = pw.debug.table_from_markdown(STREAM)
    return t.groupby(pw.this.g).reduce(
        pw.this.g,
        s=pw.reducers.sum(pw.this.v),
        n=pw.reducers.count(),
        tup=pw.reducers.sorted_tuple(pw.this.v),
    )

def _run_captured(build, n_workers: int, depth: int):
    table = build()
    runner = GraphRunner(n_workers=n_workers, pipeline_depth=depth)
    cap, names = runner.capture(table)
    runner.run()
    pw.clear_graph()
    return cap.state, names, runner


def _build_wordcount(out: str, store: str | None = None, pause: float = 0.06):
    """Per-row commit + slow stream + fast autocommit: one epoch per
    row at either depth, so runs compare byte-for-byte (same idiom as
    test_chaos_crash_window)."""

    class S(pw.Schema):
        word: str

    def reader(ctx):
        start = int(ctx.offsets.get("pos", 0))
        for i, w in enumerate(WORDS):
            if i < start:
                continue
            ctx.insert({"word": w}, offsets={"pos": i + 1})
            ctx.commit()
            time.sleep(pause)

    t = input_table_from_reader(
        S,
        reader,
        name="wsrc",
        persistent_id="w" if store is not None else None,
        supports_offsets=True,
        autocommit_duration_ms=10,
    )
    c = t.groupby(pw.this.word).reduce(pw.this.word, n=pw.reducers.count())
    pw.io.jsonlines.write(c, out)
    if store is None:
        return None
    return pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(store)
    )


def _net(text: str) -> dict[str, int]:
    state: dict[str, int] = {}
    for line in text.splitlines():
        rec = json.loads(line)
        if rec["diff"] > 0:
            state[rec["word"]] = rec["n"]
        else:
            state.pop(rec["word"], None)
    return state


# ---------------------------------------------------------------------------
# byte-identical outputs, depth 1 vs depth 2
# ---------------------------------------------------------------------------


def test_scripted_stream_depth2_byte_identical():
    s1, n1, _ = _run_captured(_scripted_build, 1, 1)
    s2, n2, _ = _run_captured(_scripted_build, 1, 2)
    assert n1 == n2
    assert s1 == s2


def test_live_stream_depth2_byte_identical(tmp_path):
    out1 = str(tmp_path / "d1.jsonl")
    _build_wordcount(out1)
    pw.run(monitoring_level="none", pipeline_depth=1)
    pw.clear_graph()

    out2 = str(tmp_path / "d2.jsonl")
    _build_wordcount(out2)
    pw.run(monitoring_level="none", pipeline_depth=2)
    pw.clear_graph()

    with open(out1) as f:
        ref = f.read()
    with open(out2) as f:
        got = f.read()
    assert ref, "depth-1 run produced no output"
    assert got == ref


def test_sharded_depth2_byte_identical():
    s1, n1, _ = _run_captured(_scripted_build, 4, 1)
    s2, n2, _ = _run_captured(_scripted_build, 4, 2)
    assert n1 == n2
    assert s1 == s2


def test_depth1_never_enters_pipeline():
    _, _, runner = _run_captured(_scripted_build, 1, 1)
    assert runner.engine.pipeline_stats is None


def test_env_var_sets_depth(monkeypatch, tmp_path):
    monkeypatch.setenv("PATHWAY_PIPELINE_DEPTH", "2")
    out = str(tmp_path / "env.jsonl")
    _build_wordcount(out, pause=0.01)
    pw.run(monitoring_level="none")
    pw.clear_graph()
    with open(out) as f:
        assert _net(f.read()) == FINAL


# ---------------------------------------------------------------------------
# overlap accounting
# ---------------------------------------------------------------------------


def test_depth2_overlap_counters_populated():
    def build():
        return _scripted_build()

    _, _, runner = _run_captured(build, 1, 2)
    stats = runner.engine.pipeline_stats
    assert stats is not None
    d = stats.as_dict()
    assert d["depth"] == 2
    assert d["staged_epochs"] >= 2
    assert d["executed_epochs"] == d["staged_epochs"]
    assert d["host_prep_s"] >= 0.0
    assert 0.0 <= d["overlap_ratio"]
    # overlap can never exceed the host prep it hides
    assert d["overlap_s"] <= d["host_prep_s"] + 1e-9


def test_monitoring_snapshot_carries_pipeline_columns(tmp_path):
    from pathway_tpu.internals.monitoring import StatsMonitor
    from pathway_tpu.internals.parse_graph import G

    out = str(tmp_path / "mon.jsonl")
    _build_wordcount(out, pause=0.01)
    mon = StatsMonitor()
    runner = GraphRunner(n_workers=1, pipeline_depth=2)
    for table, sink in list(G.outputs):
        sink["build"](runner, table)
    runner.run(monitoring_callback=mon.update)
    pw.clear_graph()
    snap = mon.snapshot
    assert snap.pipeline_depth == 2
    assert snap.host_prep_s >= 0.0
    assert snap.device_wait_s >= 0.0
    assert snap.rows_in > 0


def test_dashboard_gains_overlap_column():
    import io
    import time as _t

    from rich.console import Console

    from pathway_tpu.internals.monitoring import (
        OperatorEntry,
        StatsMonitor,
        StatsSnapshot,
        build_dashboard,
    )

    mon = StatsMonitor()
    mon.snapshot = StatsSnapshot(
        time=3, rows_in=10, rows_out=8, pipeline_depth=2,
        host_prep_s=0.12, device_wait_s=0.4, overlap_ratio=0.83,
    )
    entry = OperatorEntry(name="groupby")
    entry.rows_in, entry.rows_out = 10, 8
    mon.operators[1] = entry
    console = Console(file=io.StringIO(), width=200)
    console.print(build_dashboard(mon, _t.monotonic()))
    body = console.file.getvalue()
    assert "overlap ratio" in body
    assert "epoch pipeline (depth 2)" in body
    assert "0.83" in body

    # at depth 1 the column stays hidden
    mon.snapshot = StatsSnapshot(time=3, rows_in=10, rows_out=8)
    console = Console(file=io.StringIO(), width=200)
    console.print(build_dashboard(mon, _t.monotonic()))
    assert "overlap ratio" not in console.file.getvalue()


def test_prometheus_exposes_pipeline_series():
    import urllib.request

    from pathway_tpu.internals.http_monitoring import MonitoringHttpServer
    from pathway_tpu.internals.monitoring import StatsMonitor

    monitor = StatsMonitor()
    table = _scripted_build()
    runner = GraphRunner(n_workers=1, pipeline_depth=2)
    runner.capture(table)
    server = MonitoringHttpServer(monitor, port=0)
    server.start()
    try:
        runner.run(monitoring_callback=monitor.update)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=5
        ).read().decode()
        assert "pathway_host_prep_seconds" in body
        assert "pathway_device_wait_seconds" in body
        assert "pathway_pipeline_overlap_ratio" in body
        assert "pathway_pipeline_depth 2" in body
    finally:
        server.stop()
    pw.clear_graph()


# ---------------------------------------------------------------------------
# exactly-once composition: KIND_FEED at staging-commit time
# ---------------------------------------------------------------------------


def _clean_reference(tmp_path) -> str:
    cfg = _build_wordcount(str(tmp_path / "ref.jsonl"), str(tmp_path / "ref_store"))
    pw.run(monitoring_level="none", persistence_config=cfg)
    pw.clear_graph()
    with open(tmp_path / "ref.jsonl") as f:
        return f.read()


@pytest.mark.parametrize(
    "rule",
    [
        # crash before the staging commit: nothing durable yet, the
        # epoch's rows re-read from connector offsets on restart
        {"site": "engine.before_stage_commit", "time": 3, "action": "raise"},
        # crash after: KIND_FEED durable for a staged-but-never-executed
        # epoch — recovery must replay and deliver it exactly once
        {"site": "engine.after_stage_commit", "time": 3, "action": "raise"},
    ],
    ids=lambda r: r["site"],
)
def test_depth2_staging_crash_recovers_byte_identical(tmp_path, rule):
    ref = _clean_reference(tmp_path)
    assert ref, "clean reference run produced no output"

    out = str(tmp_path / "chaos.jsonl")
    cfg = _build_wordcount(out, str(tmp_path / "chaos_store"))
    chaos.activate([dict(rule)])
    try:
        pw.run(
            monitoring_level="none",
            persistence_config=cfg,
            pipeline_depth=2,
            recovery=Recovery(
                max_restarts=3,
                backoff=RetryPolicy(
                    first_delay_ms=1, jitter_ms=0, sleep=lambda s: None
                ),
            ),
        )
    finally:
        chaos.deactivate()
        pw.clear_graph()
    with open(out) as f:
        assert _net(f.read()) == _net(ref) == FINAL


@pytest.mark.parametrize("depth", [1, 2])
def test_persistence_clean_run_then_replay(tmp_path, depth):
    """A clean run followed by a restart from the same store behaves
    identically at both depths: the first run delivers everything, the
    restart re-delivers nothing (every epoch is behind the delivered
    frontier, so KIND_FEED-at-staging-time adds no duplicates)."""
    out = str(tmp_path / "run.jsonl")
    store = str(tmp_path / "store")
    cfg = _build_wordcount(out, store)
    pw.run(monitoring_level="none", persistence_config=cfg, pipeline_depth=depth)
    pw.clear_graph()
    with open(out) as f:
        first = f.read()
    assert _net(first) == FINAL

    cfg = _build_wordcount(out, store)
    pw.run(monitoring_level="none", persistence_config=cfg, pipeline_depth=depth)
    pw.clear_graph()
    with open(out) as f:
        assert f.read() == "", "restart re-delivered an already-delivered epoch"


def test_depth2_snapshot_while_staging_in_flight(tmp_path):
    """Satellite: a snapshot taken while the stager holds a ring buffer
    in flight must not capture aliased state. The chaos delay pins the
    stager inside the staging commit (between KIND_FEED chaos sites)
    while the executor snapshots, and recovery replay stays
    byte-identical in net state."""
    ref = _clean_reference(tmp_path)

    out = str(tmp_path / "delay.jsonl")
    cfg = _build_wordcount(out, str(tmp_path / "delay_store"))
    chaos.activate(
        [
            {
                "site": "engine.before_stage_commit",
                "action": "delay",
                "delay_s": 0.03,
                "repeat": True,
            }
        ]
    )
    try:
        pw.run(monitoring_level="none", persistence_config=cfg, pipeline_depth=2)
    finally:
        chaos.deactivate()
        pw.clear_graph()
    with open(out) as f:
        assert _net(f.read()) == _net(ref) == FINAL


# ---------------------------------------------------------------------------
# DeviceRing donation rules
# ---------------------------------------------------------------------------


def test_device_ring_stage_and_retire_rebuilt_list():
    ring = DeviceRing(depth=2, name="test")
    a = np.arange(4, dtype=np.int32)
    (ha,) = ring.stage([a])
    assert ring.in_flight() == 1
    # callers destructure stage()'s return and pass a NEW list: retire
    # must match element-wise, not by list identity
    ring.retire([ha])
    assert ring.in_flight() == 0
    assert ring.staged == 1


def test_device_ring_wrap_donates_prior_generation():
    ring = DeviceRing(depth=2, name="test")
    gens = []
    for i in range(4):
        (h,) = ring.stage([np.full(3, i, np.int32)])
        gens.append(h)
        ring.retire([h])
    # 4 stages through 2 slots: generations 0 and 1 were donated when
    # their slots were reused by 2 and 3
    assert ring.staged == 4
    assert ring.donated == 2


def test_device_ring_unretired_slot_blocks_not_corrupts():
    ring = DeviceRing(depth=2, name="test")
    (h0,) = ring.stage([np.arange(5, dtype=np.int32)])
    (h1,) = ring.stage([np.arange(5, 10, dtype=np.int32)])
    # slot 0 is still unretired; staging its replacement must first
    # drain h0 (backpressure) rather than invalidating it mid-read
    (h2,) = ring.stage([np.arange(10, 15, dtype=np.int32)])
    assert np.asarray(h2).tolist() == [10, 11, 12, 13, 14]
    ring.retire([h1])
    ring.retire([h2])


def test_device_ring_snapshot_view_is_detached_copy():
    ring = DeviceRing(depth=2, name="test")
    payload = np.arange(6, dtype=np.int32)
    (h,) = ring.stage([payload])
    # snapshot while the buffer is in flight (unretired)
    (view,) = ring.snapshot_view([h])
    assert isinstance(view, np.ndarray)
    before = view.copy()
    # wrap the ring so h's slot is donated (deleted) twice over
    for i in range(3):
        (hn,) = ring.stage([np.full(6, 90 + i, np.int32)])
        ring.retire([hn])
    # the snapshot copy must be unaffected by the donation
    assert np.array_equal(view, before)
    assert view.tolist() == list(range(6))


def test_quiesce_all_covers_registered_rings():
    ring = DeviceRing(depth=2, name="test-quiesce")
    (h,) = ring.stage([np.arange(3, dtype=np.int32)])
    assert ring in active_rings()
    quiesce_all()  # must not raise / deadlock with a buffer in flight
    ring.retire([h])
