"""Louvain community detection over multi-table pw.iterate.

Reference semantics: stdlib/graphs/louvain_communities/impl.py — local
moves maximize the modularity gain, applied in parallel-safe batches,
iterated to a fixpoint; levels contract the cluster graph.
"""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner
from pathway_tpu.stdlib.graphs import WeightedGraph, exact_modularity
from pathway_tpu.stdlib.graphs.louvain_communities import louvain_communities


class VS(pw.Schema):
    v: int
    total_weight: float


class ES(pw.Schema):
    u_: int
    v_: int
    weight: float


def _graph(undirected_edges: list[tuple[int, int, float]], n: int):
    total = 2.0 * sum(w for _u, _v, w in undirected_edges)
    verts = pw.debug.table_from_rows(
        schema=VS, rows=[(i, total) for i in range(n)]
    ).with_id_from(pw.this.v)
    vkeyed = verts.select(total_weight=pw.this.total_weight)
    doubled = [(u, v, w) for u, v, w in undirected_edges] + [
        (v, u, w) for u, v, w in undirected_edges
    ]
    e = pw.debug.table_from_rows(schema=ES, rows=doubled)
    we = e.select(
        u=e.pointer_from(pw.this.u_),
        v=e.pointer_from(pw.this.v_),
        weight=pw.this.weight,
    )
    return WeightedGraph.from_vertices_and_weighted_edges(vkeyed, we)


def _run_communities(G, **kwargs):
    res = louvain_communities(G, **kwargs)
    runner = GraphRunner()
    cap, names = runner.capture(res)
    runner.run()
    pw.clear_graph()
    return {k: row[names.index("c")] for k, row in cap.state.items()}


def test_two_triangles_one_bridge():
    """The canonical example: two triangles joined by one edge must
    split into exactly two communities (one per triangle)."""
    edges = [
        (0, 1, 1.0),
        (1, 2, 1.0),
        (0, 2, 1.0),
        (3, 4, 1.0),
        (4, 5, 1.0),
        (3, 5, 1.0),
        (2, 3, 1.0),  # bridge
    ]
    G = _graph(edges, 6)
    assign = _run_communities(G, levels=2)
    # keys are vertex pointers; group them by community id
    communities: dict = {}
    for vkey, c in assign.items():
        communities.setdefault(c, set()).add(vkey)
    assert len(communities) == 2, communities
    sizes = sorted(len(m) for m in communities.values())
    assert sizes == [3, 3]


def test_modularity_improves_over_singletons():
    edges = [
        (0, 1, 1.0),
        (1, 2, 1.0),
        (0, 2, 1.0),
        (3, 4, 1.0),
        (4, 5, 1.0),
        (3, 5, 1.0),
        (2, 3, 1.0),
    ]
    G = _graph(edges, 6)
    clustering = louvain_communities(G, levels=2).select(
        c=pw.this.c, total_weight=14.0
    )
    q = exact_modularity(G, clustering)
    pw.clear_graph()
    # two triangles: internal (directed-doubled) = 12 of 14 total weight,
    # each community holds half the degree mass
    expected = 12.0 / 14.0 - 2 * (7.0 / 14.0) ** 2
    assert q == pytest.approx(expected, abs=1e-9)


def test_weighted_graph_respects_weights():
    """Strong weights bind 0-1 and 2-3 despite the unit bridge."""
    edges = [
        (0, 1, 10.0),
        (2, 3, 10.0),
        (1, 2, 1.0),
    ]
    G = _graph(edges, 4)
    assign = _run_communities(G, levels=2)
    communities: dict = {}
    for vkey, c in assign.items():
        communities.setdefault(c, set()).add(vkey)
    assert len(communities) == 2
    sizes = sorted(len(m) for m in communities.values())
    assert sizes == [2, 2]
