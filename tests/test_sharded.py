"""Multi-worker sharded execution: N shards must produce byte-identical
results to single-worker runs.

Mirrors the reference's PATHWAY_THREADS CI matrix (tests/utils.py —
every suite runs under 1..N workers); here the representative operator
mix runs under 1 vs 4 shards and the captured states are compared."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner
from .utils import T


def _run_sharded(build, n_workers):
    """build() -> table; returns (state, names)."""
    table = build()
    runner = GraphRunner(n_workers=n_workers)
    cap, names = runner.capture(table)
    runner.run()
    pw.clear_graph()
    return cap.state, names, runner


def assert_same_result(build, n=4):
    s1, n1, _ = _run_sharded(build, 1)
    s4, n4, runner = _run_sharded(build, n)
    assert n1 == n4
    assert s1 == s4, f"single={s1}\nsharded={s4}"
    return runner


WORDS = """
  | word | n
1 | cat  | 1
2 | dog  | 2
3 | cat  | 3
4 | emu  | 4
5 | dog  | 5
6 | cat  | 6
"""


def test_sharded_groupby_reducers():
    def build():
        t = T(WORDS)
        return t.groupby(pw.this.word).reduce(
            word=pw.this.word,
            cnt=pw.reducers.count(),
            total=pw.reducers.sum(pw.this.n),
            mx=pw.reducers.max(pw.this.n),
        )

    runner = assert_same_result(build)
    # the reduction actually spread across shards
    engines = runner._cluster.engines
    gb_rows = [
        next(n for n in e.nodes if n.name == "GroupBy").stats.rows_in for e in engines
    ]
    assert sum(1 for r in gb_rows if r > 0) > 1


def test_sharded_join():
    def build():
        left = T(WORDS)
        right = T(
            """
              | word | w
            1 | cat  | 10
            2 | dog  | 20
            """
        )
        return left.join(right, left.word == right.word).select(
            word=left.word, n=left.n, w=right.w
        )

    assert_same_result(build)


def test_sharded_outer_join():
    def build():
        left = T(WORDS)
        right = T(
            """
              | word | w
            1 | cat  | 10
            2 | yak  | 99
            """
        )
        return left.join_outer(right, left.word == right.word).select(
            word=pw.coalesce(left.word, right.word), w=right.w
        )

    assert_same_result(build)


def test_sharded_flatten_groupby_chain():
    def build():
        t = T(
            """
              | phrase
            1 | a b a
            2 | b c
            3 | a
            """
        )
        toks = t.select(tok=pw.apply(lambda s: tuple(s.split()), pw.this.phrase)).flatten(
            pw.this.tok
        )
        return toks.groupby(pw.this.tok).reduce(tok=pw.this.tok, cnt=pw.reducers.count())

    assert_same_result(build)


def test_sharded_filter_select_udf():
    calls = []

    def build():
        @pw.udf
        def double(x: int) -> int:
            calls.append(x)
            return x * 2

        t = T(WORDS)
        return t.filter(pw.this.n > 1).select(word=pw.this.word, d=double(pw.this.n))

    assert_same_result(build)


def test_sharded_concat_update_rows():
    def build():
        a = T(WORDS)
        b = T(
            """
              | word | n
            7 | fox  | 7
            """
        )
        return a.concat_reindex(b).groupby(pw.this.word).reduce(
            word=pw.this.word, total=pw.reducers.sum(pw.this.n)
        )

    assert_same_result(build)


def test_sharded_deduplicate():
    def build():
        t = pw.debug.table_from_markdown(
            """
              | v  | __time__
            1 | 1  | 0
            2 | 5  | 2
            3 | 4  | 4
            4 | 10 | 6
            """
        )
        return pw.stdlib.stateful.deduplicate(
            t, col=pw.this.v, acceptor=lambda new, old: new >= old + 2
        )

    assert_same_result(build)


def test_sharded_windowby_streamed():
    def build():
        t = pw.debug.table_from_markdown(
            """
              | t | v  | __time__
            1 | 1 | 10 | 0
            2 | 5 | 30 | 2
            3 | 2 | 20 | 4
            4 | 9 | 40 | 6
            """
        )
        from pathway_tpu.stdlib import temporal

        return t.windowby(pw.this.t, window=temporal.tumbling(duration=4)).reduce(
            start=pw.this._pw_window_start,
            total=pw.reducers.sum(pw.this.v),
        )

    assert_same_result(build)


def test_sharded_subscribe_stream_matches():
    """Sink deliveries (including retract/insert updates) must be the
    same multiset under sharding."""

    def run(n):
        t = pw.debug.table_from_markdown(
            """
              | word | __time__
            1 | cat  | 0
            2 | cat  | 2
            3 | dog  | 4
            """
        )
        counts = t.groupby(pw.this.word).reduce(
            word=pw.this.word, cnt=pw.reducers.count()
        )
        events = []
        runner = GraphRunner(n_workers=n)
        runner.subscribe(
            counts,
            on_change=lambda key, row, time, diff: events.append(
                (row["word"], row["cnt"], diff)
            ),
        )
        runner.run()
        pw.clear_graph()
        return sorted(events)

    assert run(1) == run(4)


def test_sharded_error_log():
    def run(n):
        t = T(
            """
              | a  | b
            1 | 10 | 2
            2 | 7  | 0
            """
        )
        res = t.select(q=pw.apply(lambda a, b: a // b, pw.this.a, pw.this.b))
        err = pw.global_error_log()
        runner = GraphRunner(n_workers=n)
        runner.engine.terminate_on_error = False
        for r in runner._replicas:
            r.engine.terminate_on_error = False
        cap, _ = runner.capture(res)
        ecap, _ = runner.capture(err)
        runner.run()
        pw.clear_graph()
        return len(cap.state), len(ecap.state)

    assert run(1) == run(4) == (2, 1)


def test_sharded_error_log_no_key_collisions():
    """Per-shard error counters must not collide: N failing rows = N
    error-log entries regardless of which shard reported them."""

    def run(n):
        t = T(
            """
              | a  | b
            1 | 1  | 0
            2 | 2  | 0
            3 | 3  | 0
            4 | 4  | 0
            5 | 5  | 0
            6 | 6  | 0
            """
        )
        res = t.select(q=pw.apply(lambda a, b: a // b, pw.this.a, pw.this.b))
        err = pw.global_error_log()
        runner = GraphRunner(n_workers=n)
        for e in [runner.engine] + [r.engine for r in runner._replicas]:
            e.terminate_on_error = False
        # groupby forces the rows across shards before failing
        spread = res.select(q=pw.this.q)
        cap, _ = runner.capture(spread)
        ecap, _ = runner.capture(err)
        runner.run()
        pw.clear_graph()
        return len(ecap.state)

    assert run(1) == run(4) == 6


def test_sharded_windowby_with_delay_behavior():
    """Buffer watermarks are global across shards: delayed windows
    release with the same contents as single-worker."""
    from pathway_tpu.stdlib import temporal

    def run(n):
        t = pw.debug.table_from_markdown(
            """
              | t | v  | __time__
            1 | 1 | 10 | 0
            2 | 2 | 20 | 0
            3 | 3 | 30 | 0
            4 | 9 | 40 | 2
            """
        )
        res = t.windowby(
            pw.this.t,
            window=temporal.tumbling(duration=4),
            behavior=temporal.common_behavior(delay=4),
        ).reduce(
            start=pw.this._pw_window_start,
            total=pw.reducers.sum(pw.this.v),
        )
        runner = GraphRunner(n_workers=n)
        cap, names = runner.capture(res)
        runner.run()
        pw.clear_graph()
        si, ti = names.index("start"), names.index("total")
        stream = [(r[si], r[ti], d) for _k, r, _t, d in cap.stream]
        state = sorted((r[si], r[ti]) for r in cap.state.values())
        return state, stream

    s1, st1 = run(1)
    s4, st4 = run(4)
    assert s1 == s4
    assert sorted(st1) == sorted(st4)


def test_sharded_multihop_no_transient_sink_deliveries():
    """Paths with different re-key hop counts must not leak transient
    partial states to sinks: the epoch's net changes only."""

    def run(n):
        t = T(WORDS)
        per_word = t.groupby(pw.this.word).reduce(
            word=pw.this.word, total=pw.reducers.sum(pw.this.n)
        )
        # re-aggregate: one path short (t), one long (through groupby)
        rejoined = t.join(per_word, t.word == per_word.word).select(
            word=t.word, n=t.n, total=per_word.total
        )
        agg = rejoined.groupby(pw.this.word).reduce(
            word=pw.this.word, s=pw.reducers.sum(pw.this.n + pw.this.total)
        )
        events = []
        runner = GraphRunner(n_workers=n)
        runner.subscribe(
            agg,
            on_change=lambda key, row, time, diff: events.append(
                (row["word"], row["s"], diff)
            ),
        )
        runner.run()
        pw.clear_graph()
        return sorted(events)

    assert run(1) == run(4)


def test_sharded_no_phantom_time_end():
    def run(n):
        t = T(WORDS)
        res = t.groupby(pw.this.word).reduce(word=pw.this.word, c=pw.reducers.count())
        times = []
        runner = GraphRunner(n_workers=n)
        runner.subscribe(res, on_time_end=lambda time: times.append(time))
        runner.run()
        pw.clear_graph()
        return times

    assert run(1) == run(4)


def test_sharded_streaming_connector():
    class Subject(pw.io.python.ConnectorSubject):
        def run(self):
            for i in range(20):
                self.next(word=f"w{i % 5}", n=i)
            self.commit()

    class S(pw.Schema):
        word: str
        n: int

    def run(n):
        t = pw.io.python.read(Subject(), schema=S, autocommit_duration_ms=None)
        counts = t.groupby(pw.this.word).reduce(
            word=pw.this.word, cnt=pw.reducers.count(), total=pw.reducers.sum(pw.this.n)
        )
        runner = GraphRunner(n_workers=n)
        cap, names = runner.capture(counts)
        runner.run()
        pw.clear_graph()
        return {r[0]: (r[1], r[2]) for r in cap.state.values()}

    assert run(1) == run(4)
    assert run(4)["w0"] == (4, 30)


def test_sharded_persistence_recovery(tmp_path, monkeypatch):
    """Exactly-once recovery works under multi-worker execution: state
    is restored from the cluster-wide operator snapshot (or replayed),
    and restarted sinks stay silent."""
    import json

    monkeypatch.setenv("PATHWAY_TPU_FS_ONESHOT", "1")
    in_dir = tmp_path / "in"
    in_dir.mkdir()
    with open(in_dir / "a.jsonl", "w") as f:
        for w in ["cat", "dog", "cat", "emu"]:
            f.write(json.dumps({"word": w}) + "\n")
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))
    cfg = pw.persistence.Config.simple_config(backend)

    class WS(pw.Schema):
        word: str

    def run_once(n):
        words = pw.io.jsonlines.read(
            str(in_dir), schema=WS, mode="streaming", persistent_id="w"
        )
        counts = words.groupby(pw.this.word).reduce(
            word=pw.this.word, cnt=pw.reducers.count()
        )
        events = []
        runner = GraphRunner(n_workers=n)
        runner.engine.persistence_config = cfg
        runner.subscribe(
            counts,
            on_change=lambda key, row, time, diff: events.append(
                (row["word"], row["cnt"], diff)
            ),
        )
        cap, names = runner.capture(counts)
        runner.run()
        pw.clear_graph()
        state = {
            row[names.index("word")]: row[names.index("cnt")]
            for row in cap.state.values()
        }
        return events, state

    ev1, st1 = run_once(4)
    assert st1 == {"cat": 2, "dog": 1, "emu": 1}
    assert ("cat", 2, 1) in ev1

    # restart: state recovered, sink silent
    ev2, st2 = run_once(4)
    assert ev2 == []
    assert st2 == st1

    # new data lands incrementally on recovered state
    with open(in_dir / "b.jsonl", "w") as f:
        f.write(json.dumps({"word": "cat"}) + "\n")
    ev3, st3 = run_once(4)
    assert ("cat", 3, 1) in ev3 and ("cat", 2, -1) in ev3
    assert not any(w == "dog" for w, _c, _d in ev3)
    assert st3["cat"] == 3


def test_sharded_persistence_snapshot_skips_replay(tmp_path, monkeypatch):
    import json

    monkeypatch.setenv("PATHWAY_TPU_FS_ONESHOT", "1")
    in_dir = tmp_path / "in"
    in_dir.mkdir()
    with open(in_dir / "a.jsonl", "w") as f:
        for i in range(50):
            f.write(json.dumps({"word": f"w{i % 7}"}) + "\n")
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))
    cfg = pw.persistence.Config.simple_config(backend)

    class WS(pw.Schema):
        word: str

    def build_runner():
        words = pw.io.jsonlines.read(
            str(in_dir), schema=WS, mode="streaming", persistent_id="w"
        )
        counts = words.groupby(pw.this.word).reduce(
            word=pw.this.word, cnt=pw.reducers.count()
        )
        runner = GraphRunner(n_workers=4)
        runner.engine.persistence_config = cfg
        cap, names = runner.capture(counts)
        return runner, cap, names

    runner, cap, names = build_runner()
    runner.run()
    pw.clear_graph()

    runner2, cap2, names2 = build_runner()
    runner2.run()
    got = {
        row[names2.index("word")]: row[names2.index("cnt")]
        for row in cap2.state.values()
    }
    assert got == {f"w{i}": (8 if i == 0 else 7) for i in range(7)}
    # zero rows replayed through any shard's GroupBy
    for e in runner2._cluster.engines:
        gb = next(n for n in e.nodes if n.name == "GroupBy")
        assert gb.stats.rows_in == 0
    pw.clear_graph()


def test_sharded_persistence_interop_with_single_worker(tmp_path, monkeypatch):
    """Storage written by a single-worker run recovers under 4 workers
    (input replay path: the single-worker snapshot signature differs)."""
    import json

    monkeypatch.setenv("PATHWAY_TPU_FS_ONESHOT", "1")
    in_dir = tmp_path / "in"
    in_dir.mkdir()
    with open(in_dir / "a.jsonl", "w") as f:
        for w in ["x", "y", "x"]:
            f.write(json.dumps({"word": w}) + "\n")
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))
    cfg = pw.persistence.Config.simple_config(backend)

    class WS(pw.Schema):
        word: str

    def run_once(n):
        words = pw.io.jsonlines.read(
            str(in_dir), schema=WS, mode="streaming", persistent_id="w"
        )
        counts = words.groupby(pw.this.word).reduce(
            word=pw.this.word, cnt=pw.reducers.count()
        )
        events = []
        runner = GraphRunner(n_workers=n)
        runner.engine.persistence_config = cfg
        runner.subscribe(
            counts,
            on_change=lambda key, row, time, diff: events.append(row["word"]),
        )
        cap, names = runner.capture(counts)
        runner.run()
        pw.clear_graph()
        return events, {
            row[names.index("word")]: row[names.index("cnt")]
            for row in cap.state.values()
        }

    _ev1, st1 = run_once(1)
    ev2, st2 = run_once(4)
    assert st2 == st1 == {"x": 2, "y": 1}
    assert ev2 == []  # replay suppressed even though snapshot didn't match
