"""GSPMD mesh scale-out for DeviceKnnIndex: one logical index sharded
over the mesh's data axis (per-shard top-k inside shard_map + one
cross-chip merge collective). conftest forces 8 virtual CPU devices, so
these are real sharded-execution equivalence tests, not dryrun stubs.

Covers: single-device vs sharded parity under churn (adds, removes,
re-adds, growth) for every metric; odd shard occupancies; k larger than
a shard's doc count; growth without host re-upload (the compile cache is
keyed on PER-SHARD capacity); pathway_index_* metrics + flight-recorder
events; and the pw.run(mesh=...) / PATHWAY_MESH wiring end to end."""

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.ops.index_metrics import INDEX_METRICS
from pathway_tpu.ops.knn import DeviceKnnIndex, _shard_of_key
from pathway_tpu.parallel.mesh import (
    active_mesh,
    parse_mesh_spec,
    resolve_mesh,
    use_mesh,
)


@pytest.fixture(autouse=True)
def _reset_index_plane():
    yield
    INDEX_METRICS.reset()
    from pathway_tpu.internals import flight_recorder

    flight_recorder.RECORDER.clear()


def _mesh(n=8):
    return resolve_mesh(n)


def _keys_and_results(rows):
    return [[(k, round(float(s), 4)) for k, s in row] for row in rows]


def _pair(metric, reserved=64, mesh_n=8):
    """(sharded, unsharded) twin indexes."""
    sharded = DeviceKnnIndex(
        dim=16, metric=metric, reserved_space=reserved, mesh=_mesh(mesh_n)
    )
    plain = DeviceKnnIndex(dim=16, metric=metric, reserved_space=reserved)
    return sharded, plain


def _assert_same(sharded, plain, queries, k):
    rs = sharded.search_batch(queries, k)
    rp = plain.search_batch(queries, k)
    assert len(rs) == len(rp)
    for row_s, row_p in zip(rs, rp):
        # scores must match to float32 tolerance; key order can only
        # differ on exact ties, so compare (sorted keys, scores)
        ks = [k_ for k_, _ in row_s]
        kp = [k_ for k_, _ in row_p]
        ss = np.asarray([s for _, s in row_s])
        sp = np.asarray([s for _, s in row_p])
        np.testing.assert_allclose(ss, sp, rtol=1e-5, atol=1e-5)
        if not np.isclose(ss[:-1], ss[1:]).any():
            assert ks == kp


@pytest.mark.parametrize("metric", ["cos", "l2", "ip"])
def test_sharded_equals_single_device_under_churn(metric):
    rng = np.random.default_rng(7)
    sharded, plain = _pair(metric)
    n_docs = 120  # > reserved_space -> exercises growth on both sides
    vecs = rng.normal(size=(n_docs, 16)).astype(np.float32)
    for i in range(n_docs):
        for idx in (sharded, plain):
            idx.add(i, vecs[i], {"i": i})
    # churn: retract every third key, re-add a rotated payload for some
    for i in range(0, n_docs, 3):
        for idx in (sharded, plain):
            idx.remove(i)
    for i in range(0, n_docs, 6):
        for idx in (sharded, plain):
            idx.add(i, np.roll(vecs[i], 1), {"i": i})
    assert len(sharded) == len(plain)
    queries = rng.normal(size=(9, 16)).astype(np.float32)
    _assert_same(sharded, plain, queries, k=5)


def test_odd_sizes_and_k_over_shard_count():
    """Doc counts that leave shards ragged, and k greater than any
    single shard's doc count — the merge must still yield the global
    top-k."""
    rng = np.random.default_rng(11)
    sharded, plain = _pair("cos", reserved=64)
    vecs = rng.normal(size=(13, 16)).astype(np.float32)
    for i in range(13):
        for idx in (sharded, plain):
            idx.add(i, vecs[i])
    per_shard = [0] * sharded.n_shards
    for i in range(13):
        per_shard[_shard_of_key(i, sharded.n_shards)] += 1
    assert max(per_shard) < 13  # actually spread over shards
    queries = rng.normal(size=(4, 16)).astype(np.float32)
    # k exceeds every per-shard doc count and the global doc count
    _assert_same(sharded, plain, queries, k=12)
    rs = sharded.search_batch(queries, 50)
    rp = plain.search_batch(queries, 50)
    assert [len(r) for r in rs] == [len(r) for r in rp] == [13] * 4


def test_growth_keeps_per_shard_compile_key_and_skips_reupload():
    """Satellite: growth doubles PER-SHARD capacity; a meshed index
    that doubles several times must never bounce the matrix through the
    host (`_upload_full` runs once, at cold start)."""
    rng = np.random.default_rng(3)
    idx = DeviceKnnIndex(dim=8, metric="cos", reserved_space=64, mesh=_mesh())
    uploads = {"n": 0}
    real = idx._upload_full

    def counting_upload():
        uploads["n"] += 1
        real()

    idx._upload_full = counting_upload
    start_shard_cap = idx.shard_capacity
    vecs = rng.normal(size=(600, 8)).astype(np.float32)
    # cold start materializes the device arrays once
    idx.add(0, vecs[0])
    idx.search_batch(vecs[:1], 1)
    for i in range(1, 600):
        idx.add(i, vecs[i])
        if i % 25 == 0:
            # flush often enough that _sync's bulk-churn heuristic
            # (pending > capacity/2 -> full upload) never kicks in; what
            # remains is pure growth, which must stay on device
            idx.search_batch(vecs[:2], 3)
    res = idx.search_batch(vecs[:3], 5)
    assert [row[0][0] for row in res] == [0, 1, 2]
    assert idx.shard_capacity > start_shard_cap  # growth happened
    assert idx.capacity == idx.n_shards * idx.shard_capacity
    assert uploads["n"] == 1, "sharded growth must not re-upload from host"


def test_device_batch_ingest_parity():
    """add_batch_device (jax-array ingest, the fused-encoder path) lands
    in the same slots/results as host adds on a meshed index."""
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    vecs = rng.normal(size=(40, 16)).astype(np.float32)
    sharded, plain = _pair("l2")
    sharded.add_batch_device(list(range(40)), jnp.asarray(vecs))
    plain.add_batch_arrays(list(range(40)), vecs)
    queries = rng.normal(size=(6, 16)).astype(np.float32)
    _assert_same(sharded, plain, queries, k=7)


def test_search_dispatch_resolve_sharded():
    """The two-phase async contract (dispatch returns device handles,
    resolve maps to keys) must survive sharding."""
    rng = np.random.default_rng(9)
    sharded, plain = _pair("cos")
    vecs = rng.normal(size=(30, 16)).astype(np.float32)
    for i in range(30):
        sharded.add(i, vecs[i])
        plain.add(i, vecs[i])
    q = rng.normal(size=(5, 16)).astype(np.float32)
    scores, idxs = sharded.search_dispatch(q, 4)
    got = sharded.search_resolve(scores, idxs, 4)
    want = plain.search_batch(q, 4)
    assert _keys_and_results(got) == _keys_and_results(want)


def test_index_metrics_and_flight_recorder_events():
    from pathway_tpu.internals import flight_recorder
    from pathway_tpu.internals.http_monitoring import MonitoringHttpServer

    INDEX_METRICS.reset()
    flight_recorder.RECORDER.clear()
    assert MonitoringHttpServer._index_lines() == []  # nothing yet

    rng = np.random.default_rng(2)
    idx = DeviceKnnIndex(
        dim=8, metric="cos", reserved_space=64, mesh=_mesh(), name="docs"
    )
    vecs = rng.normal(size=(200, 8)).astype(np.float32)
    for i in range(30):
        idx.add(i, vecs[i])
    idx.search_batch(vecs[:1], 1)  # materialize the sharded arrays
    for i in range(30, 200):  # growth with resident arrays -> rebalance
        idx.add(i, vecs[i])
        if i % 25 == 0:
            idx.search_batch(vecs[:1], 1)
    idx.search_batch(vecs[:5], 3)

    snap = INDEX_METRICS.snapshot()
    entry = snap["indexes"]["docs"]
    assert entry["docs"] == 200
    assert entry["shards"] == idx.n_shards == 8
    assert sum(entry["docs_shard"]) == 200
    assert entry["shard_capacity"] == idx.shard_capacity
    assert entry["imbalance"] >= 1.0
    assert entry["searches"] >= 2 and entry["queries"] >= 5
    assert snap["merge_seconds"]["count"] >= 1

    text = "\n".join(MonitoringHttpServer._index_lines())
    for needle in (
        'pathway_index_docs{index="docs",shard="0"}',
        "pathway_index_valid_fraction",
        "pathway_index_imbalance",
        "pathway_index_shard_capacity",
        "pathway_index_merge_seconds_bucket",
        "pathway_index_merge_seconds_count",
    ):
        assert needle in text

    kinds = [e["kind"] for e in flight_recorder.RECORDER.events()]
    assert "index.search" in kinds
    assert "index.rebalance" in kinds
    search_evt = [
        e
        for e in flight_recorder.RECORDER.events()
        if e["kind"] == "index.search"
    ][-1]
    assert search_evt["index"] == "docs"
    assert search_evt["queries"] == 5 and search_evt["shards"] == 8
    rebalance_evt = next(
        e
        for e in flight_recorder.RECORDER.events()
        if e["kind"] == "index.rebalance"
    )
    assert rebalance_evt["index"] == "docs" and rebalance_evt["shards"] == 8


def test_parse_mesh_spec_forms():
    assert parse_mesh_spec(None) is None
    assert parse_mesh_spec("") is None
    assert parse_mesh_spec(8) == {"data": 8, "model": 1}
    assert parse_mesh_spec("8") == {"data": 8, "model": 1}
    assert parse_mesh_spec("4x2") == {"data": 4, "model": 2}
    assert parse_mesh_spec("data=4,model=2") == {"data": 4, "model": 2}
    assert parse_mesh_spec({"data": 2}) == {"data": 2, "model": 1}
    mesh = _mesh(8)
    assert parse_mesh_spec(mesh) == {"data": 8, "model": 1}
    for bad in (0, -2, "axis=3", True, 3.5):
        with pytest.raises(ValueError):
            parse_mesh_spec(bad)
    with pytest.raises(ValueError):
        resolve_mesh(512)  # more devices than the backend exposes


def _knn_pipeline(docs_v, qs_v, reserved=32):
    from pathway_tpu.stdlib.ml.index import KNNIndex

    docs = pw.debug.table_from_rows(
        pw.schema_from_types(i=int), [(i,) for i in range(len(docs_v))]
    )
    docs = docs.select(
        docs.i,
        emb=pw.apply_with_type(
            lambda i: tuple(map(float, docs_v[i])), pw.ANY, docs.i
        ),
    )
    queries = pw.debug.table_from_rows(
        pw.schema_from_types(i=int), [(i,) for i in range(len(qs_v))]
    )
    queries = queries.select(
        emb=pw.apply_with_type(
            lambda i: tuple(map(float, qs_v[i])), pw.ANY, queries.i
        )
    )
    index = KNNIndex(docs.emb, docs, n_dimensions=16, reserved_space=reserved)
    return index.get_nearest_items(
        queries.emb, k=3, collapse_rows=True, with_distances=True
    )


def _collect(res, **run_kwargs):
    rows = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[int(key)] = (tuple(row["i"]), tuple(row["dist"]))

    pw.io.subscribe(res, on_change=on_change)
    pw.run(**run_kwargs)
    return rows


def test_pw_run_mesh_end_to_end():
    """pw.run(mesh=8) serves ONE logical sharded index with zero
    query-API change — answers identical to the single-device run, and
    the run-scoped mesh never leaks past the run."""
    rng = np.random.default_rng(0)
    docs_v = rng.normal(size=(20, 16)).astype(np.float32)
    qs_v = rng.normal(size=(5, 16)).astype(np.float32)

    out_mesh = _collect(_knn_pipeline(docs_v, qs_v), mesh=8)
    assert active_mesh() is None, "run-scoped mesh leaked"
    pw.clear_graph()
    out_single = _collect(_knn_pipeline(docs_v, qs_v))
    assert out_mesh == out_single
    assert len(out_mesh) == 5


def test_pathway_mesh_env_and_run_context(monkeypatch):
    rng = np.random.default_rng(1)
    docs_v = rng.normal(size=(12, 16)).astype(np.float32)
    qs_v = rng.normal(size=(3, 16)).astype(np.float32)

    out_single = _collect(_knn_pipeline(docs_v, qs_v))
    pw.clear_graph()
    monkeypatch.setenv("PATHWAY_MESH", "4")
    out_env = _collect(_knn_pipeline(docs_v, qs_v))
    assert out_env == out_single

    # analyze-only runs record the parsed axes jax-free for PWL010
    from pathway_tpu.internals.parse_graph import G

    pw.clear_graph()
    monkeypatch.setenv("PATHWAY_ANALYZE_ONLY", "1")
    monkeypatch.setenv("PATHWAY_MESH", "4x2")
    pw.run()
    assert G.run_context["mesh_axes"] == {"data": 4, "model": 2}


def test_use_mesh_scope_survives_plain_run():
    """A run without mesh= must not clobber an enclosing use_mesh()."""
    mesh = _mesh(2)
    with use_mesh(mesh):
        assert active_mesh() is mesh
        pw.run()  # empty graph, no mesh argument
        assert active_mesh() is mesh
    assert active_mesh() is None
