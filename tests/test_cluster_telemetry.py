"""Cluster telemetry plane: one /metrics endpoint, every worker labeled.

In-process sharded runs sample every shard engine directly; multi-
process workers piggyback the same per-worker stats dict on their
authenticated protocol replies (parallel/multiprocess.py — workers
never open a listener of their own), and the coordinator's /metrics
renders all of them under ``worker=`` labels. The chaos test kills a
worker mid-epoch and asserts the black-box flight recorder preserved
its last fed epochs."""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import time
import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner
from pathway_tpu.internals.http_monitoring import MonitoringHttpServer
from pathway_tpu.internals.monitoring import StatsMonitor
from pathway_tpu.internals.parse_graph import G

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _series_lines(body: str) -> list[str]:
    return [ln for ln in body.splitlines() if ln and not ln.startswith("#")]


# ---------------------------------------------------------------------------
# in-process sharded run: every shard under worker= labels
# ---------------------------------------------------------------------------


def _run_sharded_monitored(tmp_path, n_workers: int) -> StatsMonitor:
    t = pw.debug.table_from_markdown(
        """
        | word
      1 | cat
      2 | dog
      3 | cat
      4 | emu
      5 | dog
      6 | cat
        """
    )
    c = t.groupby(pw.this.word).reduce(pw.this.word, n=pw.reducers.count())
    pw.io.jsonlines.write(c, str(tmp_path / "out.jsonl"))
    runner = GraphRunner(n_workers=n_workers)
    for table, sink in list(G.outputs):
        sink["build"](runner, table)
    monitor = StatsMonitor()
    runner.run(monitoring_callback=monitor.update)
    pw.clear_graph()
    return monitor


def test_sharded_metrics_label_every_worker(tmp_path):
    monitor = _run_sharded_monitored(tmp_path, n_workers=2)
    workers = monitor.snapshot.workers
    assert sorted(workers) == [0, 1]
    for w in workers.values():
        assert {"epoch", "rows_in", "rows_out", "pid", "rows_per_s"} <= set(w)

    body = MonitoringHttpServer(monitor, port=0)._prometheus()
    # acceptance: EVERY series carries a worker label
    lines = _series_lines(body)
    assert lines and all('worker="' in ln for ln in lines), body
    for wid in (0, 1):
        assert f'pathway_epoch{{worker="{wid}"}}' in body
        assert f'pathway_rows_input_total{{worker="{wid}"}}' in body
        assert f'pathway_worker_restarts_total{{worker="{wid}"}}' in body


def test_sharded_status_json_has_workers_and_resilience(tmp_path):
    monitor = _run_sharded_monitored(tmp_path, n_workers=2)
    status = json.loads(MonitoringHttpServer(monitor, port=0)._status())
    assert sorted(status["workers"]) == ["0", "1"]
    assert "restarts_total" in status
    assert "retries" in status
    assert status["pipeline"]["depth"] == 1
    assert "overlap_ratio" in status["pipeline"]


def test_single_process_metrics_have_no_worker_labels(tmp_path):
    monitor = _run_sharded_monitored(tmp_path, n_workers=1)
    assert monitor.snapshot.workers == {}
    body = MonitoringHttpServer(monitor, port=0)._prometheus()
    assert 'worker="' not in body
    assert "pathway_epoch " in body


# ---------------------------------------------------------------------------
# multiprocess cluster: scrape the coordinator mid-flight
# ---------------------------------------------------------------------------

MP_STREAM_PROGRAM = textwrap.dedent(
    """
    import os, threading, time, json
    import pathway_tpu as pw

    class S(pw.Schema):
        word: str

    t = pw.io.jsonlines.read(
        os.environ["WC_IN"], schema=S, mode="streaming",
        autocommit_duration_ms=100,
    )
    c = t.groupby(pw.this.word).reduce(pw.this.word, n=pw.reducers.count())
    pid = os.environ.get("PATHWAY_PROCESS_ID", "0")
    pw.io.jsonlines.write(c, os.environ["WC_OUT"] + "." + pid)

    def stop():
        time.sleep(4.0)
        os._exit(0)

    threading.Thread(target=stop, daemon=True).start()
    pw.run(
        monitoring_level="none",
        with_http_server=pid == "0",
        monitoring_http_port=int(os.environ["MET_PORT"]),
    )
    """
)


def _spawn_cluster(tmp_path, program: str, extra_env=None, processes=2):
    prog = tmp_path / "prog.py"
    prog.write_text(program)
    port = _free_port()
    procs = []
    for pid in range(processes):
        env = dict(os.environ)
        env.pop("PATHWAY_CHAOS", None)
        env.update(
            WC_IN=str(tmp_path / "in"),
            WC_OUT=str(tmp_path / "out.jsonl"),
            JAX_PLATFORMS="cpu",
            PATHWAY_THREADS="1",
            PATHWAY_PROCESSES=str(processes),
            PATHWAY_PROCESS_ID=str(pid),
            PATHWAY_FIRST_PORT=str(port),
            PATHWAY_CLUSTER_TOKEN="telemetry-test",
            PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
        )
        env.update(extra_env or {})
        procs.append(
            subprocess.Popen(
                [sys.executable, str(prog)],
                env=env,
                cwd=str(tmp_path),
                stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    return procs


@pytest.fixture()
def wc_input(tmp_path):
    d = tmp_path / "in"
    d.mkdir()
    words = ["cat", "dog", "cat", "bird", "dog", "cat", "emu", "fox"] * 6
    with open(d / "words.jsonl", "w") as f:
        for w in words:
            f.write(json.dumps({"word": w}) + "\n")
    return tmp_path


def test_multiprocess_scrape_covers_every_worker(wc_input):
    """Scrape the coordinator's /metrics while a 2-process cluster is
    live: worker 1's stats arrived piggybacked on its protocol replies,
    so both shards show up under worker= labels on the ONE endpoint."""
    tmp = wc_input
    met_port = _free_port()
    procs = _spawn_cluster(tmp, MP_STREAM_PROGRAM, {"MET_PORT": str(met_port)})
    body = None
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{met_port}/metrics", timeout=2
                ) as resp:
                    candidate = resp.read().decode()
            except OSError:
                time.sleep(0.1)
                continue
            if 'worker="0"' in candidate and 'worker="1"' in candidate:
                body = candidate
                break
            time.sleep(0.1)
        assert body is not None, f"never saw both workers:\n{candidate!r}"
        lines = _series_lines(body)
        assert all('worker="' in ln for ln in lines), body
        for wid in (0, 1):
            assert f'pathway_epoch{{worker="{wid}"}}' in body
        # /status mirrors the same per-worker stats as JSON
        with urllib.request.urlopen(
            f"http://127.0.0.1:{met_port}/status", timeout=2
        ) as resp:
            status = json.loads(resp.read().decode())
        assert sorted(status["workers"]) == ["0", "1"]
        assert status["workers"]["1"]["pid"] != os.getpid()
    finally:
        for p in procs:
            try:
                p.communicate(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()


# ---------------------------------------------------------------------------
# chaos: killed worker leaves a black-box dump behind
# ---------------------------------------------------------------------------

MP_CHAOS_PROGRAM = textwrap.dedent(
    """
    import os, time
    import pathway_tpu as pw
    from pathway_tpu.io._connector import input_table_from_reader

    NPROC = int(os.environ.get("PATHWAY_PROCESSES", "1"))
    WORDS = ["cat", "dog", "bird"]

    class S(pw.Schema):
        word: str

    def reader(ctx):
        for i in range(90):
            if i % NPROC != ctx.process_id:
                continue
            ctx.insert({"word": WORDS[i % 3]}, offsets={"pos": i + 1})
            ctx.commit()
            time.sleep(0.01)

    t = input_table_from_reader(
        S, reader, name="slow_src", parallel_readers=True,
        persistent_id="ct", supports_offsets=True,
        autocommit_duration_ms=50,
    )
    c = t.groupby(pw.this.word).reduce(pw.this.word, n=pw.reducers.count())
    pid = os.environ.get("PATHWAY_PROCESS_ID", "0")
    pw.io.jsonlines.write(c, os.environ["WC_OUT"] + "." + pid)
    pw.run(
        monitoring_level="none",
        persistence_config=pw.persistence.Config.simple_config(
            pw.persistence.Backend.filesystem(os.environ["WC_STORE"])
        ),
    )
    """
)


@pytest.mark.slow
@pytest.mark.chaos
def test_killed_worker_leaves_flight_recorder_dump(tmp_path):
    """SIGKILL worker process 1 right after it fed an epoch: the chaos
    injector dumps the ring in-process before raising the signal, so a
    blackbox file survives naming the killed worker and its last fed
    epochs, and ``pathway blackbox show`` renders the trailing epoch
    transitions."""
    bb_dir = tmp_path / "blackbox"
    spec = json.dumps(
        {"site": "worker.after_feed_log", "process": 1, "hit": 3, "action": "kill"}
    )
    procs = _spawn_cluster(
        tmp_path,
        MP_CHAOS_PROGRAM,
        {
            "PATHWAY_CHAOS": spec,
            "PATHWAY_FLIGHT_RECORDER_DIR": str(bb_dir),
            "WC_STORE": str(tmp_path / "store"),
        },
    )
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if procs[1].poll() is not None:
                break
            time.sleep(0.1)
        assert procs[1].poll() is not None, "chaos kill never fired"
        assert procs[1].returncode == -signal.SIGKILL
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.communicate()

    from pathway_tpu.internals import flight_recorder as fr

    dumps = fr.list_dumps(str(bb_dir))
    assert dumps, f"no blackbox dump in {bb_dir}"
    killed = [
        (p, d) for p in dumps for d in [fr.load_dump(p)] if d["process_id"] == 1
    ]
    assert killed, "no dump names the killed worker"
    path, data = killed[-1]
    assert data["reason"] == "chaos.kill"
    kinds = [e["kind"] for e in data["events"]]
    assert "feed.commit" in kinds, kinds
    assert "chaos.hit" in kinds
    assert fr.last_epoch(data) is not None  # the last fed epoch

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "pathway_tpu.cli", "blackbox", "show", path],
        capture_output=True,
        text=True,
        env=env,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "epoch transitions:" in proc.stdout
    assert "reason=chaos.kill" in proc.stdout
