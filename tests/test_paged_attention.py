"""Paged-KV decode attention (ops/paged_attention): CPU bitwise-parity
suite via Pallas interpret mode — the same contract the fused encoder
pins with ``fused_encoder_interpret``. For every (page_size, sequence
bucket) combination the paged kernel must match the jitted
gather-then-dense reference *bitwise*; the suite also covers dead
(all-padding) pages, empty sequences, and non-contiguous shuffled page
tables, plus the ``PagedKvPool`` host allocator contract."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pathway_tpu.ops.paged_attention import (
    PagedKvPool,
    dense_decode_attention,
    kv_pool_bytes,
    paged_attention_reference,
    paged_decode_attention,
    pages_for,
)

N_HEADS = 2
DIM = 8  # 2 heads x 4 — tiny on purpose: interpret mode is slow


def _case(seed, batch, n_pages, page_size, pages_per_seq, lens):
    """Random pool + page tables. Page tables are shuffled (pages are
    deliberately NON-contiguous in the pool) and dead entries carry the
    out-of-range sentinel ``n_pages`` to prove they are ignored."""
    rng = np.random.default_rng(seed)
    q = rng.normal(size=(batch, DIM)).astype(np.float32)
    k_pages = rng.normal(size=(n_pages, page_size, DIM)).astype(np.float32)
    v_pages = rng.normal(size=(n_pages, page_size, DIM)).astype(np.float32)
    perm = rng.permutation(n_pages)
    tables = np.full((batch, pages_per_seq), n_pages, np.int32)
    used = 0
    for b, ln in enumerate(lens):
        need = pages_for(ln, page_size)
        assert used + need <= n_pages, "test case over-allocates the pool"
        tables[b, :need] = perm[used : used + need]
        used += need
    return (
        jnp.asarray(q),
        jnp.asarray(k_pages),
        jnp.asarray(v_pages),
        jnp.asarray(tables),
        jnp.asarray(np.asarray(lens, np.int32)),
    )


def _assert_bitwise(args):
    ref = jax.jit(
        lambda *a: paged_attention_reference(*a, n_heads=N_HEADS)
    )(*args)
    out = paged_decode_attention(*args, n_heads=N_HEADS, interpret=True)
    ref, out = np.asarray(ref), np.asarray(out)
    assert ref.shape == out.shape
    assert np.array_equal(ref, out), (
        f"paged kernel diverged from reference: "
        f"max abs diff {np.abs(ref - out).max()}"
    )
    return out


# ---------------------------------------------------------------- parity


@pytest.mark.parametrize("page_size", [4, 8, 16])
@pytest.mark.parametrize("bucket", [8, 16, 32, 64])
def test_parity_every_page_size_bucket_combo(page_size, bucket):
    """The acceptance gate: for every (page_size, seq bucket) combo the
    interpret-mode kernel equals the jitted reference bitwise — ragged
    lengths inside the bucket included."""
    pages_per_seq = pages_for(bucket, page_size)
    lens = [bucket, max(1, bucket // 2), max(1, bucket - 3)]
    n_pages = sum(pages_for(ln, page_size) for ln in lens) + 2
    args = _case(
        seed=page_size * 1000 + bucket,
        batch=len(lens),
        n_pages=n_pages,
        page_size=page_size,
        pages_per_seq=pages_per_seq,
        lens=lens,
    )
    _assert_bitwise(args)


def test_parity_all_padding_and_empty_rows():
    """Rows whose context is empty (len=0 — every page dead) must come
    out exactly zero, and partially-dead rows must be untouched by the
    garbage in their dead pages."""
    page_size, pages_per_seq = 8, 4
    lens = [0, 1, 9, 32]  # empty / sub-page / page+1 / full
    n_pages = sum(pages_for(ln, page_size) for ln in lens) + 1
    args = _case(7, len(lens), n_pages, page_size, pages_per_seq, lens)
    out = _assert_bitwise(args)
    assert np.array_equal(out[0], np.zeros(DIM, np.float32))
    assert not np.array_equal(out[3], np.zeros(DIM, np.float32))


def test_parity_noncontiguous_tables_match_contiguous_context():
    """A sequence scattered over shuffled pool slots must score exactly
    like the same context laid out contiguously (dense reference)."""
    page_size, ln = 4, 14
    pages_per_seq = pages_for(16, page_size)
    rng = np.random.default_rng(21)
    q = jnp.asarray(rng.normal(size=(1, DIM)).astype(np.float32))
    ctx = rng.normal(size=(pages_per_seq * page_size, DIM)).astype(np.float32)
    vtx = rng.normal(size=(pages_per_seq * page_size, DIM)).astype(np.float32)
    # scatter the contiguous context into a shuffled pool
    n_pages = pages_per_seq + 3
    order = rng.permutation(n_pages)[:pages_per_seq]
    k_pages = np.zeros((n_pages, page_size, DIM), np.float32)
    v_pages = np.zeros((n_pages, page_size, DIM), np.float32)
    for logical, slot in enumerate(order):
        k_pages[slot] = ctx[logical * page_size : (logical + 1) * page_size]
        v_pages[slot] = vtx[logical * page_size : (logical + 1) * page_size]
    tables = jnp.asarray(order[None].astype(np.int32))
    lens = jnp.asarray(np.array([ln], np.int32))
    paged = paged_decode_attention(
        q, jnp.asarray(k_pages), jnp.asarray(v_pages), tables, lens,
        n_heads=N_HEADS, interpret=True,
    )
    dense = jax.jit(
        lambda *a: dense_decode_attention(*a, n_heads=N_HEADS)
    )(q, jnp.asarray(ctx[None]), jnp.asarray(vtx[None]), lens)
    assert np.array_equal(np.asarray(paged), np.asarray(dense))


def test_dead_table_entries_are_ignored():
    """Entries past ``pages_for(len)`` may be any value (the sentinel
    included) without perturbing the output."""
    page_size, pages_per_seq = 4, 8
    lens = [10]
    n_pages = 8
    args = list(_case(3, 1, n_pages, page_size, pages_per_seq, lens))
    base = _assert_bitwise(tuple(args))
    tables = np.asarray(args[3]).copy()
    tables[0, pages_for(10, page_size):] = 0  # in-range garbage instead
    args[3] = jnp.asarray(tables)
    again = _assert_bitwise(tuple(args))
    assert np.array_equal(base, again)


# ------------------------------------------------------------ pool math


def test_pages_for_and_pool_bytes():
    assert pages_for(0, 16) == 0
    assert pages_for(1, 16) == 1
    assert pages_for(16, 16) == 1
    assert pages_for(17, 16) == 2
    # 2 (K+V) x pages x page_size x layers x dim x dtype_bytes
    assert kv_pool_bytes(256, 16, 4, 128) == 2 * 256 * 16 * 4 * 128 * 4


def test_pool_alloc_free_lifecycle():
    pool = PagedKvPool(layers=1, dim=8, n_pages=4, page_size=4)
    assert pool.sentinel == 4
    assert pool.pages_in_use == 0
    a = pool.alloc(3)
    assert a is not None and len(a) == 3 and pool.pages_in_use == 3
    # never a partial grant: over-ask returns None and takes nothing
    assert pool.alloc(2) is None
    assert pool.pages_in_use == 3
    pool.free(a[:1])
    assert pool.pages_in_use == 2
    b = pool.alloc(2)
    assert b is not None and pool.pages_in_use == 4
    pool.free(a[1:])
    pool.free(b)
    assert pool.pages_in_use == 0
    assert pool.pool_bytes == 2 * 4 * 4 * 8 * 4


def test_pool_rejects_double_free_and_foreign_pages():
    pool = PagedKvPool(layers=1, dim=8, n_pages=2, page_size=4)
    pages = pool.alloc(1)
    pool.free(pages)
    with pytest.raises(ValueError, match="double free"):
        pool.free(pages)
    with pytest.raises(ValueError, match="not in the pool"):
        pool.free([99])
    with pytest.raises(ValueError, match="negative"):
        pool.alloc(-1)
    with pytest.raises(ValueError, match="positive"):
        PagedKvPool(layers=1, dim=8, n_pages=0, page_size=4)


# --------------------------------------------- shared-page parity (PR 19)


def _shared_vs_private_case(seed, page_size, bucket):
    """Two sequences that share their physical prefix pages (prefix
    caching's COW layout) vs the same two sequences with private page
    copies. Outputs must be bitwise identical: attention only ever
    reads pages, so aliasing the table entries is invisible."""
    rng = np.random.default_rng(seed)
    prefix_pages = max(1, pages_for(bucket, page_size) // 2)
    lens = [bucket, max(prefix_pages * page_size + 1, bucket - 3)]
    pages_per_seq = max(pages_for(ln, page_size) for ln in lens)
    tails = [pages_for(ln, page_size) - prefix_pages for ln in lens]
    n_pages = prefix_pages * 3 + sum(tails) + 1  # shared + 2 copies + tails
    q = rng.normal(size=(2, DIM)).astype(np.float32)
    k_pages = rng.normal(size=(n_pages, page_size, DIM)).astype(np.float32)
    v_pages = rng.normal(size=(n_pages, page_size, DIM)).astype(np.float32)
    perm = list(rng.permutation(n_pages - 1))  # keep one sentinel-free slot

    def take(n):
        return [int(perm.pop()) for _ in range(n)]

    shared = take(prefix_pages)
    tail_pages = [take(t) for t in tails]
    copies = [take(prefix_pages) for _ in range(2)]
    for copy in copies:  # private copies carry identical bytes
        k_pages[copy] = k_pages[shared]
        v_pages[copy] = v_pages[shared]
    aliased = np.full((2, pages_per_seq), n_pages, np.int32)
    private = np.full((2, pages_per_seq), n_pages, np.int32)
    for b in range(2):
        aliased[b, : prefix_pages + tails[b]] = shared + tail_pages[b]
        private[b, : prefix_pages + tails[b]] = copies[b] + tail_pages[b]
    lens = jnp.asarray(np.asarray(lens, np.int32))
    args = (jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages))
    return args, jnp.asarray(aliased), jnp.asarray(private), lens


def _assert_shared_page_parity(seed, page_size, bucket):
    args, aliased, private, lens = _shared_vs_private_case(
        seed, page_size, bucket
    )
    out_aliased = _assert_bitwise((*args, aliased, lens))
    out_private = _assert_bitwise((*args, private, lens))
    assert np.array_equal(out_aliased, out_private), (
        "aliased prefix pages diverged from private copies "
        f"(page_size={page_size}, bucket={bucket})"
    )


def test_shared_prefix_pages_score_like_private_copies():
    """Tier-1 witness of the sweep below: page tables that alias the
    same physical prefix pages are bitwise equal to private copies."""
    _assert_shared_page_parity(seed=101, page_size=4, bucket=16)


@pytest.mark.slow
@pytest.mark.parametrize("page_size", [4, 8, 16])
@pytest.mark.parametrize("bucket", [16, 32, 64])
def test_shared_page_parity_sweep(page_size, bucket):
    """The full (page_size, bucket) sweep of the shared-page layout —
    interpret mode is slow, so only one combo runs in tier-1."""
    _assert_shared_page_parity(
        seed=page_size * 100 + bucket, page_size=page_size, bucket=bucket
    )


def test_pool_share_refcount_lifecycle():
    """COW sharing contract: ``share`` adds holders, ``free`` drops
    them, and the physical page only returns to the free list when the
    last holder lets go — ``pages_in_use`` never double-books."""
    pool = PagedKvPool(layers=1, dim=8, n_pages=4, page_size=4)
    pages = pool.alloc(2)
    assert [pool.refcount(p) for p in pages] == [1, 1]
    pool.share(pages)
    pool.share(pages[:1])
    assert pool.refcount(pages[0]) == 3
    assert pool.refcount(pages[1]) == 2
    assert pool.pages_in_use == 2  # three holders, two bookings
    pool.free(pages)
    pool.free(pages)
    assert pool.pages_in_use == 1  # pages[1] fully released
    assert pool.refcount(pages[0]) == 1
    pool.free(pages[:1])
    assert pool.pages_in_use == 0
    with pytest.raises(ValueError, match="double free"):
        pool.free(pages[:1])
    with pytest.raises(ValueError, match="unallocated"):
        pool.share(pages[:1])
