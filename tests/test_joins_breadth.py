"""Join/groupby option breadth beyond test_joins.py: multi-key joins,
self-joins, id= derivation, groupby sort_by, UDF flag interactions
under streams (reference test_joins.py / test_common.py coverage)."""

from __future__ import annotations

import pathway_tpu as pw

from .utils import T, run_table


def test_multi_key_join():
    left = T(
        """
      | a | b | v
    1 | 1 | x | 10
    2 | 1 | y | 20
    3 | 2 | x | 30
    """
    )
    right = T(
        """
      | a | b | w
    7 | 1 | x | 100
    8 | 2 | x | 300
    9 | 2 | y | 999
    """
    )
    j = left.join(right, left.a == right.a, left.b == right.b).select(
        v=left.v, w=right.w
    )
    assert sorted(run_table(j).values()) == [(10, 100), (30, 300)]


def test_self_join():
    # self-join through value keys: who reports to whom
    emp = T(
        """
      | emp_id | boss_id | name
    1 | 1      | 0       | root
    2 | 2      | 1       | alice
    3 | 3      | 1       | bob
    """
    )
    mgr = emp.copy()
    j = emp.join(mgr, emp.boss_id == mgr.emp_id).select(
        who=emp.name, boss=mgr.name
    )
    assert sorted(run_table(j).values()) == [("alice", "root"), ("bob", "root")]


def test_join_id_from_keeps_left_universe():
    left = T(
        """
      | k | v
    1 | a | 1
    2 | b | 2
    """
    )
    right = T(
        """
      | k | w
    7 | a | 10
    8 | b | 20
    """
    )
    j = left.join(right, left.k == right.k, id=left.id).select(
        v=left.v, w=right.w
    )
    rows = run_table(j)
    base = run_table(left.select(pw.this.v))
    assert set(rows.keys()) == set(base.keys())  # ids inherited from left


def test_groupby_sort_by_controls_tuple_order():
    t = T(
        """
      | g | v | o
    1 | a | 10 | 3
    2 | a | 20 | 1
    3 | a | 30 | 2
    """
    )
    r = t.groupby(pw.this.g, sort_by=pw.this.o).reduce(
        pw.this.g, tup=pw.reducers.tuple(pw.this.v)
    )
    ((_, tup),) = run_table(r).values()
    assert tup == (20, 30, 10)  # ordered by o: 1, 2, 3


def test_udf_propagate_none_flag():
    @pw.udf(propagate_none=True)
    def add(a: int, b: int) -> int:
        return a + b

    t = T(
        """
      | a | b
    1 | 1 | 2
    2 |   | 5
    """
    )  # empty markdown cell parses as None
    r = t.select(s=add(pw.this.a, pw.this.b))
    rows = sorted(run_table(r).values(), key=repr)
    assert (3,) in rows
    assert (None,) in rows  # None input short-circuits, no TypeError


def test_deterministic_false_udf_memoizes_for_retraction():
    calls = {"n": 0}

    @pw.udf(deterministic=False)
    def stamp(v: int) -> int:
        calls["n"] += 1
        return v * 100 + calls["n"]

    t = T(
        """
      | v | __time__ | __diff__
    1 | 1 | 2        | 1
    1 | 1 | 4        | -1
    """
    )
    r = t.select(s=stamp(pw.this.v))
    assert run_table(r) == {}  # insert then retraction nets to empty
    # the retraction replayed the MEMOIZED value (1 call), instead of
    # recomputing a different stamp that would fail to cancel
    assert calls["n"] == 1


def test_join_chain_three_tables():
    a = T(
        """
      | k | x
    1 | 1 | a1
    """
    )
    b = T(
        """
      | k | y
    7 | 1 | b1
    """
    )
    c = T(
        """
      | k | z
    9 | 1 | c1
    """
    )
    ab = a.join(b, a.k == b.k).select(k=a.k, x=a.x, y=b.y)
    abc = ab.join(c, ab.k == c.k).select(x=ab.x, y=ab.y, z=c.z)
    assert list(run_table(abc).values()) == [("a1", "b1", "c1")]
