"""Sorted-index oracles (stdlib/indexing/sorting.py) — parity with
reference sorting.py:53+ semantics under insertion/retraction."""

import pathway_tpu as pw
from pathway_tpu.stdlib.indexing import (
    build_sorted_index,
    retrieve_prev_next_values,
    sort_from_index,
)

from .utils import run_table


def _keys_table(rows: str):
    return pw.debug.table_from_markdown(rows)


def test_build_sorted_index_structure():
    nodes = _keys_table(
        """
      | key
    1 | 5
    2 | 1
    3 | 9
    4 | 3
    5 | 7
    """
    )
    out = build_sorted_index(nodes)
    index, oracle = out["index"], out["oracle"]
    rows = run_table(
        index.select(key=pw.this.key, left=pw.this.left, right=pw.this.right, parent=pw.this.parent)
    )
    assert len(rows) == 5
    by_key = {r[0]: r for r in rows.values()}
    # exactly one root; every non-root's parent points into the table
    roots = [r for r in rows.values() if r[3] is None]
    assert len(roots) == 1
    ids = set(rows.keys())
    for _key, left, right, parent in rows.values():
        for p in (left, right, parent):
            assert p is None or p in ids
    # BST invariant: left subtree keys < node key < right subtree keys
    key_of = {k: v[0] for k, v in rows.items()}
    for k, (key, left, right, _p) in rows.items():
        if left is not None:
            assert key_of[left] < key
        if right is not None:
            assert key_of[right] > key


def test_sort_from_index_order_and_instances():
    nodes = pw.debug.table_from_markdown(
        """
      | key | instance
    1 | 5   | 0
    2 | 1   | 0
    3 | 9   | 1
    4 | 3   | 0
    5 | 7   | 1
    """
    )
    out = build_sorted_index(nodes, instance=nodes.instance)
    pn = sort_from_index(out["index"])
    joined = nodes.select(key=pw.this.key, inst=pw.this.instance) + pn
    rows = run_table(joined)
    # reconstruct each instance chain: follow next from the head
    by_id = dict(rows.items())
    for inst, expect in ((0, [1, 3, 5]), (1, [7, 9])):
        heads = [
            k
            for k, (key, i, prev, nxt) in rows.items()
            if i == inst and prev is None
        ]
        assert len(heads) == 1
        chain = []
        cur = heads[0]
        while cur is not None:
            chain.append(by_id[cur][0])
            cur = by_id[cur][3]
        assert chain == expect


def test_sorted_index_incremental_retraction():
    """Streamed inserts + a retraction: the treap and prev/next chain
    reflect the final state (reference streaming-semantics model)."""
    nodes = pw.debug.table_from_markdown(
        """
      | key | __time__ | __diff__
    1 | 5   | 2        | 1
    2 | 1   | 2        | 1
    3 | 9   | 4        | 1
    1 | 5   | 6        | -1
    4 | 2   | 6        | 1
    """
    )
    out = build_sorted_index(nodes)
    pn = sort_from_index(out["index"])
    joined = nodes.select(key=pw.this.key) + pn
    rows = run_table(joined)
    keys = sorted(r[0] for r in rows.values())
    assert keys == [1, 2, 9]
    by_id = dict(rows.items())
    heads = [k for k, (key, prev, nxt) in rows.items() if prev is None]
    chain, cur = [], heads[0]
    while cur is not None:
        chain.append(by_id[cur][0])
        cur = by_id[cur][2]
    assert chain == [1, 2, 9]


def test_retrieve_prev_next_values():
    # ordered chain 1->2->3->4 with values only at 1 and 4
    tbl = pw.debug.table_from_markdown(
        """
      | value | pos
    1 | 10    | 1
    2 |       | 2
    3 |       | 3
    4 | 40    | 4
    """
    ).select(
        value=pw.if_else(pw.this.value == 0, None, pw.this.value),
        pos=pw.this.pos,
    )
    srt = build_sorted_index(tbl.select(key=pw.this.pos))
    pn = sort_from_index(srt["index"])
    ordered = tbl.select(pw.this.value) + pn
    got = retrieve_prev_next_values(ordered)
    rows = run_table(ordered.select(v=pw.this.value) + got)
    vals = {k: v for k, v in rows.items()}
    by_value = {v[0]: k for k, v in rows.items()}
    id10, id40 = by_value[10], by_value[40]
    for k, (v, pv, nv) in vals.items():
        if v is not None:
            assert pv == k and nv == k  # self-inclusive seed
        else:
            assert vals[pv][0] == 10 and vals[nv][0] == 40
