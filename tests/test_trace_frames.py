"""Build-time user trace frames (reference internals/trace.py): build
errors and runtime row errors point at the USER's source line that
created the operator, not an engine internal.
"""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.dataflow import EngineError
from pathway_tpu.internals.graph_runner import GraphRunner
from pathway_tpu.internals.trace import Frame, Trace, trace_user_frame

from .utils import T


def test_build_error_carries_user_call_site():
    t1 = T(
        """
          | a
        1 | 1
        """
    )
    t2 = T(
        """
          | b
        1 | 2
        """
    )
    with pytest.raises(Exception) as excinfo:
        t1.concat(t2)  # MARKER-BUILD
    notes = getattr(excinfo.value, "__notes__", [])
    assert any("MARKER-BUILD" in n for n in notes), notes
    assert any("test_trace_frames.py" in n for n in notes)


def test_runtime_error_names_user_line_on_abort():
    t = T(
        """
          | a  | b
        1 | 10 | 0
        """
    )
    res = t.select(q=pw.apply(lambda a, b: a // b, pw.this.a, pw.this.b))  # MARKER-RUNTIME
    runner = GraphRunner()
    cap, _ = runner.capture(res)
    with pytest.raises(EngineError) as excinfo:
        runner.run()
    msg = str(excinfo.value)
    assert "Occurred here" in msg
    assert "MARKER-RUNTIME" in msg
    assert "test_trace_frames.py" in msg


def test_error_log_carries_user_frame():
    t = T(
        """
          | a  | b
        1 | 10 | 0
        2 | 4  | 2
        """
    )
    res = t.select(q=pw.apply(lambda a, b: a // b, pw.this.a, pw.this.b))  # MARKER-LOG
    err_log = pw.global_error_log()
    runner = GraphRunner()
    runner.engine.terminate_on_error = False
    cap, _ = runner.capture(res)
    ecap, _ = runner.capture(err_log)
    runner.run()
    entries = list(ecap.state.values())
    assert len(entries) == 1
    _op_id, message, trace = entries[0]
    assert "ZeroDivisionError" in message
    user = trace.value["user_frame"]
    assert user["file"].endswith("test_trace_frames.py")
    assert "MARKER-LOG" in user["line_text"]
    assert isinstance(user["line"], int)


def test_user_frame_skips_package_frames():
    tr = Trace.from_traceback()
    assert tr.user_frame is not None
    assert tr.user_frame.filename.endswith("test_trace_frames.py")
    internal = Frame(
        filename="/x/pathway_tpu/internals/table.py",
        line_number=1,
        line="x",
        function="select",
    )
    # constructed path is outside the real package dir, so approximate:
    # the real check uses the installed package location
    import pathway_tpu.internals.table as table_mod

    real_internal = Frame(
        filename=table_mod.__file__, line_number=1, line="x", function="select"
    )
    assert not real_internal.is_external()
    external = Frame(
        filename=__file__, line_number=1, line="x", function="test"
    )
    assert external.is_external()
    assert internal is not None  # silence lints


def test_trace_user_frame_decorator_reraises_once():
    @trace_user_frame
    def build():
        raise ValueError("boom")

    with pytest.raises(ValueError) as excinfo:
        build()  # MARKER-DECOR
    notes = getattr(excinfo.value, "__notes__", [])
    assert any("MARKER-DECOR" in n for n in notes)
    # re-raising through another decorated frame must not duplicate notes
    @trace_user_frame
    def outer():
        build()

    with pytest.raises(ValueError) as excinfo2:
        outer()
    notes2 = getattr(excinfo2.value, "__notes__", [])
    assert len(notes2) == len([n for n in notes2])  # no crash; single note
    assert sum("Occurred here" in n for n in notes2) == 1
