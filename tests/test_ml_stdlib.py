"""Fuzzy join, HMM reducer, gradual broadcast.

Mirrors the reference coverage of stdlib/ml/smart_table_ops
(test_fuzzy_join), ml/hmm, and the gradual_broadcast operator (R15).
"""

from __future__ import annotations

import numpy as np

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner
from .utils import T, run_table


def test_fuzzy_match_tables_basic():
    left = T(
        """
          | name
        1 | john smith
        2 | alice cooper
        3 | bob marley
        """
    )
    right = T(
        """
          | name
        11 | smith john
        12 | cooper alice
        13 | marley bob
        """
    )
    res = pw.ml.fuzzy_match_tables(left, right)
    state = run_table(res)
    got = {(int(l), int(r)) for l, r, _w in state.values()}
    # keys are the original row pointers
    lkeys, _ = _keys_by_name(left)
    rkeys, _ = _keys_by_name(right)
    assert got == {
        (lkeys["john smith"], rkeys["smith john"]),
        (lkeys["alice cooper"], rkeys["cooper alice"]),
        (lkeys["bob marley"], rkeys["marley bob"]),
    }


def _keys_by_name(table):
    state = run_table(table.select(name=pw.this.name))
    return {row[0]: int(k) for k, row in state.items()}, state


def test_smart_fuzzy_match_one_to_one():
    """Greedy assignment: the heavier pair wins, each node used once."""
    left = T(
        """
          | name
        1 | aa bb cc
        2 | aa bb
        """
    )
    right = T(
        """
          | name
        11 | aa bb cc
        12 | aa
        """
    )
    res = pw.ml.smart_fuzzy_match(left.name, right.name)
    state = run_table(res)
    lkeys, _ = _keys_by_name(left)
    rkeys, _ = _keys_by_name(right)
    got = {(int(l), int(r)) for l, r, _w in state.values()}
    assert (lkeys["aa bb cc"], rkeys["aa bb cc"]) in got
    assert (lkeys["aa bb"], rkeys["aa"]) in got


def test_fuzzy_self_match():
    t = T(
        """
          | name
        1 | data stream processing
        2 | stream data processing
        3 | quantum chess
        """
    )
    # self match: smart_fuzzy_match detects same column on same table
    res = pw.ml.smart_fuzzy_match(t.name, t.name)
    state = run_table(res)
    pairs = {(int(l), int(r)) for l, r, _w in state.values()}
    keys, _ = _keys_by_name(t)
    a, b = keys["data stream processing"], keys["stream data processing"]
    assert (min(a, b), max(a, b)) in pairs
    assert len(pairs) == 1  # quantum chess matches nobody


def test_fuzzy_match_low_level_api():
    """The Edge/Feature low-level contract (reference fuzzy_match :265)."""
    feats = T(
        """
           | weight | normalization_type
        f1 | 1.0    | 3
        f2 | 1.0    | 3
        """
    )
    # feature pointers = the rows' actual keys
    fstate = run_table(feats.select(w=pw.this.weight))
    f1, f2 = (pw.Pointer(k) for k in sorted(fstate.keys()))
    el = pw.debug.table_from_rows(_edge_schema(), [(1, f1, 1.0), (2, f2, 1.0)])
    er = pw.debug.table_from_rows(_edge_schema(), [(11, f1, 1.0), (12, f2, 1.0)])
    res = pw.ml.fuzzy_match(el, er, feats)
    state = run_table(res)
    got = {(int(l), int(r)) for l, r, _w in state.values()}
    assert len(got) == 2


def _edge_schema():
    class EdgeSchema(pw.Schema):
        node: int
        feature: pw.Pointer
        weight: float

    return EdgeSchema


def test_hmm_reducer():
    import networkx as nx
    from functools import partial

    def emission(observation, state):
        table = {
            ("HUNGRY", "GRUMPY"): 0.9,
            ("HUNGRY", "HAPPY"): 0.1,
            ("FULL", "GRUMPY"): 0.3,
            ("FULL", "HAPPY"): 0.7,
        }
        return float(np.log(table[(state, observation)]))

    g = nx.DiGraph()
    for s in ("HUNGRY", "FULL"):
        g.add_node(s, calc_emission_log_ppb=partial(emission, state=s))
    g.add_edge("HUNGRY", "HUNGRY", log_transition_ppb=float(np.log(0.4)))
    g.add_edge("HUNGRY", "FULL", log_transition_ppb=float(np.log(0.6)))
    g.add_edge("FULL", "HUNGRY", log_transition_ppb=float(np.log(0.6)))
    g.add_edge("FULL", "FULL", log_transition_ppb=float(np.log(0.4)))

    obs = T(
        """
          | observation | g
        1 | GRUMPY      | 0
        2 | GRUMPY      | 0
        3 | HAPPY       | 0
        """
    )
    hmm = pw.ml.create_hmm_reducer(g)
    res = obs.groupby(pw.this.g).reduce(path=hmm(pw.this.observation))
    state = run_table(res)
    (row,) = state.values()
    path = row[0]
    assert len(path) == 3
    assert path[-1] == "FULL"  # HAPPY strongly suggests FULL
    assert path[0] == "HUNGRY"


def test_hmm_start_nodes_restrict_initial_state():
    import networkx as nx
    from functools import partial

    def emission(observation, state):
        # HAPPY strongly favors FULL — but only HUNGRY may start
        table = {
            ("HUNGRY", "HAPPY"): 0.1,
            ("FULL", "HAPPY"): 0.9,
            ("HUNGRY", "GRUMPY"): 0.9,
            ("FULL", "GRUMPY"): 0.1,
        }
        return float(np.log(table[(state, observation)]))

    g = nx.DiGraph(start_nodes=["HUNGRY"])
    for s in ("HUNGRY", "FULL"):
        g.add_node(s, calc_emission_log_ppb=partial(emission, state=s))
    for a in ("HUNGRY", "FULL"):
        for b in ("HUNGRY", "FULL"):
            g.add_edge(a, b, log_transition_ppb=float(np.log(0.5)))

    obs = T(
        """
          | observation | g
        1 | HAPPY       | 0
        """
    )
    hmm = pw.ml.create_hmm_reducer(g)
    res = obs.groupby(pw.this.g).reduce(path=hmm(pw.this.observation))
    (row,) = run_table(res).values()
    assert row[0] == ("HUNGRY",)  # FULL forbidden as initial state


def test_gradual_broadcast():
    data = T(
        """
          | a
        1 | 10
        2 | 20
        3 | 30
        """
    )
    thresholds = pw.debug.table_from_markdown(
        """
          | lower | value | upper | __time__
        1 | 0.0   | 1.0   | 2.0   | 0
        2 | 0.5   | 1.5   | 2.5   | 2
        3 | 5.0   | 6.0   | 7.0   | 4
        """
    )
    res = data._gradual_broadcast(
        thresholds, thresholds.lower, thresholds.value, thresholds.upper
    )
    runner = GraphRunner()
    cap, names = runner.capture(res)
    runner.run()
    apx_i = names.index("apx_value")
    # final: the t=2 update stayed inside [0,2] band -> kept 1.0; the t=4
    # update left the band -> rebroadcast 6.0
    vals = {row[names.index("a")]: row[apx_i] for row in cap.state.values()}
    assert vals == {10: 6.0, 20: 6.0, 30: 6.0}
    # intermediate history shows the band logic: no re-emission at t=2
    times_with_changes = sorted({t for _k, _r, t, _d in cap.stream})
    assert 2 not in times_with_changes
    pw.clear_graph()


def test_gradual_broadcast_drifting_threshold_rebroadcasts():
    """A threshold that drifts one band-width per update must eventually
    rebroadcast: the check is attached-value vs the NEW band, not new
    value vs the old band."""
    data = T(
        """
          | a
        1 | 10
        """
    )
    thresholds = pw.debug.table_from_markdown(
        """
          | lower | value | upper | __time__
        1 | 0.0   | 1.0   | 2.0   | 0
        2 | 1.0   | 1.9   | 3.0   | 2
        3 | 1.5   | 2.9   | 4.0   | 4
        4 | 2.5   | 3.9   | 5.0   | 6
        """
    )
    res = data._gradual_broadcast(
        thresholds, thresholds.lower, thresholds.value, thresholds.upper
    )
    runner = GraphRunner()
    cap, names = runner.capture(res)
    runner.run()
    (row,) = cap.state.values()
    # attached 1.0 leaves [1.5, 4.0] at t=4 -> rebroadcast to 2.9, which
    # then stays inside the final [2.5, 5.0] band
    assert row[names.index("apx_value")] == 2.9
    pw.clear_graph()


def test_udf_propagate_none_with_cache():
    calls = []

    @pw.udf(propagate_none=True, cache_strategy=pw.udfs.InMemoryCache())
    def inc(x: int) -> int:
        calls.append(x)
        return x + 1

    t = T(
        """
          | x
        1 | 5
        2 |
        """
    )
    res = t.select(y=inc(pw.this.x))
    state = run_table(res)
    assert sorted((r[0] for r in state.values()), key=repr) == [6, None]
    assert calls == [5]
    pw.clear_graph()


def test_ml_dataset_loader_synthetic():
    train, test = pw.ml.datasets.classification.load_mnist_sample(
        1000, synthetic=True
    )
    s_train = run_table(train)
    s_test = run_table(test)
    assert len(s_train) == 900 and len(s_test) == 100
    row = next(iter(s_train.values()))
    assert row[0].shape == (784,) and row[1] in set("0123456789")
    pw.clear_graph()
