"""Schema metaclass + dtype system breadth (reference schema.py 947 LoC,
dtype.py 979 LoC; tests/test_schema.py style)."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.schema import (
    column_definition,
    schema_builder,
    schema_from_dict,
    schema_from_pandas,
    schema_from_types,
)

from .utils import T, run_table


def test_schema_class_declaration():
    class S(pw.Schema):
        a: int
        b: float
        c: str
        d: bool
        e: bytes

    assert S.column_names() == ["a", "b", "c", "d", "e"]
    types = S.dtypes()
    assert types["a"] is dt.INT and types["b"] is dt.FLOAT
    assert types["c"] is dt.STR and types["d"] is dt.BOOL
    assert types["e"] is dt.BYTES


def test_schema_optional_types():
    class S(pw.Schema):
        a: int | None
        b: str | None

    assert dt.unoptionalize(S.dtypes()["a"]) is dt.INT
    assert dt.unoptionalize(S.dtypes()["b"]) is dt.STR


def test_schema_primary_key_and_defaults():
    class S(pw.Schema):
        key: int = column_definition(primary_key=True)
        val: str = column_definition(default_value="x")

    assert S.primary_key_columns() == ["key"]
    assert S.default_values() == {"val": "x"}


def test_schema_or_merges_columns():
    class A(pw.Schema):
        a: int

    class B(pw.Schema):
        b: str

    M = A | B
    assert M.column_names() == ["a", "b"]


def test_schema_with_types_and_without():
    class S(pw.Schema):
        a: int
        b: str

    S2 = S.with_types(a=float)
    assert S2.dtypes()["a"] is dt.FLOAT
    S3 = S.without("b")
    assert S3.column_names() == ["a"]


def test_schema_builder_and_from_types():
    S = schema_builder(
        {
            "x": column_definition(dtype=dt.INT),
            "y": column_definition(dtype=dt.STR),
        },
        name="Built",
    )
    assert S.column_names() == ["x", "y"]
    S2 = schema_from_types(x=int, y=str)
    assert S2.dtypes() == S.dtypes()


def test_schema_from_dict_and_pandas():
    S = schema_from_dict({"a": int, "b": float})
    assert S.dtypes()["a"] is dt.INT
    import pandas as pd

    df = pd.DataFrame({"n": [1, 2], "s": ["x", "y"], "f": [0.5, 1.5]})
    S2 = schema_from_pandas(df)
    types = S2.dtypes()
    assert types["n"] is dt.INT and types["f"] is dt.FLOAT and types["s"] is dt.STR


def test_schema_inheritance():
    class Base(pw.Schema):
        a: int

    class Child(Base):
        b: str

    assert Child.column_names() == ["a", "b"]


def test_append_only_property():
    class S(pw.Schema, append_only=True):
        a: int

    assert S.universe_properties().append_only


# ---- dtype lattice ------------------------------------------------------


def test_dtype_wrap_and_equality():
    assert dt.wrap(int) is dt.INT
    assert dt.wrap(float) is dt.FLOAT
    assert dt.wrap(str) is dt.STR
    assert dt.wrap(dt.INT) is dt.INT


def test_dtype_optional_idempotent():
    o = dt.Optional(dt.INT)
    assert dt.unoptionalize(o) is dt.INT
    assert dt.unoptionalize(dt.INT) is dt.INT


def test_dtype_tuple_and_list():
    t = dt.Tuple(dt.INT, dt.STR)
    assert "INT" in repr(t).upper() or t is not None


def test_table_schema_flows_through_ops():
    t = T(
        """
      | a | s
    1 | 1 | x
    """
    )
    r = t.select(b=pw.this.a + 1, up=pw.this.s.str.upper())
    assert r._columns["b"].dtype is dt.INT
    assert r._columns["up"].dtype is dt.STR
    f = t.filter(pw.this.a > 0)
    assert f._columns["a"].dtype is dt.INT


def test_typed_groupby_result():
    t = T(
        """
      | g | v
    1 | a | 1
    """
    )
    r = t.groupby(pw.this.g).reduce(
        pw.this.g, s=pw.reducers.sum(pw.this.v), n=pw.reducers.count()
    )
    assert r._columns["n"].dtype is dt.INT


def test_schema_type_coercion_at_ingest():
    class S(pw.Schema):
        a: int
        b: float
        c: str

    t = pw.debug.table_from_rows(S, [("3", "1.5", 7)])
    ((a, b, c),) = run_table(t).values()
    assert (a, b, c) == (3, 1.5, "7")


def test_outer_join_columns_become_optional():
    """Null-extended join sides type their columns Optional (reference
    joins.py output typing)."""
    import pathway_tpu as pw
    from pathway_tpu.internals import dtype as dt

    t1 = pw.debug.table_from_markdown(
        """
          | a | b
        1 | 1 | x
        """
    )
    t2 = pw.debug.table_from_markdown(
        """
          | a | c
        1 | 1 | 2.5
        """
    )
    left = t1.join_left(t2, pw.left.a == pw.right.a).select(pw.left.b, c=pw.right.c)
    assert left._columns["b"].dtype is dt.STR
    assert left._columns["c"].dtype == dt.Optional(dt.FLOAT)
    outer = t1.join_outer(t2, pw.left.a == pw.right.a).select(
        b=pw.left.b, c=pw.right.c
    )
    assert outer._columns["b"].dtype == dt.Optional(dt.STR)
    assert outer._columns["c"].dtype == dt.Optional(dt.FLOAT)
    inner = t1.join(t2, pw.left.a == pw.right.a).select(pw.left.b, c=pw.right.c)
    assert inner._columns["c"].dtype is dt.FLOAT
    pw.clear_graph()
