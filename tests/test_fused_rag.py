"""Fused single-dispatch RAG pipeline (ops/fused_rag.py) — the TPU
replacement for the reference's 3-stage query path (embedders.py:270 ->
usearch_integration.rs:53 -> rerankers.py:186)."""

import numpy as np
import pytest

from pathway_tpu.models.sentence_encoder import CrossEncoderScorer, SentenceEncoder
from pathway_tpu.ops.fused_rag import FusedRagPipeline


@pytest.fixture(scope="module")
def enc():
    return SentenceEncoder(max_batch=64)


def test_retrieval_only_exact_match(enc):
    p = FusedRagPipeline(enc, None, reserved_space=128)
    docs = [f"passage {i} about topic {i % 7}" for i in range(40)]
    p.add_docs(list(range(40)), docs)
    r = p.query("passage 3 about topic 3", k=1, k_retrieve=8)
    assert r[0][0] == 3


def test_rerank_returns_k(enc):
    p = FusedRagPipeline(enc, CrossEncoderScorer(), reserved_space=128, doc_seq_len=48)
    docs = [f"passage {i} about topic {i % 7}" for i in range(30)]
    p.add_docs(list(range(30)), docs)
    r = p.query("passage 12 about topic 5", k=5, k_retrieve=16)
    assert len(r) == 5
    assert len({k for k, _ in r}) == 5  # distinct docs


def test_incremental_adds_and_removes(enc):
    p = FusedRagPipeline(enc, None, reserved_space=64)
    p.add_docs(list(range(20)), [f"doc number {i}" for i in range(20)])
    p.query("doc number 1", k=1)  # resident
    p.add_docs([100], ["an unmistakably unique zebra document"])
    r = p.query("an unmistakably unique zebra document", k=1)
    assert r[0][0] == 100
    p.remove_docs([100])
    r = p.query("an unmistakably unique zebra document", k=1)
    assert r[0][0] != 100


def test_growth_past_reserved_space(enc):
    p = FusedRagPipeline(enc, None, reserved_space=64)
    docs = [f"growing corpus item {i} flavor {i % 11}" for i in range(300)]
    p.add_docs(list(range(300)), docs)
    r = p.query("growing corpus item 250 flavor 8", k=1, k_retrieve=8)
    assert r[0][0] == 250


def test_query_async_matches_sync(enc):
    p = FusedRagPipeline(enc, None, reserved_space=64)
    p.add_docs(list(range(10)), [f"async path doc {i}" for i in range(10)])
    sync = p.query("async path doc 4", k=3, k_retrieve=8)
    hits = p.resolve(*p.query_async("async path doc 4", k=3, k_retrieve=8), k=3)
    assert [k for k, _ in sync] == [k for k, _ in hits]


def test_empty_and_missing(enc):
    p = FusedRagPipeline(enc, None, reserved_space=64)
    assert p.query_batch([], 3) == []
    assert p.query("anything", 3) == []  # empty index
    p.add_docs([1], ["only doc"])
    r = p.query("only doc", k=5, k_retrieve=8)
    assert [k for k, _ in r] == [1]  # padding slots filtered out
