"""RAG serving layer end-to-end over real HTTP (reference
xpacks/llm/servers.py + integration_tests/webserver): the QA REST
server answers /v1/pw_ai_answer against a fake chat + deterministic
embedder, and /v1/statistics reports index state."""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.request

import pytest

import pathway_tpu as pw

from .mocks import FakeChatModel, fake_embeddings_model, make_docs_table


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _post(port: int, path: str, payload: dict, timeout: float = 10.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    return json.loads(urllib.request.urlopen(req, timeout=timeout).read())


def test_qa_rest_server_answers_over_http():
    from pathway_tpu.internals.graph_runner import GraphRunner
    from pathway_tpu.xpacks.llm.question_answering import BaseRAGQuestionAnswerer
    from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer

    port = _free_port()
    docs = make_docs_table(
        [
            ("tpu pods interconnect chips over ici links", "/d/ici.txt"),
            ("streaming dataflow engines process retractions", "/d/stream.txt"),
        ]
    )
    store = VectorStoreServer(docs, embedder=fake_embeddings_model)
    rag = BaseRAGQuestionAnswerer(llm=FakeChatModel(), indexer=store)
    rag.build_server(host="127.0.0.1", port=port)

    got: dict = {}
    errors: list = []

    runner = GraphRunner()
    for table, sink in list(pw.parse_graph.outputs):
        build = sink.get("build")
        if build is not None:
            build(runner, table)
    for spec in list(pw.parse_graph.subscriptions):
        runner.subscribe(
            spec["table"],
            on_change=spec.get("on_change"),
            on_time_end=spec.get("on_time_end"),
            on_end=spec.get("on_end"),
        )

    def client():
        try:
            deadline = time.time() + 25
            while time.time() < deadline:
                try:
                    got["answer"] = _post(
                        port, "/v1/pw_ai_answer", {"prompt": "what links tpu chips?"}
                    )
                    break
                except Exception:
                    time.sleep(0.3)
            got["stats"] = _post(port, "/v1/statistics", {})
        except Exception as e:  # noqa: BLE001
            errors.append(e)
        finally:
            runner.engine.stop()

    t = threading.Thread(target=client, daemon=True)
    t.start()
    runner.run()
    t.join(timeout=30)
    pw.clear_graph()

    assert not errors, errors
    answer = got["answer"]
    text = answer if isinstance(answer, str) else json.dumps(answer)
    assert "ici" in text.lower() or text  # fake chat echoes context+prompt
    stats = got["stats"]
    assert isinstance(stats, dict) and stats  # file counts / timestamps


def test_rag_client_against_live_server():
    """RAGClient (reference question_answering.py:854) drives the same
    live server: retrieve, statistics, and answer round-trips."""
    from pathway_tpu.internals.graph_runner import GraphRunner
    from pathway_tpu.xpacks.llm.question_answering import (
        BaseRAGQuestionAnswerer,
        RAGClient,
    )
    from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer

    port = _free_port()
    docs = make_docs_table(
        [
            ("tpu pods interconnect chips over ici links", "/d/ici.txt"),
            ("streaming dataflow engines process retractions", "/d/stream.txt"),
        ]
    )
    store = VectorStoreServer(docs, embedder=fake_embeddings_model)
    rag = BaseRAGQuestionAnswerer(llm=FakeChatModel(), indexer=store)
    rag.build_server(host="127.0.0.1", port=port)

    got: dict = {}
    errors: list = []

    runner = GraphRunner()
    for table, sink in list(pw.parse_graph.outputs):
        build = sink.get("build")
        if build is not None:
            build(runner, table)
    for spec in list(pw.parse_graph.subscriptions):
        runner.subscribe(
            spec["table"],
            on_change=spec.get("on_change"),
            on_time_end=spec.get("on_time_end"),
            on_end=spec.get("on_end"),
        )

    def client():
        try:
            c = RAGClient(host="127.0.0.1", port=port)
            deadline = time.time() + 25
            while time.time() < deadline:
                try:
                    got["answer"] = c.pw_ai_answer("what links tpu chips?")
                    break
                except Exception:
                    time.sleep(0.3)
            got["docs"] = c.retrieve("tpu interconnect", k=1)
            got["stats"] = c.statistics()
        except Exception as e:  # noqa: BLE001
            errors.append(e)
        finally:
            runner.engine.stop()

    t = threading.Thread(target=client, daemon=True)
    t.start()
    runner.run()
    t.join(timeout=30)
    pw.clear_graph()

    assert not errors, errors
    assert got["answer"]
    # fake_embeddings_model hashes content (not semantic): assert the
    # result is a well-formed hit from the corpus, not which one
    assert isinstance(got["docs"], list) and len(got["docs"]) == 1
    hit = got["docs"][0]
    assert {"text", "metadata", "dist"} <= set(hit)
    assert hit["metadata"]["path"] in ("/d/ici.txt", "/d/stream.txt")
    assert isinstance(got["stats"], dict) and got["stats"]
