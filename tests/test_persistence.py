"""Checkpoint/recovery tests.

Mirrors the reference's persistence coverage
(/root/reference/python/pathway/tests/test_persistence.py and the
integration_tests/wordcount recovery harness): run a streaming pipeline
with a persistence config, "crash" (end the run), restart, and check
that sinks are exactly-once and state recovers.
"""

from __future__ import annotations

import json
import os

import pytest

import pathway_tpu as pw
from pathway_tpu.engine import persistence as eng_persist
from pathway_tpu.internals.graph_runner import GraphRunner


class WordSchema(pw.Schema):
    word: str


@pytest.fixture(autouse=True)
def _oneshot_fs(monkeypatch):
    monkeypatch.setenv("PATHWAY_TPU_FS_ONESHOT", "1")


def _write_jsonl(path, words):
    with open(path, "w") as f:
        for w in words:
            f.write(json.dumps({"word": w}) + "\n")


def _wordcount_run(in_dir, backend, events):
    words = pw.io.jsonlines.read(
        str(in_dir), schema=WordSchema, mode="streaming", persistent_id="words"
    )
    counts = words.groupby(pw.this.word).reduce(
        word=pw.this.word, count=pw.reducers.count()
    )
    pw.io.subscribe(
        counts,
        on_change=lambda key, row, time, is_addition: events.append(
            (row["word"], row["count"], is_addition)
        ),
    )
    pw.run(persistence_config=pw.persistence.Config.simple_config(backend))
    pw.clear_graph()


def test_wordcount_recovery_filesystem(tmp_path):
    in_dir = tmp_path / "in"
    in_dir.mkdir()
    _write_jsonl(in_dir / "a.jsonl", ["cat", "dog", "cat"])
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstorage"))

    ev1: list = []
    _wordcount_run(in_dir, backend, ev1)
    assert ("cat", 2, True) in ev1 and ("dog", 1, True) in ev1

    # restart with unchanged input: replay rebuilds state, sinks stay quiet
    ev2: list = []
    _wordcount_run(in_dir, backend, ev2)
    assert ev2 == []

    # restart with one new file: only incremental changes reach the sink
    _write_jsonl(in_dir / "b.jsonl", ["cat", "emu"])
    ev3: list = []
    _wordcount_run(in_dir, backend, ev3)
    assert ("emu", 1, True) in ev3
    assert ("cat", 2, False) in ev3 and ("cat", 3, True) in ev3  # 2 -> 3
    assert not any(w == "dog" for w, _c, _a in ev3)  # untouched group silent


def test_recovered_state_visible_to_capture(tmp_path):
    in_dir = tmp_path / "in"
    in_dir.mkdir()
    _write_jsonl(in_dir / "a.jsonl", ["x", "y"])
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstorage"))
    cfg = pw.persistence.Config.simple_config(backend)

    ev1: list = []
    _wordcount_run(in_dir, backend, ev1)

    # second run: capture the full recovered table state
    words = pw.io.jsonlines.read(
        str(in_dir), schema=WordSchema, mode="streaming", persistent_id="words"
    )
    counts = words.groupby(pw.this.word).reduce(
        word=pw.this.word, count=pw.reducers.count()
    )
    runner = GraphRunner()
    runner.engine.persistence_config = cfg
    cap, names = runner.capture(counts)
    runner.run()
    got = {row[names.index("word")]: row[names.index("count")] for row in cap.state.values()}
    assert got == {"x": 1, "y": 1}
    pw.clear_graph()


def test_file_modification_after_restart(tmp_path):
    in_dir = tmp_path / "in"
    in_dir.mkdir()
    _write_jsonl(in_dir / "a.jsonl", ["a", "b"])
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstorage"))

    ev1: list = []
    _wordcount_run(in_dir, backend, ev1)

    # modify the file while "down": recovered run must retract stale rows
    os.utime(in_dir / "a.jsonl")  # even with same mtime-resolution risk,
    _write_jsonl(in_dir / "a.jsonl", ["a", "c"])
    os.utime(in_dir / "a.jsonl", (1e9, 1e9))  # force a distinct mtime
    ev2: list = []
    _wordcount_run(in_dir, backend, ev2)
    words = {w for w, _c, add in ev2 if add}
    assert "c" in words
    assert ("b", 1, False) in ev2  # stale word retracted


class _RangeSubject(pw.io.python.ConnectorSubject):
    """Emits rows [start, stop); resumes from the persisted offset."""

    supports_offsets = True  # honors self.offsets → replay-safe

    def __init__(self, stop):
        super().__init__()
        self.stop = stop

    def run(self):
        start = int(self.offsets.get("next", 0))
        for i in range(start, self.stop):
            # row + bookmark move atomically: a concurrent autocommit
            # must never split them
            self.next_with_offset("next", i + 1, word=f"w{i}")
        self.commit()


def test_mock_backend_python_connector_resume():
    events_store: dict = {}
    backend = pw.persistence.Backend.mock(events_store)
    cfg = pw.persistence.Config.simple_config(backend)

    def run_once(stop):
        t = pw.io.python.read(
            _RangeSubject(stop), schema=WordSchema, autocommit_duration_ms=None,
            persistent_id="rng",
        )
        runner = GraphRunner()
        runner.engine.persistence_config = cfg
        sink: list = []
        runner.subscribe(t, on_change=lambda key, row, time, diff: sink.append(row["word"]))
        cap, names = runner.capture(t)
        runner.run()
        pw.clear_graph()
        return sink, cap.state

    sink1, state1 = run_once(5)
    assert sorted(sink1) == [f"w{i}" for i in range(5)]
    assert len(state1) == 5

    # restart with a larger range: only the new rows are read + emitted,
    # auto-generated keys keep advancing (no collisions with replayed rows)
    sink2, state2 = run_once(8)
    assert sorted(sink2) == ["w5", "w6", "w7"]
    assert len(state2) == 8


def _log_roundtrip(writer_cls, reader_cls, path):
    w = writer_cls(path, append=True)
    w.append(1, 7, 42, b"hello")
    w.append(2, 8, 0, b"world")
    w.flush()
    w.close()
    r = reader_cls(path)
    recs = list(r)
    r.close()
    assert recs == [(1, 7, 42, b"hello"), (2, 8, 0, b"world")]


def test_operator_snapshot_skips_replay(tmp_path):
    """Layer 2: a restart restores operator state from the snapshot and
    does NOT re-feed the covered input events through the graph."""
    in_dir = tmp_path / "in"
    in_dir.mkdir()
    _write_jsonl(in_dir / "a.jsonl", ["cat", "dog", "cat"])
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstorage"))

    ev1: list = []
    _wordcount_run(in_dir, backend, ev1)
    assert ("cat", 2, True) in ev1
    assert os.path.exists(tmp_path / "pstorage" / "streams" / "__operators__.bin")

    # restart with the SAME pipeline: groupby state must come from the
    # snapshot, with zero updates traveling through the GroupBy operator
    words = pw.io.jsonlines.read(
        str(in_dir), schema=WordSchema, mode="streaming", persistent_id="words"
    )
    counts = words.groupby(pw.this.word).reduce(
        word=pw.this.word, count=pw.reducers.count()
    )
    ev2: list = []
    runner = GraphRunner()
    runner.engine.persistence_config = pw.persistence.Config.simple_config(backend)
    runner.subscribe(
        counts, on_change=lambda key, row, time, diff: ev2.append(row["word"])
    )
    runner.run()
    assert ev2 == []
    engine = runner.engine
    gb = next(n for n in engine.nodes if n.name == "GroupBy")
    assert gb.stats.rows_in == 0  # no replay traveled through the graph
    assert engine._opsnap_time >= 0  # restore actually happened
    # and the restored state is real: one group per distinct word
    assert len(gb.groups) == 2
    pw.clear_graph()


def test_operator_snapshot_with_new_data_replays_only_tail(tmp_path):
    in_dir = tmp_path / "in"
    in_dir.mkdir()
    _write_jsonl(in_dir / "a.jsonl", ["cat", "dog"])
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstorage"))
    ev1: list = []
    _wordcount_run(in_dir, backend, ev1)

    _write_jsonl(in_dir / "b.jsonl", ["cat"])
    ev2: list = []
    _wordcount_run(in_dir, backend, ev2)
    # incremental update computed on top of restored groupby state
    assert ("cat", 1, False) in ev2 and ("cat", 2, True) in ev2
    assert not any(w == "dog" for w, _c, _a in ev2)

    # and a third run from the NEW snapshot is silent again
    ev3: list = []
    _wordcount_run(in_dir, backend, ev3)
    assert ev3 == []


def test_operator_snapshot_ignored_when_graph_changes(tmp_path):
    """A different program (operator signature mismatch) falls back to
    full input replay instead of restoring mismatched state."""
    in_dir = tmp_path / "in"
    in_dir.mkdir()
    _write_jsonl(in_dir / "a.jsonl", ["x", "y"])
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstorage"))
    ev1: list = []
    _wordcount_run(in_dir, backend, ev1)

    # new program over the same storage: plain passthrough, no groupby
    words = pw.io.jsonlines.read(
        str(in_dir), schema=WordSchema, mode="streaming", persistent_id="words"
    )
    runner = GraphRunner()
    runner.engine.persistence_config = pw.persistence.Config.simple_config(backend)
    cap, _names = runner.capture(words)
    runner.run()
    assert len(cap.state) == 2  # state rebuilt via replay despite stale snapshot
    pw.clear_graph()


def test_snapshot_restore_with_static_source(tmp_path):
    """Static tables mixed with persistent streams: a restart must
    neither livelock (static batch never fed) nor double-count (static
    rows already inside the restored state)."""
    in_dir = tmp_path / "in"
    in_dir.mkdir()
    _write_jsonl(in_dir / "a.jsonl", ["cat"])
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstorage"))
    cfg = pw.persistence.Config.simple_config(backend)

    def run_once():
        static = pw.debug.table_from_rows(WordSchema, [("static_word",)])
        stream = pw.io.jsonlines.read(
            str(in_dir), schema=WordSchema, mode="streaming", persistent_id="words"
        )
        both = stream.concat_reindex(static)
        counts = both.groupby(pw.this.word).reduce(
            word=pw.this.word, count=pw.reducers.count()
        )
        runner = GraphRunner()
        runner.engine.persistence_config = cfg
        cap, names = runner.capture(counts)
        runner.run()
        pw.clear_graph()
        return {
            row[names.index("word")]: row[names.index("count")]
            for row in cap.state.values()
        }

    assert run_once() == {"cat": 1, "static_word": 1}
    # restart terminates (no livelock) and does not double the static row
    assert run_once() == {"cat": 1, "static_word": 1}


def test_snapshot_ignored_when_reducer_changes(tmp_path):
    """Same topology, different reducer: the snapshot signature must
    reject the restore (count-state inside a sum program = silently
    wrong aggregates) and rebuild via full replay."""
    in_dir = tmp_path / "in"
    in_dir.mkdir()
    _write_jsonl(in_dir / "a.jsonl", ["cat", "dog"])
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstorage"))
    cfg = pw.persistence.Config.simple_config(backend)

    ev1: list = []
    _wordcount_run(in_dir, backend, ev1)  # count reducer

    words = pw.io.jsonlines.read(
        str(in_dir), schema=WordSchema, mode="streaming", persistent_id="words"
    )
    sums = words.groupby(pw.this.word).reduce(
        word=pw.this.word,
        total=pw.reducers.sum(pw.apply(len, pw.this.word)),
    )
    runner = GraphRunner()
    runner.engine.persistence_config = cfg
    cap, names = runner.capture(sums)
    runner.run()
    got = {
        row[names.index("word")]: row[names.index("total")]
        for row in cap.state.values()
    }
    assert got == {"cat": 3, "dog": 3}  # replayed + recomputed, not restored
    assert runner.engine._opsnap_time == -1
    pw.clear_graph()


def test_snapshot_disabled_with_non_persistent_source(tmp_path):
    """A snapshot contains state from ALL sources; if one source is not
    persistent, its reader re-feeds after restart, so restoring would
    double-count — such graphs must fall back to input replay."""
    in_dir = tmp_path / "in"
    in_dir.mkdir()
    _write_jsonl(in_dir / "a.jsonl", ["cat"])
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstorage"))
    cfg = pw.persistence.Config.simple_config(backend)

    class _Once(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(word="dog")  # NOT persistent: re-emits every run
            self.commit()

    def run_once():
        stream = pw.io.jsonlines.read(
            str(in_dir), schema=WordSchema, mode="streaming", persistent_id="words"
        )
        other = pw.io.python.read(
            _Once(), schema=WordSchema, autocommit_duration_ms=None
        )
        counts = stream.concat_reindex(other).groupby(pw.this.word).reduce(
            word=pw.this.word, count=pw.reducers.count()
        )
        runner = GraphRunner()
        runner.engine.persistence_config = cfg
        cap, names = runner.capture(counts)
        runner.run()
        pw.clear_graph()
        return {
            row[names.index("word")]: row[names.index("count")]
            for row in cap.state.values()
        }

    assert run_once() == {"cat": 1, "dog": 1}
    assert run_once() == {"cat": 1, "dog": 1}  # not {dog: 2}


def test_ops_log_stays_bounded(tmp_path):
    """Each snapshot REPLACES the ops log — N snapshots must not grow it
    N-fold."""
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))
    cfg = pw.persistence.Config.simple_config(backend)
    p = eng_persist.EnginePersistence(cfg)
    blob = b"x" * 10_000
    for i in range(20):
        p.save_operator_snapshot(i, blob)
    p.close()
    path = p._source_path(eng_persist.EnginePersistence.OPS_SOURCE)
    assert os.path.getsize(path) < 3 * len(blob)
    p2 = eng_persist.EnginePersistence(cfg)
    rec = p2.recover_operator_snapshot(100)
    assert rec == (19, blob)
    p2.close()


def test_py_log_roundtrip_and_torn_tail(tmp_path):
    path = str(tmp_path / "log.bin")
    _log_roundtrip(eng_persist.PyLogWriter, eng_persist.PyLogReader, path)
    # torn tail: truncate mid-record; reader returns only intact records
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 3)
    r = eng_persist.PyLogReader(path)
    recs = list(r)
    r.close()
    assert recs == [(1, 7, 42, b"hello")]


def test_native_log_roundtrip(tmp_path):
    from pathway_tpu import native

    if not native.is_available():
        pytest.skip("native runtime unavailable")
    _log_roundtrip(native.SnapshotLogWriter, native.SnapshotLogReader, str(tmp_path / "n.bin"))


def test_orphaned_data_compacted_on_recovery(tmp_path):
    """DATA logged without a finalizing ADVANCE (crash between the two)
    must not survive recovery — otherwise the re-ingested copy lands at
    the same epoch and a SECOND restart replays both, doubling state."""
    import pickle

    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))
    cfg = pw.persistence.Config.simple_config(backend)
    p = eng_persist.EnginePersistence(cfg)
    p.log_batch("s", 0, [(1, ("dog",), 1)])
    p.advance("s", 0, {"next": 1})
    p.log_batch("s", 1, [(2, ("cat",), 1)])  # crash: no ADVANCE
    p.close()

    p2 = eng_persist.EnginePersistence(cfg)
    batches, offsets, frontier = p2.recover_source("s")
    assert frontier == 0 and offsets == {"next": 1}
    assert batches == [(0, [(1, ("dog",), 1)])]
    # the orphan was compacted away: a third recovery sees it exactly once
    p2.log_batch("s", 1, [(2, ("cat",), 1)])  # re-ingest after recovery
    p2.advance("s", 1, {"next": 2})
    p2.close()
    p3 = eng_persist.EnginePersistence(cfg)
    batches3, _off3, f3 = p3.recover_source("s")
    assert f3 == 1
    assert batches3 == [(0, [(1, ("dog",), 1)]), (1, [(2, ("cat",), 1)])]
    p3.close()


def test_format_flip_native_to_python(tmp_path, monkeypatch):
    """A log written in one format stays recoverable when native
    availability flips between restarts (sniffing reader + compaction
    rewrite in the current format)."""
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))
    cfg = pw.persistence.Config.simple_config(backend)
    p = eng_persist.EnginePersistence(cfg)  # native when available
    p.log_batch("s", 0, [(1, ("dog",), 1)])
    p.advance("s", 0, {})
    p.close()

    monkeypatch.setenv("PATHWAY_PERSISTENCE_FORCE_PY", "1")
    p2 = eng_persist.EnginePersistence(cfg)
    batches, _off, frontier = p2.recover_source("s")
    assert frontier == 0 and batches == [(0, [(1, ("dog",), 1)])]
    p2.log_batch("s", 1, [(2, ("cat",), 1)])
    p2.advance("s", 1, {})
    p2.close()

    monkeypatch.delenv("PATHWAY_PERSISTENCE_FORCE_PY")
    p3 = eng_persist.EnginePersistence(cfg)
    batches3, _off3, f3 = p3.recover_source("s")
    assert f3 == 1 and len(batches3) == 2
    p3.close()


def test_mock_backend_shared_store_across_backend_objects():
    """The documented restart pattern: hand the SAME (initially empty)
    store to a fresh Backend.mock and recover from it."""
    store: list = []
    p = eng_persist.EnginePersistence(
        pw.persistence.Config.simple_config(pw.persistence.Backend.mock(store))
    )
    p.log_batch("s", 0, [(1, ("dog",), 1)])
    p.advance("s", 0, {})
    p.close()
    assert store  # records landed in the caller's store, not a private copy
    p2 = eng_persist.EnginePersistence(
        pw.persistence.Config.simple_config(pw.persistence.Backend.mock(store))
    )
    batches, _off, frontier = p2.recover_source("s")
    assert frontier == 0 and batches == [(0, [(1, ("dog",), 1)])]
    p2.close()


def test_row_and_offset_commit_atomically():
    """commit() snapshots offsets that include every row in the batch,
    even when racing the insert path (single locked append)."""
    from pathway_tpu.engine import dataflow as df

    g = df.EngineGraph()
    node = df.SessionSourceNode(g)
    s = node.session
    s.insert(1, ("a",), offsets={"next": 1})
    s.commit()
    s.drain()
    assert node.last_offsets == {"next": 1}


class _NoOffsetSubject(pw.io.python.ConnectorSubject):
    """Offset-unaware reader: re-emits everything on every run."""

    def run(self):
        for w in ("x", "y"):
            self.next(word=w)
        self.commit()


def test_record_mode_resets_offset_unaware_source(tmp_path):
    """Record mode must restart the capture for sources whose readers
    cannot seek — recovering their log would double the input."""
    import pathway_tpu.io._connector as conn

    storage = str(tmp_path / "rec")

    def run_once():
        t = conn.input_table_from_reader(
            WordSchema,
            lambda ctx: (_run_reader(ctx)),
            autocommit_duration_ms=None,
            supports_offsets=False,
        )
        runner = GraphRunner()
        cfg = pw.persistence.Config.simple_config(
            pw.persistence.Backend.filesystem(storage), persistence_mode="record"
        )
        cfg.auto_persistent_ids = True
        runner.engine.persistence_config = cfg
        cap, names = runner.capture(t)
        runner.run()
        pw.clear_graph()
        return cap.state

    def _run_reader(ctx):
        for w in ("x", "y"):
            ctx.insert({"word": w})
        ctx.commit()
        ctx.close()

    state1 = run_once()
    assert len(state1) == 2
    state2 = run_once()  # restart: capture resets, no doubling
    assert len(state2) == 2


def test_mock_backend_isolates_sources():
    events: list = []
    backend = pw.persistence.Backend.mock(events)
    cfg = pw.persistence.Config.simple_config(backend)
    p = eng_persist.EnginePersistence(cfg)
    p.log_batch("a", 0, [(1, ("from_a",), 1)])
    p.advance("a", 0, {"oa": 1})
    p.log_batch("b", 0, [(2, ("from_b",), 1)])
    p.advance("b", 0, {"ob": 2})
    p.close()
    p2 = eng_persist.EnginePersistence(cfg)
    ba, oa, _ = p2.recover_source("a")
    bb, ob, _ = p2.recover_source("b")
    assert ba == [(0, [(1, ("from_a",), 1)])] and oa == {"oa": 1}
    assert bb == [(0, [(2, ("from_b",), 1)])] and ob == {"ob": 2}
    p2.close()


def test_py_writer_heals_torn_tail_via_compaction(tmp_path, monkeypatch):
    """Records appended after a torn tail must stay reachable: recovery
    compacts the log, so the post-crash appends land on a clean file."""
    monkeypatch.setenv("PATHWAY_PERSISTENCE_FORCE_PY", "1")
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))
    cfg = pw.persistence.Config.simple_config(backend)
    p = eng_persist.EnginePersistence(cfg)
    p.log_batch("s", 0, [(1, ("good",), 1)])
    p.advance("s", 0, {})
    p.close()
    path = p._source_path("s")
    with open(path, "r+b") as f:  # torn mid-record crash
        f.truncate(os.path.getsize(path) - 3)

    p2 = eng_persist.EnginePersistence(cfg)
    batches, _off, _f = p2.recover_source("s")  # compacts/heals
    p2.log_batch("s", 1, [(2, ("post-crash",), 1)])
    p2.advance("s", 1, {})
    p2.close()
    p3 = eng_persist.EnginePersistence(cfg)
    batches3, _off3, f3 = p3.recover_source("s")
    assert f3 == 1
    rows = [row[0] for _t, ups in batches3 for _k, row, _d in ups]
    assert "post-crash" in rows
    p3.close()


def test_python_fallback_forced(tmp_path, monkeypatch):
    """The persistence layer works without the native runtime."""
    monkeypatch.setenv("PATHWAY_PERSISTENCE_FORCE_PY", "1")
    in_dir = tmp_path / "in"
    in_dir.mkdir()
    _write_jsonl(in_dir / "a.jsonl", ["p", "q"])
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "pstorage"))
    ev1: list = []
    _wordcount_run(in_dir, backend, ev1)
    assert {w for w, _c, _a in ev1} == {"p", "q"}
    ev2: list = []
    _wordcount_run(in_dir, backend, ev2)
    assert ev2 == []


def test_delivered_marker_finalizes_fed_epoch(tmp_path, monkeypatch):
    """Crash window between process 0's sink flush and a worker's
    ADVANCE: the worker fed+logged epoch 5 (KIND_FEED offsets) but never
    advanced. With p0's delivered marker at >=5, recovery promotes the
    epoch to finalized (replayed as state, reader resumes past it) —
    without it, the epoch is trimmed and the reader re-reads (the
    pre-marker at-least-once behavior)."""
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))
    cfg = pw.persistence.Config.simple_config(backend)

    # worker namespace: fed epoch 5, crash before ADVANCE
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "1")
    wp = eng_persist.EnginePersistence(cfg)
    wp.log_batch("src", 3, [(1, ("seen",), 1)])
    wp.advance("src", 3, {"cursor": 10})
    wp.log_batch("src", 5, [(2, ("window",), 1)], offsets={"cursor": 20})
    wp.close()

    # process 0 delivered epoch 5 before the cluster died
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "0")
    p0 = eng_persist.EnginePersistence(cfg)
    p0.mark_delivered(5)
    p0.close()

    # worker recovery consults the marker: epoch 5 is finalized
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "1")
    wp2 = eng_persist.EnginePersistence(cfg)
    delivered = wp2.delivered_frontier()
    assert delivered == 5
    batches, offsets, frontier = wp2.recover_source(
        "src", delivered_frontier=delivered
    )
    assert frontier == 5
    assert offsets == {"cursor": 20}, "feed-time offsets were not adopted"
    assert [t for t, _ in batches] == [3, 5]
    wp2.close()


def test_without_delivered_marker_fed_epoch_is_trimmed(tmp_path, monkeypatch):
    """Same crash, but p0 never delivered epoch 5 (marker at 3): the fed
    epoch must be trimmed and the reader offsets revert, so the input is
    re-read and delivered exactly once."""
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))
    cfg = pw.persistence.Config.simple_config(backend)

    monkeypatch.setenv("PATHWAY_PROCESS_ID", "1")
    wp = eng_persist.EnginePersistence(cfg)
    wp.log_batch("src", 3, [(1, ("seen",), 1)])
    wp.advance("src", 3, {"cursor": 10})
    wp.log_batch("src", 5, [(2, ("window",), 1)], offsets={"cursor": 20})
    wp.close()

    monkeypatch.setenv("PATHWAY_PROCESS_ID", "0")
    p0 = eng_persist.EnginePersistence(cfg)
    p0.mark_delivered(3)
    p0.close()

    monkeypatch.setenv("PATHWAY_PROCESS_ID", "1")
    wp2 = eng_persist.EnginePersistence(cfg)
    batches, offsets, frontier = wp2.recover_source(
        "src", delivered_frontier=wp2.delivered_frontier()
    )
    assert frontier == 3
    assert offsets == {"cursor": 10}
    assert [t for t, _ in batches] == [3]
    wp2.close()
