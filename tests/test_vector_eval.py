"""Columnar vectorized evaluation: equivalence with the per-row path.

The vectorized compiler (internals/vector_eval.py) must be an invisible
optimization: every result here is checked against the exact semantics
the per-row closures implement (null propagation, error routing, bool
vs int equality, pointer exactness). Reference hot loop being replaced:
/root/reference/src/engine/expression.rs:489.
"""

from __future__ import annotations

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.engine.value import Pointer, ref_scalar, ref_scalar_columns
from pathway_tpu.internals.graph_runner import GraphRunner
from pathway_tpu.internals import vector_eval


def _run(table):
    runner = GraphRunner()
    cap, names = runner.capture(table)
    runner.run()
    pw.clear_graph()
    return cap, names


def _col(cap, names, name):
    i = names.index(name)
    return sorted(
        (row[i] for row in cap.state.values()),
        key=lambda v: (v is None, repr(type(v)), str(v)),
    )


class _AB(pw.Schema):
    a: int
    b: float


def test_vectorized_select_filter_matches_per_row():
    rows = [(i, float(i) / 3.0) for i in range(100)]
    t = pw.debug.table_from_rows(schema=_AB, rows=rows)
    r = t.select(
        pw.this.a,
        c=pw.this.a * 2 + 1,
        d=pw.this.b * pw.this.a - 1.5,
        e=pw.this.a % 7 == 3,
        f=pw.if_else(pw.this.a % 2 == 0, pw.this.a + 1, pw.this.a - 1),
    ).filter(pw.this.c % 3 != 0)
    cap, names = _run(r)

    # same pipeline, vectorization force-disabled
    orig_batch = vector_eval.try_compile_batch
    orig_pred = vector_eval.try_compile_batch_pred
    vector_eval.try_compile_batch = lambda *a, **k: None
    vector_eval.try_compile_batch_pred = lambda *a, **k: None
    try:
        t2 = pw.debug.table_from_rows(schema=_AB, rows=rows)
        r2 = t2.select(
            pw.this.a,
            c=pw.this.a * 2 + 1,
            d=pw.this.b * pw.this.a - 1.5,
            e=pw.this.a % 7 == 3,
            f=pw.if_else(pw.this.a % 2 == 0, pw.this.a + 1, pw.this.a - 1),
        ).filter(pw.this.c % 3 != 0)
        cap2, names2 = _run(r2)
    finally:
        vector_eval.try_compile_batch = orig_batch
        vector_eval.try_compile_batch_pred = orig_pred
    assert cap.state == cap2.state
    # value types preserved exactly (int stays int, bool stays bool)
    row = next(iter(cap.state.values()))
    assert isinstance(row[names.index("c")], int)
    assert isinstance(row[names.index("e")], bool)
    assert isinstance(row[names.index("d")], float)


class _OptSchema(pw.Schema):
    a: int | None
    b: float | None


def test_none_batches_fall_back():
    rows = [(1, 1.0), (None, 2.0), (3, None), (4, 4.0)]
    t = pw.debug.table_from_rows(schema=_OptSchema, rows=rows)
    r = t.select(
        s=pw.this.a + 1,
        n=pw.this.a.is_none(),
        c=pw.coalesce(pw.this.b, -1.0),
    )
    cap, names = _run(r)
    assert _col(cap, names, "s") == sorted(
        [2, None, 4, 5], key=lambda v: (v is None, repr(type(v)), str(v))
    )
    assert sorted(_col(cap, names, "c")) == [-1.0, 1.0, 2.0, 4.0]
    # is_none must be honest on mixed batches
    assert _col(cap, names, "n").count(True) == 1


def test_division_by_zero_reports_per_row():
    class S(pw.Schema):
        a: int
        b: int

    rows = [(6, 2), (5, 0), (9, 3)]
    t = pw.debug.table_from_rows(schema=S, rows=rows)
    r = t.select(q=pw.this.a // pw.this.b)
    runner = GraphRunner()
    runner.engine.terminate_on_error = False
    cap, names = runner.capture(r)
    runner.run()
    pw.clear_graph()
    vals = [row[0] for row in cap.state.values()]
    from pathway_tpu.engine.value import Error

    assert sorted(v for v in vals if not isinstance(v, Error)) == [3, 3]
    assert sum(1 for v in vals if isinstance(v, Error)) == 1


def test_bool_int_equality_not_vectorized_wrong():
    class S(pw.Schema):
        a: pw.internals.dtype.ANY

    # mixed bool/int column: values_equal(True, 1) is False
    t = pw.debug.table_from_rows(schema=S, rows=[(True,), (1,), (0,)])
    r = t.select(eq=pw.this.a == 1)
    cap, names = _run(r)
    assert sorted(_col(cap, names, "eq")) == [False, False, True]


def test_pointer_columns_stay_exact():
    # the r1 fuzzy-join regression: pointers above 2^53 must not round
    big = int(ref_scalar("x"))
    assert big > 2**53
    class S(pw.Schema):
        p: pw.internals.dtype.ANY
        w: float

    rows = [(Pointer(big), 0.5), (Pointer(big + 3), 0.25)]
    t = pw.debug.table_from_rows(schema=S, rows=rows)
    g = t.groupby(pw.this.p).reduce(pw.this.p, s=pw.reducers.sum(pw.this.w))
    cap, names = _run(g)
    ps = {int(row[names.index("p")]) for row in cap.state.values()}
    assert ps == {big, big + 3}


def test_ref_scalar_columns_matches_scalar():
    ints = np.array([0, 1, -5, 2**62 - 1, 7], np.int64)
    floats = np.array([0.0, -0.0, 2.0, float("nan"), float("inf")])
    bools = np.array([True, False, True, False, True])
    for cols in ([ints], [floats], [bools], [ints, floats, bools]):
        batch = ref_scalar_columns(list(cols))
        assert batch is not None
        expect = [
            int(ref_scalar(*[c[i].item() for c in cols])) for i in range(5)
        ]
        assert [int(x) for x in batch] == expect
    # strings are not vectorized (yet): explicit fallback
    assert ref_scalar_columns([np.array(["a", "b"])]) is None


def test_groupby_fold_with_retractions_stream():
    class S(pw.Schema):
        k: int
        v: float

    t = pw.debug.table_from_markdown(
        """
          | k | v   | __time__ | __diff__
        1 | 1 | 1.0 | 0        | 1
        2 | 1 | 2.0 | 0        | 1
        3 | 2 | 5.0 | 0        | 1
        1 | 1 | 1.0 | 2        | -1
        3 | 2 | 5.0 | 4        | -1
        """
    )
    g = t.groupby(pw.this.k).reduce(
        pw.this.k,
        s=pw.reducers.sum(pw.this.v),
        n=pw.reducers.count(),
        m=pw.reducers.avg(pw.this.v),
    )
    cap, names = _run(g)
    got = {
        row[names.index("k")]: (
            row[names.index("s")],
            row[names.index("n")],
            row[names.index("m")],
        )
        for row in cap.state.values()
    }
    assert set(got) == {1}
    s, n, m = got[1]
    assert n == 1 and abs(s - 2.0) < 1e-9 and abs(m - 2.0) < 1e-9


def test_filter_with_nonidentity_projection():
    """Pred references another same-universe table → zip context widens
    the layout → FilterProj is a real projection (regression: its batch
    evaluator must follow the (keys, rows, cache) -> (rows, cache)
    contract)."""
    rows = [(i, float(i)) for i in range(2000)]
    t = pw.debug.table_from_rows(schema=_AB, rows=rows)
    s = t.select(c=pw.this.a * 2)
    f = s.filter(t.b >= 10.0)
    cap, names = _run(f)
    vals = sorted(row[names.index("c")] for row in cap.state.values())
    assert vals == [i * 2 for i in range(10, 2000)]


def test_streaming_epochs_mix_typed_and_untyped():
    t = pw.debug.table_from_markdown(
        """
          | k | v | __time__ | __diff__
        1 | 1 | 2 | 0        | 1
        2 | 1 | 3 | 2        | 1
        3 | 2 | 4 | 2        | 1
        1 | 1 | 2 | 4        | -1
        """
    )
    g = t.groupby(pw.this.k).reduce(pw.this.k, s=pw.reducers.sum(pw.this.v))
    cap, names = _run(g)
    got = {
        row[names.index("k")]: row[names.index("s")]
        for row in cap.state.values()
    }
    assert got == {1: 3, 2: 4}
    assert all(isinstance(v, int) for v in got.values())  # int sums exact
