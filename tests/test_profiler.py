"""Per-operator run profiler: scheduler timing, Chrome trace surface,
event-time lag, jit compile/execute split.

Covers the profiler subsystem end to end: engine hooks in
EngineGraph._topo_pass, the ``pw.run(profile=...)`` / PATHWAY_PROFILE /
``pathway profile`` surfaces, and the golden structure of the emitted
Chrome-trace-event JSON (loadable in Perfetto: one track per worker,
one slice per node-epoch).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

import pathway_tpu as pw
from pathway_tpu.engine import dataflow as df
from pathway_tpu.internals.graph_runner import GraphRunner
from pathway_tpu.internals.profiler import (
    HISTOGRAM_BOUNDS,
    LatencyHistogram,
    RunProfiler,
    current_profiler,
    set_current_profiler,
    wrap_jit,
)

from .utils import T

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(
    REPO_ROOT, "pathway_tpu", "debug", "demos", "word_counts.py"
)


def _word_counts_graph():
    docs = T(
        """
          | text
        1 | to be or not to be
        2 | that is the question
        3 | to be is to do
        """
    )
    words = docs.select(
        word=pw.apply_with_type(str.split, list[str], pw.this.text)
    ).flatten(pw.this.word)
    return words.groupby(pw.this.word).reduce(
        pw.this.word, count=pw.reducers.count()
    )


# ---------------------------------------------------------------- units


def test_latency_histogram_buckets_and_cumulative():
    h = LatencyHistogram()
    h.observe(0.0)          # first bucket
    h.observe(0.002)        # mid bucket
    h.observe(1e9)          # +Inf overflow
    assert h.count == 3
    assert h.total == pytest.approx(0.002 + 1e9)
    cum = h.cumulative()
    assert len(cum) == len(HISTOGRAM_BOUNDS) + 1
    assert cum[-1] == ("+Inf", 3)
    # cumulative counts are monotone non-decreasing
    counts = [c for _, c in cum]
    assert counts == sorted(counts)


def test_wrap_jit_reports_compile_then_execute():
    prof = RunProfiler()
    set_current_profiler(prof)
    try:
        cache = [0]
        grow_next = [True]

        def fn(x):
            if grow_next[0]:  # simulate a jit cache miss on first call
                cache[0] += 1
            return x + 1

        fn._cache_size = lambda: cache[0]
        wrapped = wrap_jit("test.fn", fn)

        assert wrapped(1) == 2  # cache grew -> compile
        grow_next[0] = False
        assert wrapped(1) == 2  # cache stable -> execute

        stats = prof.jit_stats["test.fn"]
        assert stats["compiles"] == 1
        assert stats["calls"] == 1
        assert stats["compile_ns"] > 0
        assert stats["execute_ns"] > 0
    finally:
        set_current_profiler(None)


def test_wrap_jit_noop_without_profiler():
    assert current_profiler() is None
    calls = []

    def fn(x):
        calls.append(x)
        return x

    wrapped = wrap_jit("n", fn)
    assert wrapped(5) == 5
    assert calls == [5]
    assert wrapped.__wrapped__ is fn


# ----------------------------------------------- engine scheduler hooks


def test_profiler_covers_every_engine_node_every_epoch():
    res = _word_counts_graph()
    runner = GraphRunner()
    cap, _ = runner.capture(res)
    prof = RunProfiler()
    runner.attach_profiler(prof)
    assert runner.engine.profiler is prof
    runner.run()

    node_ids = {n.id for n in runner.engine.nodes}
    profiled_ids = {nid for (_w, nid) in prof.profiles}
    assert profiled_ids == node_ids  # every node profiled
    epochs = {p.epochs for p in prof.profiles.values()}
    assert epochs == {1}  # static run: exactly one epoch each
    # self-time adds up and at least one node did measurable work
    assert any(p.self_time_ns > 0 for p in prof.profiles.values())
    for p in prof.profiles.values():
        assert p.histogram.count == p.epochs
    pw.clear_graph()


def test_profiler_event_lag_for_watermark_nodes():
    import time as _time

    lag_target = 5.0
    now = _time.time()
    g = df.EngineGraph()
    src = g.static_table(
        [(0, [(1, (now - lag_target,), 1), (2, (now - lag_target * 2,), 1)])]
    )
    buf = df.BufferNode(
        g,
        threshold_fn=lambda k, r: r[0],
        time_fn=lambda k, r: r[0],
    )
    buf.connect(src)
    prof = RunProfiler()
    g.profiler = prof
    g.run()
    bp = prof.profiles[(0, buf.id)]
    assert bp.event_lag_s is not None
    # watermark = max event time = now - 5s; lag measured moments later
    assert bp.event_lag_s == pytest.approx(lag_target, abs=2.0)
    agg = prof.by_operator()
    assert agg[bp.key]["event_lag_s"] == pytest.approx(bp.event_lag_s)
    # non-watermark nodes expose no lag
    sp = prof.profiles[(0, src.id)]
    assert sp.event_lag_s is None


def test_batch_apply_reports_jit_execute_split():
    t = T(
        """
          | a
        1 | 1
        2 | 2
        3 | 3
        """
    )
    @pw.udf(executor=pw.udfs.BatchExecutor(max_batch_size=8))
    def double(xs: list[int]) -> list[int]:
        return [x * 2 for x in xs]

    res = t.select(b=double(pw.this.a))
    runner = GraphRunner()
    cap, _ = runner.capture(res)
    prof = RunProfiler()
    runner.attach_profiler(prof)
    runner.run()
    batch_keys = [k for k in prof.jit_stats if k.startswith("batch_udf/")]
    assert batch_keys, f"no batch-udf jit stats recorded: {prof.jit_stats}"
    ent = prof.jit_stats[batch_keys[0]]
    assert ent["calls"] >= 1
    assert ent["execute_ns"] > 0
    assert ent["rows"] == 3
    pw.clear_graph()


# --------------------------------------------------- chrome trace surface


def _assert_trace_golden_structure(trace: dict):
    """The golden shape contract for the profile surface: valid
    trace-event JSON, process/worker metadata, complete 'X' slices
    keyed by node id, one slice per node per epoch."""
    assert set(trace) >= {"traceEvents", "displayTimeUnit"}
    events = trace["traceEvents"]
    meta = [e for e in events if e["ph"] == "M"]
    slices = [e for e in events if e["ph"] == "X"]
    assert {m["name"] for m in meta} >= {"process_name", "thread_name"}
    assert slices, "no slices recorded"
    op_slices = [s for s in slices if s.get("cat") == "operator"]
    per_node_epochs: dict[int, list[int]] = {}
    all_epochs: set[int] = set()
    for s in op_slices:
        for key in ("name", "ts", "dur", "pid", "tid", "args"):
            assert key in s, f"slice missing {key}: {s}"
        assert s["ts"] >= 0 and s["dur"] >= 0
        args = s["args"]
        assert "node_id" in args and "epoch" in args
        per_node_epochs.setdefault(args["node_id"], []).append(args["epoch"])
        all_epochs.add(args["epoch"])
    # one slice per node per epoch: every node has exactly one slice in
    # every epoch observed anywhere in the trace
    for node_id, epochs in per_node_epochs.items():
        assert sorted(epochs) == sorted(all_epochs), (
            f"node {node_id} epochs {sorted(epochs)} != {sorted(all_epochs)}"
        )
        assert len(epochs) == len(set(epochs)), f"duplicate slices for {node_id}"
    return per_node_epochs


def test_run_profile_kwarg_writes_chrome_trace(tmp_path):
    out = tmp_path / "trace.json"
    _word_counts_graph_with_sink()
    pw.run(monitoring_level=pw.MonitoringLevel.NONE, profile=str(out))
    trace = json.loads(out.read_text())
    per_node = _assert_trace_golden_structure(trace)
    assert len(per_node) >= 5  # source, select, flatten, groupby, output
    assert trace["otherData"]["producer"] == "pathway_tpu.profiler"


def _word_counts_graph_with_sink():
    counts = _word_counts_graph()
    pw.io.null.write(counts)


def test_profile_env_var_in_subprocess_demo(tmp_path):
    """PATHWAY_PROFILE on the stock word_counts demo — the acceptance
    path: pw.run picks the path from env, trace covers every node."""
    out = tmp_path / "demo_trace.json"
    env = os.environ.copy()
    env.update(
        PATHWAY_PROFILE=str(out),
        PYTHONPATH=REPO_ROOT + os.pathsep + env.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
    )
    proc = subprocess.run(
        [sys.executable, DEMO],
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    trace = json.loads(out.read_text())
    per_node = _assert_trace_golden_structure(trace)
    names = {
        e["name"]
        for e in trace["traceEvents"]
        if e["ph"] == "X" and e.get("cat") == "operator"
    }
    assert {"Flatten", "GroupBy", "Output"} <= names
    assert len(per_node) >= 5


def test_profile_cli_subcommand(tmp_path):
    out = tmp_path / "cli_trace.json"
    env = os.environ.copy()
    env.update(
        PYTHONPATH=REPO_ROOT + os.pathsep + env.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
    )
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "pathway_tpu",
            "profile",
            "-o",
            str(out),
            DEMO,
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "perfetto" in proc.stderr.lower()
    _assert_trace_golden_structure(json.loads(out.read_text()))


def test_trace_has_source_location_and_worker_tracks(tmp_path):
    out = tmp_path / "t.json"
    _word_counts_graph_with_sink()
    pw.run(monitoring_level=pw.MonitoringLevel.NONE, profile=str(out))
    trace = json.loads(out.read_text())
    slices = [
        e
        for e in trace["traceEvents"]
        if e["ph"] == "X" and e.get("cat") == "operator"
    ]
    # build-time source frames ride on the slices for user-built operators
    with_loc = [s for s in slices if "file" in s["args"]]
    assert with_loc, "no slice carries a source location"
    assert any(s["args"]["file"].endswith(".py") for s in with_loc)
    # exactly the worker tracks named
    meta_tids = {
        m["tid"]
        for m in trace["traceEvents"]
        if m["ph"] == "M" and m["name"] == "thread_name"
    }
    assert {s["tid"] for s in slices} <= meta_tids


def test_profiler_multi_worker_tracks():
    """Sharded runs profile every worker: one RunProfiler shared across
    shard engines, one trace track per worker."""
    res = _word_counts_graph()
    runner = GraphRunner(n_workers=2)
    cap, _ = runner.capture(res)
    prof = RunProfiler()
    runner.attach_profiler(prof)
    assert all(e.profiler is prof for e in runner._cluster_engines())
    runner.run()

    workers = {w for (w, _nid) in prof.profiles}
    assert workers == {0, 1}
    # both shards profiled the same node set
    ids0 = {nid for (w, nid) in prof.profiles if w == 0}
    ids1 = {nid for (w, nid) in prof.profiles if w == 1}
    assert ids0 == ids1 == {n.id for n in runner.engine.nodes}
    trace = prof.chrome_trace()
    track_names = {
        m["args"]["name"]
        for m in trace["traceEvents"]
        if m["ph"] == "M" and m["name"] == "thread_name"
    }
    assert {"worker 0", "worker 1"} <= track_names
    # aggregation merges both workers under one operator key
    agg = prof.by_operator()
    assert all(a["epochs"] >= 1 for a in agg.values())
    pw.clear_graph()


def test_profiler_bounded_events():
    prof = RunProfiler(max_events=2)
    for _ in range(5):
        prof.record_jit("x", "execute", 100, 1)
    assert len(prof.events) == 2
    assert prof.dropped_events == 3
    assert prof.chrome_trace()["otherData"]["dropped_events"] == 3
