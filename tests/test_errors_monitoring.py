"""Error-log tables, terminate_on_error routing, monitoring HTTP server.

Mirrors the reference's error-system coverage
(/root/reference/python/pathway/tests — terminate_on_error=False routes
row errors to Graph::error_log tables, graph.rs:983) and the Prometheus
endpoint (src/engine/http_server.rs:21-60).
"""

from __future__ import annotations

import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.dataflow import EngineError
from pathway_tpu.engine.value import Error
from pathway_tpu.internals.graph_runner import GraphRunner
from .utils import T


def _div_table():
    t = T(
        """
          | a  | b
        1 | 10 | 2
        2 | 7  | 0
        3 | 9  | 3
        """
    )
    return t.select(q=pw.apply(lambda a, b: a // b, pw.this.a, pw.this.b))


def test_terminate_on_error_default_aborts():
    res = _div_table()
    with pytest.raises(EngineError):
        pw.debug.compute_and_print(res)


def test_error_value_and_error_log():
    res = _div_table()
    err_log = pw.global_error_log()

    runner = GraphRunner()
    runner.engine.terminate_on_error = False
    cap, names = runner.capture(res)
    ecap, enames = runner.capture(err_log)
    runner.run()

    vals = sorted(
        (row[0] for row in cap.state.values()), key=lambda v: str(type(v))
    )
    assert sum(isinstance(v, Error) for v in vals) == 1
    assert sorted(v for v in vals if isinstance(v, int)) == [3, 5]

    entries = list(ecap.state.values())
    assert len(entries) == 1
    op_id, message, _trace = entries[0]
    assert "ZeroDivisionError" in message
    assert isinstance(op_id, int)
    pw.clear_graph()


def test_fill_error_recovers():
    res = _div_table().select(q=pw.fill_error(pw.this.q, -1))
    runner = GraphRunner()
    runner.engine.terminate_on_error = False
    cap, _names = runner.capture(res)
    runner.run()
    assert sorted(row[0] for row in cap.state.values()) == [-1, 3, 5]
    pw.clear_graph()


def test_error_rows_silently_fail_filters():
    res = _div_table().filter(pw.this.q > 0)
    runner = GraphRunner()
    runner.engine.terminate_on_error = False
    cap, _names = runner.capture(res)
    ecap, _ = runner.capture(pw.global_error_log())
    runner.run()
    assert sorted(row[0] for row in cap.state.values()) == [3, 5]
    # only ONE log entry (the original eval failure) — the downstream
    # filter must not re-report the propagated ERROR row
    assert len(ecap.state) == 1
    pw.clear_graph()


def test_retraction_does_not_duplicate_error_entry():
    """Deleting a previously-failed row re-evaluates to build the
    retraction but must NOT log the same failure twice."""
    t = pw.debug.table_from_markdown(
        """
          | a | b | __time__ | __diff__
        1 | 7 | 0 | 0        | 1
        1 | 7 | 0 | 2        | -1
        """
    )
    res = t.select(q=pw.apply(lambda a, b: a // b, pw.this.a, pw.this.b))
    runner = GraphRunner()
    runner.engine.terminate_on_error = False
    cap, _ = runner.capture(res)
    ecap, _ = runner.capture(pw.global_error_log())
    runner.run()
    assert cap.state == {}  # row fully retracted
    assert len(ecap.state) == 1  # one failure, one entry
    pw.clear_graph()


def test_fresh_failure_next_to_error_cell_still_reported():
    """A failure in an expression whose OWN operands are healthy must be
    reported even if another cell of the row already holds ERROR."""
    t = T(
        """
          | a  | b | c
        1 | 10 | 0 | 0
        """
    )
    step1 = t.select(
        a=pw.this.a,
        c=pw.this.c,
        q=pw.apply(lambda a, b: a // b, pw.this.a, pw.this.b),  # fails
    )
    step2 = step1.select(
        q=pw.this.q,
        z=pw.apply(lambda a, c: a // c, pw.this.a, pw.this.c),  # also fails
    )
    runner = GraphRunner()
    runner.engine.terminate_on_error = False
    cap, _ = runner.capture(step2)
    ecap, _ = runner.capture(pw.global_error_log())
    runner.run()
    assert len(ecap.state) == 2  # two distinct failures, two entries
    pw.clear_graph()


def test_filter_retraction_does_not_duplicate_error_entry():
    t = pw.debug.table_from_markdown(
        """
          | a | b | __time__ | __diff__
        1 | 7 | 0 | 0        | 1
        1 | 7 | 0 | 2        | -1
        """
    )
    res = t.filter(pw.apply(lambda a, b: a // b > 0, pw.this.a, pw.this.b))
    runner = GraphRunner()
    runner.engine.terminate_on_error = False
    cap, _ = runner.capture(res)
    ecap, _ = runner.capture(pw.global_error_log())
    runner.run()
    assert cap.state == {}
    assert len(ecap.state) == 1
    pw.clear_graph()


def test_local_error_log_context():
    with pw.local_error_log() as log:
        res = _div_table()
    runner = GraphRunner()
    runner.engine.terminate_on_error = False
    cap, _ = runner.capture(res)
    ecap, _ = runner.capture(log)
    runner.run()
    assert len(ecap.state) == 1
    pw.clear_graph()


def test_monitoring_http_server_metrics():
    from pathway_tpu.internals.http_monitoring import MonitoringHttpServer
    from pathway_tpu.internals.monitoring import StatsMonitor

    monitor = StatsMonitor()
    t = T(
        """
          | a
        1 | 1
        2 | 2
        """
    )
    res = t.select(b=pw.this.a * 2)
    runner = GraphRunner()
    cap, _ = runner.capture(res)
    server = MonitoringHttpServer(monitor, port=0)
    server.start()
    try:
        runner.run(monitoring_callback=monitor.update)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=5
        ).read().decode()
        assert "pathway_rows_input_total" in body
        assert 'pathway_operator_rows_total{operator=' in body
        assert "pathway_input_latency_ms" in body
        status = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/status", timeout=5
        ).read().decode()
        assert '"rows_in"' in status
    finally:
        server.stop()
    pw.clear_graph()


def test_run_with_http_server_flag():
    """pw.run(with_http_server=True) serves metrics during the run and
    shuts the server down afterwards."""
    import socket

    t = T(
        """
          | a
        1 | 1
        """
    )
    seen = []
    pw.io.subscribe(t, on_change=lambda **kw: seen.append(1))
    # pick a free port via env-less override: use process_id port; just
    # ensure run() completes with the flag on and the port closes after
    pw.run(with_http_server=True)
    assert seen
    with pytest.raises(OSError):
        # server is down — connection must fail
        socket.create_connection(("127.0.0.1", 20000), timeout=0.5).close()


def test_live_dashboard_renders_connectors_and_operators():
    """The rich PROGRESS DASHBOARD (reference monitoring.py:56):
    connectors table with minibatch/minute/start counts, operators table
    with latency, LOGS panel capturing log records."""
    import io
    import logging as _logging
    import time as _time

    from rich.console import Console

    from pathway_tpu.internals.monitoring import (
        LiveDashboard,
        MonitoringLevel,
        StatsMonitor,
        build_dashboard,
        monitor_stats,
    )

    t = T(
        """
          | a
        1 | 1
        2 | 2
        """
    )
    res = t.select(b=pw.this.a * 2)
    runner = GraphRunner()
    cap, _ = runner.capture(res)

    monitor = StatsMonitor()
    buf = io.StringIO()
    console = Console(file=buf, width=140, force_terminal=True)
    dashboard = LiveDashboard(with_operators=True, console=console, screen=False)
    monitor.attach_dashboard(dashboard)
    dashboard.start()
    try:
        _logging.getLogger().info("hello dashboard log")
        runner.run(monitoring_callback=monitor.update)
        dashboard.refresh(monitor, _time.monotonic())
    finally:
        dashboard.stop()
    pw.clear_graph()

    # collected stats: the static source is a connector with its counts
    assert monitor.connectors, "no connector stats collected"
    conn = list(monitor.connectors.values())[0]
    assert conn.num_messages_from_start == 2
    assert monitor.snapshot.rows_out >= 4  # source + select

    rendered = buf.getvalue()
    assert "PATHWAY PROGRESS DASHBOARD" in rendered
    assert "connector" in rendered
    assert "operator" in rendered
    assert "LOGS" in rendered

    # a fresh console render of the dashboard shows the counts
    buf2 = io.StringIO()
    console2 = Console(file=buf2, width=160)
    console2.print(build_dashboard(monitor, _time.monotonic()))
    out = buf2.getvalue()
    assert "since start" in out

    # monitor_stats context manager: NONE yields a bare collector
    with monitor_stats("none") as m:
        assert m.dashboard is None
    assert MonitoringLevel.coerce("all") is MonitoringLevel.ALL
    assert MonitoringLevel.coerce(None) is MonitoringLevel.NONE


# --------------------------------------------- profiler PR satellites


def test_idle_connector_resets_last_minibatch():
    """A connector that commits nothing in an epoch must show 0 as its
    last-minibatch count, not its last nonzero batch forever."""
    from types import SimpleNamespace

    from pathway_tpu.internals.monitoring import StatsMonitor

    node = SimpleNamespace(
        id=0,
        name="src",
        n_inputs=0,
        stats=SimpleNamespace(rows_in=0, rows_out=5),
        session=None,
    )
    engine = SimpleNamespace(current_time=1, nodes=[node], profiler=None)
    monitor = StatsMonitor()
    monitor.update(engine)
    assert monitor.connectors[0].num_messages_recently_committed == 5

    engine.current_time = 2  # quiet epoch: no new rows
    monitor.update(engine)
    assert monitor.connectors[0].num_messages_recently_committed == 0
    assert monitor.connectors[0].num_messages_from_start == 5


def test_metrics_port_collision_falls_back_to_ephemeral(caplog):
    import logging as _logging

    from pathway_tpu.internals.http_monitoring import MonitoringHttpServer
    from pathway_tpu.internals.monitoring import StatsMonitor

    first = MonitoringHttpServer(StatsMonitor(), port=0)
    first.start()
    try:
        second = MonitoringHttpServer(StatsMonitor(), port=first.port)
        with caplog.at_level(_logging.WARNING):
            second.start()  # would previously die with OSError
        try:
            assert second.port != first.port and second.port > 0
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{second.port}/metrics", timeout=5
            ).read().decode()
            assert "pathway_epoch" in body
            assert any(
                "unavailable" in r.message for r in caplog.records
            ), caplog.records
        finally:
            second.stop()
    finally:
        first.stop()


def test_run_accepts_monitoring_http_port():
    """pw.run(monitoring_http_port=0) binds an ephemeral port instead of
    20000 + process_id (two concurrent runs no longer race)."""
    t = T(
        """
          | a
        1 | 1
        """
    )
    seen = []
    pw.io.subscribe(t, on_change=lambda **kw: seen.append(1))
    pw.run(with_http_server=True, monitoring_http_port=0)
    assert seen


def _parse_prometheus(body: str):
    """Minimal exposition-format parser: returns ({series: value},
    {metric: type}). Raises on malformed lines — the conformance check."""
    import re

    types: dict[str, str] = {}
    samples: dict[str, float] = {}
    line_re = re.compile(
        r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (-?[0-9.eE+-]+|NaN)$'
    )
    label_re = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
    for line in body.strip().split("\n"):
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split(" ")
            types[name] = mtype
            continue
        if line.startswith("#"):
            continue
        m = line_re.match(line)
        assert m, f"malformed exposition line: {line!r}"
        labels = m.group(2) or ""
        if labels:
            # every label pair must parse; raw newlines would have
            # broken line_re already
            inner = labels[1:-1]
            parsed = label_re.findall(inner)
            reconstructed = ",".join(f'{k}="{v}"' for k, v in parsed)
            assert reconstructed == inner, f"bad labels: {labels!r}"
        samples[m.group(1) + labels] = float(m.group(3))
    return samples, types


def test_metrics_body_is_conformant_exposition_format():
    """Whole-body /metrics validation: parses cleanly, counters end in
    _total, histogram buckets are monotone and consistent with _count,
    label values with newlines/quotes are escaped."""
    from pathway_tpu.internals.http_monitoring import MonitoringHttpServer
    from pathway_tpu.internals.monitoring import StatsMonitor
    from pathway_tpu.internals.profiler import RunProfiler

    monitor = StatsMonitor()
    t = T(
        """
          | a
        1 | 1
        2 | 2
        """
    )
    res = t.select(b=pw.this.a * 2)
    runner = GraphRunner()
    cap, _ = runner.capture(res)
    prof = RunProfiler()
    runner.attach_profiler(prof)
    server = MonitoringHttpServer(monitor, port=0)
    server.start()
    try:
        runner.run(monitoring_callback=monitor.update)
        # poison a label: operator names with newline/quote must escape
        monitor.snapshot.operators['9:evil"name\nwith newline'] = (1, 1)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=5
        ).read().decode()
    finally:
        server.stop()

    samples, types = _parse_prometheus(body)
    # counters carry the _total suffix
    for name, mtype in types.items():
        if mtype == "counter":
            assert name.endswith("_total"), f"counter {name} lacks _total"
    assert types["pathway_operator_rows_total"] == "counter"
    assert types["pathway_operator_self_time_seconds"] == "histogram"
    # the escaped label round-trips (no raw newline in the body)
    assert "\\nwith" in body and 'evil\\"name' in body
    # histogram: per-operator buckets monotone, +Inf == _count
    bucket_series = sorted(
        k for k in samples if k.startswith("pathway_operator_self_time_seconds_bucket")
    )
    assert bucket_series, "no histogram buckets exposed"
    import collections

    def le_of(key: str) -> float:
        le = key.split('le="')[1].split('"')[0]
        return float("inf") if le == "+Inf" else float(le)

    per_op = collections.defaultdict(list)
    for k in bucket_series:
        op = k.split('operator="')[1].split('"')[0]
        per_op[op].append((le_of(k), samples[k]))
    for op, buckets in per_op.items():
        ordered = [v for _, v in sorted(buckets)]
        assert ordered == sorted(ordered), f"non-monotone buckets for {op}"
        inf_key = next(
            k for k in bucket_series if f'operator="{op}"' in k and 'le="+Inf"' in k
        )
        count_key = f'pathway_operator_self_time_seconds_count{{operator="{op}"}}'
        assert samples[inf_key] == samples[count_key]
        sum_key = f'pathway_operator_self_time_seconds_sum{{operator="{op}"}}'
        assert samples[sum_key] >= 0
    pw.clear_graph()


def test_streaming_scrape_histograms_monotone():
    """Tier-1 CI check (ISSUE satellite): a live streaming pipeline with
    with_http_server=True exposes the per-operator self-time histogram
    series mid-run, and their counts are monotone across two scrapes."""
    import threading
    import time as _time

    from pathway_tpu.internals import http_monitoring as hm

    class S(pw.Schema):
        a: int

    class Src(pw.io.python.ConnectorSubject):
        def run(self):
            for i in range(60):
                self.next(a=i)
                self.commit()  # one epoch per row: scrapes see progress
                _time.sleep(0.02)

    t = pw.io.python.read(Src(), schema=S, autocommit_duration_ms=10)
    res = t.select(b=pw.this.a * 2)
    pw.io.null.write(res)

    scrapes: list[str] = []
    errors: list[BaseException] = []
    orig_start = hm.MonitoringHttpServer.start

    def scraping_start(self):
        orig_start(self)
        port = self.port

        def scrape():
            def get():
                return urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5
                ).read().decode()

            try:
                # poll until the first epoch's histograms surface, then
                # take the two mid-run scrapes the assertion compares
                deadline = _time.monotonic() + 5.0
                while _time.monotonic() < deadline:
                    body = get()
                    if "pathway_operator_self_time_seconds_count" in body:
                        scrapes.append(body)
                        break
                    _time.sleep(0.02)
                _time.sleep(0.1)
                scrapes.append(get())
            except BaseException as exc:
                errors.append(exc)

        threading.Thread(target=scrape, daemon=True).start()

    hm.MonitoringHttpServer.start = scraping_start
    try:
        pw.run(
            monitoring_level=pw.MonitoringLevel.NONE,
            with_http_server=True,
            monitoring_http_port=0,
        )
    finally:
        hm.MonitoringHttpServer.start = orig_start
    assert not errors, errors
    assert len(scrapes) == 2

    def hist_counts(body: str) -> dict[str, float]:
        samples, types = _parse_prometheus(body)
        assert types.get("pathway_operator_self_time_seconds") == "histogram"
        return {
            k: v
            for k, v in samples.items()
            if k.startswith("pathway_operator_self_time_seconds_count")
        }

    first, second = hist_counts(scrapes[0]), hist_counts(scrapes[1])
    assert first, "no per-operator histogram series in first scrape"
    # same series present, counts monotone non-decreasing across scrapes
    for series, count in first.items():
        assert series in second
        assert second[series] >= count
    # the stream kept flowing between scrapes, so something advanced
    assert sum(second.values()) > sum(first.values())


def test_dashboard_shows_profiler_columns():
    """With a profiler attached, the operators table gains self-time and
    event-lag columns."""
    import io

    from rich.console import Console

    from pathway_tpu.internals.monitoring import StatsMonitor, build_dashboard
    from pathway_tpu.internals.profiler import RunProfiler

    t = T(
        """
          | a
        1 | 1
        2 | 2
        """
    )
    res = t.select(b=pw.this.a * 2)
    runner = GraphRunner()
    cap, _ = runner.capture(res)
    prof = RunProfiler()
    runner.attach_profiler(prof)
    monitor = StatsMonitor()
    runner.run(monitoring_callback=monitor.update)
    assert monitor.profiler is prof
    entries = list(monitor.operators.values())
    assert any(e.self_time_s is not None for e in entries)

    buf = io.StringIO()
    Console(file=buf, width=200).print(build_dashboard(monitor, 0.0))
    out = buf.getvalue()
    assert "self-time" in out
    assert "event lag" in out
    pw.clear_graph()
