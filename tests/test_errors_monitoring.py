"""Error-log tables, terminate_on_error routing, monitoring HTTP server.

Mirrors the reference's error-system coverage
(/root/reference/python/pathway/tests — terminate_on_error=False routes
row errors to Graph::error_log tables, graph.rs:983) and the Prometheus
endpoint (src/engine/http_server.rs:21-60).
"""

from __future__ import annotations

import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.dataflow import EngineError
from pathway_tpu.engine.value import Error
from pathway_tpu.internals.graph_runner import GraphRunner
from .utils import T


def _div_table():
    t = T(
        """
          | a  | b
        1 | 10 | 2
        2 | 7  | 0
        3 | 9  | 3
        """
    )
    return t.select(q=pw.apply(lambda a, b: a // b, pw.this.a, pw.this.b))


def test_terminate_on_error_default_aborts():
    res = _div_table()
    with pytest.raises(EngineError):
        pw.debug.compute_and_print(res)


def test_error_value_and_error_log():
    res = _div_table()
    err_log = pw.global_error_log()

    runner = GraphRunner()
    runner.engine.terminate_on_error = False
    cap, names = runner.capture(res)
    ecap, enames = runner.capture(err_log)
    runner.run()

    vals = sorted(
        (row[0] for row in cap.state.values()), key=lambda v: str(type(v))
    )
    assert sum(isinstance(v, Error) for v in vals) == 1
    assert sorted(v for v in vals if isinstance(v, int)) == [3, 5]

    entries = list(ecap.state.values())
    assert len(entries) == 1
    op_id, message, _trace = entries[0]
    assert "ZeroDivisionError" in message
    assert isinstance(op_id, int)
    pw.clear_graph()


def test_fill_error_recovers():
    res = _div_table().select(q=pw.fill_error(pw.this.q, -1))
    runner = GraphRunner()
    runner.engine.terminate_on_error = False
    cap, _names = runner.capture(res)
    runner.run()
    assert sorted(row[0] for row in cap.state.values()) == [-1, 3, 5]
    pw.clear_graph()


def test_error_rows_silently_fail_filters():
    res = _div_table().filter(pw.this.q > 0)
    runner = GraphRunner()
    runner.engine.terminate_on_error = False
    cap, _names = runner.capture(res)
    ecap, _ = runner.capture(pw.global_error_log())
    runner.run()
    assert sorted(row[0] for row in cap.state.values()) == [3, 5]
    # only ONE log entry (the original eval failure) — the downstream
    # filter must not re-report the propagated ERROR row
    assert len(ecap.state) == 1
    pw.clear_graph()


def test_retraction_does_not_duplicate_error_entry():
    """Deleting a previously-failed row re-evaluates to build the
    retraction but must NOT log the same failure twice."""
    t = pw.debug.table_from_markdown(
        """
          | a | b | __time__ | __diff__
        1 | 7 | 0 | 0        | 1
        1 | 7 | 0 | 2        | -1
        """
    )
    res = t.select(q=pw.apply(lambda a, b: a // b, pw.this.a, pw.this.b))
    runner = GraphRunner()
    runner.engine.terminate_on_error = False
    cap, _ = runner.capture(res)
    ecap, _ = runner.capture(pw.global_error_log())
    runner.run()
    assert cap.state == {}  # row fully retracted
    assert len(ecap.state) == 1  # one failure, one entry
    pw.clear_graph()


def test_fresh_failure_next_to_error_cell_still_reported():
    """A failure in an expression whose OWN operands are healthy must be
    reported even if another cell of the row already holds ERROR."""
    t = T(
        """
          | a  | b | c
        1 | 10 | 0 | 0
        """
    )
    step1 = t.select(
        a=pw.this.a,
        c=pw.this.c,
        q=pw.apply(lambda a, b: a // b, pw.this.a, pw.this.b),  # fails
    )
    step2 = step1.select(
        q=pw.this.q,
        z=pw.apply(lambda a, c: a // c, pw.this.a, pw.this.c),  # also fails
    )
    runner = GraphRunner()
    runner.engine.terminate_on_error = False
    cap, _ = runner.capture(step2)
    ecap, _ = runner.capture(pw.global_error_log())
    runner.run()
    assert len(ecap.state) == 2  # two distinct failures, two entries
    pw.clear_graph()


def test_filter_retraction_does_not_duplicate_error_entry():
    t = pw.debug.table_from_markdown(
        """
          | a | b | __time__ | __diff__
        1 | 7 | 0 | 0        | 1
        1 | 7 | 0 | 2        | -1
        """
    )
    res = t.filter(pw.apply(lambda a, b: a // b > 0, pw.this.a, pw.this.b))
    runner = GraphRunner()
    runner.engine.terminate_on_error = False
    cap, _ = runner.capture(res)
    ecap, _ = runner.capture(pw.global_error_log())
    runner.run()
    assert cap.state == {}
    assert len(ecap.state) == 1
    pw.clear_graph()


def test_local_error_log_context():
    with pw.local_error_log() as log:
        res = _div_table()
    runner = GraphRunner()
    runner.engine.terminate_on_error = False
    cap, _ = runner.capture(res)
    ecap, _ = runner.capture(log)
    runner.run()
    assert len(ecap.state) == 1
    pw.clear_graph()


def test_monitoring_http_server_metrics():
    from pathway_tpu.internals.http_monitoring import MonitoringHttpServer
    from pathway_tpu.internals.monitoring import StatsMonitor

    monitor = StatsMonitor()
    t = T(
        """
          | a
        1 | 1
        2 | 2
        """
    )
    res = t.select(b=pw.this.a * 2)
    runner = GraphRunner()
    cap, _ = runner.capture(res)
    server = MonitoringHttpServer(monitor, port=0)
    server.start()
    try:
        runner.run(monitoring_callback=monitor.update)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=5
        ).read().decode()
        assert "pathway_rows_input_total" in body
        assert 'pathway_operator_rows{operator=' in body
        assert "pathway_input_latency_ms" in body
        status = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/status", timeout=5
        ).read().decode()
        assert '"rows_in"' in status
    finally:
        server.stop()
    pw.clear_graph()


def test_run_with_http_server_flag():
    """pw.run(with_http_server=True) serves metrics during the run and
    shuts the server down afterwards."""
    import socket

    t = T(
        """
          | a
        1 | 1
        """
    )
    seen = []
    pw.io.subscribe(t, on_change=lambda **kw: seen.append(1))
    # pick a free port via env-less override: use process_id port; just
    # ensure run() completes with the flag on and the port closes after
    pw.run(with_http_server=True)
    assert seen
    with pytest.raises(OSError):
        # server is down — connection must fail
        socket.create_connection(("127.0.0.1", 20000), timeout=0.5).close()


def test_live_dashboard_renders_connectors_and_operators():
    """The rich PROGRESS DASHBOARD (reference monitoring.py:56):
    connectors table with minibatch/minute/start counts, operators table
    with latency, LOGS panel capturing log records."""
    import io
    import logging as _logging
    import time as _time

    from rich.console import Console

    from pathway_tpu.internals.monitoring import (
        LiveDashboard,
        MonitoringLevel,
        StatsMonitor,
        build_dashboard,
        monitor_stats,
    )

    t = T(
        """
          | a
        1 | 1
        2 | 2
        """
    )
    res = t.select(b=pw.this.a * 2)
    runner = GraphRunner()
    cap, _ = runner.capture(res)

    monitor = StatsMonitor()
    buf = io.StringIO()
    console = Console(file=buf, width=140, force_terminal=True)
    dashboard = LiveDashboard(with_operators=True, console=console, screen=False)
    monitor.attach_dashboard(dashboard)
    dashboard.start()
    try:
        _logging.getLogger().info("hello dashboard log")
        runner.run(monitoring_callback=monitor.update)
        dashboard.refresh(monitor, _time.monotonic())
    finally:
        dashboard.stop()
    pw.clear_graph()

    # collected stats: the static source is a connector with its counts
    assert monitor.connectors, "no connector stats collected"
    conn = list(monitor.connectors.values())[0]
    assert conn.num_messages_from_start == 2
    assert monitor.snapshot.rows_out >= 4  # source + select

    rendered = buf.getvalue()
    assert "PATHWAY PROGRESS DASHBOARD" in rendered
    assert "connector" in rendered
    assert "operator" in rendered
    assert "LOGS" in rendered

    # a fresh console render of the dashboard shows the counts
    buf2 = io.StringIO()
    console2 = Console(file=buf2, width=160)
    console2.print(build_dashboard(monitor, _time.monotonic()))
    out = buf2.getvalue()
    assert "since start" in out

    # monitor_stats context manager: NONE yields a bare collector
    with monitor_stats("none") as m:
        assert m.dashboard is None
    assert MonitoringLevel.coerce("all") is MonitoringLevel.ALL
    assert MonitoringLevel.coerce(None) is MonitoringLevel.NONE
