"""Fault-tolerant runtime: unified RetryPolicy, run supervisor,
dead-letter routing, chaos harness, cluster-formation timeouts.

Reference model: the reference's persistence/recovery integration suite
plus udfs.AsyncRetryStrategy semantics; the multi-process crash-window
proofs live in test_chaos_crash_window.py (marked slow/chaos).
"""

from __future__ import annotations

import os
import socket

import pytest

import pathway_tpu as pw
from pathway_tpu.resilience import (
    DEFAULT_RETRY_CODES,
    RETRY_METRICS,
    SUPERVISOR_METRICS,
    ChaosInjected,
    ChaosPlan,
    Recovery,
    RecoveryEscalated,
    RetryPolicy,
    Supervisor,
    chaos,
)


def _no_sleep(_s: float) -> None:
    pass


@pytest.fixture(autouse=True)
def _reset_resilience_state():
    RETRY_METRICS.reset()
    SUPERVISOR_METRICS.reset()
    yield
    chaos.deactivate()
    RETRY_METRICS.reset()
    SUPERVISOR_METRICS.reset()


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------


def test_retry_jitter_deterministic_under_seed():
    def waits(policy):
        sched = policy.spawn()
        return [sched.wait_duration_before_retry() for _ in range(5)]

    a = RetryPolicy(first_delay_ms=10, jitter_ms=100, max_retries=5, seed=42)
    b = RetryPolicy(first_delay_ms=10, jitter_ms=100, max_retries=5, seed=42)
    assert waits(a) == waits(b)
    # and a seeded policy replays the same schedule on every spawn
    assert waits(a) == waits(a)
    # different seed, different jitter
    c = RetryPolicy(first_delay_ms=10, jitter_ms=100, max_retries=5, seed=7)
    assert waits(a) != waits(c)


def test_retry_backoff_growth_without_jitter():
    p = RetryPolicy(first_delay_ms=100, backoff_factor=2.0, jitter_ms=0)
    s = p.spawn()
    assert [s.wait_duration_before_retry() for _ in range(3)] == [0.1, 0.2, 0.4]


def test_retry_execute_succeeds_after_transient_failures():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("transient")
        return "ok"

    p = RetryPolicy(first_delay_ms=1, jitter_ms=0, max_retries=5, sleep=_no_sleep)
    assert p.execute(flaky, scope="t") == "ok"
    snap = RETRY_METRICS.snapshot()["t"]
    assert snap == {"attempts": 3, "retries": 2, "successes": 1, "failures": 0}


def test_retry_execute_exhausts_budget_and_raises():
    p = RetryPolicy(first_delay_ms=1, jitter_ms=0, max_retries=2, sleep=_no_sleep)
    with pytest.raises(ValueError, match="always"):
        p.execute(lambda: (_ for _ in ()).throw(ValueError("always")), scope="x")
    snap = RETRY_METRICS.snapshot()["x"]
    assert snap["attempts"] == 3  # initial + 2 retries
    assert snap["failures"] == 1 and snap["successes"] == 0


def test_retry_execute_respects_retryable_filter():
    calls = {"n": 0}

    def fatal():
        calls["n"] += 1
        raise TypeError("not transient")

    p = RetryPolicy(first_delay_ms=1, jitter_ms=0, max_retries=5, sleep=_no_sleep)
    with pytest.raises(TypeError):
        p.execute(fatal, retryable=lambda e: isinstance(e, ConnectionError))
    assert calls["n"] == 1  # no retry on a non-retryable error


def test_retry_none_policy_single_attempt():
    calls = {"n": 0}

    def fail():
        calls["n"] += 1
        raise OSError("x")

    with pytest.raises(OSError):
        RetryPolicy.none().execute(fail)
    assert calls["n"] == 1


def test_http_retry_module_delegates_to_shared_policy():
    from pathway_tpu.io.http import _retry

    # one class, one code list — they literally ARE the shared objects
    assert _retry.RetryPolicy is RetryPolicy
    assert _retry.DEFAULT_RETRY_CODES is DEFAULT_RETRY_CODES
    assert set(DEFAULT_RETRY_CODES) == {429, 500, 502, 503, 504}


def test_exponential_backoff_strategy_accepts_injected_rng():
    import random

    from pathway_tpu.internals import udfs

    s1 = udfs.ExponentialBackoffRetryStrategy(rng=random.Random(5))
    s2 = udfs.ExponentialBackoffRetryStrategy(rng=random.Random(5))
    assert s1._rng.random() == s2._rng.random()


def test_retry_policy_coerces_into_udf_executor():
    import asyncio

    from pathway_tpu.internals.udfs import _coerce_retry_strategy

    strategy = _coerce_retry_strategy(
        RetryPolicy(first_delay_ms=1, jitter_ms=0, max_retries=3)
    )
    calls = {"n": 0}

    async def flaky():
        calls["n"] += 1
        if calls["n"] < 2:
            raise ConnectionError("blip")
        return 9

    assert asyncio.run(strategy.invoke(flaky)) == 9
    assert calls["n"] == 2


# ---------------------------------------------------------------------------
# Supervisor
# ---------------------------------------------------------------------------


def test_recovery_coerce_forms():
    assert Recovery.coerce(None) is None
    assert Recovery.coerce(False) is None
    assert Recovery.coerce(True).max_restarts == 3
    assert Recovery.coerce(7).max_restarts == 7
    r = Recovery(max_restarts=1)
    assert Recovery.coerce(r) is r
    with pytest.raises(TypeError):
        Recovery.coerce("yes")


def _fast_recovery(max_restarts: int) -> Recovery:
    return Recovery(
        max_restarts=max_restarts,
        backoff=RetryPolicy(
            first_delay_ms=1, jitter_ms=0, max_retries=max_restarts, sleep=_no_sleep
        ),
    )


def test_supervisor_restarts_until_success():
    state = {"n": 0}

    def attempt(is_restart):
        state["n"] += 1
        assert is_restart == (state["n"] > 1)
        if state["n"] < 3:
            raise OSError("worker died")
        return "done"

    assert Supervisor(_fast_recovery(5)).run(attempt) == "done"
    assert state["n"] == 3
    snap = SUPERVISOR_METRICS.snapshot()
    assert snap["restarts"] == {"OSError": 2}
    assert snap["restarts_total"] == 2 and snap["escalations"] == 0


def test_supervisor_escalates_when_budget_exhausted():
    def always(_is_restart):
        raise ConnectionError("perma-dead")

    with pytest.raises(RecoveryEscalated, match="budget exhausted"):
        Supervisor(_fast_recovery(2)).run(always)
    snap = SUPERVISOR_METRICS.snapshot()
    assert snap["restarts_total"] == 2 and snap["escalations"] == 1


def test_supervisor_does_not_catch_programming_errors():
    calls = {"n": 0}

    def broken(_is_restart):
        calls["n"] += 1
        raise KeyError("bug, not a fault")

    with pytest.raises(KeyError):
        Supervisor(_fast_recovery(3)).run(broken)
    assert calls["n"] == 1  # no restart burned on a non-fault


def test_run_recovery_restarts_through_chaos_connector_failure(tmp_path):
    """pw.run(recovery=...): a connector failing on the first attempt
    (injected via the chaos harness) restarts the run, which then
    completes and delivers every row."""
    from pathway_tpu.io._connector import input_table_from_reader

    class S(pw.Schema):
        v: int

    chaos.activate(ChaosPlan([{"site": "connector.chaotic", "action": "raise"}]))

    def reader(ctx):
        for i in range(3):
            ctx.insert({"v": i})

    t = input_table_from_reader(S, reader, name="chaotic")
    rows: list[int] = []
    pw.io.subscribe(
        t, on_change=lambda key, row, time, is_addition: rows.append(row["v"])
    )
    with pytest.warns(UserWarning, match="without persistence_config"):
        pw.run(monitoring_level="none", recovery=_fast_recovery(2))
    assert sorted(rows) == [0, 1, 2]
    assert SUPERVISOR_METRICS.snapshot()["restarts_total"] == 1


# ---------------------------------------------------------------------------
# Connector retry + metrics surface (acceptance criterion)
# ---------------------------------------------------------------------------


def test_connector_retry_policy_recovers_and_reports_metrics():
    from pathway_tpu.internals.http_monitoring import MonitoringHttpServer
    from pathway_tpu.io._connector import input_table_from_reader

    class S(pw.Schema):
        v: int

    state = {"fails": 2}

    def reader(ctx):
        if state["fails"] > 0:
            state["fails"] -= 1
            raise ConnectionError("transient network blip")
        for i in range(3):
            ctx.insert({"v": i})

    t = input_table_from_reader(
        S,
        reader,
        name="flaky",
        retry_policy=RetryPolicy(
            first_delay_ms=1, jitter_ms=0, max_retries=5, sleep=_no_sleep
        ),
    )
    rows: list[int] = []
    pw.io.subscribe(
        t, on_change=lambda key, row, time, is_addition: rows.append(row["v"])
    )
    pw.run(monitoring_level="none")
    assert sorted(rows) == [0, 1, 2]
    snap = RETRY_METRICS.snapshot()["connector:flaky"]
    assert snap == {"attempts": 3, "retries": 2, "successes": 1, "failures": 0}

    # the same counters render on the Prometheus endpoint
    class _FakeMonitor:
        class snapshot:
            time = 0
            rows_in = 0
            rows_out = 0
            operators: dict = {}
            operator_self_time_s: dict = {}
            operator_event_lag_s: dict = {}

        profiler = None

        def input_latency_ms(self, now):
            return 0

        def output_latency_ms(self, now):
            return 0

    text = MonitoringHttpServer(_FakeMonitor(), port=0)._prometheus()
    assert 'pathway_retry_attempts_total{scope="connector:flaky"} 3' in text
    assert 'pathway_retry_retries_total{scope="connector:flaky"} 2' in text
    assert 'pathway_retry_successes_total{scope="connector:flaky"} 1' in text


# ---------------------------------------------------------------------------
# Dead-letter routing
# ---------------------------------------------------------------------------


def _run_capture(pairs):
    """subscribe to [(table, sink_list)] and run once."""
    for table, out in pairs:
        pw.io.subscribe(
            table,
            on_change=lambda key, row, time, is_addition, out=out: out.append(row),
        )
    pw.run(monitoring_level="none")


def test_udf_dead_letter_routes_row_with_metadata():
    @pw.udf(on_error="dead_letter")
    def bad(x: int) -> int:
        if x == 2:
            raise ValueError("boom")
        return x * 10

    t = pw.debug.table_from_markdown(
        """
          | x
        1 | 1
        2 | 2
        3 | 3
        """
    )
    r = t.select(y=bad(pw.this.x))
    ok: list[dict] = []
    failed: list[dict] = []
    _run_capture([(r, ok), (bad.failed, failed)])
    assert sorted(row["y"] for row in ok) == [10, 30]
    assert len(failed) == 1
    rec = failed[0]
    assert rec["args"] == [2]
    assert rec["message"] == "ValueError: boom"
    assert rec["trace"]["function"] == "bad"
    assert isinstance(rec["operator_id"], int)


def test_udf_on_error_skip_drops_row_silently():
    @pw.udf(on_error="skip")
    def bad(x: int) -> int:
        if x == 2:
            raise ValueError("boom")
        return x * 10

    t = pw.debug.table_from_markdown(
        """
          | x
        1 | 1
        2 | 2
        """
    )
    ok: list[dict] = []
    _run_capture([(t.select(y=bad(pw.this.x)), ok)])
    assert [row["y"] for row in ok] == [10]


def test_udf_on_error_validation():
    with pytest.raises(ValueError, match="on_error"):
        pw.udf(on_error="explode")(lambda x: x)


def test_async_transformer_failed_table_and_lifecycle():
    class OutSchema(pw.Schema):
        ret: int

    events: list[str] = []

    class MyT(pw.AsyncTransformer, output_schema=OutSchema):
        def open(self):
            events.append("open")

        def close(self):
            events.append("close")

        async def invoke(self, x) -> dict:
            events.append(f"invoke:{x}")
            if x == 2:
                raise RuntimeError("nope")
            return {"ret": x + 100}

    t = pw.debug.table_from_markdown(
        """
          | x
        1 | 1
        2 | 2
        3 | 3
        """
    )
    mt = MyT(
        input_table=t,
        retry_strategy=RetryPolicy(
            first_delay_ms=1, jitter_ms=0, max_retries=1, sleep=_no_sleep
        ),
    )
    good: list[dict] = []
    failed: list[dict] = []
    _run_capture([(mt.successful, good), (mt.failed, failed)])
    assert sorted(row["ret"] for row in good) == [101, 103]
    assert len(failed) == 1 and failed[0]["message"] == "RuntimeError: nope"
    assert failed[0]["args"] == [2]
    # open() once before the first invoke, close() once at stream end,
    # and the retry re-entered invoke without reopening
    assert events[0] == "open" and events[-1] == "close"
    assert events.count("open") == 1 and events.count("close") == 1
    assert events.count("invoke:2") == 2


def test_async_transformer_on_error_raise_keeps_legacy_routing():
    class OutSchema(pw.Schema):
        ret: int

    class MyT(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, x) -> dict:
            if x == 2:
                raise RuntimeError("nope")
            return {"ret": x}

    t = pw.debug.table_from_markdown(
        """
          | x
        1 | 1
        2 | 2
        """
    )
    mt = MyT(input_table=t, on_error="raise")
    with pytest.raises(Exception, match="nope"):
        good: list[dict] = []
        _run_capture([(mt.successful, good)])


# ---------------------------------------------------------------------------
# Chaos harness
# ---------------------------------------------------------------------------


def test_chaos_plan_site_and_time_matching():
    plan = ChaosPlan([{"site": "s1", "time": 2, "action": "raise"}])
    chaos.activate(plan)
    chaos.inject("s0", time=2)  # wrong site: no-op
    chaos.inject("s1", time=1)  # wrong epoch: no-op
    with pytest.raises(ChaosInjected, match="site=s1"):
        chaos.inject("s1", time=2)
    # once-only by default
    chaos.inject("s1", time=2)


def test_chaos_plan_hit_count_and_repeat():
    chaos.activate(ChaosPlan([{"site": "s", "hit": 3, "action": "raise"}]))
    chaos.inject("s")
    chaos.inject("s")
    with pytest.raises(ChaosInjected):
        chaos.inject("s")  # third hit fires

    chaos.activate(ChaosPlan([{"site": "r", "repeat": True, "action": "raise"}]))
    for _ in range(3):
        with pytest.raises(ChaosInjected):
            chaos.inject("r")


def test_chaos_plan_offset_threshold():
    chaos.activate(ChaosPlan([{"site": "w", "offset": 100, "action": "raise"}]))
    chaos.inject("w", offset=50)
    chaos.inject("w", offset=None)
    with pytest.raises(ChaosInjected):
        chaos.inject("w", offset=120)


def test_chaos_plan_process_scoping(monkeypatch):
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "1")
    chaos.activate(ChaosPlan([{"site": "p", "process": 0, "action": "raise"}]))
    chaos.inject("p")  # we are process 1: no-op
    chaos.activate(ChaosPlan([{"site": "p", "process": 1, "action": "raise"}]))
    with pytest.raises(ChaosInjected):
        chaos.inject("p")


def test_chaos_from_spec_and_env(tmp_path, monkeypatch):
    plan = ChaosPlan.from_spec({"rules": [{"site": "a"}]})
    assert len(plan.rules) == 1
    plan = ChaosPlan.from_spec({"site": "b"})
    assert plan.rules[0]["site"] == "b"

    spec = tmp_path / "chaos.json"
    spec.write_text('[{"site": "envsite", "action": "raise"}]')
    monkeypatch.setenv("PATHWAY_CHAOS", str(spec))
    chaos.reload_env()  # force a re-read of the env on next inject
    try:
        with pytest.raises(ChaosInjected):
            chaos.inject("envsite")
    finally:
        chaos.deactivate()


def test_chaos_inactive_is_noop():
    chaos.deactivate()
    chaos.inject("anything", time=0, offset=0)


# ---------------------------------------------------------------------------
# Cluster formation timeouts (satellite b)
# ---------------------------------------------------------------------------


def test_coordinator_accept_timeout_names_missing_worker(monkeypatch):
    from pathway_tpu.engine import dataflow as df
    from pathway_tpu.internals.graph_runner import GraphRunner
    from pathway_tpu.parallel.multiprocess import CoordinatorCluster

    monkeypatch.setenv("PATHWAY_CLUSTER_TOKEN", "test-token")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    runner = GraphRunner(n_workers=1)
    with pytest.raises(df.EngineError) as ei:
        CoordinatorCluster([runner.engine], 3, port, accept_timeout=0.2)
    msg = str(ei.value)
    assert "worker process(es) [1, 2] never connected" in msg
    assert "PATHWAY_CLUSTER_ACCEPT_TIMEOUT" in msg


def test_cluster_timeout_env_knobs(monkeypatch):
    from pathway_tpu.internals.config import get_pathway_config

    monkeypatch.setenv("PATHWAY_CLUSTER_ACCEPT_TIMEOUT", "120.5")
    monkeypatch.setenv("PATHWAY_CLUSTER_HELLO_TIMEOUT", "2")
    cfg = get_pathway_config()
    assert cfg.cluster_accept_timeout == 120.5
    assert cfg.cluster_hello_timeout == 2.0
    monkeypatch.delenv("PATHWAY_CLUSTER_ACCEPT_TIMEOUT")
    monkeypatch.delenv("PATHWAY_CLUSTER_HELLO_TIMEOUT")
    cfg = get_pathway_config()
    assert cfg.cluster_accept_timeout is None
    assert cfg.cluster_hello_timeout is None
