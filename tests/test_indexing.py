"""Indexing stdlib tests (reference test model:
python/pathway/tests/test_external_index*.py, ml/test_index.py)."""

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.stdlib.indexing import (
    BruteForceKnnFactory,
    HybridIndexFactory,
    LshKnnFactory,
    TantivyBM25Factory,
)
from pathway_tpu.stdlib.ml.index import KNNIndex

from .utils import run_table


def one_hot_embed(texts):
    """Deterministic fake embedder: 8-dim one-hot by hash."""
    out = []
    for t in texts:
        v = np.zeros(8)
        v[sum(map(ord, t)) % 8] = 1.0
        out.append(v)
    return np.stack(out)


def _docs():
    return pw.debug.table_from_markdown(
        """
      | text | path
    1 | aaa  | /docs/x/1.txt
    2 | bbb  | /docs/y/2.txt
    3 | ccc  | /docs/x/3.txt
    """
    )


def test_brute_force_knn_as_of_now():
    docs = _docs()
    index = BruteForceKnnFactory(dimensions=8, embedder=one_hot_embed).build_index(
        docs.text, docs
    )
    queries = pw.debug.table_from_markdown(
        """
      | query
    9 | aaa
    """
    )
    res = index.query_as_of_now(queries.query, number_of_matches=1)
    rows = run_table(res.select(text=res.text))
    assert list(rows.values())[0] == (("aaa",),)


def test_knn_per_query_k():
    docs = _docs()
    index = BruteForceKnnFactory(dimensions=8, embedder=one_hot_embed).build_index(
        docs.text, docs
    )
    queries = pw.debug.table_from_markdown(
        """
      | query | k
    8 | aaa   | 1
    9 | bbb   | 3
    """
    )
    res = index.query_as_of_now(queries.query, number_of_matches=queries.k)
    rows = run_table(res.select(text=res.text))
    lens = sorted(len(v[0]) for v in rows.values())
    assert lens == [1, 3]


def test_knn_metadata_filter():
    docs = _docs()
    meta = docs.select(
        docs.text,
        meta=pw.apply_with_type(lambda p: {"path": p}, pw.ANY, docs.path),
    )
    index = BruteForceKnnFactory(dimensions=8, embedder=one_hot_embed).build_index(
        meta.text, meta, metadata_column=meta.meta
    )
    queries = pw.debug.table_from_markdown(
        """
      | query | flt
    9 | aaa   | globmatch('/docs/x/**', path)
    """
    )
    res = index.query_as_of_now(
        queries.query, number_of_matches=5, metadata_filter=queries.flt
    )
    rows = run_table(res.select(text=res.text))
    texts = list(rows.values())[0][0]
    assert set(texts) == {"aaa", "ccc"}


def test_incremental_query_updates():
    docs = pw.debug.table_from_markdown(
        """
      | text | __time__
    1 | aaa  | 2
    2 | bbb  | 4
    """
    )
    queries = pw.debug.table_from_markdown(
        """
      | query | __time__
    9 | aaa   | 0
    """
    )
    index = BruteForceKnnFactory(dimensions=8, embedder=one_hot_embed).build_index(
        docs.text, docs
    )
    res = index.query(queries.query, number_of_matches=2)
    rows = run_table(res.select(text=res.text))
    # final state reflects both docs even though the query arrived first
    assert len(list(rows.values())[0][0]) == 2


def test_bm25():
    docs = _docs()
    index = TantivyBM25Factory().build_index(docs.text, docs)
    queries = pw.debug.table_from_markdown(
        """
      | query
    9 | bbb
    """
    )
    res = index.query_as_of_now(queries.query, number_of_matches=2)
    rows = run_table(res.select(text=res.text))
    assert list(rows.values())[0] == (("bbb",),)


def test_hybrid_index():
    docs = _docs()
    factory = HybridIndexFactory(
        [
            BruteForceKnnFactory(dimensions=8, embedder=one_hot_embed),
            TantivyBM25Factory(),
        ]
    )
    index = factory.build_index(docs.text, docs)
    queries = pw.debug.table_from_markdown(
        """
      | query
    9 | ccc
    """
    )
    res = index.query_as_of_now(queries.query, number_of_matches=2)
    rows = run_table(res.select(text=res.text))
    assert "ccc" in list(rows.values())[0][0]


def test_lsh_knn():
    docs = _docs()
    index = LshKnnFactory(dimensions=8, embedder=one_hot_embed).build_index(
        docs.text, docs
    )
    queries = pw.debug.table_from_markdown(
        """
      | query
    9 | aaa
    """
    )
    res = index.query_as_of_now(queries.query, number_of_matches=1)
    rows = run_table(res.select(text=res.text))
    # LSH is approximate but identical vectors share every bucket
    assert list(rows.values())[0] == (("aaa",),)


def _embedded(table, col):
    return table.select(
        table.name,
        emb=pw.apply_with_type(lambda *a: tuple(map(float, a)), pw.ANY, *col),
    )


def test_knnindex_collapsed_and_flat():
    docs = pw.debug.table_from_markdown(
        """
      | name    | x | y
    1 | bluejay | 4 | 3
    2 | cat     | 3 | 3
    3 | eagle   | 2 | 3
    """
    )
    docs = _embedded(docs, (docs.x, docs.y))
    queries = pw.debug.table_from_markdown(
        """
      | x | y
    9 | 3 | 3
    """
    )
    queries = queries.select(
        emb=pw.apply_with_type(lambda x, y: (float(x), float(y)), pw.ANY, queries.x, queries.y)
    )
    idx = KNNIndex(docs.emb, docs, n_dimensions=2)
    collapsed = run_table(
        idx.get_nearest_items(queries.emb, k=2, with_distances=True).select(
            name=pw.this.name, dist=pw.this.dist
        )
    )
    (names, dists) = list(collapsed.values())[0]
    assert names == ("cat", "bluejay") and tuple(dists) == (0.0, 1.0)

    flat = run_table(
        idx.get_nearest_items_asof_now(
            queries.emb, k=2, collapse_rows=False
        ).select(name=pw.this.name)
    )
    assert sorted(v[0] for v in flat.values()) == ["bluejay", "cat"]


def test_engine_bulk_add_batch_protocol():
    """Regression: the engine node bulk-ingests via ``add_batch(items)``
    where items are (key, payload, metadata) triples — the round-2
    snapshot broke this with an array-style ``add_batch(keys, vectors,
    metadatas)`` signature colliding with the duck-typed protocol
    (VERDICT r2, Weak #1). Drive a multi-row epoch through the engine
    node and through DeviceKnnIndex directly."""
    import pathway_tpu.ops.knn as knn_mod

    # direct: triples protocol and array protocol must agree
    idx_t = knn_mod.DeviceKnnIndex(dim=4)
    idx_a = knn_mod.DeviceKnnIndex(dim=4)
    rng = np.random.default_rng(0)
    vecs = rng.normal(size=(16, 4)).astype(np.float32)
    idx_t.add_batch([(i, vecs[i], {"i": i}) for i in range(16)])
    idx_a.add_batch_arrays(list(range(16)), vecs, [{"i": i} for i in range(16)])
    q = rng.normal(size=(2, 4)).astype(np.float32)
    rt = idx_t.search_batch(q, 3)
    ra = idx_a.search_batch(q, 3)
    assert [[k for k, _ in row] for row in rt] == [[k for k, _ in row] for row in ra]

    # engine path: one epoch with many docs exercises _index_add bulk
    docs = pw.debug.table_from_markdown(
        "\n".join(
            ["  | text | path"]
            + [f"{i} | doc{i} | /d/{i}.txt" for i in range(1, 21)]
        )
    )
    index = BruteForceKnnFactory(dimensions=8, embedder=one_hot_embed).build_index(
        docs.text, docs
    )
    queries = pw.debug.table_from_markdown(
        """
      | query
    99 | doc7
    """
    )
    res = index.query_as_of_now(queries.query, number_of_matches=3)
    rows = run_table(res.select(text=res.text))
    assert len(list(rows.values())[0][0]) == 3


def test_device_resident_ingest():
    """Ingest path keeps embeddings in HBM: an embedder exposing
    ``encode_device`` feeds the index via ``add_batch_device`` (engine
    routes jax arrays straight to the device scatter — VERDICT r2
    Weak #4). Queries must still work and the host mirror must survive
    a later full re-upload."""
    import jax.numpy as jnp

    from pathway_tpu.ops.knn import DeviceKnnIndex

    calls = {"device": 0}
    orig = DeviceKnnIndex.add_batch_device

    def spy(self, keys, vecs, metadatas=None):
        calls["device"] += 1
        return orig(self, keys, vecs, metadatas)

    class DeviceEmbedder:
        def encode_device(self, texts):
            return jnp.stack([jnp.asarray(one_hot_embed([t])[0]) for t in texts])

        def __call__(self, texts):
            return one_hot_embed(texts)

    docs = _docs()
    index = BruteForceKnnFactory(
        dimensions=8, embedder=DeviceEmbedder()
    ).build_index(docs.text, docs)
    queries = pw.debug.table_from_markdown(
        """
      | query
    9 | bbb
    """
    )
    res = index.query_as_of_now(queries.query, number_of_matches=1)
    import unittest.mock as mock

    with mock.patch.object(DeviceKnnIndex, "add_batch_device", spy):
        rows = run_table(res.select(text=res.text))
    assert list(rows.values())[0] == (("bbb",),)
    assert calls["device"] >= 1, "ingest fell back to the host path"


def test_lsh_with_device_embedder_stays_host():
    """Regression (r3 review): the fused/device routing must not leak
    into host-side tiers — LshKnn with a device-capable embedder used
    to receive raw query strings and crash in _as_vector."""
    from pathway_tpu.xpacks.llm.embedders import SentenceTransformerEmbedder

    emb = SentenceTransformerEmbedder(max_batch_size=16)
    docs = _docs()
    index = LshKnnFactory(dimensions=384, embedder=emb).build_index(docs.text, docs)
    queries = pw.debug.table_from_markdown(
        """
      | query
    9 | aaa
    """
    )
    res = index.query_as_of_now(queries.query, number_of_matches=1)
    rows = run_table(res.select(text=res.text))
    assert len(list(rows.values())[0][0]) == 1


def test_fused_query_none_payload():
    """Regression (r3 review): a NULL query value first in the epoch
    batch must not crash the fused text path."""
    from pathway_tpu.xpacks.llm.embedders import SentenceTransformerEmbedder

    emb = SentenceTransformerEmbedder(max_batch_size=16)
    docs = _docs()
    index = BruteForceKnnFactory(dimensions=384, embedder=emb).build_index(
        docs.text, docs
    )
    queries = pw.debug.table_from_markdown(
        """
      | query
    8 |
    9 | aaa
    """
    ).select(query=pw.if_else(pw.this.query == "", None, pw.this.query))
    res = index.query_as_of_now(queries.query, number_of_matches=1)
    rows = run_table(res.select(text=res.text))
    assert len(rows) == 2


def test_search_dispatch_resolve_roundtrip():
    """Async search halves: dispatch returns device arrays; resolve maps
    slots to keys identically to the blocking search."""
    import numpy as np

    from pathway_tpu.ops.knn import DeviceKnnIndex

    rng = np.random.default_rng(0)
    idx = DeviceKnnIndex(dim=16, metric="cos", reserved_space=128)
    vecs = rng.normal(size=(100, 16)).astype(np.float32)
    idx.add_batch_arrays([f"k{i}" for i in range(100)], vecs)
    q = rng.normal(size=(3, 16)).astype(np.float32)
    blocking = idx.search_batch(q, 5)
    scores, slots = idx.search_dispatch(q, 5)
    resolved = idx.search_resolve(scores, slots, 5)
    assert [[k for k, _ in row] for row in resolved] == [
        [k for k, _ in row] for row in blocking
    ]
    for brow, rrow in zip(blocking, resolved):
        for (_, bs), (_, rs) in zip(brow, rrow):
            assert abs(bs - rs) < 1e-5
