"""Parser depth (P22): ParseUnstructured chunking modes, PypdfParser
cleanup, ImageParser/SlideParser schema extraction, openparse pipelines.

The optional packages (unstructured, pypdf, pdf2image, openparse) are
not installed in CI; tests fake them in sys.modules with minimal shims,
which exercises exactly the repo-side logic the reference tests cover
(/root/reference/python/pathway/xpacks/llm/tests/test_parsers.py).
"""

from __future__ import annotations

import asyncio
import json
import sys
import types

import pytest

import pathway_tpu as pw


# ---------------------------------------------------------------- fakes


class FakeElementMeta:
    def __init__(self, d):
        self._d = dict(d)

    def to_dict(self):
        return dict(self._d)

    @property
    def page_number(self):
        return self._d.get("page_number")


class FakeElement:
    def __init__(self, text, meta=None, category=None):
        self._text = text
        self.metadata = FakeElementMeta(meta or {})
        if category is not None:
            self.category = category
        self.applied = []

    def __str__(self):
        return self._text

    def apply(self, fn):
        self.applied.append(fn)
        self._text = fn(self._text)


@pytest.fixture
def fake_unstructured(monkeypatch):
    elements: list = []
    mod = types.ModuleType("unstructured")
    part = types.ModuleType("unstructured.partition")
    auto = types.ModuleType("unstructured.partition.auto")

    def partition(file=None, **kwargs):
        auto.last_kwargs = kwargs
        return elements

    auto.partition = partition
    mod.partition = part
    part.auto = auto
    monkeypatch.setitem(sys.modules, "unstructured", mod)
    monkeypatch.setitem(sys.modules, "unstructured.partition", part)
    monkeypatch.setitem(sys.modules, "unstructured.partition.auto", auto)
    return elements


class FakePage:
    def __init__(self, text, page_number):
        self._text = text
        self.page_number = page_number

    def extract_text(self):
        return self._text


@pytest.fixture
def fake_pypdf(monkeypatch):
    pages: list = []
    mod = types.ModuleType("pypdf")

    class PdfReader:
        def __init__(self, stream=None, **kw):
            self.pages = pages

    mod.PdfReader = PdfReader
    monkeypatch.setitem(sys.modules, "pypdf", mod)
    return pages


def _fake_vision_llm(responses):
    """A chat UDF double: returns queued responses, records messages."""
    calls = []

    @pw.udf
    async def chat(messages, **kwargs):
        calls.append(messages)
        return responses[min(len(calls) - 1, len(responses) - 1)]

    chat.calls = calls
    return chat


# ------------------------------------------------------- ParseUnstructured


def _mk_unstructured_parser(**kw):
    from pathway_tpu.xpacks.llm.parsers import ParseUnstructured

    return ParseUnstructured(**kw)


def test_unstructured_mode_elements(fake_unstructured):
    fake_unstructured.extend(
        [
            FakeElement("Title", {"page_number": 1}, category="Title"),
            FakeElement("Body text", {"page_number": 1}, category="NarrativeText"),
        ]
    )
    parser = _mk_unstructured_parser(mode="elements")
    docs = parser.__wrapped__(b"...")
    assert [t for t, _ in docs] == ["Title", "Body text"]
    assert docs[0][1]["category"] == "Title"


def test_unstructured_mode_paged_combines_metadata(fake_unstructured):
    fake_unstructured.extend(
        [
            FakeElement(
                "A", {"page_number": 1, "links": ["l1"], "languages": ["en"]}
            ),
            FakeElement(
                "B",
                {
                    "page_number": 1,
                    "links": ["l2"],
                    "languages": ["de"],
                    "coordinates": (0, 0),
                    "category_depth": 2,
                },
            ),
            FakeElement("C", {"page_number": 2}),
        ]
    )
    parser = _mk_unstructured_parser(mode="paged")
    docs = parser.__wrapped__(b"...")
    assert len(docs) == 2
    page1_text, page1_meta = docs[0]
    assert page1_text == "A\n\nB\n\n"
    assert page1_meta["links"] == ["l1", "l2"]
    assert sorted(page1_meta["languages"]) == ["de", "en"]
    # element-specific fields are dropped from merged chunks
    assert "coordinates" not in page1_meta and "category_depth" not in page1_meta
    assert docs[1][0] == "C\n\n"


def test_unstructured_mode_single_merges_all(fake_unstructured):
    fake_unstructured.extend(
        [
            FakeElement("A", {"links": ["x"], "languages": ["en"], "filename": "f"}),
            FakeElement("B", {"links": [], "languages": ["en"]}),
        ]
    )
    parser = _mk_unstructured_parser(mode="single")
    docs = parser.__wrapped__(b"...")
    assert docs[0][0] == "A\n\nB"
    assert docs[0][1]["filename"] == "f"
    assert docs[0][1]["languages"] == ["en"]


def test_unstructured_call_time_overrides_and_unknown_args(fake_unstructured):
    fake_unstructured.append(FakeElement("A", {"page_number": 1}))
    parser = _mk_unstructured_parser(mode="single")
    docs = parser.__wrapped__(b"...", mode="elements")
    assert docs[0][0] == "A"  # override applied
    with pytest.raises(ValueError, match="Unknown arguments"):
        parser.__wrapped__(b"...", bogus=1)
    with pytest.raises(ValueError, match="mode"):
        _mk_unstructured_parser(mode="nonsense")


def test_unstructured_post_processors_apply(fake_unstructured):
    fake_unstructured.append(FakeElement("hello", {}))
    parser = _mk_unstructured_parser(mode="single", post_processors=[str.upper])
    docs = parser.__wrapped__(b"...")
    assert docs[0][0] == "HELLO"


def test_unstructured_kwargs_forward_to_partition(fake_unstructured):
    import unstructured.partition.auto as auto

    fake_unstructured.append(FakeElement("A", {}))
    parser = _mk_unstructured_parser(mode="single", strategy="hi_res")
    parser.__wrapped__(b"...")
    assert auto.last_kwargs == {"strategy": "hi_res"}


# ------------------------------------------------------------ PypdfParser


def test_pypdf_parser_pages_and_cleanup(fake_pypdf):
    from pathway_tpu.xpacks.llm.parsers import PypdfParser

    fake_pypdf.extend(
        [
            FakePage("First line\ncontinues here.\nNew Paragraph", 0),
            FakePage("Second   page", 1),
        ]
    )
    parser = PypdfParser()
    docs = parser.__wrapped__(b"...")
    assert len(docs) == 2
    text0, meta0 = docs[0]
    # soft wrap before a lowercase letter unwraps; capitalized line keeps \n
    assert "First line continues here." in text0
    assert "\nNew Paragraph" in text0
    assert meta0 == {"page_number": 0}
    assert docs[1][0] == "Second page"

    raw = PypdfParser(apply_text_cleanup=False)
    docs_raw = raw.__wrapped__(b"...")
    assert docs_raw[1][0] == "Second   page"


# ------------------------------------------------------------ ImageParser


def _png_bytes(w=4, h=4):
    from io import BytesIO

    from PIL import Image

    buf = BytesIO()
    Image.new("RGB", (w, h), (255, 0, 0)).save(buf, format="PNG")
    return buf.getvalue()


def test_image_parser_describes(monkeypatch):
    from pathway_tpu.xpacks.llm.parsers import ImageParser

    llm = _fake_vision_llm(["a red square"])
    parser = ImageParser(llm=llm)
    docs = asyncio.run(parser.__wrapped__(_png_bytes()))
    assert docs == [("a red square", {})]
    # the llm received a vision-style message with the b64 payload
    (messages,) = llm.calls
    content = messages.value[0]["content"]
    assert content[0]["type"] == "text"
    assert content[1]["image_url"]["url"].startswith("data:image/jpeg;base64,")


def test_image_parser_schema_extraction():
    from pydantic import BaseModel

    from pathway_tpu.xpacks.llm.parsers import ImageParser

    class Invoice(BaseModel):
        vendor: str
        total: float

    llm = _fake_vision_llm(
        ["an invoice", json.dumps({"vendor": "ACME", "total": 12.5})]
    )
    parser = ImageParser(
        llm=llm, detail_parse_schema=Invoice, include_schema_in_text=True
    )
    docs = asyncio.run(parser.__wrapped__(_png_bytes()))
    (text, meta) = docs[0]
    assert text.startswith("an invoice\n")
    assert json.loads(text.split("\n", 1)[1]) == {"vendor": "ACME", "total": 12.5}
    assert meta["vendor"] == "ACME" and meta["total"] == 12.5


def test_image_parser_schema_required_for_include_flag():
    from pathway_tpu.xpacks.llm.parsers import ImageParser

    with pytest.raises(ValueError, match="include_schema_in_text"):
        ImageParser(llm=_fake_vision_llm(["x"]), include_schema_in_text=True)


def test_maybe_downscale():
    from PIL import Image

    from pathway_tpu.xpacks.llm._parser_utils import maybe_downscale

    big = Image.new("RGB", (4000, 2000))
    small = maybe_downscale(big, max_image_size=1024, downsize_horizontal_width=400)
    assert small.size == (400, 200)
    untouched = maybe_downscale(big, max_image_size=10**9, downsize_horizontal_width=400)
    assert untouched.size == (4000, 2000)


# ------------------------------------------------------------ SlideParser


@pytest.fixture
def fake_pdf2image(monkeypatch):
    from PIL import Image

    mod = types.ModuleType("pdf2image")
    state = {"fail_fmt": False}

    def convert_from_bytes(contents, fmt=None, size=None, **kw):
        if fmt is not None and state["fail_fmt"]:
            raise RuntimeError("bad fmt")
        return [Image.new("RGB", size or (32, 32)) for _ in range(2)]

    mod.convert_from_bytes = convert_from_bytes
    monkeypatch.setitem(sys.modules, "pdf2image", mod)
    return state


def test_slide_parser_pages(fake_pdf2image):
    from pathway_tpu.xpacks.llm.parsers import SlideParser

    llm = _fake_vision_llm(["slide one", "slide two"])
    parser = SlideParser(llm=llm, run_mode="sequential")
    docs = asyncio.run(parser.__wrapped__(b"%PDF-1.4 fake"))
    assert [t for t, _ in docs] == ["slide one", "slide two"]
    for idx, (_t, meta) in enumerate(docs):
        assert meta["image_page"] == idx
        assert meta["tot_pages"] == 2
        assert isinstance(meta["b64_image"], str) and meta["b64_image"]


def test_slide_parser_format_fallback(fake_pdf2image):
    from pathway_tpu.xpacks.llm.parsers import SlideParser

    fake_pdf2image["fail_fmt"] = True  # first convert (with fmt) raises
    llm = _fake_vision_llm(["s1", "s2"])
    parser = SlideParser(llm=llm)
    docs = asyncio.run(parser.__wrapped__(b"%PDF-1.4 fake"))
    assert len(docs) == 2


def test_slide_parser_detects_pptx(fake_pdf2image):
    import io
    import zipfile

    from pathway_tpu.xpacks.llm.parsers import SlideParser

    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("ppt/slides/slide1.xml", "<x/>")
    assert SlideParser._is_pptx(buf.getvalue())
    assert not SlideParser._is_pptx(b"%PDF-1.4")
    assert not SlideParser._is_pptx(b"PK\x03\x04 not a zip really")


# --------------------------------------------------------------- openparse


def _install_fake_openparse(monkeypatch):
    """Minimal openparse shim: Node/elements, pipelines, tables.parse."""
    op = types.ModuleType("openparse")

    class Node:
        def __init__(self, elements=()):
            self.elements = tuple(elements)

        def model_dump(self):
            return {"text": " ".join(e.text for e in self.elements)}

        @property
        def text(self):
            return self.model_dump()["text"]

    class ProcessingStep:
        def process(self, nodes):
            raise NotImplementedError

    class IngestionPipeline:
        transformations: list = []

        def run(self, nodes):
            for t in self.transformations:
                nodes = t.process(nodes)
            return nodes

    class DocumentParser:
        def __init__(self, processing_pipeline=None, table_args=None):
            self.processing_pipeline = processing_pipeline or IngestionPipeline()
            self.table_args = table_args
            self._verbose = False

        @staticmethod
        def _elems_to_nodes(elems):
            return [Node(elements=(e,)) for e in elems]

    class ParsedDocument:
        def __init__(self, nodes=None, **kw):
            self.nodes = list(nodes or [])
            self.meta = kw

    class Bbox:
        def __init__(self, **kw):
            self.__dict__.update(kw)

    class TableElement:
        def __init__(self, bbox=None, text=""):
            self.bbox, self.text = bbox, text
            self.page = getattr(bbox, "page", 0)

    class _Elem:
        def __init__(self, text, page):
            self.text, self.page = text, page

    class Pdf:
        def __init__(self, file=None):
            self.file = file
            self.num_pages = 1
            self.file_metadata = {}

    # submodules
    processing = types.ModuleType("openparse.processing")
    processing.IngestionPipeline = IngestionPipeline
    processing.ProcessingStep = ProcessingStep
    processing.CombineNodesSpatially = type(
        "CombineNodesSpatially",
        (ProcessingStep,),
        {
            "__init__": lambda self, **kw: None,
            "process": lambda self, nodes: nodes,
        },
    )
    basic = types.ModuleType("openparse.processing.basic_transforms")
    for name in (
        "CombineBullets",
        "CombineHeadingsWithClosestText",
        "RemoveFullPageStubs",
        "RemoveMetadataElements",
        "RemoveNodesBelowNTokens",
        "RemoveRepeatedElements",
        "RemoveTextInsideTables",
    ):
        setattr(
            basic,
            name,
            type(
                name,
                (ProcessingStep,),
                {
                    "__init__": lambda self, **kw: None,
                    "process": lambda self, nodes: nodes,
                },
            ),
        )
    schemas = types.ModuleType("openparse.schemas")
    schemas.Bbox, schemas.Node, schemas.ParsedDocument, schemas.TableElement = (
        Bbox,
        Node,
        ParsedDocument,
        TableElement,
    )
    tables = types.ModuleType("openparse.tables")

    class PyMuPDFArgs:
        def __init__(self, **kw):
            self.kw = kw

        def model_dump(self):
            return dict(self.kw)

    class TableTransformersArgs(PyMuPDFArgs):
        pass

    class UnitableArgs(PyMuPDFArgs):
        pass

    tables.PyMuPDFArgs = PyMuPDFArgs
    tables.TableTransformersArgs = TableTransformersArgs
    tables.UnitableArgs = UnitableArgs
    tables_parse = types.ModuleType("openparse.tables.parse")
    tables_parse.PyMuPDFArgs = PyMuPDFArgs
    tables_parse.TableTransformersArgs = TableTransformersArgs
    tables_parse.UnitableArgs = UnitableArgs
    tables_parse._ingest_with_pymupdf = lambda doc, args, verbose=False: [
        TableElement(bbox=Bbox(page=0), text="pymupdf-table")
    ]
    tables_parse._ingest_with_table_transformers = (
        lambda doc, args, verbose=False: []
    )
    tables_parse._ingest_with_unitable = lambda doc, args, verbose=False: []
    text_mod = types.ModuleType("openparse.text")
    text_mod.ingest = lambda doc, parsing_method=None: [
        _Elem("hello", 0),
        _Elem("world", 1),
    ]
    pdf_mod = types.ModuleType("openparse.pdf")
    pdf_mod.Pdf = Pdf
    consts = types.ModuleType("openparse.consts")
    consts.COORDINATE_SYSTEM = "bottom-left"

    op.processing = processing
    op.schemas = schemas
    op.tables = tables
    op.text = text_mod
    op.pdf = pdf_mod
    op.consts = consts
    op.Pdf = Pdf
    op.DocumentParser = DocumentParser
    op.Node = Node

    for name, mod in {
        "openparse": op,
        "openparse.processing": processing,
        "openparse.processing.basic_transforms": basic,
        "openparse.schemas": schemas,
        "openparse.tables": tables,
        "openparse.tables.parse": tables_parse,
        "openparse.text": text_mod,
        "openparse.pdf": pdf_mod,
        "openparse.consts": consts,
    }.items():
        monkeypatch.setitem(sys.modules, name, mod)
    return op


@pytest.fixture
def fresh_openparse_utils():
    """Purge openparse_utils' lazy-class cache so the names re-resolve
    against the fake (or absent) openparse of this test. A plain reload
    is not enough: reload reuses the module dict, so previously built
    classes (bound to a previous test's fake) would survive."""
    import pathway_tpu.xpacks.llm.openparse_utils as opu

    def clear():
        for name in opu._LAZY_NAMES:
            opu.__dict__.pop(name, None)

    clear()
    yield opu
    clear()


def test_openparse_utils_importerror_without_package(fresh_openparse_utils):
    opu = fresh_openparse_utils
    assert "openparse" not in sys.modules or sys.modules["openparse"] is not None
    with pytest.raises(ImportError, match="openparse"):
        opu.SimpleIngestionPipeline
    # non-lazy names always work
    args = opu.LLMArgs(llm=None)
    assert args.parsing_algorithm == "llm"
    with pytest.raises(Exception):
        opu.LLMArgs(unexpected_field=1)


def test_openparse_pipelines_with_fake_package(monkeypatch, fresh_openparse_utils):
    op = _install_fake_openparse(monkeypatch)
    opu = fresh_openparse_utils

    # SimpleIngestionPipeline constructs with the documented transform chain
    pipeline = opu.SimpleIngestionPipeline()
    assert len(pipeline.transformations) == 11

    # PageChunker merges node elements by page
    class E:
        def __init__(self, text, page):
            self.text, self.page = text, page

    n1 = op.Node(elements=(E("a", 0), E("b", 1)))
    n2 = op.Node(elements=(E("c", 0),))
    merged = opu.PageChunker().process([n1, n2])
    by_text = sorted(n.text for n in merged)
    assert by_text == ["a c", "b"]

    same_page = opu.SamePageIngestionPipeline()
    out = same_page.run([n1, n2])
    assert sorted(n.text for n in out) == ["a c", "b"]


def test_openparse_table_args_dispatch(monkeypatch, fresh_openparse_utils):
    _install_fake_openparse(monkeypatch)
    opu = fresh_openparse_utils
    assert type(opu._table_args_dict_to_model({"parsing_algorithm": "pymupdf"})).__name__ == "PyMuPDFArgs"
    assert isinstance(
        opu._table_args_dict_to_model({"parsing_algorithm": "llm"}), opu.LLMArgs
    )
    with pytest.raises(ValueError, match="Unsupported"):
        opu._table_args_dict_to_model({"parsing_algorithm": "nope"})


def test_openparse_pymu_document_parser(monkeypatch, fresh_openparse_utils):
    op = _install_fake_openparse(monkeypatch)
    opu = fresh_openparse_utils
    parser = opu.PyMuDocumentParser(
        table_args={"parsing_algorithm": "pymupdf"},
        processing_pipeline=opu.SamePageIngestionPipeline(),
    )
    doc = op.Pdf(file=None)
    parsed = parser.parse(doc)
    texts = sorted(n.text for n in parsed.nodes)
    # page 0 merges the text elem with the pymupdf table elem; page 1 alone
    assert texts == ["hello pymupdf-table", "world"]


def test_openparse_parser_udf_end_to_end(monkeypatch, fresh_openparse_utils):
    """parsers.OpenParse over the fake package: chunks come back."""
    _install_fake_openparse(monkeypatch)
    import pathway_tpu.xpacks.llm.parsers as parsers_mod

    parser = parsers_mod.OpenParse(
        table_args={"parsing_algorithm": "pymupdf"},
        processing_pipeline="merge_same_page",
    )
    docs = asyncio.run(parser.__wrapped__(b"%PDF fake"))
    assert sorted(t for t, _ in docs) == ["hello pymupdf-table", "world"]
    with pytest.raises(ValueError, match="processing_pipeline"):
        parsers_mod.OpenParse(processing_pipeline="bogus")
    with pytest.raises(ValueError, match="Image parsing"):
        parsers_mod.OpenParse(
            parse_images=True, image_args={"parsing_algorithm": "pymupdf"}
        )
