"""Pad-fraction regression for the bucketed batching layer.

The MFU round replaced the coarse seq-bucket set with intermediate
buckets (48/96 below 128; 160/192/224 between 128 and 256; 320/384/448
between 256 and 512) so a sorted length-group pads to the gap to the
NEXT bucket, not a 2x step. These tests pin the wins: the new set is
never worse than the old one under the batching layer's own FLOP-waste
model (``batching.pad_fraction``), and the 150-wordpiece headline
regime lands in the 160 bucket instead of paying the 256 tax.
"""

from __future__ import annotations

import numpy as np

from pathway_tpu.models.batching import DEFAULT_SEQ_BUCKETS, bucket, pad_fraction

#: the pre-MFU-round bucket set (PR 5), kept here as the regression
#: baseline the finer set must dominate
OLD_SEQ_BUCKETS = (16, 32, 64, 128, 160, 192, 256, 512)


def test_headline_chunks_land_in_160_bucket():
    # TokenCountSplitter-regime chunks (~130-190 wordpieces)
    assert bucket(150, DEFAULT_SEQ_BUCKETS) == 160
    assert bucket(129, DEFAULT_SEQ_BUCKETS) == 160
    assert bucket(190, DEFAULT_SEQ_BUCKETS) == 192
    # the new intermediate buckets catch what the old set rounded up
    assert bucket(210, OLD_SEQ_BUCKETS) == 256
    assert bucket(210, DEFAULT_SEQ_BUCKETS) == 224
    assert bucket(90, OLD_SEQ_BUCKETS) == 128
    assert bucket(90, DEFAULT_SEQ_BUCKETS) == 96


def test_finer_buckets_strictly_cut_pad_fraction():
    # lengths that sit in an old-set gap: 200..220 padded to 256 before,
    # 224 now — a strict, deterministic improvement
    lens = list(range(200, 221))
    new = pad_fraction(lens, DEFAULT_SEQ_BUCKETS)
    old = pad_fraction(lens, OLD_SEQ_BUCKETS)
    assert new < old, (new, old)


def test_finer_buckets_never_worse_on_mixed_lengths():
    rng = np.random.default_rng(0)
    cases = [
        np.clip(rng.normal(150, 35, 4096).astype(int), 8, 512),  # headline
        rng.integers(8, 512, 2048),  # uniform mix
        np.full(1000, 160),  # exact-bucket lengths
    ]
    for lens in cases:
        for group in (64, 256, None):
            new = pad_fraction(lens, DEFAULT_SEQ_BUCKETS, group=group)
            old = pad_fraction(lens, OLD_SEQ_BUCKETS, group=group)
            assert new <= old + 1e-12, (group, new, old)


def test_headline_regime_pad_fraction_bound():
    """Sorted + grouped realistic chunk lengths: the residual pad tax
    inside live rows stays small — the number the
    pathway_encoder_pad_fraction gauge should hover near in the
    streaming pipeline."""
    rng = np.random.default_rng(1)
    lens = np.clip(rng.normal(150, 35, 4096).astype(int), 8, 512)
    new = pad_fraction(lens, DEFAULT_SEQ_BUCKETS, group=256)
    old = pad_fraction(lens, OLD_SEQ_BUCKETS, group=256)
    # measured: ~0.13 new vs ~0.21 old — a real FLOP refund, not noise
    assert old - new > 0.03, (new, old)
    assert new < 0.16, new


def test_pad_fraction_edges():
    assert pad_fraction([]) == 0.0
    assert pad_fraction([160] * 10) == 0.0  # exact bucket: no padding
    # one group vs sorted sub-groups: grouping can only help
    lens = [10, 500] * 50
    assert pad_fraction(lens, group=50) <= pad_fraction(lens, group=None)
