"""Every module in the package imports cleanly (packaging smoke test:
catches broken relative imports, missing deps, and circular imports
that narrower suites can step around)."""

from __future__ import annotations

import importlib
import pkgutil

import pathway_tpu


def test_all_modules_import():
    failures = []
    for mod in pkgutil.walk_packages(pathway_tpu.__path__, "pathway_tpu."):
        if mod.name.endswith("__main__"):
            continue  # executes the CLI on import
        try:
            importlib.import_module(mod.name)
        except Exception as e:  # pragma: no cover - failure reporting
            failures.append((mod.name, repr(e)))
    assert not failures, failures


def test_public_surface():
    """Spot-check the reference-parity public names exist."""
    import pathway_tpu as pw

    for name in [
        "Table", "Schema", "this", "left", "right", "udf", "apply", "run",
        "iterate", "sql", "load_yaml", "transformer", "ClassArg",
        "AsyncTransformer", "LiveTable", "export_table", "import_table",
        "global_error_log", "reducers", "io", "debug", "demo", "persistence",
        "universes", "xpacks", "stdlib", "ml", "indexing", "temporal",
    ]:
        assert hasattr(pw, name), name
    for name in ["fs", "csv", "jsonlines", "plaintext", "kafka", "s3",
                 "python", "http", "airbyte", "subscribe", "null"]:
        assert hasattr(pw.io, name), f"io.{name}"


def test_reference_top_level_export_parity():
    """Every name in the reference's pathway.__all__ resolves here
    (the drop-in completeness contract)."""
    import os
    import re

    import pytest

    ref_path = "/root/reference/python/pathway/__init__.py"
    if not os.path.exists(ref_path):
        pytest.skip("reference pathway checkout not present in this environment")
    ref = open(ref_path).read()
    m = re.search(r"__all__\s*=\s*\[(.*?)\]", ref, re.S)
    ref_names = set(re.findall(r'"([^"]+)"', m.group(1)))
    import pathway_tpu as pw

    missing = sorted(
        n for n in ref_names if not hasattr(pw, n)
    )
    assert missing == [], f"missing top-level names: {missing}"


def test_reference_namespace_module_parity():
    """Every reference io/stdlib/xpacks.llm submodule resolves here."""
    import importlib
    import os

    import pytest

    if not os.path.isdir("/root/reference"):
        pytest.skip("reference checkout not present")
    for name, refpath in [
        ("io", "/root/reference/python/pathway/io"),
        ("stdlib", "/root/reference/python/pathway/stdlib"),
        ("xpacks.llm", "/root/reference/python/pathway/xpacks/llm"),
    ]:
        missing = []
        for entry in sorted(os.listdir(refpath)):
            base = entry[:-3] if entry.endswith(".py") else entry
            if base.startswith("_") or base in ("tests", "py.typed", "README.md"):
                continue
            if not (entry.endswith(".py") or os.path.isdir(os.path.join(refpath, entry))):
                continue
            target = f"pathway_tpu.{name}.{base}"
            try:
                importlib.import_module(target)
            except ImportError as e:
                # a missing TRANSITIVE dep (or broken import) is a
                # different failure than a missing module — report it
                # distinctly, but keep scanning the rest
                ename = getattr(e, "name", None)
                missing.append(base if ename == target else f"{base} ({e!r})")
        assert missing == [], f"pathway_tpu.{name} missing modules: {missing}"
