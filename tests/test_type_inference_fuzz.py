"""Type-inference-vs-runtime fuzz: random expression trees over typed
columns. The contract with the build-time checker
(internals/expression.py):

1. an expression the checker ACCEPTS evaluates without TypeError, and
   every produced value inhabits the inferred dtype;
2. the checker's accept/reject decision is deterministic and
   construction-order independent (building the same shape twice agrees).

Trees are built from column refs, constants, arithmetic/comparison/
boolean operators, if_else and coalesce; evaluation runs through the
full engine (columnar evaluators + per-row fallback)."""

from __future__ import annotations

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.internals import dtype as dt

from .utils import T, run_table

COLS = {
    "i1": dt.INT,
    "i2": dt.INT,
    "f1": dt.FLOAT,
    "s1": dt.STR,
    "b1": dt.BOOL,
}


def _table():
    return T(
        """
          | i1 | i2 | f1  | s1  | b1
        1 | 3  | -2 | 0.5 | ab  | True
        2 | 0  | 7  | -1.5| cd  | False
        3 | -4 | 1  | 2.0 | ab  | True
        """
    )


def _leaf(rng, t):
    c = int(rng.integers(0, 7))
    if c < 5:
        name = list(COLS)[c]
        return t[name], COLS[name]
    if c == 5:
        v = int(rng.integers(-5, 6))
        return v, dt.INT
    return float(rng.integers(-3, 4)), dt.FLOAT


def _build(rng, t, depth=0):
    """Returns (expr, static_ok) — static_ok None means 'didn't raise'."""
    if depth >= 3 or rng.random() < 0.4:
        e, _ = _leaf(rng, t)
        return e
    kind = int(rng.integers(0, 4))
    if kind == 0:
        op = rng.choice(["+", "-", "*", "/", "//", "%"])
        l = _build(rng, t, depth + 1)
        r = _build(rng, t, depth + 1)
        return {
            "+": lambda a, b: a + b,
            "-": lambda a, b: a - b,
            "*": lambda a, b: a * b,
            "/": lambda a, b: a / b,
            "//": lambda a, b: a // b,
            "%": lambda a, b: a % b,
        }[op](l, r)
    if kind == 1:
        op = rng.choice(["==", "<", ">="])
        l = _build(rng, t, depth + 1)
        r = _build(rng, t, depth + 1)
        return {
            "==": lambda a, b: a == b,
            "<": lambda a, b: a < b,
            ">=": lambda a, b: a >= b,
        }[op](l, r)
    if kind == 2:
        cond = _build(rng, t, depth + 1)
        a = _build(rng, t, depth + 1)
        b = _build(rng, t, depth + 1)
        return pw.if_else(cond, a, b)
    return pw.coalesce(_build(rng, t, depth + 1), _build(rng, t, depth + 1))


def _inhabits(value, d: dt.DType) -> bool:
    d = dt.unoptionalize(d)
    if value is None:
        return True  # division-by-zero etc. route to ERROR/None cells
    if d is dt.INT:
        return isinstance(value, (int, np.integer)) and not isinstance(value, bool)
    if d is dt.FLOAT:
        return isinstance(value, (float, np.floating, int, np.integer))
    if d is dt.BOOL:
        return isinstance(value, (bool, np.bool_))
    if d is dt.STR:
        return isinstance(value, str)
    return True  # ANY and composites: no constraint to check


@pytest.mark.parametrize("seed", range(30))
def test_accepted_expressions_evaluate_and_inhabit(seed):
    rng = np.random.default_rng(seed)
    t = _table()
    try:
        e = _build(rng, t)
    except TypeError:
        pw.clear_graph()
        return  # checker rejected at build — contract part 2 below
    if not hasattr(e, "_dtype"):
        pw.clear_graph()
        return  # degenerate tree: bare constant
    inferred = e._dtype
    sel = t.select(out=e)
    assert sel._columns["out"].dtype == inferred
    state = run_table(sel)
    from pathway_tpu.engine.value import ERROR

    for (val,) in state.values():
        if val is ERROR or isinstance(val, type(ERROR)):
            continue  # runtime errors (div by zero) route to ERROR cells
        assert _inhabits(val, inferred), (
            f"value {val!r} does not inhabit inferred {inferred} (seed {seed})"
        )
    pw.clear_graph()


@pytest.mark.parametrize("seed", range(30))
def test_checker_decision_is_deterministic(seed):
    def attempt():
        rng = np.random.default_rng(seed)
        t = _table()
        try:
            e = _build(rng, t)
            d = getattr(e, "_dtype", None)
            pw.clear_graph()
            return ("ok", repr(d))
        except TypeError as exc:
            pw.clear_graph()
            return ("reject", str(exc))

    assert attempt() == attempt()
