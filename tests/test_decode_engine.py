"""Continuous-batching decode engine (pathway_tpu/decode): spec
parsing and the run-scoped config, the continuous-batching invisibility
gate (interleaved streams bitwise-equal to one-at-a-time runs and to
the in-jit ``decode_greedy`` path), deadline preemption, the
``decode.step`` chaos site's compute-then-commit atomicity, flight
events, ``pathway_decode_*`` metrics gating, the ``DecodeService``
front door, the ``pw.run(decode=)`` knob, and the fused-RAG on-chip
answer path."""

from __future__ import annotations

import time

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.decode import (
    DECODE_METRICS,
    DecodeConfig,
    DecodeEngine,
    DecodeService,
    DecoderConfig,
    decode_greedy,
    init_decoder_params,
    parse_decode_spec,
    use_decode,
)
from pathway_tpu.decode.config import active_decode, degraded
from pathway_tpu.internals import flight_recorder as fr
from pathway_tpu.resilience import chaos
from pathway_tpu.serving.deadline import Deadline

# tiny geometry: everything below must run in seconds on CPU
MODEL = DecoderConfig(
    vocab_size=97,
    hidden_size=16,
    num_layers=2,
    num_heads=2,
    intermediate_size=32,
    max_position=64,
)
CONFIG = DecodeConfig(
    pages=64,
    page_size=4,
    lanes=4,
    max_new_tokens=6,
    degrade_max_new_tokens=2,
    max_seq=48,
    impl="xla",
)
PARAMS = init_decoder_params(MODEL, seed=0)


@pytest.fixture(autouse=True)
def _fresh_metrics():
    DECODE_METRICS.reset()
    yield
    DECODE_METRICS.reset()
    chaos.deactivate()


def _engine(**over) -> DecodeEngine:
    cfg = CONFIG if not over else DecodeConfig(**{**CONFIG.as_dict(), **over})
    return DecodeEngine(MODEL, cfg, params=PARAMS)


PROMPTS = [
    [3, 1, 4, 1, 5],
    [2, 7, 1, 8, 2, 8, 1, 8],
    [9, 9],
    [31, 41, 5, 92, 6, 53, 5, 89, 79, 3],
]


# ----------------------------------------------------------------- config


def test_parse_decode_spec_forms():
    assert parse_decode_spec(None) is None
    assert parse_decode_spec(False) is None
    assert parse_decode_spec(0) is None
    assert parse_decode_spec("off") is None
    assert parse_decode_spec(True) == DecodeConfig()
    assert parse_decode_spec("auto") == DecodeConfig()
    assert parse_decode_spec(128) == DecodeConfig(pages=128)
    cfg = parse_decode_spec("pages=128, page=8, max_new=32, rerank=off")
    assert (cfg.pages, cfg.page_size, cfg.max_new_tokens, cfg.rerank) == (
        128, 8, 32, False,
    )
    cfg = parse_decode_spec({"pages": 16, "batch": 2, "impl": "XLA"})
    assert (cfg.pages, cfg.lanes, cfg.impl) == (16, 2, "xla")
    already = DecodeConfig(pages=99)
    assert parse_decode_spec(already) is already


def test_parse_decode_spec_rejects_malformed():
    with pytest.raises(ValueError, match="unknown spec key"):
        parse_decode_spec("pagez=4")
    with pytest.raises(ValueError, match="key=value"):
        parse_decode_spec("pages")
    with pytest.raises(ValueError, match="impl"):
        parse_decode_spec("impl=cuda")
    with pytest.raises(ValueError, match="cannot parse"):
        parse_decode_spec(3.5)
    with pytest.raises(ValueError, match="degrade_max_new_tokens"):
        DecodeConfig(max_new_tokens=4, degrade_max_new_tokens=8)


def test_env_and_run_scoped_active_config(monkeypatch):
    monkeypatch.delenv("PATHWAY_DECODE", raising=False)
    assert active_decode() is None
    monkeypatch.setenv("PATHWAY_DECODE", "pages=32,page=8")
    assert active_decode().pages == 32
    monkeypatch.setenv("PATHWAY_DECODE", "not a spec !!")
    assert active_decode() is None  # malformed env counts as off
    monkeypatch.setenv("PATHWAY_DECODE", "pages=32,page=8")
    with use_decode("pages=8,page=4,max_seq=16"):
        assert active_decode().pages == 8  # run-scoped beats env
    assert active_decode().pages == 32


def test_degraded_config_semantics():
    cfg = degraded(CONFIG)
    assert cfg.rerank is False
    assert cfg.max_new_tokens == CONFIG.degrade_max_new_tokens


def test_pool_budget_rejected_at_parse_time():
    huge = DecodeConfig(pages=1 << 22, page_size=64, hbm_bytes=1 << 20)
    with pytest.raises(ValueError, match="HBM budget"):
        DecodeEngine(MODEL, huge, params=PARAMS)


def test_run_knob_lands_in_run_context(monkeypatch):
    monkeypatch.setenv("PATHWAY_ANALYZE_ONLY", "1")
    pw.clear_graph()
    t = pw.debug.table_from_markdown("""
        | x
      1 | 1
    """)
    pw.io.null.write(t.select(pw.this.x))
    assert pw.run(decode="pages=16,page=4,max_seq=16") is None
    ctx = pw.internals.parse_graph.G.run_context
    assert ctx["decode"]["pages"] == 16
    assert ctx["decode"]["page_size"] == 4
    # the analyze-only run must not leave a run-scoped config installed
    monkeypatch.delenv("PATHWAY_DECODE", raising=False)
    assert active_decode() is None
    pw.clear_graph()


# ----------------------------------------------------- batching invisibility


def test_continuous_batching_is_semantically_invisible():
    """The acceptance gate: streams decoded interleaved (shared lanes,
    shared pool) are bitwise identical to each prompt decoded alone in
    a fresh engine, and to the single-trace ``decode_greedy`` path."""
    together = _engine().generate(PROMPTS)
    alone = [_engine().generate([p])[0] for p in PROMPTS]
    assert together == alone
    import jax.numpy as jnp

    for prompt, stream in zip(PROMPTS, together):
        assert len(stream) == CONFIG.max_new_tokens
        seq = 8 if len(prompt) <= 8 else 16
        ids = np.zeros(seq, np.int32)
        ids[: len(prompt)] = prompt
        ref = decode_greedy(
            PARAMS, MODEL, jnp.asarray(ids), jnp.int32(len(prompt)),
            CONFIG.max_new_tokens,
        )
        assert stream == [int(t) for t in np.asarray(ref)]


def test_more_prompts_than_lanes_queue_and_finish():
    prompts = [[(7 * i + j) % 97 for j in range(3 + i % 5)] for i in range(11)]
    eng = _engine(lanes=2, pages=24)
    streams = eng.generate(prompts)
    assert all(len(s) == CONFIG.max_new_tokens for s in streams)
    assert eng.pool.pages_in_use == 0
    assert not eng.busy()
    alone = [_engine().generate([p])[0] for p in prompts]
    assert streams == alone


def test_degraded_clamps_max_new():
    eng = _engine()
    ticket = eng.submit(PROMPTS[0], degraded=True)
    eng.drain()
    assert len(ticket.result()) == CONFIG.degrade_max_new_tokens
    assert ticket.skip_rerank
    assert DECODE_METRICS.snapshot()["degraded_total"] == 1


def test_ticket_validation():
    eng = _engine()
    with pytest.raises(ValueError, match="empty prompt"):
        eng.make_ticket([])
    with pytest.raises(ValueError, match="context limit"):
        eng.make_ticket(list(range(60)))


# --------------------------------------------------------------- deadlines


def test_mid_stream_deadline_preempts_only_the_expired_lane():
    eng = _engine()
    expired = Deadline(1.0, start=time.monotonic() - 10.0)
    victim = eng.submit(PROMPTS[0], deadline=expired)
    others = [eng.submit(p, deadline=Deadline.none()) for p in PROMPTS[1:]]
    before = fr.RECORDER._seq
    eng.drain()
    assert victim.preempted
    assert len(victim.result()) < CONFIG.max_new_tokens
    # the victim's pages went back to the pool...
    assert eng.pool.pages_in_use == 0
    kinds = [e["kind"] for e in fr.RECORDER.events() if e["seq"] > before]
    assert "decode.preempt" in kinds
    assert "decode.kv_evict" in kinds
    assert DECODE_METRICS.snapshot()["preempted_total"] == 1
    # ...and everyone else's stream is bitwise what it would have been
    for prompt, t in zip(PROMPTS[1:], others):
        assert not t.preempted
        assert t.result() == _engine().generate([prompt])[0]


# ------------------------------------------------------------------- chaos


def test_chaos_kill_at_decode_step_then_retry_is_identical():
    """A step killed at the ``decode.step`` site (between compute and
    commit) must leave the engine at the pre-step state: re-running the
    drain produces exactly the streams an unchaosed engine produces."""
    eng = _engine()
    tickets = [eng.submit(p) for p in PROMPTS]
    chaos.activate([{"site": "decode.step", "time": 2, "action": "raise"}])
    with pytest.raises(chaos.ChaosInjected):
        eng.drain()
    assert eng.steps == 2  # the killed step committed nothing
    chaos.deactivate()
    eng.drain()
    streams = [t.result() for t in tickets]
    assert streams == _engine().generate(PROMPTS)


# ---------------------------------------------------------- flight events


def test_flight_events_cover_the_decode_lifecycle():
    before = fr.RECORDER._seq
    _engine().generate(PROMPTS[:2])
    events = [e for e in fr.RECORDER.events() if e["seq"] > before]
    by_kind = {}
    for e in events:
        by_kind.setdefault(e["kind"], []).append(e)
    prefills = by_kind["decode.prefill"]
    assert len(prefills) == 2
    assert {e["prompt_tokens"] for e in prefills} == {5, 8}
    assert all(e["pages"] > 0 and e["wall_ms"] >= 0 for e in prefills)
    steps = by_kind["decode.step"]
    assert len(steps) == CONFIG.max_new_tokens - 1
    assert steps[0]["batch"] == 2 and steps[0]["tokens"] == 2
    evicts = by_kind["decode.kv_evict"]
    assert len(evicts) == 2
    assert all(e["reason"] == "finish" for e in evicts)


# ----------------------------------------------------------------- metrics


def test_metrics_gate_and_snapshot():
    assert not DECODE_METRICS.active()
    eng = _engine()
    eng.generate(PROMPTS[:2])
    assert DECODE_METRICS.active()
    snap = DECODE_METRICS.snapshot()
    assert snap["queries_total"] == 2
    assert snap["prefill_total"] == 2
    assert snap["steps_total"] == CONFIG.max_new_tokens - 1
    assert snap["tokens_total"] == 2 * CONFIG.max_new_tokens
    assert snap["kv_pages_in_use"] == 0
    assert snap["kv_page_pool"] == CONFIG.pages
    assert snap["tokens_per_second"] > 0
    assert set(snap["stage_latency_s"]) == {"prefill", "decode_step"}
    DECODE_METRICS.reset()
    assert not DECODE_METRICS.active()


def test_status_and_prometheus_surface_decode_block():
    import json

    from pathway_tpu.internals.http_monitoring import MonitoringHttpServer
    from pathway_tpu.internals.monitoring import StatsMonitor

    srv = MonitoringHttpServer(StatsMonitor(), port=0)
    quiet = srv._prometheus()
    assert "pathway_decode_" not in quiet  # inactive plane: no series
    assert "decode" not in json.loads(srv._status())
    _engine().generate(PROMPTS[:1])
    prom = srv._prometheus()
    for series in (
        "pathway_decode_tokens_total",
        "pathway_decode_steps_total",
        "pathway_decode_kv_page_pool",
        "pathway_decode_tokens_per_second",
        "pathway_decode_prefill_seconds_bucket",
        "pathway_decode_decode_step_seconds_count",
    ):
        assert series in prom, series
    assert json.loads(srv._status())["decode"]["queries_total"] == 1


# ----------------------------------------------------------------- service


def test_decode_service_front_door():
    eng = _engine()
    svc = DecodeService(eng)
    try:
        tickets = [svc.submit(p, deadline=Deadline.none()) for p in PROMPTS]
        streams = [t.result(timeout=60.0) for t in tickets]
        assert svc.error is None
    finally:
        svc.stop()
    assert streams == _engine().generate(PROMPTS)


def test_decode_service_drops_queue_expired_tickets():
    eng = _engine()
    svc = DecodeService(eng)
    try:
        dead = Deadline(1.0, start=time.monotonic() - 10.0)
        ticket = svc.submit(PROMPTS[0], deadline=dead)
        ticket.done.wait(timeout=60.0)
        assert ticket.preempted
    finally:
        svc.stop()
    assert DECODE_METRICS.snapshot()["preempted_total"] >= 1


# ------------------------------------------------------- fused answer path


def test_fused_rag_answer_path_on_chip():
    """embed -> retrieve -> rerank -> generate without leaving the
    device: the answer tokens must equal running ``decode_greedy`` by
    hand over the spliced query+doc prompt."""
    from pathway_tpu.models.encoder import EncoderConfig
    from pathway_tpu.models.sentence_encoder import SentenceEncoder
    from pathway_tpu.ops.fused_rag import FusedRagPipeline

    ecfg = EncoderConfig(
        vocab_size=30522,
        hidden_size=32,
        num_layers=1,
        num_heads=2,
        intermediate_size=64,
        max_position=64,
        pooling="mean",
    )
    enc = SentenceEncoder(config=ecfg, max_seq_len=64, max_batch=64)
    pipe = FusedRagPipeline(enc, None, reserved_space=64, doc_seq_len=32)
    pipe.add_docs(
        ["tpu", "pelican", "joins"],
        [
            "tpus multiply matrices quickly",
            "pelicans eat fish",
            "streaming joins need watermarks",
        ],
    )
    pipe.set_decoder(DecoderConfig(
        vocab_size=2048,
        hidden_size=16,
        num_layers=1,
        num_heads=2,
        intermediate_size=32,
        max_position=96,
    ))
    out = pipe.answer("what do pelicans eat", k=2, max_new=4)
    assert len(out["hits"]) == 2
    assert len(out["tokens"]) == 4
    assert all(isinstance(t, int) for t in out["tokens"])
    again = pipe.answer("what do pelicans eat", k=2, max_new=4)
    assert out["tokens"] == again["tokens"]  # greedy decode is reproducible
    bare = pipe.answer("what do pelicans eat", k=2, max_new=4, rerank=False)
    assert len(bare["tokens"]) == 4


# ------------------------------------------------- serving spec keys (PR 19)


def test_parse_decode_spec_serving_keys():
    cfg = parse_decode_spec("cache=1,spec=4,draft=1,chunk=8,draft_weights=32M")
    assert cfg.prefix_cache is True
    assert cfg.spec_tokens == 4
    assert cfg.draft_layers == 1
    assert cfg.prefill_chunk == 8
    assert cfg.draft_weights == 32 * 1024 * 1024
    cfg = parse_decode_spec("spec=4,ngram=2")
    assert (cfg.spec_tokens, cfg.draft_ngram) == (4, 2)
    cfg = parse_decode_spec("temp=0.5,top_k=10,top_p=0.9,seed=3")
    assert (cfg.temperature, cfg.top_k, cfg.top_p, cfg.seed) == (0.5, 10, 0.9, 3)
    with pytest.raises(ValueError, match="greedy"):
        parse_decode_spec("spec=4,temp=0.5")
    with pytest.raises(ValueError, match="draft_ngram"):
        DecodeConfig(draft_ngram=-1)


# -------------------------------------------------------- prefix caching


def test_prefix_cache_on_streams_equal_cache_off():
    """The correctness gate: mapping shared pages instead of
    re-prefilling must not change a single token — cold paths, warm
    paths, and mixed-prefix batches alike."""
    shared = [11, 22, 33, 44, 55, 66, 77, 88, 99]
    prompts = PROMPTS + [shared + [5], shared + [7, 9], shared + [7, 9]]
    off = _engine().generate(prompts)
    on = _engine(prefix_cache=True).generate(prompts)
    assert on == off


def test_prefix_cache_warm_hit_skips_prefill_work():
    shared = [11, 22, 33, 44, 55, 66, 77, 88, 99]
    eng = _engine(prefix_cache=True)
    eng.generate([shared + [5]])  # warms the cache
    assert eng.cache.cached_pages == 2  # (9-1) // page_size=4
    before = fr.RECORDER._seq
    eng.generate([shared + [7, 9]])
    hits = [
        e for e in fr.RECORDER.events()
        if e["seq"] > before and e["kind"] == "decode.prefill"
    ]
    assert hits and hits[0]["prefix_hit_tokens"] == 8
    snap = DECODE_METRICS.snapshot()
    assert snap["prefix_hit_ratio"] > 0
    assert snap["prefix_cached_pages"] == eng.cache.cached_pages


def test_shared_prefix_pages_booked_once_in_flight():
    """Two co-resident lanes holding the same prefix must book its
    physical pages once — the ``decode.kv`` ledger invariant, observed
    mid-flight through ``pool.pages_in_use``."""
    shared = [11, 22, 33, 44, 55, 66, 77, 88, 99]
    a, b = shared + [5], shared + [7]

    def admit(cache: bool) -> tuple[int, DecodeEngine]:
        eng = _engine(prefix_cache=cache, lanes=2)
        eng.generate([a])  # warm (pages stay cached only when cache=True)
        eng.submit(a)
        eng.submit(b)
        eng.step()  # admission + first decode tick, both lanes resident
        return eng.pool.pages_in_use, eng

    with_cache, eng_on = admit(True)
    without, eng_off = admit(False)
    # the 2 shared prefix pages are booked once instead of once per lane
    assert with_cache < without
    assert without - with_cache == 2
    eng_on.drain()
    eng_off.drain()
    assert eng_off.pool.pages_in_use == 0
    # retired lanes release holds; only the cached prefix remains
    assert eng_on.pool.pages_in_use == eng_on.cache.cached_pages


def test_prefix_cache_reclaims_under_pool_pressure():
    """A full pool evicts idle cached prefixes instead of queueing."""
    shared = [11, 22, 33, 44, 55, 66, 77, 88, 99]
    eng = _engine(prefix_cache=True, pages=8, lanes=1, max_seq=32)
    eng.generate([shared + [5]])
    assert eng.cache.cached_pages > 0
    # a disjoint prompt needing most of the pool forces reclaim
    t = eng.submit([7] * 13)
    eng.drain()
    assert len(t.result()) == CONFIG.max_new_tokens
    assert t.result() == _engine().generate([[7] * 13])[0]


# -------------------------------------------------------- chunked prefill


def test_chunked_prefill_streams_equal_unchunked():
    long = [(3 * i + 1) % 97 for i in range(30)]
    prompts = PROMPTS + [long]
    whole = _engine().generate(prompts)
    chunked = _engine(prefill_chunk=4).generate(prompts)
    assert chunked == whole
    combo = _engine(prefill_chunk=4, prefix_cache=True).generate(prompts)
    assert combo == whole


def test_long_prefill_interleaves_with_decode_ticks():
    """A long chunked prefill must not stall in-flight decodes: the
    short lane keeps emitting while the long lane is mid-prefill."""
    eng = _engine(prefill_chunk=2)
    short = eng.submit(PROMPTS[0])
    eng.step()
    emitted_before = len(short.tokens)
    long = eng.submit([(3 * i + 1) % 97 for i in range(30)])
    saw_interleave = False
    while eng.busy() and not long.done.is_set():
        eng.step()
        lanes = [ln for ln in eng._lanes if ln is not None]
        mid_prefill = any(ln.prefilling for ln in lanes)
        if mid_prefill and len(short.tokens) > emitted_before:
            saw_interleave = True
    eng.drain()
    assert saw_interleave, "short lane starved during the long prefill"
    assert short.result() == _engine().generate([PROMPTS[0]])[0]


# ----------------------------------------------------- speculative decode


def test_speculative_layer_skip_streams_equal_greedy():
    greedy = _engine().generate(PROMPTS)
    spec = _engine(spec_tokens=3, draft_layers=1).generate(PROMPTS)
    assert spec == greedy
    snap = DECODE_METRICS.snapshot()
    assert 0.0 <= snap["spec_acceptance_rate"] <= 1.0


def test_speculative_prompt_lookup_streams_equal_greedy():
    greedy = _engine().generate(PROMPTS)
    spec = _engine(spec_tokens=4, draft_ngram=2).generate(PROMPTS)
    assert spec == greedy
    assert "spec_acceptance_rate" in DECODE_METRICS.snapshot()


def test_speculative_composes_with_cache_and_chunking():
    shared = [11, 22, 33, 44, 55, 66, 77, 88, 99]
    prompts = PROMPTS + [shared + [5], shared + [7, 9]]
    greedy = _engine().generate(prompts)
    combo = _engine(
        spec_tokens=3, draft_layers=1, prefix_cache=True, prefill_chunk=3
    ).generate(prompts)
    assert combo == greedy


def test_chip_ledger_books_draft_and_verify_separately(monkeypatch):
    from pathway_tpu.internals.chip_ledger import CHIP_LEDGER

    monkeypatch.delenv("PATHWAY_CHIP_LEDGER", raising=False)
    CHIP_LEDGER.reset()
    CHIP_LEDGER.set_enabled(True)
    try:
        _engine(spec_tokens=3, draft_layers=1).generate(PROMPTS[:2])
        accounts = CHIP_LEDGER.snapshot()["accounts"]
        assert accounts["decode.draft"]["seconds"] > 0
        assert accounts["decode.verify"]["seconds"] > 0
        CHIP_LEDGER.reset()
        # prompt-lookup drafting books (near-)zero draft device-seconds:
        # the verify forward is the tick's only real chip spend
        _engine(spec_tokens=3, draft_ngram=2).generate(PROMPTS[:2])
        accounts = CHIP_LEDGER.snapshot()["accounts"]
        assert accounts["decode.verify"]["seconds"] > accounts["decode.draft"]["seconds"]
    finally:
        CHIP_LEDGER.set_enabled(None)
        CHIP_LEDGER.reset()


def test_chaos_kill_mid_spec_tick_then_retry_is_identical():
    eng = _engine(spec_tokens=3, draft_layers=1)
    tickets = [eng.submit(p) for p in PROMPTS]
    chaos.activate([{"site": "decode.step", "time": 1, "action": "raise"}])
    with pytest.raises(chaos.ChaosInjected):
        eng.drain()
    chaos.deactivate()
    eng.drain()
    assert [t.result() for t in tickets] == _engine().generate(PROMPTS)


# --------------------------------------------------------- sampled decode


SAMPLED = dict(temperature=0.7, top_k=5, top_p=0.9, seed=11)


def test_sampled_decode_is_deterministic_per_seed():
    first = _engine(**SAMPLED).generate(PROMPTS)
    again = _engine(**SAMPLED).generate(PROMPTS)
    assert first == again
    other = _engine(**{**SAMPLED, "seed": 12}).generate(PROMPTS)
    assert first != other  # seed actually reaches the draws
    greedy = _engine().generate(PROMPTS)
    assert first != greedy  # temperature actually samples


def test_sampled_decode_batching_is_invisible():
    together = _engine(**SAMPLED).generate(PROMPTS)
    alone = [_engine(**SAMPLED).generate([p])[0] for p in PROMPTS]
    assert together == alone


def test_sampled_decode_replays_identically_after_chaos():
    """Counter-based draws: a chaos kill + resume may not perturb a
    sampled stream (the recovery-replay determinism contract)."""
    eng = _engine(**SAMPLED)
    tickets = [eng.submit(p) for p in PROMPTS]
    chaos.activate([{"site": "decode.step", "time": 2, "action": "raise"}])
    with pytest.raises(chaos.ChaosInjected):
        eng.drain()
    chaos.deactivate()
    eng.drain()
    assert [t.result() for t in tickets] == _engine(**SAMPLED).generate(PROMPTS)
