"""Datetime/duration fuzz sweeps behind the .dt namespace — VERDICT r2
Weak #7 called out the absence of strptime/timezone fuzzing. Python's
datetime/zoneinfo is the oracle (the reference's chrono/chrono-tz plays
that role for its engine, src/engine/time.rs)."""

from __future__ import annotations

import datetime as dtm
import random
from zoneinfo import ZoneInfo

import pytest

import pathway_tpu as pw

from .utils import run_table


class _SSchema(pw.Schema):
    s: str


class _SSecsSchema(pw.Schema):
    s: str
    secs: int

FORMATS = [
    "%Y-%m-%d %H:%M:%S",
    "%Y-%m-%dT%H:%M:%S",
    "%d.%m.%Y %H:%M:%S",
    "%m/%d/%Y %H:%M",
    "%Y-%m-%d",
]

TZS = ["UTC", "Europe/Warsaw", "America/New_York", "Asia/Tokyo", "Australia/Sydney"]


def _rand_dt(rng: random.Random) -> dtm.datetime:
    return dtm.datetime(
        rng.randint(1971, 2037),
        rng.randint(1, 12),
        rng.randint(1, 28),
        rng.randint(0, 23),
        rng.randint(0, 59),
        rng.randint(0, 59),
    )


def _run_scalar(build):
    """build(table_of_strings) -> table with one output column; returns
    {input_string: value}."""
    rng = random.Random(7)
    return rng


def test_strptime_strftime_roundtrip_fuzz():
    rng = random.Random(1234)
    for fmt in FORMATS:
        samples = [_rand_dt(rng) for _ in range(25)]
        texts = [d.strftime(fmt) for d in samples]
        t = pw.debug.table_from_rows(_SSchema, [(x,) for x in texts])
        r = t.select(out=pw.this.s.dt.strptime(fmt).dt.strftime(fmt))
        got = sorted(v[0] for v in run_table(r).values())
        want = sorted(dtm.datetime.strptime(x, fmt).strftime(fmt) for x in texts)
        assert got == want, f"roundtrip failed for {fmt}"
        pw.clear_graph()


def test_timezone_conversion_fuzz_vs_zoneinfo():
    rng = random.Random(99)
    samples = [_rand_dt(rng) for _ in range(40)]
    fmt = "%Y-%m-%d %H:%M:%S"
    for tz in TZS:
        texts = [d.strftime(fmt) for d in samples]
        t = pw.debug.table_from_rows(_SSchema, [(x,) for x in texts])
        r = t.select(
            out=pw.this.s.dt.strptime(fmt).dt.to_utc(from_timezone=tz).dt.strftime(
                "%Y-%m-%d %H:%M:%S %z"
            )
        )
        got = sorted(v[0] for v in run_table(r).values())
        want = sorted(
            dtm.datetime.strptime(x, fmt)
            .replace(tzinfo=ZoneInfo(tz))
            .astimezone(dtm.timezone.utc)
            .strftime("%Y-%m-%d %H:%M:%S %z")
            for x in texts
        )
        assert got == want, f"to_utc mismatch for {tz}"
        pw.clear_graph()


def test_dst_gap_and_fold_transitions():
    """Spring-forward gaps and fall-back folds around real transitions."""
    fmt = "%Y-%m-%d %H:%M:%S"
    cases = [
        ("Europe/Warsaw", "2024-03-31 01:59:59"),   # just before gap
        ("Europe/Warsaw", "2024-03-31 03:00:00"),   # just after gap
        ("Europe/Warsaw", "2024-10-27 02:30:00"),   # inside the fold
        ("America/New_York", "2024-03-10 01:59:59"),
        ("America/New_York", "2024-11-03 01:30:00"),
    ]
    for tz, text in cases:
        t = pw.debug.table_from_rows(_SSchema, [(text,)])
        r = t.select(
            out=pw.this.s.dt.strptime(fmt).dt.to_utc(from_timezone=tz).dt.timestamp(unit="s")
        )
        (got,) = [v[0] for v in run_table(r).values()]
        want = (
            dtm.datetime.strptime(text, fmt)
            .replace(tzinfo=ZoneInfo(tz))
            .timestamp()
        )
        assert abs(got - want) < 1e-6, (tz, text, got, want)
        pw.clear_graph()


def test_duration_arithmetic_fuzz():
    rng = random.Random(5)
    fmt = "%Y-%m-%d %H:%M:%S"
    samples = [(_rand_dt(rng), rng.randint(-10**7, 10**7)) for _ in range(30)]
    t = pw.debug.table_from_rows(
        _SSecsSchema, [(d.strftime(fmt), secs) for d, secs in samples]
    )
    r = t.select(
        out=(
            pw.this.s.dt.strptime(fmt) + pw.Duration(seconds=1) * pw.this.secs
        ).dt.strftime(fmt)
    )
    got = sorted(v[0] for v in run_table(r).values())
    want = sorted(
        (d + dtm.timedelta(seconds=secs)).strftime(fmt) for d, secs in samples
    )
    assert got == want


def test_round_floor_fuzz_vs_oracle():
    rng = random.Random(21)
    fmt = "%Y-%m-%d %H:%M:%S"
    samples = [_rand_dt(rng) for _ in range(30)]
    t = pw.debug.table_from_rows(_SSchema, [(d.strftime(fmt),) for d in samples])
    hour = pw.Duration(hours=1)
    r = t.select(
        fl=pw.this.s.dt.strptime(fmt).dt.floor(hour).dt.strftime(fmt),
        rd=pw.this.s.dt.strptime(fmt).dt.round(hour).dt.strftime(fmt),
    )
    got = sorted((v[0], v[1]) for v in run_table(r).values())

    def oracle(d: dtm.datetime):
        fl = d.replace(minute=0, second=0)
        half = dtm.timedelta(minutes=30)
        rd = fl if (d - fl) < half else fl + dtm.timedelta(hours=1)
        return fl.strftime(fmt), rd.strftime(fmt)

    want = sorted(oracle(d) for d in samples)
    assert got == want
