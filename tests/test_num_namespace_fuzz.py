"""Numerical expression namespace vs Python math semantics, through the
full engine over a fuzzed corpus (reference analogue:
internals/expressions/numerical.py per-method tests)."""

from __future__ import annotations

import math

import pytest

import pathway_tpu as pw

from .utils import run_table

FLOATS = [0.0, -0.0, 1.5, -2.75, 3.14159, 100.0, 0.001, -17.25, 9.0]
INTS = [0, 1, -1, 7, -42, 1000]


def _ftab():
    return pw.debug.table_from_rows(
        pw.schema_from_types(x=float), [(v,) for v in FLOATS]
    )


def _itab():
    return pw.debug.table_from_rows(
        pw.schema_from_types(x=int), [(v,) for v in INTS]
    )


FCASES = [
    ("abs", lambda c: c.num.abs(), abs),
    ("round", lambda c: c.num.round(), lambda v: round(v)),
    ("round2", lambda c: c.num.round(2), lambda v: round(v, 2)),
    ("floor", lambda c: c.num.floor(), math.floor),
    ("ceil", lambda c: c.num.ceil(), math.ceil),
    ("exp", lambda c: c.num.exp(), math.exp),
    ("sin", lambda c: c.num.sin(), math.sin),
    ("cos", lambda c: c.num.cos(), math.cos),
    ("tan", lambda c: c.num.tan(), math.tan),
]


@pytest.mark.parametrize("name,build,oracle", FCASES, ids=[c[0] for c in FCASES])
def test_num_method_matches_python_floats(name, build, oracle):
    t = _ftab()
    out = t.select(x=pw.this.x, r=build(t.x))
    for x, r in run_table(out).values():
        w = oracle(x)
        assert r == pytest.approx(w, rel=1e-9, abs=1e-12), (name, x, r, w)
    pw.clear_graph()


def test_num_positive_only_methods():
    t = pw.debug.table_from_rows(
        pw.schema_from_types(x=float), [(1.0,), (4.0,), (0.25,), (math.e,)]
    )
    out = t.select(
        x=pw.this.x,
        sq=t.x.num.sqrt(),
        ln=t.x.num.log(),
        l2=t.x.num.log2(),
        l10=t.x.num.log10(),
    )
    for x, sq, ln, l2, l10 in run_table(out).values():
        assert sq == pytest.approx(math.sqrt(x))
        assert ln == pytest.approx(math.log(x))
        assert l2 == pytest.approx(math.log2(x))
        assert l10 == pytest.approx(math.log10(x))
    pw.clear_graph()


def test_num_abs_round_on_ints():
    t = _itab()
    out = t.select(x=pw.this.x, a=t.x.num.abs(), r=t.x.num.round())
    for x, a, r in run_table(out).values():
        assert a == abs(x) and r == round(x), (x, a, r)
    pw.clear_graph()


def test_num_fill_na():
    t = pw.debug.table_from_rows(
        pw.schema_from_types(x=float),
        [(1.5,), (float("nan"),), (-2.0,)],
    )
    out = t.select(r=t.x.num.fill_na(0.0))
    vals = sorted(v[0] for v in run_table(out).values())
    assert vals == [-2.0, 0.0, 1.5]
    pw.clear_graph()
