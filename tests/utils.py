"""Shared test helpers.

Rebuild of /root/reference/python/pathway/tests/utils.py
(assert_table_equality :544-556, DiffEntry checkers :119, run :589)."""

from __future__ import annotations

from typing import Any

import pathway_tpu as pw
from pathway_tpu.debug import _run_capture, table_to_stream


def _normalize(v):
    import numpy as np

    if isinstance(v, float) and v == int(v):
        return v
    if isinstance(v, np.ndarray):
        return ("ndarray", v.shape, tuple(np.asarray(v).ravel().tolist()))
    if isinstance(v, tuple):
        return tuple(_normalize(x) for x in v)
    return v


def _capture_state(table):
    cap, names = _run_capture(table)
    return cap.state, names


def assert_table_equality(t0: pw.Table, t1: pw.Table) -> None:
    s0, n0 = _capture_state(t0)
    s1, n1 = _capture_state(t1)
    assert n0 == n1, f"column names differ: {n0} vs {n1}"
    assert set(s0.keys()) == set(s1.keys()), (
        f"key sets differ: only-left={set(s0) - set(s1)} only-right={set(s1) - set(s0)}"
    )
    for k in s0:
        r0 = tuple(_normalize(v) for v in s0[k])
        r1 = tuple(_normalize(v) for v in s1[k])
        assert r0 == r1, f"row {k:#x} differs: {r0} vs {r1}"


def assert_table_equality_wo_index(t0: pw.Table, t1: pw.Table) -> None:
    s0, n0 = _capture_state(t0)
    s1, n1 = _capture_state(t1)
    assert n0 == n1, f"column names differ: {n0} vs {n1}"
    rows0 = sorted((tuple(_normalize(v) for v in r) for r in s0.values()), key=repr)
    rows1 = sorted((tuple(_normalize(v) for v in r) for r in s1.values()), key=repr)
    assert rows0 == rows1, f"rows differ:\n{rows0}\nvs\n{rows1}"


def assert_table_equality_wo_types(t0: pw.Table, t1: pw.Table) -> None:
    assert_table_equality(t0, t1)


def assert_table_equality_wo_index_types(t0: pw.Table, t1: pw.Table) -> None:
    assert_table_equality_wo_index(t0, t1)


def assert_stream_equality(table: pw.Table, expected: list[tuple]) -> None:
    """expected: list of (row_tuple, time, diff)."""
    stream, names = table_to_stream(table)
    got = sorted(
        ((tuple(_normalize(v) for v in row), time, diff) for _, row, time, diff in stream),
        key=repr,
    )
    want = sorted(
        ((tuple(_normalize(v) for v in row), time, diff) for row, time, diff in expected),
        key=repr,
    )
    assert got == want, f"streams differ:\n{got}\nvs\n{want}"


T = pw.debug.table_from_markdown


def run_all(**kwargs):
    pw.run(**kwargs)


def run_table(table: pw.Table) -> dict:
    """Run to completion; return {key: row_tuple} of the final state."""
    state, _names = _capture_state(table)
    return state
