"""Black-box flight recorder: bounded ring, crash dumps, CLI.

The recorder (pathway_tpu.internals.flight_recorder) rings recent
engine events in every process and dumps them to JSON on a crash,
chaos kill, or recovery escalation; the ``pathway blackbox`` CLI
lists/renders/diffs the dumps. These tests cover the ring semantics,
the dump file contract, the run-level integration (RunResult,
supervisor escalation attaching its dump path), and that enabling the
recorder leaves sink output byte-identical."""

from __future__ import annotations

import json
import os

import pytest
from click.testing import CliRunner

import pathway_tpu as pw
from pathway_tpu.cli import cli
from pathway_tpu.internals import flight_recorder as fr


# ---------------------------------------------------------------------------
# ring semantics
# ---------------------------------------------------------------------------


def test_ring_is_bounded_and_keeps_newest():
    rec = fr.FlightRecorder(size=16, enabled=True)
    for i in range(100):
        rec.record("epoch.begin", t=i)
    events = rec.events()
    assert len(events) == 16
    # the ring keeps the newest events, with monotonic sequence numbers
    assert [e["t"] for e in events] == list(range(84, 100))
    assert [e["seq"] for e in events] == list(range(85, 101))
    rec.clear()
    assert len(rec) == 0


def test_disabled_recorder_records_and_dumps_nothing(tmp_path):
    rec = fr.FlightRecorder(size=16, enabled=False)
    rec.record("epoch.begin", t=0)
    assert len(rec) == 0
    assert rec.dump("test", directory=str(tmp_path)) is None
    assert list(tmp_path.iterdir()) == []


def test_env_controls(monkeypatch):
    monkeypatch.setenv("PATHWAY_FLIGHT_RECORDER", "0")
    assert not fr.FlightRecorder().enabled
    monkeypatch.setenv("PATHWAY_FLIGHT_RECORDER", "1")
    monkeypatch.setenv("PATHWAY_FLIGHT_RECORDER_SIZE", "64")
    rec = fr.FlightRecorder()
    assert rec.enabled and rec._ring.maxlen == 64
    # floor: a ring too small to hold one epoch's transitions is useless
    monkeypatch.setenv("PATHWAY_FLIGHT_RECORDER_SIZE", "2")
    assert fr.FlightRecorder()._ring.maxlen == 16
    monkeypatch.setenv("PATHWAY_FLIGHT_RECORDER_DIR", "/some/dir")
    assert fr.default_dump_dir() == "/some/dir"


def test_record_swallows_unserializable_fields(tmp_path):
    rec = fr.FlightRecorder(size=16, enabled=True)
    rec.record("connector.failed", error=ValueError("boom"), obj=object())
    path = rec.dump("test", directory=str(tmp_path))
    assert path is not None
    data = fr.load_dump(path)  # default=repr made it JSON-clean
    assert data["events"][0]["kind"] == "connector.failed"


# ---------------------------------------------------------------------------
# dump files: roundtrip, render, diff
# ---------------------------------------------------------------------------


def _dump_with_epochs(tmp_path, n_epochs=5, reason="crash") -> str:
    rec = fr.FlightRecorder(size=64, enabled=True)
    for t in range(n_epochs):
        rec.record("epoch.begin", t=t, worker=0)
        rec.record("feed.commit", source=1, t=t, rows=3)
        rec.record("epoch.delivered", t=t)
        rec.record("epoch.advance", t=t, worker=0)
    path = rec.dump(reason, RuntimeError("engine died"), directory=str(tmp_path))
    assert path is not None
    return path


def test_dump_load_roundtrip(tmp_path):
    path = _dump_with_epochs(tmp_path)
    assert os.path.basename(path).startswith("blackbox-")
    data = fr.load_dump(path)
    assert data["version"] == fr.DUMP_FORMAT_VERSION
    assert data["reason"] == "crash"
    assert data["pid"] == os.getpid()
    assert data["error"] == {"type": "RuntimeError", "message": "engine died"}
    assert len(data["events"]) == 20
    assert fr.last_epoch(data) == 4


def test_render_highlights_last_epoch_transitions(tmp_path):
    data = fr.load_dump(_dump_with_epochs(tmp_path))
    text = fr.render(data, tail_epochs=3)
    assert "reason=crash" in text
    assert "error: RuntimeError: engine died" in text
    assert "last 3 epoch transitions:" in text
    tail = text.split("last 3 epoch transitions:")[1].split("events (")[0]
    # the three newest epoch-boundary events, in order
    assert tail.index("epoch.delivered") < tail.index("epoch.advance")
    assert "t=4" in tail and "t=0" not in tail
    assert "events (20 ringed):" in text


def test_list_dumps_and_diff(tmp_path):
    a = _dump_with_epochs(tmp_path, n_epochs=2, reason="first")
    b = _dump_with_epochs(tmp_path, n_epochs=5, reason="second")
    assert fr.list_dumps(str(tmp_path)) == sorted([a, b])
    text = fr.diff(fr.load_dump(a), fr.load_dump(b))
    assert "epoch.begin" in text
    assert "last_epoch=1" in text and "last_epoch=4" in text
    assert fr.list_dumps(str(tmp_path / "missing")) == []


def test_load_dump_rejects_non_dump_json(tmp_path):
    p = tmp_path / "blackbox-notadump.json"
    p.write_text(json.dumps({"foo": 1}))
    with pytest.raises(ValueError):
        fr.load_dump(str(p))


# ---------------------------------------------------------------------------
# pathway blackbox CLI
# ---------------------------------------------------------------------------


def test_blackbox_cli(tmp_path):
    a = _dump_with_epochs(tmp_path, n_epochs=2, reason="first")
    b = _dump_with_epochs(tmp_path, n_epochs=5, reason="second")
    runner = CliRunner()

    res = runner.invoke(cli, ["blackbox", "list", "--dir", str(tmp_path)])
    assert res.exit_code == 0, res.output
    assert a in res.output and b in res.output
    assert "reason=first" in res.output and "last_epoch=4" in res.output

    res = runner.invoke(cli, ["blackbox", "show", b])
    assert res.exit_code == 0, res.output
    assert "last 3 epoch transitions:" in res.output
    assert "epoch.advance" in res.output

    res = runner.invoke(cli, ["blackbox", "diff", a, b])
    assert res.exit_code == 0, res.output
    assert "epoch.begin" in res.output

    res = runner.invoke(cli, ["blackbox", "show", str(tmp_path / "nope.json")])
    assert res.exit_code != 0

    res = runner.invoke(cli, ["blackbox", "list", "--dir", str(tmp_path / "empty")])
    assert res.exit_code == 0 and "no dumps" in res.output


# ---------------------------------------------------------------------------
# run-level integration
# ---------------------------------------------------------------------------


def _wordcount(out: str):
    t = pw.debug.table_from_markdown(
        """
        | word
      1 | cat
      2 | dog
      3 | cat
        """
    )
    c = t.groupby(pw.this.word).reduce(pw.this.word, n=pw.reducers.count())
    pw.io.jsonlines.write(c, out)


def test_run_returns_bound_monitoring_port(tmp_path):
    _wordcount(str(tmp_path / "out.jsonl"))
    result = pw.run(
        monitoring_level="none", with_http_server=True, monitoring_http_port=0
    )
    pw.clear_graph()
    assert isinstance(result, pw.RunResult)
    # port 0 resolved to the actually-bound ephemeral port
    assert result.monitoring_http_port and result.monitoring_http_port > 0
    assert result.flight_recorder_dumps == []


def test_recorder_leaves_output_byte_identical(tmp_path, monkeypatch):
    out_on = str(tmp_path / "on.jsonl")
    _wordcount(out_on)
    pw.run(monitoring_level="none")
    pw.clear_graph()

    monkeypatch.setenv("PATHWAY_FLIGHT_RECORDER", "0")
    rec_off = fr.FlightRecorder()  # env honored for fresh recorders
    assert not rec_off.enabled
    monkeypatch.setattr(fr, "RECORDER", rec_off)
    out_off = str(tmp_path / "off.jsonl")
    _wordcount(out_off)
    pw.run(monitoring_level="none")
    pw.clear_graph()

    with open(out_on) as f_on, open(out_off) as f_off:
        assert f_on.read() == f_off.read()


def test_engine_seams_ring_epoch_events(tmp_path):
    before = fr.RECORDER._seq
    _wordcount(str(tmp_path / "out.jsonl"))
    pw.run(monitoring_level="none")
    pw.clear_graph()
    kinds = {e["kind"] for e in fr.RECORDER.events() if e["seq"] > before}
    assert "epoch.begin" in kinds
    assert "epoch.advance" in kinds


def test_escalation_attaches_dump_path(tmp_path, monkeypatch):
    from pathway_tpu.resilience import (
        Recovery,
        RecoveryEscalated,
        RetryPolicy,
        Supervisor,
    )

    monkeypatch.setenv("PATHWAY_FLIGHT_RECORDER_DIR", str(tmp_path / "bb"))
    fr.record("epoch.begin", t=7)

    def attempt(is_restart):
        raise OSError("worker socket died")

    sup = Supervisor(
        Recovery(
            max_restarts=1,
            backoff=RetryPolicy(first_delay_ms=1, jitter_ms=0, sleep=lambda s: None),
        )
    )
    with pytest.raises(RecoveryEscalated) as ei:
        sup.run(attempt)
    path = ei.value.flight_recorder_dump
    assert path and os.path.exists(path)
    data = fr.load_dump(path)
    assert data["reason"] == "recovery_escalated"
    kinds = [e["kind"] for e in data["events"]]
    # the restart and the escalation themselves are on the record
    assert "supervisor.restart" in kinds
    assert "supervisor.escalated" in kinds
