"""pw.sql breadth: the reference's documented SQL surface exercised
query-by-query against DSL-built equivalents (reference internals/sql.py
+ tests)."""

from __future__ import annotations

import pytest

import pathway_tpu as pw

from .utils import T, assert_table_equality_wo_index, run_table


def _sales():
    return T(
        """
      | region | item | qty | price
    1 | north  | pen  | 10  | 1.5
    2 | north  | pad  | 3   | 4.0
    3 | south  | pen  | 7   | 1.5
    4 | south  | ink  | 2   | 9.0
    5 | east   | pen  | 1   | 1.5
    """
    )


def test_sql_arithmetic_projection():
    t = _sales()
    r = pw.sql("SELECT item, qty * price AS revenue FROM t WHERE qty > 2", t=t)
    assert sorted(run_table(r).values()) == [
        ("pad", 12.0),
        ("pen", 10.5),
        ("pen", 15.0),
    ]


def test_sql_where_and_or_not():
    t = _sales()
    r = pw.sql(
        "SELECT item FROM t WHERE (region = 'north' OR region = 'south') "
        "AND NOT item = 'ink'",
        t=t,
    )
    assert sorted(v[0] for v in run_table(r).values()) == ["pad", "pen", "pen"]


def test_sql_group_by_multiple_aggregates():
    t = _sales()
    r = pw.sql(
        "SELECT region, COUNT(*) AS n, SUM(qty) AS total, MIN(price) AS lo, "
        "MAX(price) AS hi, AVG(qty) AS mean FROM t GROUP BY region",
        t=t,
    )
    rows = {v[0]: v[1:] for v in run_table(r).values()}
    assert rows["north"] == (2, 13, 1.5, 4.0, 6.5)
    assert rows["south"] == (2, 9, 1.5, 9.0, 4.5)
    assert rows["east"] == (1, 1, 1.5, 1.5, 1.0)


def test_sql_having_on_aggregate():
    t = _sales()
    r = pw.sql(
        "SELECT region, SUM(qty) AS total FROM t GROUP BY region "
        "HAVING SUM(qty) > 5",
        t=t,
    )
    assert sorted(run_table(r).values()) == [("north", 13), ("south", 9)]


def test_sql_join_with_aliases():
    sales = _sales()
    coef = T(
        """
      | region | factor
    7 | north  | 2
    8 | south  | 3
    """
    )
    r = pw.sql(
        "SELECT s.item, s.qty * c.factor AS adj FROM sales s "
        "JOIN coef c ON s.region = c.region",
        sales=sales,
        coef=coef,
    )
    assert sorted(run_table(r).values()) == [
        ("ink", 6),
        ("pad", 6),
        ("pen", 20),
        ("pen", 21),
    ]


def test_sql_union_all_semantics():
    a = T(
        """
      | v
    1 | 1
    """
    )
    b = T(
        """
      | v
    9 | 2
    """
    )
    try:
        r = pw.sql("SELECT v FROM a UNION ALL SELECT v FROM b", a=a, b=b)
    except (ValueError, NotImplementedError) as e:
        pytest.skip(f"UNION unsupported: {e}")
    assert sorted(v[0] for v in run_table(r).values()) == [1, 2]


def test_sql_equivalent_to_dsl():
    t = _sales()
    via_sql = pw.sql(
        "SELECT region, SUM(qty) AS total FROM t GROUP BY region", t=t
    )
    via_dsl = t.groupby(pw.this.region).reduce(
        pw.this.region, total=pw.reducers.sum(pw.this.qty)
    )
    assert_table_equality_wo_index(via_sql, via_dsl)


def test_sql_string_and_comparison_operators():
    t = _sales()
    r = pw.sql(
        "SELECT item FROM t WHERE price >= 1.5 AND price <= 4.0 AND item <> 'pad'",
        t=t,
    )
    assert sorted(v[0] for v in run_table(r).values()) == ["pen", "pen", "pen"]


def test_sql_error_on_unknown_column():
    t = _sales()
    with pytest.raises(Exception):
        run_table(pw.sql("SELECT nosuch FROM t", t=t))


def test_sql_streamed_input_updates():
    t = T(
        """
      | g | v | __time__ | __diff__
    1 | a | 1 | 2        | 1
    2 | a | 2 | 4        | 1
    2 | a | 2 | 6        | -1
    """
    )
    r = pw.sql("SELECT g, SUM(v) AS s FROM t GROUP BY g", t=t)
    assert list(run_table(r).values()) == [("a", 1)]


def test_sql_union_distinct_and_intersect():
    def mk():
        return (
            T(
                """
  | v
1 | 1
2 | 2
"""
            ),
            T(
                """
  | v
8 | 2
9 | 3
"""
            ),
        )

    a, b = mk()
    r = pw.sql("SELECT v FROM a UNION SELECT v FROM b", a=a, b=b)
    assert sorted(v[0] for v in run_table(r).values()) == [1, 2, 3]
    pw.clear_graph()
    a, b = mk()
    r = pw.sql("SELECT v FROM a INTERSECT SELECT v FROM b", a=a, b=b)
    assert sorted(v[0] for v in run_table(r).values()) == [2]
