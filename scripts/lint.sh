#!/usr/bin/env bash
# Repo self-lint: the full deep verifier (--deep, rules PWL001-PWL020)
# over every shipped demo pipeline and every *_clean analysis fixture,
# with error findings fatal (--fail-on=error, the CLI default). This is
# the command the CI hook runs; tests/test_bench_smoke.py gates the
# same sweep's latency (<10s per program on the CPU backend).
#
# Usage: scripts/lint.sh [extra analyze flags...]
#   scripts/lint.sh                 # error findings fail
#   scripts/lint.sh --fail-on=warn # warnings fail too
set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}"

PROGRAMS=()
for demo in pathway_tpu/debug/demos/*.py; do
    [[ "$(basename "$demo")" == "__init__.py" ]] && continue
    PROGRAMS+=("$demo")
done
for fixture in tests/fixtures/analysis/*_clean.py tests/fixtures/analysis/composed_planes.py; do
    [[ -f "$fixture" ]] && PROGRAMS+=("$fixture")
done

rc=0
for prog in "${PROGRAMS[@]}"; do
    echo "== analyze --deep $* $prog"
    if ! python -m pathway_tpu.cli analyze --deep "$@" "$prog"; then
        rc=1
    fi
done

if [[ $rc -ne 0 ]]; then
    echo "lint.sh: FAIL — unsuppressed deep findings above" >&2
else
    echo "lint.sh: OK (${#PROGRAMS[@]} programs clean)"
fi
exit $rc
